"""Byzantine gradient synthesis — the attack registry.

TPU-native contract (redesign of reference `attacks/__init__.py:15-35`):
an attack is a pure function

    attack(grad_honests: f32[h, d], f_decl: int, f_real: int,
           defense: callable, **kwargs) -> f32[f_real, d]

where `defense(gradients=f32[n,d], f=int) -> f32[d]` is the live aggregation
rule (adaptive attacks line-search against it *inside* the same XLA program,
see `ops/linesearch.py`). The reference returns `f_real` references to one
tensor; here the result is a stacked (f_real, d) matrix — identical
semantics once concatenated with the honest rows.

Registry parity: `attacks: name -> Attack`, each with `.checked` /
`.unchecked` / `.check` members (reference `attacks/__init__.py:46-87`).

Stateful (adaptive) attacks: an attack registered with a `state_init`
hook threads history across steps — `state_init(f_real, d) -> pytree`
builds the initial state, the attack function receives a `state=` kwarg
and returns `(f32[f_real, d], new_state)` instead of the bare matrix.
The engine carries the pytree in `TrainState.attack_state` (donated,
checkpointed, sharding-replicated like every scalar counter), so a
time-coupled attack — e.g. one exploiting a defense's EWMA warm-up
window (`attacks/warmup.py`) — composes with the fused step, the arena
closed loop and resume. Static attacks are untouched: no `state_init`
means no `state` kwarg, a bare matrix return, and an empty `()` state
leaf in `TrainState`.
"""

import pathlib

import jax.numpy as jnp

from byzantinemomentum_tpu import utils
from byzantinemomentum_tpu.ops import as_matrix

__all__ = ["attacks", "register", "Attack"]

# Registry: name -> Attack
attacks = {}


class Attack:
    """A registered attack; calling it runs the checked path."""

    def __init__(self, name, unchecked, check, state_init=None):
        self.name = name
        self.unchecked = unchecked
        self.check = check
        self.state_init = state_init

    @property
    def stateful(self):
        """Whether the attack threads history (see the module docstring):
        it takes `state=` and returns `(matrix, new_state)`."""
        return self.state_init is not None

    def checked(self, grad_honests, f_decl, f_real, defense=None, state=None,
                **kwargs):
        grad_honests = as_matrix(grad_honests)
        message = self.check(
            grad_honests=grad_honests, f_decl=f_decl, f_real=f_real, defense=defense, **kwargs)
        if message is not None:
            raise utils.UserException(f"Attack {self.name!r} cannot be used: {message}")
        if self.stateful:
            if state is None:
                state = self.state_init(f_real=f_real,
                                        d=grad_honests.shape[1])
            result, state = self.unchecked(
                grad_honests, f_decl=f_decl, f_real=f_real, defense=defense,
                state=state, **kwargs)
        else:
            result = self.unchecked(
                grad_honests, f_decl=f_decl, f_real=f_real, defense=defense,
                **kwargs)
        expected = (f_real, grad_honests.shape[1])
        if result.shape != expected:
            raise utils.UserException(
                f"Attack {self.name!r} returned shape {result.shape}, expected {expected}")
        return (result, state) if self.stateful else result

    def __call__(self, grad_honests, f_decl, f_real, defense=None, **kwargs):
        return self.checked(grad_honests, f_decl, f_real, defense=defense, **kwargs)

    def __repr__(self):
        return f"Attack({self.name!r})"


def register(name, unchecked, check, state_init=None):
    """Register an attack under `name` (reference `attacks/__init__.py:46-77`).

    `state_init(f_real, d) -> pytree` marks the attack STATEFUL: its
    `unchecked` must accept `state=` and return `(matrix, new_state)` —
    see the module docstring."""
    if name in attacks:
        utils.warning(f"Attack {name!r} registered twice; keeping the last")
    atk = Attack(name, unchecked, check, state_init=state_init)
    attacks[name] = atk
    return atk


def empty_byzantine(grad_honests):
    """The (0, d) result for f_real == 0 (reference returns an empty list)."""
    return jnp.zeros((0, grad_honests.shape[1]), dtype=grad_honests.dtype)


# Self-registering attack modules (plugin pattern, reference
# `attacks/__init__.py:81-87`)
utils.import_directory(__name__, pathlib.Path(__file__).parent)
