"""Byzantine gradient synthesis — the attack registry.

TPU-native contract (redesign of reference `attacks/__init__.py:15-35`):
an attack is a pure function

    attack(grad_honests: f32[h, d], f_decl: int, f_real: int,
           defense: callable, **kwargs) -> f32[f_real, d]

where `defense(gradients=f32[n,d], f=int) -> f32[d]` is the live aggregation
rule (adaptive attacks line-search against it *inside* the same XLA program,
see `ops/linesearch.py`). The reference returns `f_real` references to one
tensor; here the result is a stacked (f_real, d) matrix — identical
semantics once concatenated with the honest rows.

Registry parity: `attacks: name -> Attack`, each with `.checked` /
`.unchecked` / `.check` members (reference `attacks/__init__.py:46-87`).
"""

import pathlib

import jax.numpy as jnp

from byzantinemomentum_tpu import utils
from byzantinemomentum_tpu.ops import as_matrix

__all__ = ["attacks", "register", "Attack"]

# Registry: name -> Attack
attacks = {}


class Attack:
    """A registered attack; calling it runs the checked path."""

    def __init__(self, name, unchecked, check):
        self.name = name
        self.unchecked = unchecked
        self.check = check

    def checked(self, grad_honests, f_decl, f_real, defense=None, **kwargs):
        grad_honests = as_matrix(grad_honests)
        message = self.check(
            grad_honests=grad_honests, f_decl=f_decl, f_real=f_real, defense=defense, **kwargs)
        if message is not None:
            raise utils.UserException(f"Attack {self.name!r} cannot be used: {message}")
        result = self.unchecked(
            grad_honests, f_decl=f_decl, f_real=f_real, defense=defense, **kwargs)
        expected = (f_real, grad_honests.shape[1])
        if result.shape != expected:
            raise utils.UserException(
                f"Attack {self.name!r} returned shape {result.shape}, expected {expected}")
        return result

    def __call__(self, grad_honests, f_decl, f_real, defense=None, **kwargs):
        return self.checked(grad_honests, f_decl, f_real, defense=defense, **kwargs)

    def __repr__(self):
        return f"Attack({self.name!r})"


def register(name, unchecked, check):
    """Register an attack under `name` (reference `attacks/__init__.py:46-77`)."""
    if name in attacks:
        utils.warning(f"Attack {name!r} registered twice; keeping the last")
    atk = Attack(name, unchecked, check)
    attacks[name] = atk
    return atk


def empty_byzantine(grad_honests):
    """The (0, d) result for f_real == 0 (reference returns an empty list)."""
    return jnp.zeros((0, grad_honests.shape[1]), dtype=grad_honests.dtype)


# Self-registering attack modules (plugin pattern, reference
# `attacks/__init__.py:81-87`)
utils.import_directory(__name__, pathlib.Path(__file__).parent)
