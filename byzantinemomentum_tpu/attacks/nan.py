"""All-NaN gradient attack (reference `attacks/nan.py`).

Doubles as the framework's numerical fault-injection: GARs are expected to
be NaN-resilient (reference `median.py:13`, `krum.py:46-47`, `brute.py:55-57`).
"""

import jax.numpy as jnp

from byzantinemomentum_tpu.attacks import empty_byzantine, register

__all__ = ["attack"]


def attack(grad_honests, f_real, **kwargs):
    """Return f_real all-NaN gradients (reference `attacks/nan.py:24-40`)."""
    if f_real == 0:
        return empty_byzantine(grad_honests)
    return jnp.full((f_real, grad_honests.shape[1]), jnp.nan, dtype=grad_honests.dtype)


def detect(gradients):
    """Rows carrying any non-finite coordinate — the detection counterpart
    of this attack, generalized to every numerically-corrupt submission
    (NaN shards, inf blowups). The faults subsystem's NaN-quarantine routes
    through this single predicate (`faults/sanitize.py`), so what the
    attack can emit, the sanitizer can flag. `f32[n, d] -> bool[n]`."""
    return ~jnp.all(jnp.isfinite(gradients), axis=1)


def check(grad_honests, f_real, **kwargs):
    if grad_honests.shape[0] == 0:
        return "Expected a non-empty list of honest gradients"
    if not isinstance(f_real, int) or f_real < 0:
        return f"Expected a non-negative number of Byzantine gradients to generate, got {f_real!r}"


register("nan", attack, check)
