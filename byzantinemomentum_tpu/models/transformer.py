"""Transformer classifier: `transformer-classifier`.

The reference has no attention models (SURVEY.md §5.7 — it scales in worker
count and model dimension, not sequence length), but long-context scaling is
a first-class axis of this framework, so the model zoo carries a sequence
model wired to the sequence-parallel kernels in `parallel/ring.py`.

Design: images tokenize as rows — `(B, H, W, C) -> (B, L=H, W*C)` — giving
mnist L=28 / cifar L=32 sequences without a new data pipeline; then a
standard pre-LN encoder (MHA + MLP blocks), mean pool, linear head,
log-softmax. The attention implementation is selected at build time:

  attn_impl="dense"   — single-device softmax attention (default);
  attn_impl="ring"    — ring attention: K/V blocks rotate over the mesh
                        axis `seq_axis` via `lax.ppermute` (run the model
                        under `shard_map` with the sequence sharded);
  attn_impl="ulysses" — all-to-all head/sequence swap over `seq_axis`.

All three are exact — `tests/test_ring.py` verifies the sharded variants
reproduce the dense logits on a virtual 8-device mesh. Under sequence
sharding, per-token ops run on local chunks; the positional table is sliced
by `axis_index`, and the mean pool closes with a `psum`.
"""

import jax
import jax.numpy as jnp
from jax import lax

from byzantinemomentum_tpu.models import ModelDef, register
from byzantinemomentum_tpu.models.core import dense_init
from byzantinemomentum_tpu.parallel.ring import (
    dense_attention, ring_attention, ulysses_attention)

__all__ = []


def _ln_init(dim):
    return {"g": jnp.ones((dim,), jnp.float32),
            "b": jnp.zeros((dim,), jnp.float32)}


def _ln_apply(p, x, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mu) ** 2, axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * p["g"] + p["b"]


def make_transformer(depth=2, dim=64, heads=4, mlp_ratio=4, num_classes=10,
                     input_shape=(28, 28, 1), causal=False,
                     attn_impl="dense", seq_axis="seq", **kwargs):
    if attn_impl not in ("dense", "ring", "ulysses"):
        raise ValueError(f"Unknown attention implementation {attn_impl!r}")
    if dim % heads != 0:
        raise ValueError(f"dim={dim} not divisible by heads={heads}")
    h_img, w_img, c_img = input_shape
    seq_len, token_dim = h_img, w_img * c_img
    head_dim = dim // heads
    hidden = mlp_ratio * dim

    def init(key):
        keys = jax.random.split(key, 2 + 4 * depth + 1)
        params = {
            "embed": dense_init(keys[0], token_dim, dim),
            "pos": 0.02 * jax.random.normal(keys[1], (seq_len, dim),
                                            jnp.float32),
            "head": dense_init(keys[-1], dim, num_classes),
            "ln_f": _ln_init(dim),
            "blocks": [],
        }
        for i in range(depth):
            k = keys[2 + 4 * i: 6 + 4 * i]
            params["blocks"].append({
                "ln1": _ln_init(dim), "ln2": _ln_init(dim),
                "qkv": dense_init(k[0], dim, 3 * dim),
                "proj": dense_init(k[1], dim, dim),
                "fc1": dense_init(k[2], dim, hidden),
                "fc2": dense_init(k[3], hidden, dim),
            })
        return params, {}

    def attend(q, k, v):
        # (B, L, H, Dh) -> (B, H, L, Dh) expected by the kernels
        q, k, v = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
        if attn_impl == "ring":
            out = ring_attention(q, k, v, seq_axis, causal=causal)
        elif attn_impl == "ulysses":
            out = ulysses_attention(q, k, v, seq_axis, causal=causal)
        else:
            out = dense_attention(q, k, v, causal=causal)
        return jnp.swapaxes(out, 1, 2)

    def apply(params, state, x, train=False, rng=None):
        b = x.shape[0]
        x = x.reshape(b, x.shape[1], -1)  # (B, L or Lc, W*C) row tokens
        lc = x.shape[1]
        x = x @ params["embed"]["w"] + params["embed"]["b"]
        if attn_impl == "dense":
            pos = params["pos"][:lc]
        else:
            # Local chunk of the (replicated) positional table
            me = lax.axis_index(seq_axis)
            pos = lax.dynamic_slice_in_dim(params["pos"], me * lc, lc)
        x = x + pos[None]
        for blk in params["blocks"]:
            y = _ln_apply(blk["ln1"], x)
            qkv = y @ blk["qkv"]["w"] + blk["qkv"]["b"]
            q, k, v = (t.reshape(b, lc, heads, head_dim)
                       for t in jnp.split(qkv, 3, axis=-1))
            y = attend(q, k, v).reshape(b, lc, dim)
            x = x + (y @ blk["proj"]["w"] + blk["proj"]["b"])
            y = _ln_apply(blk["ln2"], x)
            y = jax.nn.gelu(y @ blk["fc1"]["w"] + blk["fc1"]["b"])
            x = x + (y @ blk["fc2"]["w"] + blk["fc2"]["b"])
        x = _ln_apply(params["ln_f"], x)
        pooled = jnp.sum(x, axis=1)
        if attn_impl == "dense":
            # Divide by the actual token count (the pos[:lc] slice tolerates
            # sequences shorter than the configured seq_len)
            pooled = pooled / lc
        else:
            # Sharded: each chip holds lc = L/p tokens of the full sequence
            pooled = lax.psum(pooled, seq_axis) / seq_len
        out = pooled @ params["head"]["w"] + params["head"]["b"]
        return jax.nn.log_softmax(out), state

    return ModelDef("transformer-classifier", init, apply, input_shape)


register("transformer-classifier", make_transformer)
register("transformer", make_transformer)
