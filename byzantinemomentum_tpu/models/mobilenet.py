"""`mobilenet_v2` — torchvision MobileNetV2, as a pure-pytree ModelDef.

Registry-tail extension in the `models/resnet.py` pattern (the reference
resolves every `torchvision.models` name, reference
`experiments/model.py:40-90`); the parameter count is pinned against
torchvision in `tests/test_vgg_densenet.py`.

Architecture (torchvision `mobilenetv2.py`, width_mult 1.0):
conv3x3(3,32,s2,nobias) BN ReLU6, then inverted residuals
(expansion t, out c, repeats n, first-stride s):
(1,16,1,1) (6,24,2,2) (6,32,3,2) (6,64,4,2) (6,96,3,1) (6,160,3,2)
(6,320,1,1) — each block: [1x1 expand BN ReLU6 (skipped at t=1)],
3x3 DEPTHWISE(s) BN ReLU6, 1x1 project BN (linear); residual add iff
stride 1 and cin == cout — then conv1x1(320,1280) BN ReLU6, global
average pool, Dropout(0.2), Linear(1280, num_classes).

Initialization parity: kaiming-normal(fan_out) conv kernels (bias-free),
BN gamma=1/beta=0, classifier W ~ N(0, 0.01) with zero bias
(`MobileNetV2.__init__`'s init loop).
"""

import math

import jax
import jax.numpy as jnp
from jax import lax

from byzantinemomentum_tpu.models import ModelDef, register
from byzantinemomentum_tpu.models.core import (
    batchnorm_apply, batchnorm_init, dropout_apply)

__all__ = []

# (expansion, out channels, repeats, first stride)
_CFG = ((1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
        (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1))


def _conv_init(key, kh, kw, cin, cout):
    fan_out = kh * kw * cout
    std = math.sqrt(2.0 / fan_out)
    return {"w": std * jax.random.normal(key, (kh, kw, cin, cout),
                                         jnp.float32)}


def _dw_init(key, c):
    """Depthwise 3x3: torch shape (c, 1, 3, 3); kaiming fan_out counts the
    per-group output (9 * 1). HWIO for feature_group_count=c is
    (3, 3, 1, c)."""
    std = math.sqrt(2.0 / 9.0)
    return {"w": std * jax.random.normal(key, (3, 3, 1, c), jnp.float32)}


def _conv(params, x, *, stride=1, pad=0, groups=1):
    return lax.conv_general_dilated(
        x, params["w"], window_strides=(stride, stride),
        padding=[(pad, pad), (pad, pad)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups)


def _relu6(x):
    return jnp.clip(x, 0.0, 6.0)


def _block_init(key, cin, cout, t):
    keys = jax.random.split(key, 3)
    h = cin * t
    params, state = {}, {}
    if t != 1:
        params["expand"] = _conv_init(keys[0], 1, 1, cin, h)
        params["bn_e"], state["bn_e"] = batchnorm_init(h)
    params["dw"] = _dw_init(keys[1], h)
    params["bn_d"], state["bn_d"] = batchnorm_init(h)
    params["project"] = _conv_init(keys[2], 1, 1, h, cout)
    params["bn_p"], state["bn_p"] = batchnorm_init(cout)
    return params, state


def _block_apply(params, state, x, *, stride, train):
    new_state = dict(state)
    out = x
    if "expand" in params:
        out = _conv(params["expand"], out)
        out, new_state["bn_e"] = batchnorm_apply(params["bn_e"],
                                                 state["bn_e"], out,
                                                 train=train)
        out = _relu6(out)
    h = out.shape[-1]
    out = _conv(params["dw"], out, stride=stride, pad=1, groups=h)
    out, new_state["bn_d"] = batchnorm_apply(params["bn_d"], state["bn_d"],
                                             out, train=train)
    out = _relu6(out)
    out = _conv(params["project"], out)
    out, new_state["bn_p"] = batchnorm_apply(params["bn_p"], state["bn_p"],
                                             out, train=train)
    if stride == 1 and x.shape[-1] == out.shape[-1]:
        out = out + x
    return out, new_state


def make_mobilenet_v2(num_classes=10, **kwargs):
    n_blocks = sum(n for _, _, n, _ in _CFG)

    def init(key):
        keys = jax.random.split(key, n_blocks + 3)
        params, state = {}, {}
        params["stem"] = _conv_init(keys[0], 3, 3, 3, 32)
        params["bn0"], state["bn0"] = batchnorm_init(32)
        cin, k = 32, 1
        for t, c, n, _s in _CFG:
            for i in range(n):
                name = f"b{k - 1}"
                params[name], state[name] = _block_init(keys[k], cin, c, t)
                cin, k = c, k + 1
        params["head"] = _conv_init(keys[k], 1, 1, cin, 1280)
        params["bn1"], state["bn1"] = batchnorm_init(1280)
        kw_, kb = jax.random.split(keys[k + 1])
        params["fc"] = {
            "w": 0.01 * jax.random.normal(kw_, (1280, num_classes),
                                          jnp.float32),
            "b": jnp.zeros((num_classes,), jnp.float32)}
        return params, state

    def apply(params, state, x, train=False, rng=None):
        if train and rng is None:
            raise ValueError("mobilenet_v2 needs a PRNG key in train mode "
                             "(classifier dropout)")
        new_state = dict(state)
        x = _conv(params["stem"], x, stride=2, pad=1)
        x, new_state["bn0"] = batchnorm_apply(params["bn0"], state["bn0"], x,
                                              train=train)
        x = _relu6(x)
        k = 0
        for t, c, n, s in _CFG:
            for i in range(n):
                name = f"b{k}"
                x, new_state[name] = _block_apply(
                    params[name], state[name], x,
                    stride=(s if i == 0 else 1), train=train)
                k += 1
        x = _conv(params["head"], x)
        x, new_state["bn1"] = batchnorm_apply(params["bn1"], state["bn1"], x,
                                              train=train)
        x = _relu6(x)
        x = jnp.mean(x, axis=(1, 2))
        x = dropout_apply(rng, x, 0.2, train=train)
        return x @ params["fc"]["w"] + params["fc"]["b"], new_state

    return ModelDef("mobilenet_v2", init, apply, (32, 32, 3))


register("mobilenet_v2", make_mobilenet_v2)
