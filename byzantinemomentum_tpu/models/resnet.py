"""`resnet18/34/50/101/152` — standard torchvision models, as pure-pytree
ModelDefs.

The reference exposes every `torchvision.models` entry point by name
(reference `experiments/model.py:40-90`); this repo's registry is the
grid-parity set (see PARITY.md "registry scoping"), and this module shows
the registry extending to the torchvision zoo the same way: torchvision's
resnets' architecture and initialization, NHWC/HWIO, no module framework.

Architecture (torchvision `resnet.py`; resnet18 = BasicBlock [2, 2, 2, 2],
resnet34 = BasicBlock [3, 4, 6, 3]; Bottleneck: resnet50 [3, 4, 6, 3],
resnet101 [3, 4, 23, 3], resnet152 [3, 8, 36, 3]):
  conv7x7(3,64,s2,p3,nobias) bn relu maxpool3x3(s2,p1),
  4 stages of [depth-dependent] blocks (64, 128, 256, 512 base channels;
  first block of stages 2-4 downsamples with stride 2 + 1x1 projection),
  global average pool, fc(512*expansion, num_classes).
BasicBlock: conv3x3 bn relu conv3x3 bn, + identity/projection, relu.
Bottleneck (expansion 4, torchvision v1.5: stride on the 3x3 conv):
  conv1x1 bn relu conv3x3(s) bn relu conv1x1(4w) bn, + identity/projection,
  relu.

Initialization parity with torchvision: kaiming-normal(fan_out, relu) conv
kernels (no biases), BN gamma=1/beta=0, torch-default fc init. On CIFAR
shapes (32x32) the stem reduces to 8x8 before the stages, exactly as torch
would compute it.
"""

import math

import jax
import jax.numpy as jnp
from jax import lax

from byzantinemomentum_tpu.models import ModelDef, register
from byzantinemomentum_tpu.models.core import (
    batchnorm_apply, batchnorm_init, dense_apply, dense_init)

__all__ = []

_STAGES = (64, 128, 256, 512)


def _conv_init(key, kh, kw, cin, cout):
    """torchvision resnet conv init: kaiming_normal_(fan_out, relu), bias-free
    (`torchvision/models/resnet.py` `_resnet` init loop)."""
    fan_out = kh * kw * cout
    std = math.sqrt(2.0 / fan_out)
    return {"w": std * jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)}


def _conv(params, x, *, stride=1, pad=1):
    return lax.conv_general_dilated(
        x, params["w"], window_strides=(stride, stride),
        padding=[(pad, pad), (pad, pad)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _max_pool_3x3s2p1(x):
    """torch `MaxPool2d(3, stride=2, padding=1)` (pads with -inf)."""
    return lax.reduce_window(
        x, -jnp.inf, lax.max, window_dimensions=(1, 3, 3, 1),
        window_strides=(1, 2, 2, 1), padding=((0, 0), (1, 1), (1, 1), (0, 0)))


def _block_init(key, cin, cout, downsample):
    keys = jax.random.split(key, 3)
    params, state = {}, {}
    params["conv1"] = _conv_init(keys[0], 3, 3, cin, cout)
    params["bn1"], state["bn1"] = batchnorm_init(cout)
    params["conv2"] = _conv_init(keys[1], 3, 3, cout, cout)
    params["bn2"], state["bn2"] = batchnorm_init(cout)
    if downsample:
        params["down"] = _conv_init(keys[2], 1, 1, cin, cout)
        params["dbn"], state["dbn"] = batchnorm_init(cout)
    return params, state


def _block_apply(params, state, x, *, stride, train):
    new_state = dict(state)
    out = _conv(params["conv1"], x, stride=stride, pad=1)
    out, new_state["bn1"] = batchnorm_apply(params["bn1"], state["bn1"], out,
                                            train=train)
    out = jax.nn.relu(out)
    out = _conv(params["conv2"], out, stride=1, pad=1)
    out, new_state["bn2"] = batchnorm_apply(params["bn2"], state["bn2"], out,
                                            train=train)
    if "down" in params:
        x = _conv(params["down"], x, stride=stride, pad=0)
        x, new_state["dbn"] = batchnorm_apply(params["dbn"], state["dbn"], x,
                                              train=train)
    return jax.nn.relu(out + x), new_state


def _bottleneck_init(key, cin, width, downsample):
    keys = jax.random.split(key, 4)
    params, state = {}, {}
    params["conv1"] = _conv_init(keys[0], 1, 1, cin, width)
    params["bn1"], state["bn1"] = batchnorm_init(width)
    params["conv2"] = _conv_init(keys[1], 3, 3, width, width)
    params["bn2"], state["bn2"] = batchnorm_init(width)
    params["conv3"] = _conv_init(keys[2], 1, 1, width, 4 * width)
    params["bn3"], state["bn3"] = batchnorm_init(4 * width)
    if downsample:
        params["down"] = _conv_init(keys[3], 1, 1, cin, 4 * width)
        params["dbn"], state["dbn"] = batchnorm_init(4 * width)
    return params, state


def _bottleneck_apply(params, state, x, *, stride, train):
    new_state = dict(state)
    out = _conv(params["conv1"], x, stride=1, pad=0)
    out, new_state["bn1"] = batchnorm_apply(params["bn1"], state["bn1"], out,
                                            train=train)
    out = jax.nn.relu(out)
    out = _conv(params["conv2"], out, stride=stride, pad=1)
    out, new_state["bn2"] = batchnorm_apply(params["bn2"], state["bn2"], out,
                                            train=train)
    out = jax.nn.relu(out)
    out = _conv(params["conv3"], out, stride=1, pad=0)
    out, new_state["bn3"] = batchnorm_apply(params["bn3"], state["bn3"], out,
                                            train=train)
    if "down" in params:
        x = _conv(params["down"], x, stride=stride, pad=0)
        x, new_state["dbn"] = batchnorm_apply(params["dbn"], state["dbn"], x,
                                              train=train)
    return jax.nn.relu(out + x), new_state


def _make_resnet(name, blocks, num_classes=10, bottleneck=False):
    n_blocks = sum(blocks)
    expansion = 4 if bottleneck else 1

    def init(key):
        keys = jax.random.split(key, n_blocks + 2)
        params, state = {}, {}
        params["stem"] = _conv_init(keys[0], 7, 7, 3, 64)
        params["bn"], state["bn"] = batchnorm_init(64)
        cin = 64
        k = 1
        for s, width in enumerate(_STAGES):
            cout = width * expansion
            for b in range(blocks[s]):
                downsample = b == 0 and (s > 0 or cin != cout)
                bname = f"s{s}b{b}"
                if bottleneck:
                    params[bname], state[bname] = _bottleneck_init(
                        keys[k], cin, width, downsample)
                else:
                    params[bname], state[bname] = _block_init(
                        keys[k], cin, cout, downsample)
                k += 1
                cin = cout
        params["fc"] = dense_init(keys[n_blocks + 1], 512 * expansion,
                                  num_classes)
        return params, state

    block_apply = _bottleneck_apply if bottleneck else _block_apply

    def apply(params, state, x, train=False, rng=None):
        new_state = dict(state)
        x = _conv(params["stem"], x, stride=2, pad=3)
        x, new_state["bn"] = batchnorm_apply(params["bn"], state["bn"], x,
                                             train=train)
        x = jax.nn.relu(x)
        x = _max_pool_3x3s2p1(x)
        for s in range(len(_STAGES)):
            for b in range(blocks[s]):
                bname = f"s{s}b{b}"
                stride = 2 if (s > 0 and b == 0) else 1
                x, new_state[bname] = block_apply(
                    params[bname], state[bname], x, stride=stride, train=train)
        x = jnp.mean(x, axis=(1, 2))  # adaptive avg pool to 1x1
        return dense_apply(params["fc"], x), new_state

    return ModelDef(name, init, apply, (32, 32, 3))


def make_resnet18(num_classes=10, **kwargs):
    return _make_resnet("resnet18", (2, 2, 2, 2), num_classes)


def make_resnet34(num_classes=10, **kwargs):
    return _make_resnet("resnet34", (3, 4, 6, 3), num_classes)


def make_resnet50(num_classes=10, **kwargs):
    return _make_resnet("resnet50", (3, 4, 6, 3), num_classes,
                        bottleneck=True)


def make_resnet101(num_classes=10, **kwargs):
    return _make_resnet("resnet101", (3, 4, 23, 3), num_classes,
                        bottleneck=True)


def make_resnet152(num_classes=10, **kwargs):
    return _make_resnet("resnet152", (3, 8, 36, 3), num_classes,
                        bottleneck=True)


register("resnet18", make_resnet18)
register("resnet34", make_resnet34)
register("resnet50", make_resnet50)
register("resnet101", make_resnet101)
register("resnet152", make_resnet152)
