"""Wide ResNet: `wide_resnet-Wide_ResNet` — own implementation (the
reference pulls WRN from a non-vendored git submodule, reference
`experiments/models/wide_resnet.py` symlink + `.gitmodules:1-3`; used as
`Wide_ResNet(depth, widen_factor, dropout_rate, num_classes)` by the
appendix grid, reference `reproduce-appendix.py:124-125`).

Pre-activation wide basic blocks: bn-relu-conv3x3-dropout-bn-relu-conv3x3
with identity (or 1x1-conv) shortcut; groups of width 16k/32k/64k at
strides 1/2/2; final bn-relu, global average pool, fc.
"""

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu.models import ModelDef, register
from byzantinemomentum_tpu.models.core import (
    batchnorm_apply, batchnorm_init, conv_apply, conv_init, dense_apply,
    dense_init, dropout_apply, grouped_batchnorm_apply, grouped_conv_apply,
    grouped_dense_apply, grouped_dropout_apply, grouped_unpack, log_softmax)

__all__ = []


def _block_init(key, cin, cout, stride):
    keys = jax.random.split(key, 3)
    params = {
        "conv1": conv_init(keys[0], 3, 3, cin, cout),
        "conv2": conv_init(keys[1], 3, 3, cout, cout),
    }
    state = {}
    params["bn1"], state["bn1"] = batchnorm_init(cin)
    params["bn2"], state["bn2"] = batchnorm_init(cout)
    if stride != 1 or cin != cout:
        params["shortcut"] = conv_init(keys[2], 1, 1, cin, cout)
    return params, state


def _block_apply(params, state, x, stride, dropout_rate, train, rng):
    new_state = dict(state)
    out, new_state["bn1"] = batchnorm_apply(params["bn1"], state["bn1"], x, train=train)
    out = jax.nn.relu(out)
    shortcut = x
    if "shortcut" in params:
        shortcut = conv_apply(params["shortcut"], out, padding="VALID", stride=stride)
    out = conv_apply(params["conv1"], out, padding="SAME", stride=stride)
    out = dropout_apply(rng, out, dropout_rate, train=train)
    out, new_state["bn2"] = batchnorm_apply(params["bn2"], state["bn2"], out, train=train)
    out = jax.nn.relu(out)
    out = conv_apply(params["conv2"], out, padding="SAME")
    return out + shortcut, new_state


def _block_apply_grouped(params_s, state, x, stride, dropout_rate, train,
                         rngs, batch):
    new_state = dict(state)
    out, new_state["bn1"] = grouped_batchnorm_apply(
        params_s["bn1"], state["bn1"], x, train=train)
    out = jax.nn.relu(out)
    shortcut = x
    if "shortcut" in params_s:
        shortcut = grouped_conv_apply(params_s["shortcut"], out,
                                      padding="VALID", stride=stride)
    out = grouped_conv_apply(params_s["conv1"], out, padding="SAME",
                             stride=stride)
    # `batch` disambiguates a batch-slot-packed carry (BMT_BATCH_PACK)
    out = grouped_dropout_apply(rngs, out, dropout_rate, train=train,
                                batch=batch)
    out, new_state["bn2"] = grouped_batchnorm_apply(
        params_s["bn2"], state["bn2"], out, train=train)
    out = jax.nn.relu(out)
    out = grouped_conv_apply(params_s["conv2"], out, padding="SAME")
    return out + shortcut, new_state


def make_wide_resnet(depth=28, widen_factor=10, dropout_rate=0.3, num_classes=10, **kwargs):
    assert (depth - 4) % 6 == 0, "Wide-ResNet depth must be 6n+4"
    n_blocks = (depth - 4) // 6
    widths = [16, 16 * widen_factor, 32 * widen_factor, 64 * widen_factor]
    strides = [1, 2, 2]

    def init(key):
        keys = jax.random.split(key, 3 * n_blocks + 3)
        params, state = {}, {}
        params["conv0"] = conv_init(keys[0], 3, 3, 3, widths[0])
        cin = widths[0]
        ki = 1
        for gi in range(3):
            for bi in range(n_blocks):
                stride = strides[gi] if bi == 0 else 1
                name = f"g{gi}b{bi}"
                params[name], state[name] = _block_init(keys[ki], cin, widths[gi + 1], stride)
                cin = widths[gi + 1]
                ki += 1
        params["bn_out"], state["bn_out"] = batchnorm_init(widths[3])
        params["fc"] = dense_init(keys[ki], widths[3], num_classes)
        return params, state

    def apply(params, state, x, train=False, rng=None):
        if train and rng is None:
            raise ValueError("wide_resnet needs a PRNG key in train mode (dropout)")
        n_drop = 3 * n_blocks
        drop_keys = jax.random.split(rng, n_drop) if train else [None] * n_drop
        new_state = dict(state)
        out = conv_apply(params["conv0"], x, padding="SAME")
        ki = 0
        for gi in range(3):
            for bi in range(n_blocks):
                stride = strides[gi] if bi == 0 else 1
                name = f"g{gi}b{bi}"
                out, new_state[name] = _block_apply(
                    params[name], state[name], out, stride, dropout_rate, train, drop_keys[ki])
                ki += 1
        out, new_state["bn_out"] = batchnorm_apply(params["bn_out"], state["bn_out"], out, train=train)
        out = jax.nn.relu(out)
        out = jnp.mean(out, axis=(1, 2))  # global average pool (8x8 at CIFAR scale)
        out = dense_apply(params["fc"], out)
        return log_softmax(out), new_state

    def apply_grouped(params_s, state, xs, train=False, rng=None):
        """All S per-worker WRNs in one merged program (worker axis as
        channel groups) — same math as `vmap(apply)`, incl. identical
        per-worker dropout draws and batch-stat BN."""
        if train and rng is None:
            raise ValueError("wide_resnet needs PRNG keys in train mode (dropout)")
        S, B = xs.shape[0], xs.shape[1]
        n_drop = 3 * n_blocks
        dks = (jax.vmap(lambda k: jax.random.split(k, n_drop))(rng)
               if train else None)
        new_state = dict(state)
        x = xs.transpose(1, 2, 3, 0, 4)  # worker-expanded (B, 32, 32, S, 3)
        out = grouped_conv_apply(params_s["conv0"], x, padding="SAME")
        ki = 0
        for gi in range(3):
            for bi in range(n_blocks):
                stride = strides[gi] if bi == 0 else 1
                name = f"g{gi}b{bi}"
                out, new_state[name] = _block_apply_grouped(
                    params_s[name], state[name], out, stride, dropout_rate,
                    train, dks[:, ki] if train else None, B)
                ki += 1
        out, new_state["bn_out"] = grouped_batchnorm_apply(
            params_s["bn_out"], state["bn_out"], out, train=train)
        out = jax.nn.relu(out)
        # head needs the true worker axis AND the true batch (the carry
        # may be batch-slot-packed under BMT_BATCH_PACK)
        out = grouped_unpack(out, S, batch=B)
        out = jnp.mean(out, axis=(1, 2))                 # (B, S, 64k)
        out = grouped_dense_apply(params_s["fc"], out)
        return log_softmax(out).transpose(1, 0, 2), new_state

    return ModelDef("wide_resnet-Wide_ResNet", init, apply, (32, 32, 3),
                    apply_grouped=apply_grouped)


register("wide_resnet-Wide_ResNet", make_wide_resnet)
register("wide-resnet", make_wide_resnet)
