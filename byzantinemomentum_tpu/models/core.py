"""Layer primitives and parameter-initialization registry for the
pure-pytree model zoo.

Design: a model is an (init, apply) pair over explicit parameter pytrees —
no module framework — so `jax.vmap`/`jax.grad`/`pjit` compose directly and
the flat gradient space is just `ravel_pytree(params)`. Layouts are NHWC
(TPU-native); convolution kernels are HWIO.

Initialization parity: torch's default Linear/Conv init is
kaiming-uniform(a=sqrt(5)) for weights and U(+-1/sqrt(fan_in)) for biases —
both reduce to U(+-1/sqrt(fan_in)) — which `default_dense_init` /
`default_conv_init` reproduce (distributionally; RNG streams differ by
construction). The named init registry mirrors the reference's exposure of
`torch.nn.init.*_` (reference `experiments/model.py:92-113`), applied
separately to multi-dim vs mono-dim parameters via `--init-multi` /
`--init-mono`.
"""

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "dense_init", "dense_apply",
    "conv_init", "conv_apply", "max_pool",
    "batchnorm_init", "batchnorm_apply",
    "dropout_apply",
    "log_softmax",
    "grouped_conv_apply", "grouped_dense_apply",
    "grouped_batchnorm_apply", "grouped_dropout_apply", "grouped_unpack",
    "inits", "apply_named_init",
]


# --------------------------------------------------------------------------- #
# Dense

def dense_init(key, din, dout, dtype=jnp.float32):
    """torch-default Linear init: W, b ~ U(+-1/sqrt(din))."""
    kw, kb = jax.random.split(key)
    bound = 1.0 / math.sqrt(din)
    return {
        "w": jax.random.uniform(kw, (din, dout), dtype, -bound, bound),
        "b": jax.random.uniform(kb, (dout,), dtype, -bound, bound),
    }


def dense_apply(params, x):
    return x @ params["w"] + params["b"]


# --------------------------------------------------------------------------- #
# Conv (NHWC x HWIO -> NHWC)

def conv_init(key, kh, kw_, cin, cout, dtype=jnp.float32):
    """torch-default Conv2d init: U(+-1/sqrt(cin*kh*kw))."""
    kkey, bkey = jax.random.split(key)
    fan_in = cin * kh * kw_
    bound = 1.0 / math.sqrt(fan_in)
    return {
        "w": jax.random.uniform(kkey, (kh, kw_, cin, cout), dtype, -bound, bound),
        "b": jax.random.uniform(bkey, (cout,), dtype, -bound, bound),
    }


def conv_apply(params, x, *, padding="VALID", stride=1):
    stride = (stride, stride) if isinstance(stride, int) else stride
    out = lax.conv_general_dilated(
        x, params["w"], window_strides=stride, padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return out + params["b"]


def max_pool(x, window=2, stride=None):
    """Spatial max pool over axes (1, 2) of (B, H, W, ...channel axes) —
    rank-agnostic so the worker-expanded (B, H, W, S, C) grouped layout
    pools with the same call."""
    stride = window if stride is None else stride
    tail = (1,) * (x.ndim - 3)
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, window, window) + tail,
        window_strides=(1, stride, stride) + tail,
        padding="VALID")


# --------------------------------------------------------------------------- #
# BatchNorm (torch semantics: batch stats in train mode, running stats in
# eval; running update r <- (1-m) r + m s with unbiased batch variance)

BN_MOMENTUM = 0.1
BN_EPS = 1e-5


def batchnorm_init(c, dtype=jnp.float32):
    params = {"gamma": jnp.ones((c,), dtype), "beta": jnp.zeros((c,), dtype)}
    state = {"mean": jnp.zeros((c,), dtype), "var": jnp.ones((c,), dtype)}
    return params, state


@functools.lru_cache(maxsize=None)
def _bn_train(n_param_dims):
    """Train-mode batch-stat BN with a hand-written VJP, specialized on the
    number of trailing parameter dims (1 = per-worker (C,), 2 = grouped
    (S, C)).

    Two measured wins over the autodiff version on TPU (the BN passes are
    bandwidth-bound on the big worker-expanded activations — see
    PERF_NOTES.md):
    * one-pass statistics (sum and sum-of-squares in one read of x,
      accumulated at `promote_types(x.dtype, f32)` — so f64 inputs keep f64
      statistics end-to-end) instead of jnp.mean + jnp.var's two passes, and
    * the closed-form backward (one fused read of (dy, xhat) for both
      reductions and dx) instead of autodiff's chain through the two-pass
      statistics.
    Returns (out, mean, var) with accumulation-dtype statistics; the
    running-stat fold happens in the callers (which cast back to the state
    dtype so scan carries stay dtype-stable).

    Numerical regime: the one-pass E[x^2]-E[x]^2 variance cancels
    catastrophically when |mean| >> std (the maximum(..., 0) clamp then
    yields var=0 and inv=rsqrt(eps)). Post-BN+conv activations are
    well-conditioned (|mean|/std is O(1)), which is the only place this
    runs; f64 inputs use the centered two-pass form instead, since f64
    callers are asking for precision, not bandwidth. The closed-form
    backward also treats the clamp as identity (no zero-gradient at the
    clamp point through dvar) — exact in the training step, where the
    mean/var outputs are aux state with zero cotangents.
    """

    @jax.custom_vjp
    def bn(gamma, beta, x):
        axes = tuple(range(x.ndim - n_param_dims))
        cnt = x.size // _tail_size(x.shape, n_param_dims)
        acc = jnp.promote_types(x.dtype, jnp.float32)
        xf = x.astype(acc)
        mean = jnp.sum(xf, axis=axes) / cnt
        if acc == jnp.float64:
            xc = xf - mean
            var = jnp.sum(xc * xc, axis=axes) / cnt
        else:
            var = jnp.maximum(
                jnp.sum(xf * xf, axis=axes) / cnt - mean * mean, 0.0)
        inv = lax.rsqrt(var + BN_EPS)
        out = ((x - mean) * inv * gamma + beta).astype(x.dtype)
        return out, mean, var

    def fwd(gamma, beta, x):
        out, mean, var = bn(gamma, beta, x)
        return (out, mean, var), (gamma, x, mean, lax.rsqrt(var + BN_EPS))

    def bwd(res, cts):
        dy, dmean, dvar = cts
        gamma, x, mean, inv = res
        axes = tuple(range(x.ndim - n_param_dims))
        cnt = x.size // _tail_size(x.shape, n_param_dims)
        acc = jnp.promote_types(x.dtype, jnp.float32)
        dyf = dy.astype(acc)
        xc = x.astype(acc) - mean
        xhat = xc * inv
        sum_dy = jnp.sum(dyf, axis=axes)
        sum_dy_xhat = jnp.sum(dyf * xhat, axis=axes)
        # Batch-stat BN dx, plus the mean/var primal outputs' cotangents
        # (zero in the training step, where new_state is an aux output)
        dx = ((gamma.astype(acc) * inv)
              * (dyf - sum_dy / cnt - xhat * (sum_dy_xhat / cnt))
              + dmean / cnt + xc * (2.0 * dvar / cnt))
        return (sum_dy_xhat.astype(gamma.dtype), sum_dy.astype(gamma.dtype),
                dx.astype(x.dtype))

    bn.defvjp(fwd, bwd)
    return bn


def _tail_size(shape, n):
    out = 1
    for s in shape[len(shape) - n:]:
        out *= s
    return out


def _fold_running_stats(state, mean, unbiased):
    """Fold one batch's statistics into the running stats, casting the
    (accumulation-dtype) batch stats back to the state dtype so scan carries
    stay dtype-stable (the --nb-local-steps lax.scan requires an exact
    carry-type match)."""
    sdt = state["mean"].dtype
    return {
        "mean": ((1 - BN_MOMENTUM) * state["mean"]
                 + BN_MOMENTUM * mean).astype(sdt),
        "var": ((1 - BN_MOMENTUM) * state["var"]
                + BN_MOMENTUM * unbiased).astype(sdt),
    }


def batchnorm_apply(params, state, x, *, train):
    """Normalize over all but the channel axis.

    Returns (out, new_state); in train mode `new_state` carries the running
    stats updated by THIS batch (the sequential-equivalent composition across
    vmapped workers happens in the training step — see
    `engine/step.py:compose_bn_updates`).
    """
    if train:
        out, mean, var = _bn_train(1)(params["gamma"], params["beta"], x)
        count = x.size // x.shape[-1]
        unbiased = var * (count / max(count - 1, 1))
        return out, _fold_running_stats(state, mean, unbiased)
    mean, var = state["mean"], state["var"]
    inv = lax.rsqrt(var + BN_EPS)
    # Eval under mixed precision normalizes with the f32 running stats (the
    # arithmetic promotes), but the activation stream must come back in
    # x.dtype — the next conv requires matching operand dtypes.
    out = ((x - mean) * inv * params["gamma"] + params["beta"]).astype(x.dtype)
    return out, state


# --------------------------------------------------------------------------- #
# Worker-grouped layers (merged-batch execution of S per-worker networks)
#
# The simulation computes S independent per-worker gradients per step
# (reference `attack.py:786-795`). `jax.vmap` of the backward pass turns
# every conv weight-gradient into a batch-grouped convolution wrapped in
# XLA layout transposes — measurably slower than expressing the worker
# axis as CHANNEL GROUPS up front. These helpers run all S workers in one
# merged program: activations are worker-expanded `(B, H, W, S, C)` (the
# worker axis next-to-minor, so BatchNorm/dropout parameters broadcast
# naturally and no layout churn is introduced between layers), convolutions
# view them merged `(B, H, W, S*C)` for one `feature_group_count=S` conv
# (same FLOPs as a shared-weight conv over the S*B merged batch — groups
# partition, they do not duplicate), dense layers are per-worker einsums,
# and the per-worker weight gradients fall out of one backward pass with
# respect to the stacked parameters. Numerics match the vmapped path
# op-for-op (same batch-stat BatchNorm, same per-worker-key dropout draws).
#
# WORKER PACKING: a `(B, H, W, S, C)` tensor with C < 128 tiles its minor
# dim into the TPU's 128 lanes padded (C=64 -> 2x physical bytes, and every
# elementwise/BN/dropout/pool pass pays it — the r5 trace shows these
# fusions at the padded-bandwidth floor). When a divisor P of S makes
# (P*C) % 128 == 0, the helpers below carry the activation PACKED as
# `(B, H, W, S/P, P*C)`: workers pP..pP+P-1 concatenated on the channel
# axis. With P*C a multiple of 128 the packed form and the conv's merged
# `(B, H, W, S*C)` view share the same physical bytes (the conv-boundary
# reshapes are bitcasts), the lane padding disappears, and every per-(s, c)
# semantic (BN statistics, dropout draws, pooling) is preserved exactly —
# only the tensor's logical factorization changes. Helpers infer P by
# comparing `x.shape[-2]` with the parameter stack's true S, so models
# need no changes; `BMT_NO_WORKER_PACK=1` disables packing (A/B knob).


# Largest pack factor worth engaging: the paired block-diagonal conv pays
# P x the MXU FLOPs of the unpacked grouped conv (the off-diagonal zero
# blocks), against at most a (128 - c)/128 bandwidth saving on the
# elementwise passes. P <= 4 keeps the measured-win regime (c = 32/64 on
# the benchmarked CNNs); larger S/c combinations (e.g. S = 64 with c = 2)
# would otherwise silently auto-engage fully-dense P = 64 packing whose
# zero-block FLOPs dwarf the padding saved.
_MAX_WORKER_PACK = 4


def _worker_packing(S, c):
    """Smallest P <= _MAX_WORKER_PACK dividing S with (P*c) % 128 == 0,
    else 1 (no packing)."""
    no_pack = os.environ.get("BMT_NO_WORKER_PACK", "").lower() not in (
        "", "0", "false", "no")
    if no_pack or c % 128 == 0:
        return 1
    for P in range(2, min(S, _MAX_WORKER_PACK) + 1):
        if S % P == 0 and (P * c) % 128 == 0:
            return P
    return 1


# BATCH-SLOT PACKING (the second ROADMAP escape for worker counts that
# admit no P, e.g. WRN's S = 9 with C in {160, 320}): concatenate Q BATCH
# items on the channel axis instead — activations carried
# `(B/Q, H, W, S, Q*C)`, convs run block-diagonal over the Q slots inside
# each worker group (Q x the MXU FLOPs on those convs, exactly the
# worker-packing trade), BatchNorm folds its statistics across the slots
# (same per-(s, c) moments over the whole batch), and dropout draws the
# vmapped path's per-worker masks and merely re-factorizes them. Opt-in
# via `BMT_BATCH_PACK` (unset/0 = off; `1`/`auto` = smallest working Q;
# an integer > 1 forces that Q): unlike worker packing it shrinks the
# sublane-resident batch axis (B/Q pads up toward the 8/16-row tile), so
# whether the lane alignment it buys outweighs that is a per-cell
# device measurement (`scripts/wrn_pack_ab.py`), not a default.


def _batch_packing(B, S, c):
    """Batch-slot pack factor for a conv of channel width `c`: smallest
    Q <= _MAX_WORKER_PACK dividing B with (Q*c) % 128 == 0, only when the
    `BMT_BATCH_PACK` knob is on and worker packing found no P (worker
    packing is the measured-win default; the two never compose)."""
    raw = os.environ.get("BMT_BATCH_PACK", "").lower()
    if raw in ("", "0", "false", "no"):
        return 1
    if c % 128 == 0 or _worker_packing(S, c) != 1:
        return 1
    if raw not in ("1", "auto", "true", "yes"):
        try:
            forced = int(raw)
        except ValueError:
            return 1
        return forced if (forced > 1 and B % forced == 0
                          and (forced * c) % 128 == 0) else 1
    for Q in range(2, min(B, _MAX_WORKER_PACK) + 1):
        if B % Q == 0 and (Q * c) % 128 == 0:
            return Q
    return 1


def _batch_repack(x, q_from, q_to):
    """Refactor a worker-expanded activation between batch-slot packings:
    `(B/q_from, ..., S, q_from*C) -> (B/q_to, ..., S, q_to*C)`. A real
    relayout copy when the factors differ (the one-time transition cost at
    pack boundaries — same trade as the worker-packing P transition,
    PERF_NOTES.md r5)."""
    if q_from == q_to:
        return x
    if q_from > 1:  # unpack to the plain batch factorization
        C = x.shape[-1] // q_from
        x = x.reshape(x.shape[:-1] + (q_from, C))
        x = jnp.moveaxis(x, -2, 1)                      # (B/qf, qf, ...)
        x = x.reshape((x.shape[0] * q_from,) + x.shape[2:])
    if q_to > 1:
        x = x.reshape((x.shape[0] // q_to, q_to) + x.shape[1:])
        x = jnp.moveaxis(x, 1, -2)                      # (..., S, qt, C)
        x = x.reshape(x.shape[:-2] + (x.shape[-2] * x.shape[-1],))
    return x


def grouped_conv_apply(params_s, x, *, padding="VALID", stride=1):
    """Per-worker convolution on a worker-expanded activation.

    params_s: stacked conv params {"w": (S, kh, kw, cin, cout),
    "b": (S, cout)}; x: (B, H, W, S, cin) — the worker axis lives
    NEXT-TO-MINOR throughout the grouped network (so BatchNorm/dropout
    broadcast naturally); only this helper views it merged as (B, H, W,
    S*cin) for one `feature_group_count=S` convolution on the MXU, and
    splits the result back — both reshapes are layout-preserving views.
    Returns (B, H', W', S, cout).
    """
    S, kh, kw_, cin, cout = params_s["w"].shape
    stride = (stride, stride) if isinstance(stride, int) else stride
    # Worker packing (see the section comment). When the conv's input or
    # output channel count is lane-misaligned, run it as S/P PAIRED groups
    # with block-diagonal weights: 2x the MXU work on the packed convs
    # (the off-diagonal zero blocks), but no (S, C<128) tensor ever exists,
    # so the elementwise/BN/pool passes around it run unpadded and no
    # relayout copies appear at the conv boundaries (forcing packed
    # activations around an S-group conv was measured WORSE — XLA's grouped
    # conv rewrite pins the split form; see PERF_NOTES.md).
    P_in = S // x.shape[-2]
    # Batch-slot packing (the BMT_BATCH_PACK escape, section comment): the
    # carry is (B/Q, H, W, S, Q*cin), so Q is read off the channel width
    # and the true batch off shape[0] * Q. Never composes with P.
    Q_in = x.shape[-1] // (P_in * cin)
    P_out = _worker_packing(S, cout)
    Q_out = 1
    if P_in == 1 and P_out == 1:
        Q_out = _batch_packing(x.shape[0] * Q_in, S, cout)
    if Q_in != Q_out:
        x = _batch_repack(x, Q_in, Q_out)
    if Q_out > 1:
        Q = Q_out
        Bq, H, W = x.shape[0], x.shape[1], x.shape[2]
        xm = x.reshape(Bq, H, W, S * Q * cin)
        # Block-diagonal over the Q batch slots WITHIN each worker group:
        # group s's filter maps slot q's cin to slot q's cout with worker
        # s's kernel (autodiff extracts the diagonal blocks' gradients)
        eye = jnp.eye(Q, dtype=params_s["w"].dtype)
        wbd = jnp.einsum("sklio,qr->klqisro", params_s["w"], eye)
        wbd = wbd.reshape(kh, kw_, Q * cin, S * Q * cout)
        out = lax.conv_general_dilated(
            xm, wbd, window_strides=stride, padding=padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=S)
        out = out.reshape(out.shape[:3] + (S, Q * cout))
        return out + jnp.tile(params_s["b"], (1, Q))
    B, H, W = x.shape[0], x.shape[1], x.shape[2]
    xm = x.reshape(B, H, W, S * cin)  # the universal interchange form
    P = max(P_in, P_out)
    if P == 1:
        w = (params_s["w"].transpose(1, 2, 3, 0, 4)
             .reshape(kh, kw_, cin, S * cout))
        out = lax.conv_general_dilated(
            xm, w, window_strides=stride, padding=padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=S)
        out = out.reshape(out.shape[:3] + (S, cout))
        return out + params_s["b"]
    G = S // P
    # Block-diagonal paired weights: group g holds workers gP..gP+P-1 on
    # the diagonal (autodiff through the einsum extracts exactly the
    # diagonal blocks' gradients, so the zeros stay zero-cost in memory)
    w_pair = params_s["w"].reshape(G, P, kh, kw_, cin, cout)
    eye = jnp.eye(P, dtype=params_s["w"].dtype)
    wbd = jnp.einsum("gpklio,pq->klgpiqo", w_pair, eye)
    wbd = wbd.reshape(kh, kw_, G, P * cin, P * cout)
    wbd = wbd.transpose(0, 1, 3, 2, 4).reshape(kh, kw_, P * cin,
                                               G * P * cout)
    out = lax.conv_general_dilated(
        xm, wbd, window_strides=stride, padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=G)
    # Emit (B, H', W', S/P_out, P_out*cout): the group outputs are already
    # worker-major, so this is a pure refactorization of the merged axis
    out = out.reshape(out.shape[:3] + (S // P_out, P_out * cout))
    return out + params_s["b"].reshape(S // P_out, P_out * cout)


def grouped_unpack(x, S, batch=None):
    """Restore the plain (B, ..., S, C) factorization of a possibly
    worker- or batch-slot-packed activation (no-op when already unpacked)
    — used before stages that need the true worker axis and batch (global
    pools, flatten, dense). `batch` is the true batch size; callers on a
    possibly batch-packed carry (BMT_BATCH_PACK) must pass it."""
    if x.shape[-2] != S:
        x = x.reshape(x.shape[:-2] + (S, (x.shape[-2] * x.shape[-1]) // S))
    if batch is not None and x.shape[0] != batch:
        x = _batch_repack(x, batch // x.shape[0], 1)
    return x


def grouped_dense_apply(params_s, x):
    """Per-worker dense layer: params_s {"w": (S, din, dout),
    "b": (S, dout)}; x: (B, S, din) -> (B, S, dout) (batched matmul over the
    worker axis)."""
    return jnp.einsum("bsi,sio->bso", x, params_s["w"]) + params_s["b"]


def grouped_batchnorm_apply(params_s, state, x, *, train):
    """Per-worker BatchNorm on a worker-expanded activation.

    params_s: {"gamma", "beta"} each (S, C); state: the SHARED running stats
    {"mean", "var"} each (C,) (every vmapped worker normalizes from the same
    pre-step state — see `engine/step.py:compose_bn_updates`);
    x: (..., S, C), or worker-PACKED (..., S/P, P*C) (see the section
    comment — P is inferred from the shapes). Train mode computes each
    worker's batch statistics (the moments over all leading axes —
    identical to the vmapped per-worker `batchnorm_apply`) and returns
    `new_state` leaves of shape (S, C) regardless of packing, the
    per-worker running-stat updates the step composer expects.
    """
    S, C = params_s["gamma"].shape
    S2 = x.shape[-2]
    P = S // S2
    Q = x.shape[-1] // (P * C)  # batch-slot packing factor (never with P)
    gamma, beta = params_s["gamma"], params_s["beta"]
    if S2 != S:  # packed: per-(s, c) params follow the same factorization
        gamma = gamma.reshape(S2, -1)
        beta = beta.reshape(S2, -1)
    elif Q > 1:  # batch-packed: per-(s, c) params tile across the Q slots
        gamma = jnp.tile(gamma, (1, Q))
        beta = jnp.tile(beta, (1, Q))
    if train:
        if Q > 1:
            return _bn_train_batch_packed(gamma, beta, x, state, S, C, Q)
        out, mean, var = _bn_train(2)(gamma, beta, x)
        count = x.size // (x.shape[-1] * x.shape[-2])
        unbiased = var * (count / max(count - 1, 1))
        new_state = _fold_running_stats(
            state, mean.reshape(S, C), unbiased.reshape(S, C))
        return out, new_state
    mean, var = state["mean"], state["var"]
    if x.shape[-1] != C:  # shared (C,) stats tile across the packed slots
        reps = x.shape[-1] // C  # P workers or Q batch slots (never both)
        mean = jnp.tile(mean, reps)
        var = jnp.tile(var, reps)
    inv = lax.rsqrt(var + BN_EPS)
    # Same mixed-precision note as `batchnorm_apply`: keep the activation
    # stream in x.dtype after normalizing with (possibly f32) stats
    out = ((x - mean) * inv * gamma + beta).astype(x.dtype)
    return out, state


def _bn_train_batch_packed(gamma_t, beta_t, x, state, S, C, Q):
    """Train-mode BN on a batch-slot-packed activation (..., S, Q*C).

    The Q slots of a packed channel are the SAME worker-channel's data
    split across the batch, so the statistics must fold across them
    before normalizing — per-(s, c) moments over the WHOLE batch, exactly
    the unpacked semantics (the fold reorders the reduction, so equality
    is to reduction rounding, not bitwise). One-pass sum/sum-of-squares
    moments in f32 accumulation as `_bn_train`; autodiff backward (the
    packed path is an opt-in experiment, `BMT_BATCH_PACK` — a closed-form
    VJP like `_bn_train`'s is a follow-up if the A/B harness lands it)."""
    axes = tuple(range(x.ndim - 2))
    cnt = x.size // (S * C)  # true per-(s, c) element count
    acc = jnp.promote_types(x.dtype, jnp.float32)
    xf = x.astype(acc)
    ssum = jnp.sum(xf, axis=axes).reshape(S, Q, C)
    ssq = jnp.sum(xf * xf, axis=axes).reshape(S, Q, C)
    mean = jnp.sum(ssum, axis=1) / cnt                       # (S, C)
    var = jnp.maximum(jnp.sum(ssq, axis=1) / cnt - mean * mean, 0.0)
    inv = lax.rsqrt(var + BN_EPS)
    mean_t = jnp.tile(mean, (1, Q))
    inv_t = jnp.tile(inv, (1, Q))
    out = ((x - mean_t) * inv_t * gamma_t + beta_t).astype(x.dtype)
    unbiased = var * (cnt / max(cnt - 1, 1))
    return out, _fold_running_stats(state, mean, unbiased)


def grouped_dropout_apply(rngs, x, rate, *, train, axis=-2, batch=None):
    """Per-worker dropout on a worker-expanded activation.

    rngs: (S,) stacked per-worker keys; `axis` is the worker axis of `x`
    (next-to-minor in the grouped convention, e.g. (B, H, W, S, C) or
    (B, S, F)); `x` may be worker-PACKED (..., S/P, P*C) (see the section
    comment), or batch-slot-packed (B/Q, ..., S, Q*C) when the caller
    passes the true `batch` size (the BMT_BATCH_PACK carry cannot be told
    apart from a wider channel count by shape alone). Draws EXACTLY the
    masks the vmapped path draws — one
    `_dropout_mask(key_s, shape-without-worker-axis)` per worker — so the
    two execution paths produce identical trajectories (packing only
    changes where a mask element lands on the channel axis).
    """
    if not train or rate <= 0.0:
        return x
    keep = 1.0 - rate
    ax = axis % x.ndim
    S = rngs.shape[0]
    S2 = x.shape[ax]
    if (batch is not None and S2 == S and ax == x.ndim - 2
            and x.shape[0] != batch):
        # Batch-slot-packed: draw the per-worker masks in their TRUE
        # (batch, ..., C) shape — the identical vmapped-path bits — and
        # re-factorize them into the packed layout (the transpose fuses
        # into the `where` consumer)
        Q = batch // x.shape[0]
        C = x.shape[-1] // Q
        per_true = (batch,) + x.shape[1:ax] + (C,)
        masks = jax.vmap(lambda k: _dropout_mask(k, keep, per_true))(rngs)
        m = masks.reshape((S, x.shape[0], Q) + per_true[1:])
        perm = (1,) + tuple(range(3, m.ndim - 1)) + (0, 2, m.ndim - 1)
        m = jnp.transpose(m, perm)              # (B/Q, ..., S, Q, C)
        m = m.reshape(m.shape[:-2] + (Q * C,))
        return jnp.where(m, x / keep, 0.0)
    per_shape = x.shape[:ax] + x.shape[ax + 1:]
    if S2 == S:
        masks = jax.vmap(lambda k: _dropout_mask(k, keep, per_shape))(rngs)
    else:  # packed: draw each worker's (..., C) mask, concat P per row
        P = S // S2
        per_worker = per_shape[:-1] + (x.shape[-1] // P,)
        masks = jax.vmap(jax.vmap(
            lambda k: _dropout_mask(k, keep, per_worker)))(
                rngs.reshape((S2, P) + rngs.shape[1:]))  # (S2, P, ..., C)
        masks = jnp.moveaxis(masks, 1, -2)     # (S2, ..., P, C)
        masks = masks.reshape((S2,) + per_shape)
    masks = jnp.moveaxis(masks, 0, ax)
    return jnp.where(masks, x / keep, 0.0)


# --------------------------------------------------------------------------- #
# Dropout

def _dropout_mask(rng, keep, shape):
    """Bernoulli(keep) mask for dropout.

    When `keep` is exactly representable on 8 bits (keep*256 integer — true
    for the reference models' 0.25/0.5 rates), draw uint8 random bits and
    threshold: identical distribution, 4x fewer random bits than the f32
    uniform behind `jax.random.bernoulli`, measurably faster on TPU (mask
    generation is a per-step cost on ~25M activations in the CIFAR bench).
    (A packed-u32-words draw bitcast to bytes is ~20% cheaper in isolation
    but measured 28% SLOWER in the real program — the flat draw + bitcast +
    reshape cannot fuse into the 5-D consumer the way the direct u8 draw
    does; see PERF_NOTES.md.)
    """
    t = keep * 256.0
    if t == int(t) and 0 < t < 256:
        return jax.random.bits(rng, shape, jnp.uint8) < jnp.uint8(int(t))
    return jax.random.bernoulli(rng, keep, shape)


def dropout_apply(rng, x, rate, *, train):
    """Inverted dropout (torch semantics: scale by 1/(1-p) at train time)."""
    if not train or rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = _dropout_mask(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


# --------------------------------------------------------------------------- #
# Named init registry (`--init-multi` / `--init-mono`,
# reference `experiments/model.py:92-113, 128-136, 157-164`)

def _fans(shape):
    if len(shape) < 2:
        fan = shape[0] if shape else 1
        return fan, fan
    receptive = 1
    for s in shape[:-2]:
        receptive *= s
    # HWIO kernels / (din, dout) dense matrices
    return shape[-2] * receptive, shape[-1] * receptive


def _gain(nonlinearity, a=0.0):
    if nonlinearity in ("sigmoid", "linear"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3.0
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        return math.sqrt(2.0 / (1.0 + a * a))
    return 1.0


def _init_uniform(key, shape, a=0.0, b=1.0, **kw):
    return jax.random.uniform(key, shape, jnp.float32, a, b)


def _init_normal(key, shape, mean=0.0, std=1.0, **kw):
    return mean + std * jax.random.normal(key, shape, jnp.float32)


def _init_constant(key, shape, val=0.0, **kw):
    return jnp.full(shape, val, jnp.float32)


def _init_ones(key, shape, **kw):
    return jnp.ones(shape, jnp.float32)


def _init_zeros(key, shape, **kw):
    return jnp.zeros(shape, jnp.float32)


def _init_xavier_uniform(key, shape, gain=1.0, **kw):
    fan_in, fan_out = _fans(shape)
    bound = gain * math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, jnp.float32, -bound, bound)


def _init_xavier_normal(key, shape, gain=1.0, **kw):
    fan_in, fan_out = _fans(shape)
    std = gain * math.sqrt(2.0 / (fan_in + fan_out))
    return std * jax.random.normal(key, shape, jnp.float32)


def _init_kaiming_uniform(key, shape, a=0.0, mode="fan_in", nonlinearity="leaky_relu", **kw):
    fan_in, fan_out = _fans(shape)
    fan = fan_in if mode == "fan_in" else fan_out
    bound = _gain(nonlinearity, a) * math.sqrt(3.0 / fan)
    return jax.random.uniform(key, shape, jnp.float32, -bound, bound)


def _init_kaiming_normal(key, shape, a=0.0, mode="fan_in", nonlinearity="leaky_relu", **kw):
    fan_in, fan_out = _fans(shape)
    fan = fan_in if mode == "fan_in" else fan_out
    std = _gain(nonlinearity, a) / math.sqrt(fan)
    return std * jax.random.normal(key, shape, jnp.float32)


def _init_orthogonal(key, shape, gain=1.0, **kw):
    return gain * jax.nn.initializers.orthogonal()(key, shape, jnp.float32)


def _init_trunc_normal(key, shape, mean=0.0, std=1.0, a=-2.0, b=2.0, **kw):
    """torch `trunc_normal_`: N(mean, std) truncated to values in [a, b]."""
    lo = (a - mean) / std
    hi = (b - mean) / std
    return mean + std * jax.random.truncated_normal(
        key, lo, hi, shape, jnp.float32)


def _init_eye(key, shape, **kw):
    """torch `eye_`: 2D identity (preserves input identity in a Linear)."""
    if len(shape) != 2:
        raise ValueError("eye init requires a 2-dimensional parameter")
    return jnp.eye(shape[0], shape[1], dtype=jnp.float32)


def _init_dirac(key, shape, groups=1, **kw):
    """torch `dirac_` in HWIO layout: the {3,4,5}D conv kernel that preserves
    channel identity (delta at the spatial center, per group)."""
    if len(shape) not in (3, 4, 5):
        raise ValueError("dirac init requires a {3,4,5}-dimensional kernel")
    spatial, cin, cout = shape[:-2], shape[-2], shape[-1]
    if cout % groups != 0:
        raise ValueError("out channels must be divisible by groups")
    per_group = cout // groups
    w = jnp.zeros(shape, jnp.float32)
    center = tuple(s // 2 for s in spatial)
    for g in range(groups):
        for d in range(min(per_group, cin)):
            w = w.at[center + (d, g * per_group + d)].set(1.0)
    return w


def _init_sparse(key, shape, sparsity=0.1, std=0.01, **kw):
    """torch `sparse_`: N(0, std) 2D matrix with a `sparsity` fraction of
    each column zeroed (exactly ceil(sparsity*rows) zeros per column)."""
    if len(shape) != 2:
        raise ValueError("sparse init requires a 2-dimensional parameter")
    rows, _ = shape
    nz = math.ceil(sparsity * rows)
    kn, kp = jax.random.split(key)
    w = std * jax.random.normal(kn, shape, jnp.float32)
    if nz <= 0:
        return w
    # Uniform ranks give an independent random permutation per column; keep
    # entries above each column's nz-th smallest rank
    u = jax.random.uniform(kp, shape)
    thresh = jnp.sort(u, axis=0)[nz - 1]
    return w * (u > thresh)


inits = {
    "uniform": _init_uniform,
    "normal": _init_normal,
    "trunc_normal": _init_trunc_normal,
    "constant": _init_constant,
    "ones": _init_ones,
    "zeros": _init_zeros,
    "eye": _init_eye,
    "dirac": _init_dirac,
    "xavier_uniform": _init_xavier_uniform,
    "xavier_normal": _init_xavier_normal,
    "kaiming_uniform": _init_kaiming_uniform,
    "kaiming_normal": _init_kaiming_normal,
    "orthogonal": _init_orthogonal,
    "sparse": _init_sparse,
}
# Accept the torch in-place spellings too ("xavier_uniform_", ...)
inits.update({k + "_": v for k, v in list(inits.items())})


def apply_named_init(params, key, init_multi=None, init_multi_args=None,
                     init_mono=None, init_mono_args=None):
    """Re-initialize multi-dim params with `init_multi` and 1-dim params with
    `init_mono` (reference `experiments/model.py:128-136, 157-164`)."""
    leaves, treedef = jax.tree.flatten(params)
    keys = jax.random.split(key, len(leaves))
    out = []
    for leaf, k in zip(leaves, keys):
        if leaf.ndim >= 2 and init_multi is not None:
            out.append(inits[init_multi](k, leaf.shape, **(init_multi_args or {})))
        elif leaf.ndim < 2 and init_mono is not None:
            out.append(inits[init_mono](k, leaf.shape, **(init_mono_args or {})))
        else:
            out.append(leaf)
    return jax.tree.unflatten(treedef, out)
