"""`vgg11/vgg13/vgg16/vgg19` — torchvision VGG (configs A/B/D/E), as
pure-pytree ModelDefs.

Registry-tail extension in the `models/resnet.py` pattern: the reference
resolves every `torchvision.models` name (reference
`experiments/model.py:40-90`); each variant here is pinned to torchvision's
exact parameter count in `tests/test_vgg_densenet.py`.

Architecture (torchvision `vgg.py`): stacks of 3x3 pad-1 convs (with bias)
+ ReLU, maxpool2x2/s2 between stages, then AdaptiveAvgPool2d(7) and the
classifier Linear(512*7*7, 4096) ReLU Dropout(.5) Linear(4096, 4096) ReLU
Dropout(.5) Linear(4096, num_classes). Initialization parity:
kaiming-normal(fan_out, relu) conv kernels with zero biases, classifier
Linear weights ~ N(0, 0.01^2) with zero biases (torchvision
`VGG._initialize_weights`).
"""

import math

import jax
import jax.numpy as jnp
from jax import lax

from byzantinemomentum_tpu.models import ModelDef, register
from byzantinemomentum_tpu.models.core import dropout_apply

__all__ = []

# torchvision `cfgs`: channel per conv, "M" = maxpool
_CFGS = {
    "vgg11": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "vgg13": (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
              512, 512, "M"),
    "vgg16": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
              "M", 512, 512, 512, "M"),
    "vgg19": (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
              512, 512, "M", 512, 512, 512, 512, "M"),
}
_DROPOUT = 0.5


def _conv_init(key, cin, cout):
    """kaiming_normal_(fan_out, relu) kernel + zero bias (torchvision
    `VGG._initialize_weights`)."""
    fan_out = 3 * 3 * cout
    std = math.sqrt(2.0 / fan_out)
    return {"w": std * jax.random.normal(key, (3, 3, cin, cout), jnp.float32),
            "b": jnp.zeros((cout,), jnp.float32)}


def _fc_init(key, din, dout):
    """Classifier Linear: W ~ N(0, 0.01), b = 0 (torchvision)."""
    return {"w": 0.01 * jax.random.normal(key, (din, dout), jnp.float32),
            "b": jnp.zeros((dout,), jnp.float32)}


def _max_pool_2x2(x):
    return lax.reduce_window(
        x, -jnp.inf, lax.max, window_dimensions=(1, 2, 2, 1),
        window_strides=(1, 2, 2, 1), padding="VALID")


def adaptive_avg_pool(x, out_hw):
    """torch `AdaptiveAvgPool2d`: output pixel (i, j) averages the input
    window [floor(i*H/out), ceil((i+1)*H/out)) x [...]. Static shapes, so
    the window set unrolls at trace time (49 slices for 7x7); on the 1x1
    activations a 32x32 input leaves, every window is the single pixel
    (pure replication), exactly as torch computes it."""
    H, W = x.shape[1], x.shape[2]
    oh, ow = out_hw
    if (H, W) == (oh, ow):
        return x
    rows = []
    for i in range(oh):
        h0, h1 = (i * H) // oh, -((-(i + 1) * H) // oh)
        cols = []
        for j in range(ow):
            w0, w1 = (j * W) // ow, -((-(j + 1) * W) // ow)
            cols.append(jnp.mean(x[:, h0:h1, w0:w1, :], axis=(1, 2)))
        rows.append(jnp.stack(cols, axis=1))
    return jnp.stack(rows, axis=1)  # (B, oh, ow, C)


def _make_vgg(name, num_classes=10):
    cfg = _CFGS[name]
    n_convs = sum(1 for c in cfg if c != "M")

    def init(key):
        keys = jax.random.split(key, n_convs + 3)
        params = {}
        cin, k = 3, 0
        for c in cfg:
            if c == "M":
                continue
            params[f"conv{k}"] = _conv_init(keys[k], cin, c)
            cin, k = c, k + 1
        params["fc0"] = _fc_init(keys[n_convs], 512 * 7 * 7, 4096)
        params["fc1"] = _fc_init(keys[n_convs + 1], 4096, 4096)
        params["fc2"] = _fc_init(keys[n_convs + 2], 4096, num_classes)
        return params, {}

    def apply(params, state, x, train=False, rng=None):
        if train and rng is None:
            raise ValueError(f"{name} needs a PRNG key in train mode "
                             "(classifier dropout)")
        k = 0
        for c in cfg:
            if c == "M":
                x = _max_pool_2x2(x)
                continue
            p = params[f"conv{k}"]
            x = lax.conv_general_dilated(
                x, p["w"], window_strides=(1, 1),
                padding=[(1, 1), (1, 1)],
                dimension_numbers=("NHWC", "HWIO", "NHWC")) + p["b"]
            x = jax.nn.relu(x)
            k += 1
        x = adaptive_avg_pool(x, (7, 7))
        x = x.reshape(x.shape[0], -1)
        rngs = jax.random.split(rng, 2) if train else (None, None)
        x = jax.nn.relu(x @ params["fc0"]["w"] + params["fc0"]["b"])
        x = dropout_apply(rngs[0], x, _DROPOUT, train=train)
        x = jax.nn.relu(x @ params["fc1"]["w"] + params["fc1"]["b"])
        x = dropout_apply(rngs[1], x, _DROPOUT, train=train)
        return x @ params["fc2"]["w"] + params["fc2"]["b"], state

    return ModelDef(name, init, apply, (32, 32, 3))


for _name in _CFGS:
    register(_name, (lambda name: lambda num_classes=10, **kw:
                     _make_vgg(name, num_classes))(_name))
