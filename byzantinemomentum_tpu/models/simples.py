"""Simple model collection: `simples-full`, `simples-conv`, `simples-logit`,
`simples-linear` (reference `experiments/models/simples.py`).

* full   — MNIST 784-100-10 MLP, relu + log_softmax (reference `:23-55`).
* conv   — MNIST LeNet-style: conv(1->20,5) relu pool2, conv(20->50,5) relu
           pool2, fc 800-500-10, log_softmax (reference `:60-98`; the CLI
           default model, reference `attack.py:126-129`).
* logit  — sigmoid(linear(din->dout)) (reference `:103-137`).
* linear — linear(din->dout) (reference `:142-176`).
"""

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu.models import ModelDef, register
from byzantinemomentum_tpu.models.core import (
    conv_apply, conv_init, dense_apply, dense_init, log_softmax, max_pool)

__all__ = []


def make_full(**kwargs):
    def init(key):
        k1, k2 = jax.random.split(key)
        params = {
            "f1": dense_init(k1, 28 * 28, 100),
            "f2": dense_init(k2, 100, 10),
        }
        return params, {}

    def apply(params, state, x, train=False, rng=None):
        x = x.reshape((x.shape[0], -1))
        x = jax.nn.relu(dense_apply(params["f1"], x))
        return log_softmax(dense_apply(params["f2"], x)), state

    return ModelDef("simples-full", init, apply, (28, 28, 1))


def make_conv(**kwargs):
    def init(key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        params = {
            "c1": conv_init(k1, 5, 5, 1, 20),
            "c2": conv_init(k2, 5, 5, 20, 50),
            "f1": dense_init(k3, 800, 500),
            "f2": dense_init(k4, 500, 10),
        }
        return params, {}

    def apply(params, state, x, train=False, rng=None):
        x = jax.nn.relu(conv_apply(params["c1"], x, padding="VALID"))
        x = max_pool(x, 2)
        x = jax.nn.relu(conv_apply(params["c2"], x, padding="VALID"))
        x = max_pool(x, 2)
        x = x.reshape((x.shape[0], -1))  # (B, 4*4*50) = (B, 800)
        x = jax.nn.relu(dense_apply(params["f1"], x))
        return log_softmax(dense_apply(params["f2"], x)), state

    return ModelDef("simples-conv", init, apply, (28, 28, 1))


def make_logit(din=68, dout=1, **kwargs):
    def init(key):
        return {"linear": dense_init(key, din, dout)}, {}

    def apply(params, state, x, train=False, rng=None):
        x = x.reshape((x.shape[0], din))
        return jax.nn.sigmoid(dense_apply(params["linear"], x)), state

    return ModelDef("simples-logit", init, apply, (din,))


def make_linear(din=68, dout=1, **kwargs):
    def init(key):
        return {"linear": dense_init(key, din, dout)}, {}

    def apply(params, state, x, train=False, rng=None):
        x = x.reshape((x.shape[0], din))
        return dense_apply(params["linear"], x), state

    return ModelDef("simples-linear", init, apply, (din,))


register("simples-full", make_full)
register("simples-conv", make_conv)
register("simples-logit", make_logit)
register("simples-linear", make_linear)
