"""Simple model collection: `simples-full`, `simples-conv`, `simples-logit`,
`simples-linear` (reference `experiments/models/simples.py`).

* full   — MNIST 784-100-10 MLP, relu + log_softmax (reference `:23-55`).
* conv   — MNIST LeNet-style: conv(1->20,5) relu pool2, conv(20->50,5) relu
           pool2, fc 800-500-10, log_softmax (reference `:60-98`; the CLI
           default model, reference `attack.py:126-129`).
* logit  — sigmoid(linear(din->dout)) (reference `:103-137`).
* linear — linear(din->dout) (reference `:142-176`).
"""

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu.models import ModelDef, register
from byzantinemomentum_tpu.models.core import (
    conv_apply, conv_init, dense_apply, dense_init, grouped_conv_apply,
    grouped_dense_apply, grouped_unpack, log_softmax, max_pool)

__all__ = []


def make_full(**kwargs):
    def init(key):
        k1, k2 = jax.random.split(key)
        params = {
            "f1": dense_init(k1, 28 * 28, 100),
            "f2": dense_init(k2, 100, 10),
        }
        return params, {}

    def apply(params, state, x, train=False, rng=None):
        x = x.reshape((x.shape[0], -1))
        x = jax.nn.relu(dense_apply(params["f1"], x))
        return log_softmax(dense_apply(params["f2"], x)), state

    def apply_grouped(params_s, state, xs, train=False, rng=None):
        """All S per-worker MLPs as two batched einsums over the worker
        axis (same math as `vmap(apply)`)."""
        S, B = xs.shape[0], xs.shape[1]
        x = jnp.moveaxis(xs, 0, 1).reshape(B, S, 28 * 28)
        x = jax.nn.relu(grouped_dense_apply(params_s["f1"], x))
        x = log_softmax(grouped_dense_apply(params_s["f2"], x))
        return x.transpose(1, 0, 2), state

    return ModelDef("simples-full", init, apply, (28, 28, 1),
                    apply_grouped=apply_grouped)


def make_conv(**kwargs):
    def init(key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        params = {
            "c1": conv_init(k1, 5, 5, 1, 20),
            "c2": conv_init(k2, 5, 5, 20, 50),
            "f1": dense_init(k3, 800, 500),
            "f2": dense_init(k4, 500, 10),
        }
        return params, {}

    def apply(params, state, x, train=False, rng=None):
        x = jax.nn.relu(conv_apply(params["c1"], x, padding="VALID"))
        x = max_pool(x, 2)
        x = jax.nn.relu(conv_apply(params["c2"], x, padding="VALID"))
        x = max_pool(x, 2)
        x = x.reshape((x.shape[0], -1))  # (B, 4*4*50) = (B, 800)
        x = jax.nn.relu(dense_apply(params["f1"], x))
        return log_softmax(dense_apply(params["f2"], x)), state

    def apply_grouped(params_s, state, xs, train=False, rng=None):
        """All S per-worker LeNets in one merged program: worker axis as
        channel groups for the convs, batched einsums for the fcs."""
        S, B = xs.shape[0], xs.shape[1]
        x = xs.transpose(1, 2, 3, 0, 4)  # worker-expanded (B, 28, 28, S, 1)
        x = jax.nn.relu(grouped_conv_apply(params_s["c1"], x, padding="VALID"))
        x = max_pool(x, 2)
        x = jax.nn.relu(grouped_conv_apply(params_s["c2"], x, padding="VALID"))
        x = max_pool(x, 2)
        # (B, 4, 4, S, 50) -> per-worker flat (h, w, c) rows (unpack first:
        # worker packing may have factorized the (S, C) tail)
        x = grouped_unpack(x, S)
        x = x.transpose(0, 3, 1, 2, 4).reshape(B, S, 800)
        x = jax.nn.relu(grouped_dense_apply(params_s["f1"], x))
        x = log_softmax(grouped_dense_apply(params_s["f2"], x))
        return x.transpose(1, 0, 2), state

    return ModelDef("simples-conv", init, apply, (28, 28, 1),
                    apply_grouped=apply_grouped)


def make_logit(din=68, dout=1, **kwargs):
    def init(key):
        return {"linear": dense_init(key, din, dout)}, {}

    def apply(params, state, x, train=False, rng=None):
        x = x.reshape((x.shape[0], din))
        return jax.nn.sigmoid(dense_apply(params["linear"], x)), state

    def apply_grouped(params_s, state, xs, train=False, rng=None):
        x = jnp.moveaxis(xs, 0, 1).reshape(xs.shape[1], xs.shape[0], din)
        out = jax.nn.sigmoid(grouped_dense_apply(params_s["linear"], x))
        return out.transpose(1, 0, 2), state

    return ModelDef("simples-logit", init, apply, (din,),
                    apply_grouped=apply_grouped)


def make_linear(din=68, dout=1, **kwargs):
    def init(key):
        return {"linear": dense_init(key, din, dout)}, {}

    def apply(params, state, x, train=False, rng=None):
        x = x.reshape((x.shape[0], din))
        return dense_apply(params["linear"], x), state

    def apply_grouped(params_s, state, xs, train=False, rng=None):
        x = jnp.moveaxis(xs, 0, 1).reshape(xs.shape[1], xs.shape[0], din)
        return grouped_dense_apply(params_s["linear"], x).transpose(1, 0, 2), state

    return ModelDef("simples-linear", init, apply, (din,),
                    apply_grouped=apply_grouped)


register("simples-full", make_full)
register("simples-conv", make_conv)
register("simples-logit", make_logit)
register("simples-linear", make_linear)
