"""`densenet121/169/201` — torchvision DenseNet, as pure-pytree ModelDefs.

Registry-tail extension in the `models/resnet.py` pattern (the reference
resolves every `torchvision.models` name, reference
`experiments/model.py:40-90`); parameter counts pinned against torchvision
in `tests/test_vgg_densenet.py`.

Architecture (torchvision `densenet.py`; growth 32, bn_size 4,
num_init_features 64): conv7x7(3,64,s2,p3,nobias) BN relu maxpool3x3(s2,p1);
dense blocks of layers [BN relu conv1x1(c, 4*growth, nobias) BN relu
conv3x3(4*growth, growth, p1, nobias)] whose outputs concatenate onto the
running feature map; transitions [BN relu conv1x1(c, c//2, nobias)
avgpool2x2(s2)] between blocks; final BN relu, global average pool,
Linear(c, num_classes). Block configs: 121 = (6, 12, 24, 16),
169 = (6, 12, 32, 32), 201 = (6, 12, 48, 32).

Initialization parity: kaiming-normal conv kernels (torchvision uses
`kaiming_normal_(m.weight)` — fan_in, relu gain), BN gamma=1/beta=0,
classifier bias 0 with torch-default weight.
"""

import math

import jax
import jax.numpy as jnp
from jax import lax

from byzantinemomentum_tpu.models import ModelDef, register
from byzantinemomentum_tpu.models.core import batchnorm_apply, batchnorm_init

__all__ = []

_GROWTH = 32
_BN_SIZE = 4
_BLOCKS = {
    "densenet121": (6, 12, 24, 16),
    "densenet169": (6, 12, 32, 32),
    "densenet201": (6, 12, 48, 32),
}


def _conv_init(key, kh, kw, cin, cout):
    """torchvision densenet conv init: `kaiming_normal_(m.weight)` —
    default mode fan_in, relu-family gain sqrt(2), bias-free."""
    fan_in = kh * kw * cin
    std = math.sqrt(2.0 / fan_in)
    return {"w": std * jax.random.normal(key, (kh, kw, cin, cout),
                                         jnp.float32)}


def _conv(params, x, *, stride=1, pad=0):
    return lax.conv_general_dilated(
        x, params["w"], window_strides=(stride, stride),
        padding=[(pad, pad), (pad, pad)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _fc_init(key, din, dout):
    """Classifier: torch-default kaiming-uniform weight, zero bias
    (torchvision zeroes only the bias)."""
    bound = 1.0 / math.sqrt(din)
    return {"w": jax.random.uniform(key, (din, dout), jnp.float32,
                                    -bound, bound),
            "b": jnp.zeros((dout,), jnp.float32)}


def _layer_init(key, cin):
    k1, k2 = jax.random.split(key)
    params, state = {}, {}
    params["bn1"], state["bn1"] = batchnorm_init(cin)
    params["conv1"] = _conv_init(k1, 1, 1, cin, _BN_SIZE * _GROWTH)
    params["bn2"], state["bn2"] = batchnorm_init(_BN_SIZE * _GROWTH)
    params["conv2"] = _conv_init(k2, 3, 3, _BN_SIZE * _GROWTH, _GROWTH)
    return params, state


def _layer_apply(params, state, x, *, train):
    new_state = dict(state)
    out, new_state["bn1"] = batchnorm_apply(params["bn1"], state["bn1"], x,
                                            train=train)
    out = _conv(params["conv1"], jax.nn.relu(out))
    out, new_state["bn2"] = batchnorm_apply(params["bn2"], state["bn2"], out,
                                            train=train)
    out = _conv(params["conv2"], jax.nn.relu(out), pad=1)
    return out, new_state


def _make_densenet(name, num_classes=10):
    blocks = _BLOCKS[name]

    def init(key):
        keys = jax.random.split(key, sum(blocks) + len(blocks) + 2)
        params, state = {}, {}
        params["stem"] = _conv_init(keys[0], 7, 7, 3, 64)
        params["bn0"], state["bn0"] = batchnorm_init(64)
        c, k = 64, 1
        for b, n_layers in enumerate(blocks):
            for i in range(n_layers):
                lname = f"b{b}l{i}"
                params[lname], state[lname] = _layer_init(keys[k], c)
                c, k = c + _GROWTH, k + 1
            if b < len(blocks) - 1:
                tname = f"t{b}"
                tp, ts = {}, {}
                tp["bn"], ts["bn"] = batchnorm_init(c)
                tp["conv"] = _conv_init(keys[k], 1, 1, c, c // 2)
                params[tname], state[tname] = tp, ts
                c, k = c // 2, k + 1
        params["bn5"], state["bn5"] = batchnorm_init(c)
        params["fc"] = _fc_init(keys[k], c, num_classes)
        return params, state

    def apply(params, state, x, train=False, rng=None):
        new_state = dict(state)
        x = _conv(params["stem"], x, stride=2, pad=3)
        x, new_state["bn0"] = batchnorm_apply(params["bn0"], state["bn0"], x,
                                              train=train)
        x = jax.nn.relu(x)
        x = lax.reduce_window(
            x, -jnp.inf, lax.max, window_dimensions=(1, 3, 3, 1),
            window_strides=(1, 2, 2, 1),
            padding=((0, 0), (1, 1), (1, 1), (0, 0)))
        for b, n_layers in enumerate(blocks):
            for i in range(n_layers):
                lname = f"b{b}l{i}"
                out, new_state[lname] = _layer_apply(
                    params[lname], state[lname], x, train=train)
                x = jnp.concatenate([x, out], axis=-1)
            if b < len(blocks) - 1:
                tname = f"t{b}"
                x, nbn = batchnorm_apply(params[tname]["bn"],
                                         state[tname]["bn"], x, train=train)
                new_state[tname] = dict(state[tname], bn=nbn)
                x = _conv(params[tname]["conv"], jax.nn.relu(x))
                x = lax.reduce_window(
                    x, 0.0, lax.add, window_dimensions=(1, 2, 2, 1),
                    window_strides=(1, 2, 2, 1), padding="VALID") / 4.0
        x, new_state["bn5"] = batchnorm_apply(params["bn5"], state["bn5"], x,
                                              train=train)
        x = jax.nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        return x @ params["fc"]["w"] + params["fc"]["b"], new_state

    return ModelDef(name, init, apply, (32, 32, 3))


for _name in _BLOCKS:
    register(_name, (lambda name: lambda num_classes=10, **kw:
                     _make_densenet(name, num_classes))(_name))
