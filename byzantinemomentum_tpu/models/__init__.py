"""Model registry: name -> builder returning a `ModelDef`.

A `ModelDef` is the TPU-native redesign of the reference's `Model` wrapper
(reference `experiments/model.py:30-396`): instead of relinking torch
parameters into a flat buffer, parameters live in a pytree and
`jax.flatten_util.ravel_pytree` provides the flat `d`-dim gradient space
on demand. Network state (BatchNorm running stats) is a separate pytree so
the flat parameter space matches the reference's `d` (torch buffers are not
parameters).

Model names follow the reference's `<module>-<entrypoint>` convention
(reference `experiments/model.py:40-90`): `simples-conv`, `simples-full`,
`empire-cnn`, `wide_resnet-Wide_ResNet`, ...
"""

import dataclasses
import pathlib
import typing

import jax
import jax.flatten_util

from byzantinemomentum_tpu import utils

__all__ = ["ModelDef", "models", "register", "build", "flatten_params"]

# Registry: name -> builder(**model_args) -> ModelDef
models = {}


@dataclasses.dataclass(frozen=True)
class ModelDef:
    """A pure (init, apply) model.

    init:  (key) -> (params, net_state)
    apply: (params, net_state, x, train, rng) -> (output, new_net_state)
    input_shape: per-example input shape (NHWC for images).
    apply_grouped: optional merged-batch execution of S per-worker networks
      (params_s with a stacked leading worker axis on every leaf, shared
      net_state, xs: (S, B, ...), rng: (S,) stacked per-worker keys) ->
      (output (S, B, ...), new_net_state). In train mode `new_net_state`
      leaves are stacked (S, ...) per-worker updates (what
      `compose_bn_updates` consumes); in eval mode the shared `net_state`
      is returned unchanged (unstacked), as evaluation must not touch it.
      Same math as `vmap(apply)` over the worker axis, but expressed with
      worker-grouped convolutions/einsums (`models/core.py` grouped
      helpers), which avoid the XLA layout copies `vmap` puts around every
      per-worker conv weight gradient. The engine uses it automatically for
      the honest phase when present (`engine/step.py`).
    """
    name: str
    init: typing.Callable
    apply: typing.Callable
    input_shape: tuple
    apply_grouped: typing.Callable = None

    def param_count(self, key=None):
        key = jax.random.PRNGKey(0) if key is None else key
        params, _ = jax.eval_shape(self.init, key)
        return sum(int(_size(leaf)) for leaf in jax.tree.leaves(params))


def _size(leaf):
    out = 1
    for s in leaf.shape:
        out *= s
    return out


def register(name, builder):
    """Register a model builder under `name`."""
    if name in models:
        utils.warning(f"Model {name!r} registered twice; keeping the last")
    models[name] = builder
    return builder


def build(name, **model_args):
    """Instantiate a ModelDef by registry name
    (reference `experiments/model.py:115-182`)."""
    if name not in models:
        utils.fatal_unavailable(models, name, what="model name")
    return models[name](**model_args)


def flatten_params(params):
    """Flatten a parameter pytree into (flat f32[d], unravel fn) — the
    TPU-native equivalent of the reference's flat-tensor relink
    (reference `tools/pytorch.py:30-64`, `experiments/model.py:170`)."""
    return jax.flatten_util.ravel_pytree(params)


# Self-registering model modules (plugin pattern, reference
# `experiments/model.py:60-90`)
utils.import_directory(__name__, pathlib.Path(__file__).parent)
