"""The "Fall of Empires" CIFAR CNN: `empire-cnn`
(reference `experiments/models/empire.py:24-98`).

Architecture (note the unusual conv -> relu -> BN order, kept for parity):
  conv3x3(3,64) relu bn, conv3x3(64,64) relu bn, maxpool2, dropout .25,
  conv3x3(64,128) relu bn, conv3x3(128,128) relu bn,
  maxpool2, dropout .25, flatten(8192),
  fc(8192,128) relu dropout .25 fc(128,10), log_softmax
  (CIFAR-100 variant: fc(8192,256), fc(256,100)).

BatchNorm + Dropout under vmap: each worker's forward normalizes with its
own minibatch statistics (exactly torch train-mode behavior) and draws its
own dropout mask from a per-worker PRNG key; the sequential running-stat
update across workers is composed in the training step
(`engine/step.py:compose_bn_updates`) — see SURVEY.md §7 "hard parts" #2.
"""

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu.models import ModelDef, register
from byzantinemomentum_tpu.models.core import (
    batchnorm_apply, batchnorm_init, conv_apply, conv_init, dense_apply,
    dense_init, dropout_apply, grouped_batchnorm_apply, grouped_conv_apply,
    grouped_dense_apply, grouped_dropout_apply, grouped_unpack, log_softmax,
    max_pool)

__all__ = []


def make_cnn(cifar100=False, **kwargs):
    fc1_out = 256 if cifar100 else 128
    n_classes = 100 if cifar100 else 10

    def init(key):
        keys = jax.random.split(key, 6)
        params, state = {}, {}
        params["c1"] = conv_init(keys[0], 3, 3, 3, 64)
        params["b1"], state["b1"] = batchnorm_init(64)
        params["c2"] = conv_init(keys[1], 3, 3, 64, 64)
        params["b2"], state["b2"] = batchnorm_init(64)
        params["c3"] = conv_init(keys[2], 3, 3, 64, 128)
        params["b3"], state["b3"] = batchnorm_init(128)
        params["c4"] = conv_init(keys[3], 3, 3, 128, 128)
        params["b4"], state["b4"] = batchnorm_init(128)
        params["f1"] = dense_init(keys[4], 8192, fc1_out)
        params["f2"] = dense_init(keys[5], fc1_out, n_classes)
        return params, state

    def apply(params, state, x, train=False, rng=None):
        if train and rng is None:
            raise ValueError("empire-cnn needs a PRNG key in train mode (dropout)")
        drop_keys = jax.random.split(rng, 3) if train else (None, None, None)
        new_state = dict(state)
        x = jax.nn.relu(conv_apply(params["c1"], x, padding="SAME"))
        x, new_state["b1"] = batchnorm_apply(params["b1"], state["b1"], x, train=train)
        x = jax.nn.relu(conv_apply(params["c2"], x, padding="SAME"))
        x, new_state["b2"] = batchnorm_apply(params["b2"], state["b2"], x, train=train)
        x = max_pool(x, 2)
        x = dropout_apply(drop_keys[0], x, 0.25, train=train)
        x = jax.nn.relu(conv_apply(params["c3"], x, padding="SAME"))
        x, new_state["b3"] = batchnorm_apply(params["b3"], state["b3"], x, train=train)
        x = jax.nn.relu(conv_apply(params["c4"], x, padding="SAME"))
        x, new_state["b4"] = batchnorm_apply(params["b4"], state["b4"], x, train=train)
        x = max_pool(x, 2)
        x = dropout_apply(drop_keys[1], x, 0.25, train=train)
        x = x.reshape((x.shape[0], -1))  # (B, 8*8*128) = (B, 8192)
        x = jax.nn.relu(dense_apply(params["f1"], x))
        x = dropout_apply(drop_keys[2], x, 0.25, train=train)
        x = dense_apply(params["f2"], x)
        return log_softmax(x), new_state

    def apply_grouped(params_s, state, xs, train=False, rng=None):
        """Merged-batch execution of all S per-worker forwards — same math
        as `vmap(apply)` (incl. identical per-worker dropout draws and
        batch-stat BN), with the worker axis carried as channel groups so
        the per-worker conv weight gradients compile to clean grouped
        convolutions instead of vmap's transposed batch-group ones."""
        if train and rng is None:
            raise ValueError("empire-cnn needs PRNG keys in train mode (dropout)")
        S, B = xs.shape[0], xs.shape[1]
        dks = (jax.vmap(lambda k: jax.random.split(k, 3))(rng)
               if train else (None, None, None))
        new_state = dict(state)
        x = xs.transpose(1, 2, 3, 0, 4)  # worker-expanded (B, 32, 32, S, 3)
        x = jax.nn.relu(grouped_conv_apply(params_s["c1"], x, padding="SAME"))
        x, new_state["b1"] = grouped_batchnorm_apply(
            params_s["b1"], state["b1"], x, train=train)
        x = jax.nn.relu(grouped_conv_apply(params_s["c2"], x, padding="SAME"))
        x, new_state["b2"] = grouped_batchnorm_apply(
            params_s["b2"], state["b2"], x, train=train)
        x = max_pool(x, 2)
        x = grouped_dropout_apply(
            dks[:, 0] if train else None, x, 0.25, train=train)
        x = jax.nn.relu(grouped_conv_apply(params_s["c3"], x, padding="SAME"))
        x, new_state["b3"] = grouped_batchnorm_apply(
            params_s["b3"], state["b3"], x, train=train)
        x = jax.nn.relu(grouped_conv_apply(params_s["c4"], x, padding="SAME"))
        x, new_state["b4"] = grouped_batchnorm_apply(
            params_s["b4"], state["b4"], x, train=train)
        x = max_pool(x, 2)
        x = grouped_dropout_apply(
            dks[:, 1] if train else None, x, 0.25, train=train)
        # (B, 8, 8, S, 128) -> per-worker flat (h, w, c) rows, matching the
        # vmapped path's x.reshape(B, -1)
        x = grouped_unpack(x, S)  # no-op here (C=128 never packs), defensive
        x = x.transpose(0, 3, 1, 2, 4).reshape(B, S, 8192)
        x = jax.nn.relu(grouped_dense_apply(params_s["f1"], x))
        x = grouped_dropout_apply(
            dks[:, 2] if train else None, x, 0.25, train=train)
        x = grouped_dense_apply(params_s["f2"], x)
        return log_softmax(x).transpose(1, 0, 2), new_state

    return ModelDef("empire-cnn", init, apply, (32, 32, 3),
                    apply_grouped=apply_grouped)


register("empire-cnn", make_cnn)
