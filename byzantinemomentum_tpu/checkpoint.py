"""Checkpoint/restore of the full training state.

Redesign of reference `experiments/checkpoint.py:30-169` + the load/init
logic of `attack.py:621-682`: instead of a collection of torch `state_dict`s
keyed by class name, a checkpoint here is one msgpack file holding the whole
`TrainState` pytree — params, momentum buffer(s), origin, past-gradient
ring, counters AND the PRNG key. Checkpointing the PRNG key fixes the
reference's documented limitation that resumed runs are not reproducible
(reference `README.md:105`, `attack.py:297-300`).

Validation parity on load (reference `attack.py:629-667`): version match,
non-negative counters, momentum buffer shape/count checks.
"""

import pathlib

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from byzantinemomentum_tpu import utils
from byzantinemomentum_tpu.engine.state import TrainState

__all__ = ["VERSION", "save", "load"]

# Must be unique and incremented on every incompatible layout change
# (reference `attack.py:622` — the reference is at version 4; this framework
# numbers its own lineage).
VERSION = 2


def save(path, state, *, data_state=None):
    """Serialize `state` to `path` (reference `Checkpoint.save`,
    `experiments/checkpoint.py:134-148`).

    `data_state` optionally carries the host data-sampler snapshots
    (`Dataset.get_state()` dicts, e.g. {"train": ..., "test": ...}) so a
    resumed run replays the exact same batch sequence — the dataloader-state
    gap the reference documents as unfixed (reference `README.md:105`).
    """
    state = jax.device_get(state)
    # to_state_dict converts non-dict containers (e.g. optax opt_state
    # tuples) into msgpack-serializable nested dicts
    payload = {"version": VERSION,
               "state": {name: serialization.to_state_dict(value)
                         for name, value in state._asdict().items()}}
    if data_state is not None:
        payload["data"] = data_state
    data = serialization.msgpack_serialize(payload)
    path = pathlib.Path(path)
    path.write_bytes(data)
    return path


def load(path, template, *, return_data=False):
    """Deserialize a checkpoint against a template `TrainState` (shapes are
    taken from the template, values from the file), with the reference's
    validation (reference `attack.py:624-667`).

    With `return_data=True` returns `(state, data_state)` where `data_state`
    is the sampler snapshot stored by `save` (or None for checkpoints
    written without one)."""
    raw = serialization.msgpack_restore(pathlib.Path(path).read_bytes())
    version = raw.get("version")
    if version != VERSION:
        raise utils.UserException(
            f"Unable to load checkpoint {str(path)!r}: expected version "
            f"{VERSION!r}, got {version!r}")
    stored = raw.get("state")
    if not isinstance(stored, dict):
        raise utils.UserException(
            f"Unable to load checkpoint {str(path)!r}: missing state payload")

    out = {}
    for name, ref in template._asdict().items():
        if name not in stored:
            if name == "fault_buffer":
                # Pre-faults checkpoints (same VERSION) lack the straggler
                # buffer; resuming them under a fresh fault plan starts the
                # buffer at the template's zeros — the documented cold-start
                out[name] = jnp.asarray(ref)
                continue
            raise utils.UserException(
                f"Unable to load checkpoint {str(path)!r}: missing field {name!r}")
        value = stored[name]
        if name in ("net_state", "opt_state"):
            value = serialization.from_state_dict(ref, value)
        else:
            value = jnp.asarray(value)
            ref_arr = jnp.asarray(ref)
            if value.shape != ref_arr.shape:
                raise utils.UserException(
                    f"Unable to load checkpoint {str(path)!r}: field {name!r} "
                    f"has shape {tuple(value.shape)}, expected "
                    f"{tuple(ref_arr.shape)}")
            if name in ("steps", "datapoints") and int(value) < 0:
                raise utils.UserException(
                    f"Unable to load checkpoint {str(path)!r}: invalid "
                    f"{name} counter {int(value)!r}")
            if name == "rng":
                # PRNG keys may round-trip as uint32 arrays
                value = value.astype(np.uint32)
            elif ref_arr.dtype != value.dtype:
                value = value.astype(ref_arr.dtype)
        out[name] = value
    state = TrainState(**out)
    if return_data:
        return state, raw.get("data")
    return state
