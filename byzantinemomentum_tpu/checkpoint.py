"""Checkpoint/restore of the full training state.

Redesign of reference `experiments/checkpoint.py:30-169` + the load/init
logic of `attack.py:621-682`: instead of a collection of torch `state_dict`s
keyed by class name, a checkpoint here is one msgpack file holding the whole
`TrainState` pytree — params, momentum buffer(s), origin, past-gradient
ring, counters AND the PRNG key. Checkpointing the PRNG key fixes the
reference's documented limitation that resumed runs are not reproducible
(reference `README.md:105`, `attack.py:297-300`).

Validation parity on load (reference `attack.py:629-667`): version match,
non-negative counters, momentum buffer shape/count checks.

Crash safety (preemptible-slice hardening, PR 2):

* `save` is ATOMIC: payload to a same-directory `<name>.tmp`, fsync, then
  `os.replace` onto the final name (+ a best-effort directory fsync). A
  SIGKILL at any instant leaves either the previous checkpoint or the new
  one — never a torn file under the final name.
* Every file carries an integrity footer — `MAGIC` + CRC32 of the payload —
  so a file torn by a pre-atomic writer, a bad disk or a partial copy is
  *detected* instead of poisoning the resume (`verify`).
* `find_latest_valid(dir)` walks the run's `checkpoint-<step>` files newest
  first and returns the first one that verifies, skipping torn/corrupt
  tails — what `--auto-resume` and the `Jobs` supervisor restart from.
* A per-run manifest (`checkpoints.json`, atomically rewritten) records the
  saved checkpoints, drives retention GC (`save(..., keep=N)` keeps the
  newest N) and persists the run's restart counter across preemptions. The
  manifest is advisory: resume scans the directory, so a kill between the
  checkpoint rename and the manifest update loses nothing.
* `save(..., mirror=dir)` additionally lands the SAME sealed bytes in a
  second directory with the same atomic protocol — the off-slice mirror of
  a multi-host run (`byzantinemomentum_tpu/cluster/`): when a host dies
  and takes its local disk with it, the fleet resumes from the mirror and
  losing the local copy costs nothing. `find_latest_valid_any(dirs)` scans
  several directories (local + mirror) and returns the globally newest
  valid checkpoint.
"""

import json
import os
import pathlib
import struct
import zlib

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from byzantinemomentum_tpu import utils
from byzantinemomentum_tpu.engine.state import TrainState
# Telemetry hooks (`obs.span`/`obs.emit` are no-ops when no recorder is
# active): checkpoint write/load cost and torn-file skips belong on the
# run's system timeline
from byzantinemomentum_tpu.obs import recorder as obs

__all__ = ["VERSION", "MAGIC", "MANIFEST_NAME", "save", "load", "seal",
           "verify", "find_latest_valid", "find_latest_valid_any",
           "checkpoint_step", "read_manifest", "bump_restarts"]

# Must be unique and incremented on every incompatible layout change
# (reference `attack.py:622` — the reference is at version 4; this framework
# numbers its own lineage).
VERSION = 2

# Integrity footer: MAGIC + CRC32(payload), little-endian, appended to the
# serialized payload. Pre-footer checkpoints (same VERSION) remain loadable:
# a file not ending in MAGIC is treated as a bare legacy payload.
MAGIC = b"BMTC"
_FOOTER = struct.Struct("<4sI")

# Per-run checkpoint manifest (deliberately NOT `checkpoint-*`: the resume
# scan keys on that prefix)
MANIFEST_NAME = "checkpoints.json"


def seal(data):
    """Append the integrity footer to a serialized payload."""
    return data + _FOOTER.pack(MAGIC, zlib.crc32(data) & 0xFFFFFFFF)


def _unseal(path, data):
    """Strip and check the integrity footer; raises on a CRC mismatch.
    Footer-less data passes through (legacy pre-footer checkpoints)."""
    if len(data) >= _FOOTER.size:
        magic, crc = _FOOTER.unpack(data[-_FOOTER.size:])
        if magic == MAGIC:
            payload = data[:-_FOOTER.size]
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                raise utils.UserException(
                    f"Unable to load checkpoint {str(path)!r}: integrity "
                    f"footer mismatch (torn or corrupt file)")
            return payload
    return data


def _fsync_directory(directory):
    """Durably record the rename in the directory entry (best-effort: not
    every platform/filesystem exposes directory fds)."""
    try:
        fd = os.open(str(directory), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _chaos_torn_write(path, data, step):
    """Chaos-test instrumentation (`tests/test_chaos.py`): simulate a
    preemption landing in the middle of a checkpoint write — flush half the
    bytes to the tmp file, then die the hard way. The atomic-rename protocol
    must make this indistinguishable from dying just before the save."""
    target = os.environ.get("BMT_CHAOS_TORN_CHECKPOINT_STEP")
    if target is None or int(target) != step:
        return
    tmp = path.with_name(path.name + ".tmp")
    with tmp.open("wb") as fd:
        fd.write(data[:max(1, len(data) // 2)])
        fd.flush()
        os.fsync(fd.fileno())
    os._exit(137)


def save(path, state, *, data_state=None, keep=None, mirror=None):
    """Serialize `state` to `path` (reference `Checkpoint.save`,
    `experiments/checkpoint.py:134-148`) — atomically, with the integrity
    footer, and registered in the run's manifest.

    `data_state` optionally carries the host data-sampler snapshots
    (`Dataset.get_state()` dicts, e.g. {"train": ..., "test": ...}) so a
    resumed run replays the exact same batch sequence — the dataloader-state
    gap the reference documents as unfixed (reference `README.md:105`).

    `keep`: retention — after a successful save, delete this run's oldest
    checkpoints beyond the newest `keep` (None/0 keeps everything).

    `mirror`: optional second directory receiving the same sealed bytes
    under the same file name with the same atomic protocol — the off-slice
    replica a multi-host resume survives local-disk loss through. The
    primary write commits first; a kill between the two leaves the mirror
    one checkpoint behind, which the multi-directory resume scan
    (`find_latest_valid_any`) absorbs.
    """
    state = jax.device_get(state)
    path = pathlib.Path(path)
    step = int(np.asarray(state.steps))
    with obs.span("checkpoint_save", step=step):
        # to_state_dict converts non-dict containers (e.g. optax opt_state
        # tuples) into msgpack-serializable nested dicts
        payload = {"version": VERSION,
                   "state": {name: serialization.to_state_dict(value)
                             for name, value in state._asdict().items()}}
        if data_state is not None:
            payload["data"] = data_state
        data = seal(serialization.msgpack_serialize(payload))
        _chaos_torn_write(path, data, step)
        _atomic_write(path, data)
        _manifest_add(path.parent, path.name, step, len(data), keep=keep)
        if mirror is not None:
            mirror = pathlib.Path(mirror)
            mirror.mkdir(parents=True, exist_ok=True)
            _atomic_write(mirror / path.name, data)
            _manifest_add(mirror, path.name, step, len(data), keep=keep)
            obs.emit("checkpoint_mirrored", file=path.name, step=step)
    return path


def _atomic_write(path, data):
    """tmp + fsync + `os.replace` + best-effort directory fsync — the
    crash-safe write every checkpoint copy (primary and mirror) uses."""
    tmp = path.with_name(path.name + ".tmp")
    with tmp.open("wb") as fd:
        fd.write(data)
        fd.flush()
        os.fsync(fd.fileno())
    os.replace(tmp, path)
    _fsync_directory(path.parent)


def load(path, template, *, return_data=False):
    """Deserialize a checkpoint against a template `TrainState` (shapes are
    taken from the template, values from the file), with the reference's
    validation (reference `attack.py:624-667`).

    With `return_data=True` returns `(state, data_state)` where `data_state`
    is the sampler snapshot stored by `save` (or None for checkpoints
    written without one)."""
    path = pathlib.Path(path)
    with obs.span("checkpoint_load", file=path.name):
        raw = serialization.msgpack_restore(_unseal(path, path.read_bytes()))
    version = raw.get("version")
    if version != VERSION:
        raise utils.UserException(
            f"Unable to load checkpoint {str(path)!r}: expected version "
            f"{VERSION!r}, got {version!r}")
    stored = raw.get("state")
    if not isinstance(stored, dict):
        raise utils.UserException(
            f"Unable to load checkpoint {str(path)!r}: missing state payload")

    out = {}
    for name, ref in template._asdict().items():
        if name not in stored:
            if name == "fault_buffer":
                # Pre-faults checkpoints (same VERSION) lack the straggler
                # buffer; resuming them under a fresh fault plan starts the
                # buffer at the template's zeros — the documented cold-start
                out[name] = jnp.asarray(ref)
                continue
            if name == "attack_state":
                # Pre-adaptive-attack checkpoints lack the attack history;
                # resuming them under a stateful attack restarts it at the
                # template's `state_init` value — the documented cold-start
                out[name] = ref
                continue
            raise utils.UserException(
                f"Unable to load checkpoint {str(path)!r}: missing field {name!r}")
        value = stored[name]
        if name in ("net_state", "opt_state", "attack_state"):
            value = serialization.from_state_dict(ref, value)
        else:
            value = jnp.asarray(value)
            ref_arr = jnp.asarray(ref)
            if value.shape != ref_arr.shape:
                raise utils.UserException(
                    f"Unable to load checkpoint {str(path)!r}: field {name!r} "
                    f"has shape {tuple(value.shape)}, expected "
                    f"{tuple(ref_arr.shape)}")
            if name in ("steps", "datapoints") and int(value) < 0:
                raise utils.UserException(
                    f"Unable to load checkpoint {str(path)!r}: invalid "
                    f"{name} counter {int(value)!r}")
            if name == "rng":
                # PRNG keys may round-trip as uint32 arrays
                value = value.astype(np.uint32)
            elif ref_arr.dtype != value.dtype:
                value = value.astype(ref_arr.dtype)
        out[name] = value
    state = TrainState(**out)
    if return_data:
        return state, raw.get("data")
    return state


# ------------------------------------------------------------------------- #
# Resume scanning

def verify(path):
    """Whether `path` holds a complete, CRC-consistent, version-matching
    checkpoint. Cheap (no template reconciliation) and never raises — the
    predicate `find_latest_valid` walks the directory with."""
    try:
        path = pathlib.Path(path)
        raw = serialization.msgpack_restore(_unseal(path, path.read_bytes()))
    except Exception:  # bmt: noqa[BMT-E05] a never-raises predicate over arbitrary torn bytes; msgpack raises library-specific types on garbage
        return False
    return (isinstance(raw, dict) and raw.get("version") == VERSION
            and isinstance(raw.get("state"), dict))


def checkpoint_step(path):
    """The step number encoded in a `checkpoint-<step>` file name (None for
    names that do not follow the run convention)."""
    suffix = pathlib.Path(path).name.rsplit("-", 1)[-1]
    return int(suffix) if suffix.isdigit() else None


def find_latest_valid(directory, prefix="checkpoint-"):
    """The newest valid checkpoint file in a run directory, walking past
    torn/corrupt tails (a preempted run's last write may be garbage — the
    trajectory must restart from the newest checkpoint that verifies).

    Returns a `pathlib.Path` or None. Files whose suffix is not a bare step
    number (`checkpoints.json`, stale `*.tmp` from a mid-write kill) are
    ignored.
    """
    directory = pathlib.Path(directory)
    if not directory.is_dir():
        return None
    candidates = []
    for entry in directory.iterdir():
        if not entry.name.startswith(prefix) or not entry.is_file():
            continue
        suffix = entry.name[len(prefix):]
        if not suffix.isdigit():
            continue
        candidates.append((int(suffix), entry))
    for _, entry in sorted(candidates, key=lambda c: c[0], reverse=True):
        if verify(entry):
            return entry
        utils.warning(f"Skipping torn/corrupt checkpoint {entry.name}")
        obs.emit("checkpoint_invalid", file=entry.name)
    return None


def find_latest_valid_any(directories, prefix="checkpoint-"):
    """The globally newest valid checkpoint across several directories
    (e.g. a run's local directory plus its off-slice mirror): the
    candidate with the highest step wins; a tie keeps the earlier
    directory's copy (the primary). Directories that do not exist simply
    contribute nothing."""
    best = None
    best_step = -1
    for directory in directories:
        if directory is None:
            continue
        found = find_latest_valid(directory, prefix=prefix)
        if found is None:
            continue
        step = checkpoint_step(found)
        step = -1 if step is None else step
        if step > best_step:
            best, best_step = found, step
    return best


# ------------------------------------------------------------------------- #
# Per-run manifest: retention GC + the restart counter

def read_manifest(directory):
    """The run's checkpoint manifest (a fresh empty one when absent or
    unreadable — the manifest is advisory, the directory scan is the
    authority)."""
    path = pathlib.Path(directory) / MANIFEST_NAME
    try:
        manifest = json.loads(path.read_text())
        if isinstance(manifest, dict):
            manifest.setdefault("version", 1)
            manifest.setdefault("checkpoints", [])
            manifest.setdefault("restarts", 0)
            return manifest
    except (OSError, ValueError):
        pass  # absent or torn manifest: rebuild from the empty default
    return {"version": 1, "checkpoints": [], "restarts": 0}


def _write_manifest(directory, manifest):
    path = pathlib.Path(directory) / MANIFEST_NAME
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(manifest, indent="\t"))
    os.replace(tmp, path)


def _manifest_add(directory, name, step, size, keep=None):
    """Register a freshly saved checkpoint; with `keep`, GC this run's
    oldest checkpoints beyond the newest `keep` (files + entries)."""
    directory = pathlib.Path(directory)
    manifest = read_manifest(directory)
    entries = [e for e in manifest["checkpoints"]
               if isinstance(e, dict) and e.get("file") != name
               and (directory / str(e.get("file"))).exists()]
    entries.append({"file": name, "step": step, "bytes": size})
    entries.sort(key=lambda e: e.get("step", -1))
    if keep is not None and keep > 0:
        while len(entries) > keep:
            stale = entries.pop(0)
            try:
                (directory / str(stale["file"])).unlink()
            except OSError:
                pass
    manifest["checkpoints"] = entries
    _write_manifest(directory, manifest)


def bump_restarts(directory):
    """Increment and persist the run's restart counter (the `Restarts`
    study-CSV column); returns the new count."""
    manifest = read_manifest(directory)
    manifest["restarts"] = int(manifest.get("restarts", 0)) + 1
    _write_manifest(directory, manifest)
    return manifest["restarts"]
