"""Training state pytree.

The reference scatters its mutable state across the torch model, the
optimizer and a `Storage` dict (reference `attack.py:668-681`); here it is
one immutable NamedTuple-of-arrays, so a step is a pure function and the
whole thing checkpoints/donates/shards uniformly.

Parameters live as ONE flat `f32[d]` vector — the TPU-native mirror of the
reference's relink-into-a-flat-buffer design (reference
`tools/pytorch.py:30-64`, `experiments/model.py:170`): all momentum algebra,
GAR kernels and study metrics operate directly on flat vectors, and
`unravel` (a pytree of cheap reshapes, fused by XLA) recovers the structured
parameters only inside the model's forward pass.
"""

import typing

import jax
import jax.numpy as jnp

__all__ = ["TrainState"]


class TrainState(typing.NamedTuple):
    """One step's complete input/output state."""

    theta: jax.Array             # f32[d] flat parameters
    net_state: typing.Any        # model state pytree (BatchNorm running stats)
    opt_state: typing.Any        # optimizer state pytree (empty for plain SGD)
    momentum_server: jax.Array   # f32[d] (zeros when placement is 'worker')
    momentum_workers: jax.Array  # f32[h, d] (shape (0, d) unless 'worker')
    origin: jax.Array            # f32[d] initial params (zeros if no study)
    past_grads: jax.Array        # f32[P, d] ring of past sampled averages
    past_norms: jax.Array        # f32[P] their norms ('appendleft' order)
    past_count: jax.Array        # i32[] number of valid past entries
    steps: jax.Array             # i32[] step counter
    datapoints: jax.Array        # i32[] training point counter
    rng: jax.Array               # PRNG key (checkpointed — fixes the
    #                              reference's resume nondeterminism,
    #                              reference README.md:105)
    fault_buffer: jax.Array = () # f32[h, d] last fresh per-worker
    #                              submissions, feeding straggler faults
    #                              (shape (0, d) unless the engine carries
    #                              a fault schedule with stragglers —
    #                              `faults/inject.py`)
    attack_state: typing.Any = ()  # adaptive-attack history pytree
    #                              (`attacks/__init__.py` state hook);
    #                              empty for static attacks — zero leaves,
    #                              zero cost


def init_state(cfg, theta, net_state, rng, *, study, opt_state=(),
               fault_buffer_rows=0, attack_state=()):
    """Fresh-run initialization (reference `attack.py:668-681`).

    `fault_buffer_rows`: honest-worker count when the engine's fault
    schedule contains stragglers (the stale-submission buffer), else 0 —
    the buffer starts at zeros, so a straggler window opening at step 0
    replays a no-progress submission.

    `attack_state`: the adaptive attack's initial history pytree
    (`Attack.state_init`); `()` for static attacks.
    """
    d = theta.shape[0]
    h = cfg.nb_honests
    past = cfg.nb_for_study_past if study else 0
    return TrainState(
        theta=theta,
        net_state=net_state,
        opt_state=opt_state,
        momentum_server=jnp.zeros((d,), theta.dtype),
        momentum_workers=jnp.zeros(
            (h if cfg.momentum_at == "worker" else 0, d), theta.dtype),
        # A distinct buffer from theta: the state pytree is donated to the
        # jitted step, and XLA rejects donating one buffer twice.
        origin=jnp.array(theta, copy=True) if study else jnp.zeros((0,), theta.dtype),
        past_grads=jnp.zeros((past, d), theta.dtype),
        past_norms=jnp.zeros((past,), theta.dtype),
        past_count=jnp.int32(0),
        steps=jnp.int32(0),
        datapoints=jnp.int32(0),
        rng=rng,
        fault_buffer=jnp.zeros((fault_buffer_rows, d), theta.dtype),
        attack_state=attack_state,
    )
