"""In-jit tensor-health statistics — the numerics flight recorder's
device half.

The paper's whole argument runs through one observable — the
variance-to-norm ratio of the submitted momenta — yet before this module
it only surfaced under opt-in full GAR diagnostics
(`engine/metrics.py::FORENSIC_COLUMNS`), and the divergence watchdog was
a post-hoc `isfinite(max|theta|)` flag that fires after the state is
already destroyed. ALIE-style attacks (Baruch et al., PAPERS.md) win
precisely by hiding *inside* the honest variance envelope, so the
envelope itself must be a first-class, always-cheap, continuously
monitored signal. This module computes, INSIDE the compiled step:

  norm histogram    fixed-bin log2-scale histogram of the per-worker
                    submitted-momentum L2 norms (`HIST_BINS` bins of
                    `HIST_WIDTH` octaves starting at `2**HIST_LO`; exact
                    zeros land in the underflow bin, non-finite rows in
                    the overflow bin) — the shape of the submission cloud
                    without shipping the cloud.
  Var ratio         the paper's variance-to-norm ratio of the honest
                    submissions (`ops/diag.py::var_norm_ratio` formula),
                    promoted out of the diagnostics path — and computed
                    from the SAME `avg`/`dev²` subexpressions the study
                    pipeline already builds (`metrics.avg_dev_max`), so
                    under the study (always, for health) XLA CSE makes it
                    free.
  weight/update     global L2 norms of the updated parameter vector and
                    of the applied update, plus their ratio — the
                    classical "update-to-weight" training-health signal.
  non-finite counts per phase: submitted rows whose norm is non-finite
                    (derived from the per-row norms — no extra pass over
                    the (n, d) stack), and NaN/Inf entries in the
                    aggregated defense gradient and the updated
                    parameter vector.

Everything is a flat dict of f32 scalars plus ONE f32[HIST_BINS] vector,
keyed by `engine/metrics.py::HEALTH_COLUMNS`, merged into the step's
metrics dict — it rides the existing device->host metrics fetch with
zero extra syncs. The gate is a trace-time config switch
(`EngineConfig.health`): off compiles the exact pre-health program
(byte-identical lowerings, the drift gate's contract). The incremental
work is engineered to the few passes the study pipeline does not already
do — per-row norms of the submitted rows and two d-vector reductions —
measured ≤ 3% steps/s on the CPU smoke config
(`scripts/health_overhead.py`).

Sharded form: under a `--mesh` run the flat parameter axis is d-sharded,
so `sharded_health_metrics(mesh)` computes the same reduction partials
d-locally inside a `shard_map` (width-aware real-column masks exclude
the divisibility padding from the vector non-finite counts,
`parallel/sharded.py::_coord_diag_builder` discipline) and psums ONE
(per-row norm², scalar-pack) tuple — two all_reduce ops, the collective
census `analysis/lattice.py` pins. The unsharded path is literally the
one-shard case (`_partials` + `_finalize` shared), so the histogram
BUCKET counts and non-finite counts are bit-identical across shardings
(integer counts of per-row bucket predicates, oracle-tested in
`tests/test_health.py`; the continuous scalars match to psum-vs-full-
width reduction rounding).
"""

import jax.numpy as jnp
from jax import lax

__all__ = ["HIST_BINS", "HIST_LO", "HIST_WIDTH", "norm_histogram",
           "health_metrics", "sharded_health_metrics", "HEALTH_PSUMS"]

# Log2-scale histogram geometry: HIST_BINS bins of HIST_WIDTH octaves
# each, starting at 2**HIST_LO. Bin 0 doubles as the underflow bin
# (exact-zero and sub-2**HIST_LO norms), the last bin as overflow AND the
# non-finite route — fixed at trace time so the bucket assignment is a
# pure per-row predicate (bit-stable across shardings and paddings).
HIST_BINS = 16
HIST_LO = -12
HIST_WIDTH = 2

# Collective census of the sharded form (`analysis/lattice.py`): one
# tupled psum of (per-row norm² partials, packed scalar partials) —
# StableHLO spells the tuple as one all_reduce per leaf.
HEALTH_PSUMS = 2

# Update-to-weight guard against a zero weight vector (the ratio is a
# health signal, not an invariant; +inf there would poison the monitor)
_TINY = 1e-30


def norm_histogram(norms):
    """`f32[m] -> f32[HIST_BINS]` fixed-bin log2 histogram of L2 norms.

    Exact zeros land in bin 0 (underflow), non-finite norms in the last
    bin (overflow — their count also rides the non-finite columns); the
    finite positive range buckets by `floor((log2(n) - HIST_LO) /
    HIST_WIDTH)`, clipped into range.
    """
    finite = jnp.isfinite(norms)
    safe = jnp.where(finite & (norms > 0), norms, jnp.float32(1.0))
    idx = jnp.floor((jnp.log2(safe) - HIST_LO) / HIST_WIDTH).astype(jnp.int32)
    idx = jnp.clip(idx, 0, HIST_BINS - 1)
    idx = jnp.where(norms == 0, 0, idx)
    idx = jnp.where(finite, idx, HIST_BINS - 1)
    onehot = idx[:, None] == jnp.arange(HIST_BINS, dtype=jnp.int32)[None, :]
    return jnp.sum(onehot.astype(jnp.float32), axis=0)


def _partials(G_honest, G_attack, grad_defense, theta_old, theta_new):
    """The d-local reduction partials of one (shard of the) health
    vector: (per-row norm² over the submitted stack, packed scalars).
    Plain `jnp.sum` reductions on purpose — the honest avg/dev²
    subexpressions then match the study pipeline's
    (`metrics.avg_dev_max`), `sum(grad_defense²)` matches its 'Defense
    gradient norm', and the d-vector sums XLA's fuser folds into the
    update phase — so under the study (always, for health) CSE leaves
    only the passes nothing else does: the per-row norms of the
    submitted rows and the theta/update reductions."""
    norm2 = jnp.concatenate([jnp.sum(G_honest * G_honest, axis=1),
                             jnp.sum(G_attack * G_attack, axis=1)])
    avg = jnp.mean(G_honest, axis=0)
    dev = G_honest - avg
    update = theta_old - theta_new
    scalars = jnp.stack([
        jnp.sum(dev * dev),                              # dev² total
        jnp.sum(avg * avg),                              # ||avg||²
        jnp.sum(theta_new * theta_new),                  # ||theta||²
        jnp.sum(update * update),                        # ||update||²
        jnp.sum(grad_defense * grad_defense),            # ||aggregate||²
    ])
    return norm2, scalars


def _finalize(norm2, scalars, m_honest):
    """The health metric dict from the (psum'd) reduction totals, keyed
    by `engine/metrics.py::HEALTH_COLUMNS`. The non-finite signals are
    DERIVED from reductions already on hand — a sum-of-squares is
    NaN/Inf iff its operand holds a NaN/Inf (or overflows f32, which is
    the same emergency one step earlier) — so they cost no pass:
    'Nonfinite submitted' counts rows with a non-finite norm, the
    aggregate/state columns are 0/1 indicators off `||aggregate||²` /
    `||theta||²`."""
    dev2, navg2, w2, u2, agg2 = (scalars[i] for i in range(5))
    if m_honest >= 2:
        var_ratio = ((dev2 / (m_honest - 1)) / navg2).astype(jnp.float32)
    else:
        var_ratio = jnp.float32(jnp.nan)
    weight_norm = jnp.sqrt(w2)
    update_norm = jnp.sqrt(u2)
    return {
        "Var ratio": var_ratio,
        "Weight norm": weight_norm,
        "Update norm": update_norm,
        "Update/weight": update_norm / jnp.maximum(weight_norm, _TINY),
        "Norm hist": norm_histogram(jnp.sqrt(norm2)),
        "Nonfinite submitted": jnp.sum(
            (~jnp.isfinite(norm2)).astype(jnp.float32)),
        "Nonfinite aggregate": (~jnp.isfinite(agg2)).astype(jnp.float32),
        "Nonfinite state": (~jnp.isfinite(w2)).astype(jnp.float32),
    }


def _as_f32(*arrays):
    # Identity for f32 inputs ON PURPOSE (not just an optimization): an
    # f32->f32 convert would make the honest avg/dev² subexpressions
    # structurally different from the study pipeline's and defeat the
    # CSE this module's cost budget leans on
    return tuple(a if a.dtype == jnp.float32 else a.astype(jnp.float32)
                 for a in arrays)


def health_metrics(G_honest, G_attack, grad_defense, theta_old,
                   theta_new):
    """The per-step health vector, single-device form.

    Args:
      G_honest: f32[h, d] — the honest submissions, post fault injection
        (the paper's Var/norm ratio cohort, matching the forensic
        column's definition).
      G_attack: f32[f, d] — the Byzantine rows (f may be 0); the norm
        histogram and non-finite counts cover honest + attack rows, what
        the server actually saw.
      grad_defense: f32[d] — the aggregated defense gradient.
      theta_old / theta_new: f32[d] — parameters before/after the update.
    """
    G_honest, G_attack, grad_defense, theta_old, theta_new = _as_f32(
        G_honest, G_attack, grad_defense, theta_old, theta_new)
    norm2, scalars = _partials(G_honest, G_attack, grad_defense,
                               theta_old, theta_new)
    return _finalize(norm2, scalars, G_honest.shape[0])


def sharded_health_metrics(mesh):
    """The per-step health vector as an explicit d-sharded `shard_map`:
    shard-local `_partials` with the width-aware real-column mask, ONE
    tupled psum (`HEALTH_PSUMS` all_reduce ops — the census
    `analysis/lattice.py` pins), replicated output. Returns a drop-in
    for `health_metrics` (same signature, same dict)."""
    from jax.sharding import PartitionSpec as P

    from byzantinemomentum_tpu.parallel.mesh import MODEL, shard_map

    axis = mesh.shape[MODEL]

    def fn(G_honest, G_attack, grad_defense, theta_old, theta_new):
        G_honest, G_attack, grad_defense, theta_old, theta_new = _as_f32(
            G_honest, G_attack, grad_defense, theta_old, theta_new)
        d = theta_new.shape[0]
        pad = (-d) % axis
        if pad:
            G_honest = jnp.pad(G_honest, ((0, 0), (0, pad)))
            G_attack = jnp.pad(G_attack, ((0, 0), (0, pad)))
            grad_defense = jnp.pad(grad_defense, (0, pad))
            theta_old = jnp.pad(theta_old, (0, pad))
            theta_new = jnp.pad(theta_new, (0, pad))
        m_honest = G_honest.shape[0]

        def kernel(g_hon, g_att, g_def, t_old, t_new):
            # Width-aware real-column mask (`_coord_diag_builder`
            # discipline): the divisibility padding is finite zeros by
            # construction — exact identities for every sum below — but
            # masking the shard inputs keeps the partials correct
            # regardless of what the padder shipped
            width = t_new.shape[0]
            start = lax.axis_index(MODEL).astype(jnp.int32) * width
            real = (start + jnp.arange(width, dtype=jnp.int32)) < d
            zero = jnp.float32(0.0)
            norm2, scalars = _partials(
                jnp.where(real[None, :], g_hon, zero),
                jnp.where(real[None, :], g_att, zero),
                jnp.where(real, g_def, zero),
                jnp.where(real, t_old, zero),
                jnp.where(real, t_new, zero))
            norm2, scalars = lax.psum((norm2, scalars), MODEL)
            return _finalize(norm2, scalars, m_honest)

        out_specs = {
            "Var ratio": P(), "Weight norm": P(), "Update norm": P(),
            "Update/weight": P(), "Norm hist": P(),
            "Nonfinite submitted": P(), "Nonfinite aggregate": P(),
            "Nonfinite state": P(),
        }
        # check_vma=False: the replicated outputs ride the tupled psum
        # (the `_coord_diag_builder` discipline)
        return shard_map(
            kernel, mesh=mesh,
            in_specs=(P(None, MODEL), P(None, MODEL), P(MODEL), P(MODEL),
                      P(MODEL)),
            out_specs=out_specs, check_vma=False,
        )(G_honest, G_attack, grad_defense, theta_old, theta_new)

    return fn
