"""Compositional step-program builder — ONE lowering path under the
(GAR × diagnostics × masked-quorum × sharding) lattice.

Before this module, every feature threaded its own variant through
`engine/step.py`: plain aggregation, `diagnostics=True` forensics (PR 4),
masked dynamic-quorum fault steps (PR 1), and the `--mesh`/`--device-gar`
sharded placements (`parallel/sharded.py`). Each variant re-implemented
the same dispatch skeleton, and the lowering goldens
(`tests/goldens/lowerings.json`) had to enumerate the product by hand.

Here each lattice axis is a *transform* over a single lowering path:

  kernel axis     `defense_kernel(gar, variant, ...)` — the traceable
                  program of ONE (GAR, variant) cell. `variant` selects
                  the kernel family: "plain" (`gar.unchecked`), "diag"
                  (`gar.diagnosed`, the uniform `ops/diag.py` aux) or
                  "masked" (`faults/quorum.py::masked_aggregate`, the
                  dynamic-quorum degradation). This is exactly what the
                  golden cells fingerprint (`analysis/lattice.py` lowers
                  these callables), so the contract surface and the
                  engine execute the same trace by construction.
  mixture axis    `defense_program(defenses, variant, ...)` — a single
                  `--gar` inlines its kernel; a `--gars` mixture
                  `lax.switch`es over per-defense kernels under the
                  variant's `jax.named_scope` (the PR 6 phase names).
  sharding axis   `shard_axis(defenses, mesh, ...)` — every defense
                  rebuilt as an explicit d-sharded kernel
                  (`parallel/sharded.py`: psum'd Gram for the selection
                  rules, shard-local kernels for coordinate-wise rules,
                  native psum'd-Gram diagnostics for krum/bulyan/brute).
  placement axis  `build_step(engine, ...)` — the fused single-device
                  step, the mesh-sharded step, or the `--device-gar`
                  hop step (`device_gar_step` below), all drop-ins for
                  `engine.train_step`.

`Engine._run_defense` / `_run_defense_diag` / `_run_defense_masked` and
`make_device_gar_step` are thin wrappers over these transforms; the
refactor is trace-equivalent (all pre-existing StableHLO goldens are
byte-identical — the drift gate proved it before the lattice was
regenerated).
"""

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["VARIANTS", "SCOPES", "defense_kernel", "defense_program",
           "mixture_index", "shard_axis", "device_gar_step", "build_step"]

# The kernel-family axis (lattice vocabulary shared with
# `analysis/lattice.py` and the golden-cell keys).
VARIANTS = ("plain", "diag", "masked")

# The phase-attribution scope each variant traces under (PR 6 names —
# static by contract, jaxlint BMT-E08).
SCOPES = {"plain": "gar", "diag": "gar_diag", "masked": "gar_masked"}


def defense_kernel(gar, variant, *, f, kwargs=None, dynamic=True):
    """The traceable program of ONE (GAR, variant) lattice cell.

    Returns a callable over the stacked matrix — `(G)` for plain/diag,
    `(G, active)` for masked. `analysis/lattice.py` lowers exactly these
    callables into the golden fingerprints, so the blessed contract and
    the engine's executed trace cannot drift apart.
    """
    kwargs = {} if kwargs is None else kwargs
    if variant == "plain":
        return lambda G: gar.unchecked(G, f=f, **kwargs)
    if variant == "diag":
        return lambda G: gar.diagnosed(G, f=f, **kwargs)
    if variant == "masked":
        from byzantinemomentum_tpu.faults import quorum

        return lambda G, active: quorum.masked_aggregate(
            gar, G, active, f_decl=f, dynamic=dynamic, **kwargs)
    raise ValueError(
        f"Unknown lattice variant {variant!r}; expected one of {VARIANTS}")


def mixture_index(defenses, mix_u):
    """The defense drawn this step: inverse-CDF over the configured
    cumulative frequencies (reference `attack.py:504-509` semantics, one
    shared draw per step — see the divergence note in `engine/step.py`)."""
    cum = jnp.asarray([fc for _, fc, _ in defenses], jnp.float32)
    return jnp.searchsorted(cum, mix_u * cum[-1], side="right").astype(
        jnp.int32).clip(0, len(defenses) - 1)


def defense_program(defenses, variant, *, f, dynamic=True):
    """The mixture axis over `defense_kernel`: one defense inlines its
    kernel, several `lax.switch` over per-defense kernels (the uniform
    diag aux schema / masked return pair is what makes the branches
    structurally compatible). Returns `program(G, mix_u, *extra)` where
    `extra` is `(active,)` for the masked variant."""

    def program(G, mix_u, *extra):
        with jax.named_scope(SCOPES[variant]):
            if len(defenses) == 1:
                gar, _, kwargs = defenses[0]
                return defense_kernel(gar, variant, f=f, kwargs=kwargs,
                                      dynamic=dynamic)(G, *extra)
            branches = [
                (lambda G, gar=gar, kwargs=kwargs:
                 defense_kernel(gar, variant, f=f, kwargs=kwargs,
                                dynamic=dynamic)(G, *extra))
                for gar, _, kwargs in defenses
            ]
            return lax.switch(mixture_index(defenses, mix_u), branches, G)

    return program


def shard_axis(defenses, mesh, *, f):
    """The mesh axis: the defense list with every GAR rebuilt as an
    explicit d-sharded kernel (`parallel/sharded.py::shard_defense_list`
    — psum'd Gram + native sharded diagnostics for the selection rules,
    shard-local kernels for coordinate-wise rules)."""
    from byzantinemomentum_tpu.parallel import sharded

    return sharded.shard_defense_list(defenses, mesh, f=f)


def device_gar_step(engine, gar_device):
    """The heterogeneous-placement axis — the reference's `--device-gar`
    (reference `attack.py:461-465`, `:811-827`): the defense phase (attack
    synthesis + aggregation + influence) runs on a different device, with
    the honest gradient matrix hopping there and the Byzantine rows +
    defense gradient hopping back EVERY step — three separately-compiled
    programs instead of one fused one.

    The whole defense phase hops, so an adaptive attack's line search runs
    entirely on the GAR device (the reference instead moved the stack on
    every inner defense call, `attack.py:505-510` — one hop per step is the
    faithful-but-not-pathological placement; the arithmetic is identical).

    Note: this path uses plain cross-device `device_put` transfers, NOT host
    callbacks, so it works on backends without send/recv callback support.

    Returns `step(state, xs, ys, lr) -> (state, metrics)` — a drop-in for
    `engine.train_step`.
    """
    from byzantinemomentum_tpu.ops import pallas_sort

    dev = jax.devices(gar_device)[0]
    pre = jax.jit(engine._phase_honest)
    # `state` is dead after the post call, so donate it as the fused
    # train_step does — otherwise the hop path doubles peak state memory
    post = jax.jit(engine._phase_update, static_argnums=(11,),
                   donate_argnums=(0,))

    def mid_traced(G_honest, mix_key, fault, attack_state):
        if dev.platform != "tpu":
            # The GAR device cannot run Mosaic kernels
            with pallas_sort.disabled():
                return engine._phase_defense(G_honest, mix_key, fault,
                                             attack_state)
        return engine._phase_defense(G_honest, mix_key, fault, attack_state)

    mid = jax.jit(mid_traced)

    def step(state, xs, ys, lr):
        (rng, mix_key, G_sampled, loss_avg, net_state, new_mw,
         G_honest, fault, new_fb) = pre(state, xs, ys, lr)
        main_dev = list(G_honest.devices())[0]
        # --- the hop (reference `attack.py:811-815`; the tiny fault
        # context — active mask + counter — and the adaptive attack's
        # history pytree hop along with the rows) --- #
        out = mid(jax.device_put(G_honest, dev),
                  jax.device_put(mix_key, dev),
                  None if fault is None else jax.device_put(fault, dev),
                  jax.device_put(state.attack_state, dev))
        (G_attack, grad_defense, accept_ratio, fault_metrics, diag_metrics,
         attack_state) = jax.device_put(out, main_dev)
        batch = engine._batch_of(xs)
        return post(state, rng, G_sampled, loss_avg, net_state, new_mw,
                    G_honest, G_attack, grad_defense, accept_ratio, lr,
                    batch, fault_metrics, new_fb, diag_metrics, attack_state)

    return step


def build_step(engine, *, mesh=None, state_example=None, gar_device=None,
               multi=False):
    """The placement axis, as one entry point: compile the engine's step
    for its placement cell of the lattice.

    Args:
      engine: a built `Engine`.
      mesh: a (workers, model) `Mesh` — the multi-chip sharded placement
        (requires `state_example`; the defenses are rebuilt through
        `shard_axis` at trace time).
      state_example: a `TrainState` whose shapes pin the sharding specs
        (mesh placement only).
      gar_device: a jax platform/device string — the `--device-gar`
        heterogeneous placement (`device_gar_step`).
      multi: build the fused M-steps-per-dispatch program
        (`lax.scan`) instead of the single step.

    Returns a `step(state, xs, ys, lr[s]) -> (state, metrics)` drop-in.
    """
    if mesh is not None and gar_device is not None:
        raise ValueError(
            "mesh sharding and device-GAR placement are exclusive lattice "
            "cells; pass one of mesh= / gar_device=")
    if mesh is not None:
        if state_example is None:
            raise ValueError("mesh placement needs state_example to pin "
                             "the sharding specs")
        from byzantinemomentum_tpu.parallel import sharded

        builder = (sharded.sharded_train_multi if multi
                   else sharded.sharded_train_step)
        return builder(engine, mesh, state_example)
    if gar_device is not None:
        if multi:
            raise ValueError(
                "device-GAR placement has no fused multi-step program "
                "(the per-step hop is the point of the placement)")
        return device_gar_step(engine, gar_device)
    return engine.train_multi if multi else engine.train_step
