"""The jitted training step and its builder.

One call to `Engine.train_step(state, xs, ys, lr)` performs everything the
reference does per iteration of its hot loop (reference `attack.py:752-882`):

  honest phase  — `jax.vmap` of the per-worker loss/gradient over the worker
                  axis (the reference's sequential backprops,
                  `attack.py:786-795`), with the Nesterov parameter lookahead
                  variant (`attack.py:757-783`);
  clipping      — per-sampled-gradient L2 cap (`attack.py:776-779, 791-794`);
  momentum      — one of the three placements (`attack.py:799-810, 832-839`);
  attack        — Byzantine row synthesis, with adaptive line searches
                  against the inlined defense (`attack.py:818`);
  defense       — the GAR kernel over the stacked (n, d) matrix
                  (`attack.py:821`);
  update        — SGD with weight decay (`attack.py:832-839`,
                  torch-SGD semantics from `attack.py:543-544`);
  metrics       — the 24-column study pipeline, in-graph
                  (`attack.py:842-878`).

Multi-local-step SGD (`--nb-local-steps > 1`) is implemented (via
`lax.scan` over local steps), unlike the reference where it is advertised
but hard-disabled (`attack.py:796-798`).

Phase attribution (PR 6): every phase is wrapped in a STATIC
`jax.named_scope` (`honest`, `attack`, `gar`/`gar_masked`/`gar_diag`,
`update`, `metrics`), so each compiled HLO op carries its phase in its
metadata `op_name` and `obs/attrib/` can attribute a profiler trace per
phase without hand archaeology. The names are trace-time metadata only —
they change no computation, no cache key, and no donation; dynamic
(formatted) scope names are a lint error (jaxlint BMT-E08).
"""

import contextlib
import functools
import os
import typing

import jax
import jax.numpy as jnp
from jax import lax

from byzantinemomentum_tpu.engine import metrics as metrics_mod
from byzantinemomentum_tpu.engine import program as program_mod
from byzantinemomentum_tpu.engine.state import TrainState, init_state
from byzantinemomentum_tpu.models import flatten_params
from byzantinemomentum_tpu.models.core import BN_MOMENTUM

__all__ = ["Engine", "build_engine", "grouped_disabled", "grouped_sharded"]


class _FaultCtx(typing.NamedTuple):
    """This step's injected-fault context, threaded from the honest phase
    into the defense (`faults/inject.py` output)."""

    active: jax.Array    # bool[n] — rows present this step (drops excluded)
    injected: jax.Array  # i32[] — fault conditions live this step

# Trace-time mode for the merged-batch grouped honest phase:
#   None          — single-device: use the grouped path when available;
#   "off"         — always trace the vmapped phase;
#   a jax Mesh    — multi-chip (`--mesh`): run the grouped program PER
#                   workers-axis shard inside an explicit `shard_map`
#                   (`_workers_grad_grouped_sharded`) — the jit sharding
#                   propagator cannot batch-shard the channel-group form
#                   on its own, but each shard's local workers can run it.
_grouped_mode = None


@contextlib.contextmanager
def _grouped_mode_as(mode):
    global _grouped_mode
    saved = _grouped_mode
    _grouped_mode = mode
    try:
        yield
    finally:
        _grouped_mode = saved


def grouped_disabled():
    """Trace the vmapped (non-grouped) honest phase within this context.

    Safe with the jitted `Engine.train_*` entry points: they pass the
    current mode as a static jit argument (`Engine._mode_jit`), so calls
    inside/outside the context hit different trace-cache entries instead of
    reusing whichever mode was traced first."""
    return _grouped_mode_as("off")


def grouped_sharded(mesh):
    """Trace the honest phase as a `shard_map` over the mesh's workers axis
    with the grouped program on each shard's local workers (falls back to
    the vmapped form for models without `apply_grouped` or when the worker
    axis does not divide the sampled count). Mode caching: see
    `grouped_disabled`."""
    return _grouped_mode_as(mesh)


def _worker_pad_rows(S):
    """Extra worker rows the grouped honest phase appends at trace time.

    `BMT_WORKER_PAD=<S'>` pads the sampled-worker stack to S' rows so the
    worker-packing machinery (`models/core.py::_worker_packing`) can
    engage on counts it otherwise cannot (WRN's S = 9 has no divisor P
    with P*C lane-aligned; S' = 12 buys P = 4/2 for C = 160/320). Like
    `BMT_NO_WORKER_PACK`, the knob is read at TRACE time — set it before
    the engine compiles, not between steps. Targets past 2S are clamped
    (recycling each real row more than once buys no further packing
    factor at WRN scale and only multiplies dummy compute)."""
    raw = os.environ.get("BMT_WORKER_PAD", "")
    if not raw:
        return 0
    try:
        target = int(raw)
    except ValueError:
        from byzantinemomentum_tpu import utils
        utils.warning(f"BMT_WORKER_PAD={raw!r} is not an integer; ignored")
        return 0
    return min(max(0, target - S), S)


def _cast_tree(tree, dtype):
    """Cast every inexact leaf of a pytree to `dtype` (ints/keys untouched)."""
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact) else x, tree)


def _clip_rows(G, clip):
    """Per-row L2 clip: row *= clip/||row|| iff ||row|| > clip
    (reference `attack.py:776-779`)."""
    if clip is None:
        return G
    norms = jnp.sqrt(jnp.sum(G * G, axis=1, keepdims=True))
    scale = jnp.where(norms > clip, clip / norms, 1.0)
    return G * scale


def compose_bn_updates(net_state0, per_worker_states, count, local_steps=1):
    """Sequential-equivalent composition of per-worker BatchNorm running-stat
    updates.

    The reference runs workers sequentially through one module, so running
    stats fold as r_k = (1-m) r_{k-1} + m s_k over the k-th worker's batch
    stats (reference `experiments/model.py:246-248`, `models/empire.py:36-47`).
    Under vmap every worker computed r0-based chains instead; inverting each
    chain for its batch stats and refolding the full worker-major sequence
    yields the exact sequential result:
      r_T = (1-m)^T r0 + m * sum_t (1-m)^(T-1-t) s_t,  T = count*local_steps.

    `per_worker_states` leaves: (count, ...) for local_steps == 1, else
    (count, local_steps, ...) — each worker's chain of running states, all
    chained from the shared r0.
    """
    if not jax.tree.leaves(net_state0):
        return net_state0
    m = BN_MOMENTUM
    total = count * local_steps
    decay = (1.0 - m) ** total
    weights = (1.0 - m) ** jnp.arange(total - 1, -1, -1, dtype=jnp.float32)

    def fold(r0, new_stack):
        # The chain inversion is precision-sensitive; run it in at least f32
        # (f64 stays f64) and cast back so low-precision dtypes keep the
        # state dtype stable (donation requires output dtypes to match)
        out_dtype = r0.dtype
        acc = jnp.promote_types(out_dtype, jnp.float32)
        r0 = r0.astype(acc)
        new_stack = new_stack.astype(acc)
        if local_steps == 1:
            s = (new_stack - (1.0 - m) * r0) / m  # per-worker batch stats
        else:
            # Invert each worker's chain: new[j] = (1-m) new[j-1] + m s[j]
            prev = jnp.concatenate([
                jnp.broadcast_to(r0, new_stack[:, :1].shape),
                new_stack[:, :-1]], axis=1)
            s = ((new_stack - (1.0 - m) * prev) / m).reshape(
                (total,) + r0.shape)
        contrib = jnp.tensordot(weights, s, axes=1)
        return (decay * r0 + m * contrib).astype(out_dtype)

    return jax.tree.map(fold, net_state0, per_worker_states)


class Engine:
    """Compiled training/eval programs for one experiment configuration."""

    def __init__(self, cfg, model_def, loss, criterion, defenses, attack,
                 attack_kwargs, optimizer=None, faults=None):
        """Use `build_engine` — this constructor wires the already-resolved
        pieces.

        Args:
          cfg: `EngineConfig`.
          model_def: `models.ModelDef`.
          loss: callable `(output, target, theta) -> scalar`.
          criterion: callable `(output, target) -> f32[2]`.
          defenses: list of `(gar, freq_cum, kwargs)` — one entry for a
            single `--gar`, several for a `--gars` random mixture
            (reference `attack.py:467-517`).
          attack: `attacks.Attack` (or None when f_real == 0 paths are
            exercised with the `nan` default).
          attack_kwargs: plugin args for the attack.
          faults: optional `faults.FaultSchedule` — per-step fault
            injection into the stacked gradient batch before aggregation,
            plus the dynamic-quorum/quarantine degradation policy
            (`cfg.fault_*`). None (the default, and what an empty plan
            compiles to) traces the exact fault-free program.
        """
        self.cfg = cfg
        self.faults = faults
        # f64 without the x64 flag would silently truncate every cast to f32
        # while the run labels itself float64 — refuse upfront (the CLI flips
        # the flag itself; library callers must opt in explicitly)
        if (jnp.float64 in (cfg.jnp_dtype, cfg.jnp_compute_dtype)
                and not jax.config.jax_enable_x64):
            raise ValueError(
                "dtype float64 requires x64 mode: call "
                "jax.config.update('jax_enable_x64', True) before building "
                "the engine")
        self.model_def = model_def
        self.loss = loss
        self.criterion = criterion
        self.defenses = defenses
        self.attack = attack
        self.attack_kwargs = dict(attack_kwargs or {})
        if optimizer is None:
            from byzantinemomentum_tpu import optim
            optimizer = optim.build("sgd", weight_decay=cfg.weight_decay)
        self.optimizer = optimizer

        params, net_state = model_def.init(jax.random.PRNGKey(0))
        # Parameters live in cfg.dtype (reference Configuration's dtype,
        # `configuration.py:26-101`); the unravel closure is built over the
        # cast leaves so the flat vector round-trips in that dtype.
        params = _cast_tree(params, cfg.jnp_dtype)
        theta0, unravel = flatten_params(params)
        self.d = theta0.shape[0]
        self.unravel = unravel
        self._net_state0 = _cast_tree(net_state, cfg.jnp_dtype)

        self.train_step = self._mode_jit(self._train_step)
        self.train_multi = self._mode_jit(self._train_multi)
        self.eval_step = jax.jit(self._eval_step)
        self.eval_many = jax.jit(self._eval_many)
        self._train_data = None
        self._test_data = None

    def _mode_jit(self, fn):
        """Jit `fn(state, *args)` with the CURRENT grouped mode as a static
        argument, read at call time: entering `grouped_disabled()` /
        `grouped_sharded(mesh)` after a first trace retraces instead of
        silently reusing the cached trace's old mode (the mode is trace-time
        state, `_grouped_mode` above)."""
        @functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
        def jitted(mode, state, *args):
            with _grouped_mode_as(mode):
                return fn(state, *args)

        def call(state, *args):
            return jitted(_grouped_mode, state, *args)

        # Keep `.lower()` reachable for FLOP accounting (bench.py)
        call.lower = lambda state, *args: jitted.lower(
            _grouped_mode, state, *args)
        return call

    def attach_data(self, train_data, test_data=None):
        """Enable the device-resident input path (`data/device.py`): batches
        materialize in-graph from `(S, B)` index arrays, removing the
        host->device batch transfer from the step critical path."""
        self._train_data = train_data
        self._test_data = test_data
        self.train_step_indexed = self._mode_jit(self._train_step_indexed)
        self.train_multi_indexed = self._mode_jit(self._train_multi_indexed)
        self.eval_step_indexed = jax.jit(self._eval_step_indexed)
        self.eval_many_indexed = jax.jit(self._eval_many_indexed)
        return self

    def _train_step_indexed(self, state, idx, flips, lr):
        xs, ys = self._train_data.gather(idx, flips)
        return self._train_step(state, xs, ys, lr)

    # Multi-step programs: M training steps per dispatch via `lax.scan` —
    # the per-step trajectory (PRNG folds, batch order, metrics) is
    # IDENTICAL to M single dispatches; only the host round-trips go away
    # (the remote-TPU tunnel costs ~2.5 ms per program execution).

    def _train_multi(self, state, xs, ys, lrs):
        """xs: f32[M, S, B, ...], lrs: f32[M] -> (state, stacked metrics)."""
        def body(st, inp):
            x, y, lr = inp
            st, m = self._train_step(st, x, y, lr)
            return st, m
        return lax.scan(body, state, (xs, ys, lrs))

    def _train_multi_indexed(self, state, idx, flips, lrs):
        def body(st, inp):
            i, fl, lr = inp
            st, m = self._train_step_indexed(st, i, fl, lr)
            return st, m
        return lax.scan(body, state, (idx, flips, lrs))

    def _eval_step_indexed(self, theta, net_state, idx, flips):
        x, y = self._test_data.gather(idx, flips)
        return self._eval_step(theta, net_state, x, y)

    # ----------------------------------------------------------------- #
    # Initialization

    def init(self, key, params=None, net_state=None, *, study=None):
        """Build a fresh `TrainState` (reference `attack.py:668-681`)."""
        study = self.cfg.study if study is None else study
        if params is None:
            params, net_state = self.model_def.init(key)
        params = _cast_tree(params, self.cfg.jnp_dtype)
        net_state = _cast_tree(net_state, self.cfg.jnp_dtype)
        theta, _ = flatten_params(params)
        # The straggler stale-submission buffer exists only when the fault
        # schedule needs it (empty plans pay nothing — `faults/inject.py`)
        buffer_rows = (self.cfg.nb_honests
                       if self.faults is not None and self.faults.has_stale
                       else 0)
        attack_state = ()
        if self.attack is not None and self.attack.stateful:
            attack_state = self.attack.state_init(
                f_real=self.cfg.nb_real_byz, d=self.d)
        return init_state(self.cfg, theta, net_state,
                          jax.random.fold_in(key, 1), study=study,
                          opt_state=self.optimizer.init(theta),
                          fault_buffer_rows=buffer_rows,
                          attack_state=attack_state)

    # ----------------------------------------------------------------- #
    # Per-worker gradient

    def _worker_grad(self, theta, net_state, x, y, rng):
        cdtype = self.cfg.jnp_compute_dtype
        if jnp.issubdtype(x.dtype, jnp.inexact):
            x = x.astype(cdtype)

        def scalar_loss(th):
            # Forward/backward run in the compute dtype; when it differs from
            # the parameter dtype (mixed precision) the casts' transposes
            # bring the gradient back in the parameter dtype — bf16 MXU
            # matmuls with f32 master weights, momentum and GAR space.
            params = _cast_tree(self.unravel(th), cdtype)
            out, new_state = self.model_def.apply(
                params, net_state, x, train=True, rng=rng)
            return self.loss(out, y, th), new_state
        (loss_val, new_state), grad = jax.value_and_grad(
            scalar_loss, has_aux=True)(theta)
        return loss_val, grad, new_state

    def _workers_grad_grouped(self, theta_eff, net_state, xs, ys, wkeys,
                              theta_axis):
        """Merged-batch grouped-worker gradients — the honest phase as ONE
        forward/backward over all S worker batches.

        Same math as `vmap(_worker_grad)` (the model's `apply_grouped`
        mirrors its `apply` op-for-op, including per-worker BN batch stats
        and identical per-worker-key dropout draws), but the worker axis is
        carried as channel groups, so each per-worker conv weight gradient
        compiles to one clean grouped convolution instead of vmap's
        transpose-wrapped batch-group conv — measured 25% (bf16-mixed) to
        30% (f32) faster full training steps on TPU v5e for the reference's
        CIFAR CNN (accelerates reference `attack.py:786-795`).
        """
        th_s, xs = self._grouped_operands(theta_eff, xs, theta_axis)
        pad = _worker_pad_rows(xs.shape[0])
        if pad:
            return self._grouped_padded(th_s, net_state, xs, ys, wkeys, pad)
        return self._grouped_local(th_s, net_state, xs, ys, wkeys)

    def _grouped_padded(self, th_s, net_state, xs, ys, wkeys, pad):
        """The grouped phase with `pad` recycled worker rows appended —
        the `BMT_WORKER_PAD` packing escape (PERF_NOTES.md r7): a worker
        count like WRN's S = 9 admits no divisor P with P*C lane-aligned,
        so the worker-packing machinery (`models/core.py`) cannot engage;
        padding the stack to e.g. S' = 12 buys P = 4/2 packings for
        C = 160/320 at the price of the dummy rows' compute plus the
        block-diagonal zero FLOPs. Worker rows are independent (the
        summed grouped loss has block-diagonal structure), so the kept
        rows' gradients, losses and BatchNorm statistics are STRUCTURALLY
        the unpadded ones — no dummy-row value ever feeds a kept row;
        numerically they match to reduction rounding (XLA's grouped-conv
        codegen varies with the group count, exactly as the packed-vs-
        unpacked A/B already does). The dummy rows recycle the leading
        workers' inputs and parameters with derived (discarded) dropout
        keys, and every output is sliced back before anything downstream
        sees it."""
        S = xs.shape[0]
        idx = jnp.arange(pad) % S

        def recycle(a):
            return jnp.concatenate([a, a[idx]])

        extra_keys = jax.vmap(
            lambda k: jax.random.fold_in(k, 0x5AD))(wkeys[idx])
        losses, grads, states = self._grouped_local(
            recycle(th_s), net_state, recycle(xs), recycle(ys),
            jnp.concatenate([wkeys, extra_keys]))
        return (losses[:S], grads[:S],
                jax.tree.map(lambda leaf: leaf[:S], states))

    def _grouped_operands(self, theta_eff, xs, theta_axis):
        cfg = self.cfg
        th_s = (jnp.broadcast_to(theta_eff, (cfg.nb_sampled,)
                                 + theta_eff.shape)
                if theta_axis is None else theta_eff)
        if jnp.issubdtype(xs.dtype, jnp.inexact):
            xs = xs.astype(cfg.jnp_compute_dtype)
        return th_s, xs

    def _grouped_local(self, th_s, net_state, xs, ys, wkeys):
        """The grouped forward/backward over whatever worker rows the caller
        holds — the whole stack single-device, or one shard's slice inside
        `_workers_grad_grouped_sharded`."""
        cdtype = self.cfg.jnp_compute_dtype

        def scalar_loss(th_s):
            params_s = _cast_tree(jax.vmap(self.unravel)(th_s), cdtype)
            out, new_states = self.model_def.apply_grouped(
                params_s, net_state, xs, train=True, rng=wkeys)
            per_worker = jax.vmap(self.loss)(out, ys, th_s)
            # Row gradients are independent (worker j's loss only touches
            # th_s[j]), so grad of the sum IS the per-worker gradient stack
            return jnp.sum(per_worker), (per_worker, new_states)

        (_, (losses, new_states)), grads = jax.value_and_grad(
            scalar_loss, has_aux=True)(th_s)
        return losses, grads, new_states

    def _workers_grad_grouped_sharded(self, mesh, theta_eff, net_state, xs,
                                      ys, wkeys, theta_axis):
        """Multi-chip grouped honest phase: `shard_map` over the mesh's
        workers axis, each shard running the merged grouped program on its
        local worker rows (same trajectory as the single-device grouped and
        vmapped paths — per-worker dropout keys shard with their rows).

        Worker rows are data-parallel, so the per-shard backward needs no
        collectives; the parameter stack enters replicated on `d` (XLA
        inserts the all-gather of the model-sharded theta at the boundary)
        and the (S, d) gradient rows leave workers-sharded, exactly the
        layout the clip/momentum algebra and the d-sharded GAR kernels
        reshard from today. Compute replicates over the model axis (the
        per-worker BatchNorm statistics need each worker's full batch on
        one device).
        """
        from jax.sharding import PartitionSpec as P

        from byzantinemomentum_tpu.parallel.mesh import WORKERS, shard_map

        th_s, xs = self._grouped_operands(theta_eff, xs, theta_axis)
        ns_spec = jax.tree.map(lambda _: P(), net_state)
        states_spec = jax.tree.map(lambda _: P(WORKERS), net_state)
        return shard_map(
            self._grouped_local,
            mesh=mesh,
            in_specs=(P(WORKERS), ns_spec, P(WORKERS), P(WORKERS),
                      P(WORKERS)),
            out_specs=(P(WORKERS), P(WORKERS), states_spec),
            check_vma=False,
        )(th_s, net_state, xs, ys, wkeys)

    def _local_steps(self, theta, net_state, xs, ys, rng, lr):
        """`k` local SGD steps; the submitted gradient is the accumulated
        parameter displacement divided by the learning rate — the standard
        local-SGD pseudo-gradient (capability the reference gates off,
        `attack.py:796-798`). `xs: f32[k, B, ...]`."""
        rngs = jax.random.split(rng, xs.shape[0])
        def body(carry, inputs):
            th, st = carry
            x, y, r = inputs
            loss_val, grad, new_st = self._worker_grad(th, st, x, y, r)
            return (th - lr * grad, new_st), (loss_val, new_st)
        (theta_end, _), (losses, state_chain) = lax.scan(
            body, (theta, net_state), (xs, ys, rngs))
        grad = (theta - theta_end) / lr
        # state_chain: each local step's running state, (k, ...) per leaf —
        # compose_bn_updates needs the whole chain to stay exact
        return losses[0], grad, state_chain

    # ----------------------------------------------------------------- #
    # Defense dispatch (single GAR or per-step random mixture)
    #
    # The dispatchers below are thin wrappers over the compositional
    # program builder (`engine/program.py`): each lattice axis — kernel
    # variant (plain/diag/masked), mixture, sharding, placement — is a
    # transform over ONE lowering path, and `analysis/lattice.py` lowers
    # the same `defense_kernel` callables into the golden fingerprints.
    #
    # DELIBERATE DIVERGENCE from the reference (default mode): a `--gars`
    # mixture here draws ONE GAR per step (`mix_u` is shared by the attack's
    # inner defense evaluations, the outer aggregation and the influence),
    # while the reference re-draws `random.random()` on every defense call
    # (reference `attack.py:504-509`), so its adaptive attacks line-search
    # against a per-call random GAR. Per-step drawing makes the attack
    # optimize against the defense actually used that step — deterministic
    # under the step PRNG, and at least as favorable to the attacker.
    #
    # `cfg.gars_per_call` restores the reference's per-call semantics: each
    # defense invocation derives fresh entropy by folding a content hash of
    # its operand into the step's mixture key (`_per_call_uniform`). Distinct
    # line-search probes present distinct stacked matrices, so each inner
    # evaluation re-draws — the traceable counterpart of the reference's
    # per-call `random.random()` (an impure counter cannot live inside a
    # `lax.while_loop` body; operand-derived entropy can).

    def _per_call_uniform(self, key, gradients):
        """Fresh U[0,1) per defense invocation: fold a content hash of the
        operand into the step's mixture key.

        The hash covers EVERY element and is position-dependent (each bit
        pattern scaled by a Knuth-constant multiple of its flat index before
        the wraparound sum), so probes that differ in any single coordinate
        — e.g. the `bulyan` attack's target-coordinate direction — or only
        by a permutation still re-draw.

        Residual divergence (quantified in
        `tests/test_engine.py::test_per_call_mixture_draw_counts_one_step`):
        two invocations on byte-identical operand matrices within one step
        draw the SAME member, where the reference's impure
        `random.random()` (reference `attack.py:504-509`) would re-draw
        independently. Real attacks' line-search probes are never
        byte-identical (each probe varies the factor), so the divergence is
        unreachable from the shipped attacks; distinct-operand draws match
        the configured frequencies."""
        bits = lax.bitcast_convert_type(
            gradients.astype(jnp.float32), jnp.uint32)
        mult = (jnp.arange(bits.size, dtype=jnp.uint32).reshape(bits.shape)
                * jnp.uint32(2654435761) | jnp.uint32(1))
        h = jnp.sum(bits * mult, dtype=jnp.uint32)
        return jax.random.uniform(jax.random.fold_in(key, h))

    def _run_defense(self, G, mix_u):
        """Thin wrapper over the compositional builder
        (`engine/program.py`): the plain-variant defense program over this
        engine's defense list."""
        return program_mod.defense_program(
            self.defenses, "plain", f=self.cfg.nb_decl_byz)(G, mix_u)

    def _run_defense_diag(self, G, mix_u):
        """The diag-variant defense program (`engine/program.py`): returns
        `(aggregate, aux)` with the uniform `ops/diag.py` aux schema (the
        schema uniformity is what lets a `--gars` mixture `lax.switch`
        over the diagnostic branches). Only traced when
        `cfg.gar_diagnostics` — the False path compiles the exact
        pre-diagnostics program."""
        return program_mod.defense_program(
            self.defenses, "diag", f=self.cfg.nb_decl_byz)(G, mix_u)

    def _mixture_index(self, mix_u):
        return program_mod.mixture_index(self.defenses, mix_u)

    def _run_influence(self, G_honest, G_attack, mix_u):
        cfg = self.cfg
        nan = jnp.float32(jnp.nan)

        def one(gar, kwargs):
            if gar.influence is None:
                return nan
            return jnp.float32(gar.influence(
                G_honest, G_attack, f=cfg.nb_decl_byz, **kwargs))

        # The acceptation-ratio readout is a study metric, not server work
        with jax.named_scope("metrics"):
            if len(self.defenses) == 1:
                gar, _, kwargs = self.defenses[0]
                return one(gar, kwargs)
            idx = self._mixture_index(mix_u)
            return lax.switch(
                idx,
                [lambda g=gar, k=kwargs: one(g, k)
                 for gar, _, kwargs in self.defenses])

    # ----------------------------------------------------------------- #
    # The step

    def _phase_honest(self, state: TrainState, xs, ys, lr):
        """Honest phase + momentum placement on honest rows: everything up
        to (and excluding) the attack (reference `attack.py:752-810`).
        Split out so `--device-gar` can run the defense phase on another
        device (`make_device_gar_step`); the fused `_train_step` inlines all
        three phases into one program."""
        with jax.named_scope("honest"):
            return self._phase_honest_impl(state, xs, ys, lr)

    def _phase_honest_impl(self, state: TrainState, xs, ys, lr):
        cfg = self.cfg
        S, h = cfg.nb_sampled, cfg.nb_honests
        mu, damp = cfg.momentum, cfg.dampening
        # The lr arrives as an f32 scalar; cast so the momentum/update algebra
        # stays in the parameter dtype (f32*bf16 would silently promote)
        lr = jnp.asarray(lr).astype(state.theta.dtype)

        rng, mix_key, *wkeys = jax.random.split(state.rng, S + 2)
        wkeys = jnp.stack(wkeys)

        # --- honest phase (vmapped; reference `attack.py:752-795`) --- #
        if cfg.nesterov:
            if cfg.momentum_at == "worker":
                # Per-worker lookahead theta - mu*lr*m_i; study extras beyond
                # the h buffers use zero lookahead (the reference would index
                # out of bounds in that configuration, `attack.py:766-767`).
                pad = jnp.zeros((S - h, self.d), state.theta.dtype)
                buffers = jnp.concatenate([state.momentum_workers, pad])
                theta_eff = state.theta[None, :] - (mu * lr) * buffers
                theta_axis = 0
            else:
                theta_eff = state.theta - (mu * lr) * state.momentum_server
                theta_axis = None
        else:
            theta_eff = state.theta
            theta_axis = None

        mode = _grouped_mode
        use_grouped = (cfg.grouped_workers and mode != "off"
                       and self.model_def.apply_grouped is not None
                       and cfg.nb_local_steps == 1)
        if use_grouped and mode is not None:
            # A mesh: shard-mapped grouped phase, if the workers axis
            # divides the sampled rows (otherwise fall through to vmap,
            # which the jit propagator shards on its own)
            from byzantinemomentum_tpu.parallel.mesh import WORKERS
            use_grouped = S % mode.shape[WORKERS] == 0
        if use_grouped:
            if mode is not None:
                losses, grads, new_states = self._workers_grad_grouped_sharded(
                    mode, theta_eff, state.net_state, xs, ys, wkeys,
                    theta_axis)
            else:
                losses, grads, new_states = self._workers_grad_grouped(
                    theta_eff, state.net_state, xs, ys, wkeys, theta_axis)
        else:
            if cfg.nb_local_steps == 1:
                worker = self._worker_grad
            else:
                worker = functools.partial(self._local_steps, lr=lr)
            losses, grads, new_states = jax.vmap(
                worker, in_axes=(theta_axis, None, 0, 0, 0))(
                    theta_eff, state.net_state, xs, ys, wkeys)

        G_sampled = _clip_rows(grads, cfg.gradient_clip)
        loss_avg = jnp.mean(losses)
        net_state = compose_bn_updates(state.net_state, new_states, S,
                                       cfg.nb_local_steps)

        # --- momentum placement on honest rows (`attack.py:799-810`) --- #
        if cfg.momentum_at == "worker":
            new_mw = mu * state.momentum_workers + (1.0 - damp) * G_sampled[:h]
            G_honest = new_mw
        elif cfg.momentum_at == "server":
            new_mw = state.momentum_workers
            G_honest = (1.0 - damp) * G_sampled[:h] + mu * state.momentum_server
        else:
            new_mw = state.momentum_workers
            G_honest = G_sampled[:h]

        # --- fault injection on the submitted rows (`faults/inject.py`):
        # the faults mangle what each worker SHIPS this step (stale copies,
        # corruption, duplication), never the momentum buffers — a
        # transient fault must not poison the worker's own future steps
        if self.faults is not None:
            from byzantinemomentum_tpu.faults import inject as inject_mod
            G_honest, new_fb, active, injected = inject_mod.inject(
                self.faults, state.steps, G_honest, state.fault_buffer)
            fault = _FaultCtx(active=active, injected=injected)
        else:
            new_fb = state.fault_buffer
            fault = None

        return (rng, mix_key, G_sampled, loss_avg, net_state, new_mw,
                G_honest, fault, new_fb)

    def _phase_defense(self, G_honest, mix_key, fault=None, attack_state=()):
        """Attack synthesis + aggregation + influence (reference
        `attack.py:818-822`). Pure in (G_honest, mix_key, fault,
        attack_state) given the static config, so it compiles for whatever
        device its inputs live on. With a `fault` context the aggregation
        runs the degradation policy: absent rows masked out, non-finite
        rows quarantined (`cfg.fault_quarantine`) and the effective quorum
        recomputed (`cfg.fault_dynamic_quorum`); returns the fault metric
        dict as the fourth element (None without faults). The fifth
        element is the forensic metric dict when `cfg.gar_diagnostics` is
        on with the study active (None otherwise): the outer aggregation
        runs through the GAR's diagnostics kernel and its aux pytree is
        digested in-graph (`engine/metrics.py::forensic_metrics`) — the
        attack's line-search probes keep hitting the plain kernels. The
        sixth element is the attack's updated history pytree (stateful
        attacks only — `attacks/__init__.py` state hook; `()` in, `()`
        out otherwise)."""
        cfg = self.cfg
        mix_u = jax.random.uniform(mix_key)
        per_call = cfg.gars_per_call and len(self.defenses) > 1

        def defense_fn(gradients, f):
            u = (self._per_call_uniform(mix_key, gradients)
                 if per_call else mix_u)
            # Adaptive attacks line-search against the defense the server
            # actually runs — including its masked degradation under
            # faults (probes share the step's active set; a probe of a
            # different row count falls back to the unmasked kernel)
            if (fault is not None
                    and gradients.shape[0] == fault.active.shape[0]):
                return self._run_defense_masked(
                    gradients, u, fault.active)[0]
            return self._run_defense(gradients, u)

        if cfg.nb_real_byz > 0:
            # The "attack" scope encloses the adaptive line search's inner
            # defense calls too: they nest `attack/.../gar/...` and the
            # attribution's outermost-first precedence charges them to the
            # attack, matching PERF_NOTES' "attack incl. its defense call"
            with jax.named_scope("attack"):
                if self.attack.stateful:
                    G_attack, attack_state = self.attack.unchecked(
                        G_honest, f_decl=cfg.nb_decl_byz,
                        f_real=cfg.nb_real_byz,
                        defense=defense_fn, state=attack_state,
                        **self.attack_kwargs)
                else:
                    G_attack = self.attack.unchecked(
                        G_honest, f_decl=cfg.nb_decl_byz,
                        f_real=cfg.nb_real_byz,
                        defense=defense_fn, **self.attack_kwargs)
                # Attack internals (line-search factors) may promote to
                # f32; pin the Byzantine rows back to the gradient dtype
                G_attack = G_attack.astype(G_honest.dtype)
        else:
            G_attack = jnp.zeros((0, self.d), G_honest.dtype)

        G_all = jnp.concatenate([G_honest, G_attack])
        if per_call:
            # The outer aggregation and the influence each re-draw too, as
            # the reference's per-call random.random() does
            mix_u = self._per_call_uniform(mix_key, G_all)
            infl_u = self._per_call_uniform(
                jax.random.fold_in(mix_key, 1), G_all)
        else:
            infl_u = mix_u

        diagnostics = cfg.gar_diagnostics and cfg.study

        if fault is None:
            if diagnostics:
                # One diagnostics call yields BOTH the aggregate and the
                # aux (the kernels share their distance matrix / weights
                # between the two outputs — no double aggregation)
                grad_defense, aux = self._run_defense_diag(G_all, mix_u)
                grad_defense = grad_defense.astype(G_honest.dtype)
                diag_metrics = metrics_mod.forensic_metrics(aux, G_honest)
            else:
                grad_defense = self._run_defense(G_all, mix_u).astype(
                    G_honest.dtype)
                diag_metrics = None
            accept_ratio = self._run_influence(G_honest, G_attack, infl_u)
            return (G_attack, grad_defense, accept_ratio, None, diag_metrics,
                    attack_state)

        active = fault.active
        if cfg.fault_quarantine:
            from byzantinemomentum_tpu.faults import sanitize
            active, _ = sanitize.quarantine(G_all, active)
        grad_defense, f_eff = self._run_defense_masked(G_all, mix_u, active)
        grad_defense = grad_defense.astype(G_honest.dtype)
        accept_ratio = self._run_influence(G_honest, G_attack, infl_u)
        fault_metrics = {
            "Faults injected": fault.injected,
            "Workers active": jnp.sum(active.astype(jnp.int32)),
            "Quorum f": f_eff,
        }
        diag_metrics = None
        if diagnostics:
            # Under faults the authoritative aggregate stays the masked
            # degradation kernel above; the diagnostics view re-runs the
            # plain rule on the full stack (fault steps are rare; the
            # selection read-out deliberately shows what the UNDEGRADED
            # rule would have chosen) plus the post-quarantine active mask
            # so the suspicion tracker sees who was quarantined
            _, aux = self._run_defense_diag(G_all, mix_u)
            diag_metrics = metrics_mod.forensic_metrics(aux, G_honest)
            diag_metrics["Active mask"] = active.astype(jnp.float32)
        return (G_attack, grad_defense, accept_ratio, fault_metrics,
                diag_metrics, attack_state)

    def _run_defense_masked(self, G, mix_u, active):
        """The masked-variant defense program (`engine/program.py`):
        aggregate the active rows only, with the per-GAR effective quorum
        (`faults/quorum.py`). Returns (f32[d], i32[] effective f)."""
        return program_mod.defense_program(
            self.defenses, "masked", f=self.cfg.nb_decl_byz,
            dynamic=self.cfg.fault_dynamic_quorum)(G, mix_u, active)

    def _train_step(self, state: TrainState, xs, ys, lr):
        """xs: f32[S, B, ...] (or f32[S, k, B, ...] for k local steps)."""
        (rng, mix_key, G_sampled, loss_avg, net_state, new_mw,
         G_honest, fault, new_fb) = self._phase_honest(state, xs, ys, lr)
        (G_attack, grad_defense, accept_ratio, fault_metrics, diag_metrics,
         attack_state) = self._phase_defense(G_honest, mix_key, fault,
                                             state.attack_state)
        return self._phase_update(
            state, rng, G_sampled, loss_avg, net_state, new_mw, G_honest,
            G_attack, grad_defense, accept_ratio, lr, self._batch_of(xs),
            fault_metrics, new_fb, diag_metrics, attack_state)

    def _phase_update(self, state, rng, G_sampled, loss_avg, net_state,
                      new_mw, G_honest, G_attack, grad_defense, accept_ratio,
                      lr, batch, fault_metrics=None, fault_buffer=None,
                      diag_metrics=None, attack_state=None):
        """Model update + study metrics (reference `attack.py:832-878`)."""
        cfg = self.cfg
        h = cfg.nb_honests
        mu, damp = cfg.momentum, cfg.dampening
        lr = jnp.asarray(lr).astype(state.theta.dtype)

        # --- model update (`attack.py:832-839`) --- #
        with jax.named_scope("update"):
            if cfg.momentum_at == "worker":
                new_ms = state.momentum_server
                update_grad = grad_defense
            elif cfg.momentum_at == "server":
                new_ms = grad_defense
                update_grad = grad_defense
            else:
                new_ms = (mu * state.momentum_server
                          + (1.0 - damp) * grad_defense)
                update_grad = new_ms

            # The optimizer applies the final update (torch-SGD semantics
            # by default, incl. --weight-decay; reference
            # `attack.py:543-545`, `experiments/model.py:368-380`)
            theta, opt_state = self.optimizer.update(
                update_grad, state.opt_state, state.theta, lr)

        # --- study metrics (`attack.py:842-878`) --- #
        if cfg.study:
            with jax.named_scope("metrics"):
                l2_origin = jnp.sqrt(
                    jnp.sum((state.theta - state.origin) ** 2))
                metrics, (pg, pn, pc) = metrics_mod.study_metrics(
                    loss_avg=loss_avg, l2_origin=l2_origin,
                    G_sampled=G_sampled, G_honest=G_honest,
                    G_attack=G_attack,
                    grad_defense=grad_defense, accept_ratio=accept_ratio,
                    past_grads=state.past_grads,
                    past_norms=state.past_norms,
                    past_count=state.past_count, momentum=mu)
        else:
            metrics = {}
            pg, pn, pc = state.past_grads, state.past_norms, state.past_count
        if cfg.study and fault_metrics is not None:
            metrics.update(fault_metrics)
        if cfg.study and diag_metrics is not None:
            metrics.update(diag_metrics)
        if cfg.study and cfg.health:
            # Numerics flight recorder (`engine/health.py`): the health
            # vector rides the metrics dict — zero extra syncs, and a
            # trace-time switch (off compiles the exact pre-health
            # program). Under a `--mesh` step (`_grouped_mode` is the
            # mesh inside the sharded builder's trace) the d axis is
            # sharded, so the stats reduce through the explicit
            # width-aware shard_map form.
            from byzantinemomentum_tpu.engine import health as health_mod
            mode = _grouped_mode
            health_fn = (health_mod.sharded_health_metrics(mode)
                         if mode is not None and mode != "off"
                         else health_mod.health_metrics)
            with jax.named_scope("metrics"):
                metrics.update(health_fn(
                    G_honest, G_attack, grad_defense, state.theta, theta))

        new_state = TrainState(
            theta=theta, net_state=net_state, opt_state=opt_state,
            momentum_server=new_ms, momentum_workers=new_mw,
            origin=state.origin,
            past_grads=pg, past_norms=pn, past_count=pc,
            steps=state.steps + 1,
            datapoints=state.datapoints + batch * h * cfg.nb_local_steps,
            rng=rng,
            fault_buffer=(state.fault_buffer if fault_buffer is None
                          else fault_buffer),
            attack_state=(state.attack_state if attack_state is None
                          else attack_state),
        )
        return new_state, metrics

    def _batch_of(self, xs):
        """Per-worker batch size from the stacked input
        (xs: [S, B, ...] or [S, k, B, ...])."""
        return xs.shape[2] if self.cfg.nb_local_steps > 1 else xs.shape[1]

    # ----------------------------------------------------------------- #
    # Evaluation (reference `experiments/model.py:382-396`)

    def _eval_step(self, theta, net_state, x, y):
        cdtype = self.cfg.jnp_compute_dtype
        if jnp.issubdtype(x.dtype, jnp.inexact):
            x = x.astype(cdtype)
        params = _cast_tree(self.unravel(theta), cdtype)
        # net_state (BN running stats) stays in the parameter dtype, exactly
        # as the training forward (_worker_grad) sees it — eval must not run
        # with lower-precision normalization statistics than training
        out, _ = self.model_def.apply(params, net_state, x, train=False,
                                      rng=jax.random.PRNGKey(0))
        return self.criterion(out, y)

    def _eval_many(self, theta, net_state, xs, ys):
        """One compiled evaluation over a whole milestone: `lax.scan` of the
        criterion across `reps` stacked test batches, returning the summed
        `[#correct, #samples]` — one host transfer per evaluation instead of
        the reference's one synchronous call per batch
        (reference `attack.py:709-715`). `xs: f32[reps, B, ...]`."""
        def body(acc, xy):
            x, y = xy
            return acc + self._eval_step(theta, net_state, x, y), None
        acc, _ = lax.scan(body, jnp.zeros((2,), jnp.float32), (xs, ys))
        return acc

    def _eval_many_indexed(self, theta, net_state, idx, flips):
        """`_eval_many` over the device-resident test split: ships only the
        `(reps, B)` index/flip arrays; batches materialize in-graph."""
        def body(acc, inp):
            i, fl = inp
            x, y = self._test_data.gather(i, fl)
            return acc + self._eval_step(theta, net_state, x, y), None
        acc, _ = lax.scan(body, jnp.zeros((2,), jnp.float32), (idx, flips))
        return acc


def make_device_gar_step(engine, gar_device):
    """Heterogeneous GAR placement — a thin wrapper over the builder's
    placement axis (`engine/program.py::device_gar_step`): the defense
    phase runs on `gar_device` with the gradient matrix hopping there and
    back every step. Returns a drop-in for `engine.train_step`."""
    return program_mod.device_gar_step(engine, gar_device)


def build_engine(*, cfg, model_def, loss, criterion, defenses, attack=None,
                 attack_kwargs=None, optimizer=None, faults=None):
    """Assemble an `Engine` (the reference's `setup` phase,
    `attack.py:451-591`, collapsed into one constructor). `faults` is an
    optional compiled `faults.FaultSchedule` (see `faults/__init__.py`)."""
    return Engine(cfg, model_def, loss, criterion, defenses, attack,
                  attack_kwargs, optimizer=optimizer, faults=faults)
