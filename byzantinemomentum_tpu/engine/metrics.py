"""In-graph study metrics — the 24-column per-step diagnostic pipeline.

Reference: the `study` CSV schema (`attack.py:564-571`), the per-step
computation (`attack.py:842-878`) and the `compute_avg_dev_max` helper
(`tools/pytorch.py:97-125`). Formula parity notes:

* "norm" columns are the norm OF the class average (not the average of
  norms), and cosines are normalized by those average-norms — a deliberate
  reference quirk preserved here.
* "deviation" is the SAMPLE standard deviation (n-1 denominator) of the
  per-gradient L2 deviations from the class average; NaN for < 2 gradients.
* The composite curvature is `mu * sum_i mu^i <avg_t, past_i>` over the
  `appendleft`-ordered ring of past sampled averages (`attack.py:861-866`).

Everything is computed inside the jitted step and returned as a flat dict of
f32 scalars; the host merely formats them (`%.8e`, reference
`attack.py:869-870`).
"""

import jax.numpy as jnp

__all__ = ["STUDY_COLUMNS", "FAULT_COLUMNS", "RECOVERY_COLUMNS",
           "FORENSIC_COLUMNS", "HEALTH_COLUMNS",
           "avg_dev_max", "cosine",
           "forensic_metrics", "study_metrics", "push_past"]

# CSV header, byte-identical to the reference's (reference `attack.py:564-571`)
STUDY_COLUMNS = (
    "Step number", "Training point count",
    "Average loss", "l2 from origin",
    "Sampled gradient deviation", "Honest gradient deviation", "Attack gradient deviation",
    "Sampled gradient norm", "Honest gradient norm", "Attack gradient norm", "Defense gradient norm",
    "Sampled max coordinate", "Honest max coordinate", "Attack max coordinate", "Defense max coordinate",
    "Sampled-honest cosine", "Sampled-attack cosine", "Sampled-defense cosine",
    "Honest-attack cosine", "Honest-defense cosine", "Attack-defense cosine",
    "Sampled-prev cosine", "Sampled composite curvature",
    "Attack acceptation ratio",
)

# Resilience columns, appended to the study CSV when a fault plan is
# active (`--fault-plan`): scheduled fault conditions live this step, the
# effective worker count after drops/quarantine, and the effective
# Byzantine tolerance the aggregation ran with (`faults/quorum.py`). Kept
# out of STUDY_COLUMNS so fault-free runs stay byte-identical to the
# reference's CSV schema.
FAULT_COLUMNS = ("Faults injected", "Workers active", "Quorum f")

# Crash-recovery columns, appended when the driver runs with crash recovery
# enabled (`--auto-resume` or a `--rollback-budget`): divergence rollbacks
# performed by this process, and how many times the run was auto-resumed
# after a kill (persisted in the run's checkpoint manifest). Host-side
# counters — not in-graph metrics — and, like FAULT_COLUMNS, kept out of
# STUDY_COLUMNS so default runs keep the reference's exact CSV schema.
RECOVERY_COLUMNS = ("Rollbacks", "Restarts")

# Aggregation-forensics columns, appended to the study CSV when the
# defense runs its diagnostics kernel (`--gar-diagnostics`): which workers
# the GAR selected (';'-joined indices, formatted host-side from the
# in-graph selection mask), the honest-vs-all pairwise-distance median,
# the paper's per-step variance-to-norm ratio of the submitted momenta,
# the coordinate-trim fraction, and the max host-side suspicion score
# (`obs/forensics.py`). Opt-in like FAULT_COLUMNS/RECOVERY_COLUMNS so
# default runs keep the reference's exact CSV schema.
FORENSIC_COLUMNS = ("Sel workers", "Dist honest med", "Var/norm ratio",
                    "Clip frac", "Suspicion max")

# Tensor-health columns, appended when the numerics flight recorder is on
# (`--health` / `EngineConfig.health`; `engine/health.py`): the paper's
# variance-to-norm ratio of the honest submissions ('Var ratio' — the
# forensic 'Var/norm ratio' promoted out of the diagnostics path), global
# weight/update norms and their ratio, the ';'-joined fixed-bin log2
# histogram of the submitted-momentum norms, and the per-phase NaN/Inf
# signals ('Nonfinite submitted' counts ROWS of the submitted stack with
# a non-finite norm; 'Nonfinite aggregate'/'Nonfinite state' are 0/1
# indicators — all derived from sums-of-squares already on hand, so the
# non-finite surveillance costs no extra pass; see engine/health.py).
# Opt-in like the other extension families so default runs keep the
# reference's exact CSV schema; when off the compiled step is
# byte-identical to the pre-health program (trace-time switch).
HEALTH_COLUMNS = ("Var ratio", "Weight norm", "Update norm",
                  "Update/weight", "Norm hist", "Nonfinite submitted",
                  "Nonfinite aggregate", "Nonfinite state")

# NaN as a Python float: creating a device array at import time would
# initialize the JAX backend before the CLI's --device platform selection
# can take effect.
_NAN = float("nan")


def avg_dev_max(G):
    """(average gradient, ||avg||, sample std-dev of deviations, max |avg|)
    over the rows of `G: f32[m, d]` (reference `tools/pytorch.py:97-125`).

    Returns (None, nan, nan, nan) for m == 0 and dev = nan for m == 1,
    matching the reference's edge cases.
    """
    m = G.shape[0]
    if m == 0:
        return None, _NAN, _NAN, _NAN
    avg = jnp.mean(G, axis=0)
    norm_avg = jnp.sqrt(jnp.sum(avg * avg))
    norm_max = jnp.max(jnp.abs(avg))
    if m >= 2:
        dev = G - avg
        dev = jnp.sqrt(jnp.sum(dev * dev) / (m - 1))
    else:
        dev = _NAN
    return avg, norm_avg, norm_max, dev


def cosine(a, na, b, nb):
    """dot(a, b) / (na * nb) — the reference's 'cosine of solid angles'
    normalized by average-norms (reference `attack.py:854-859`)."""
    if a is None or b is None:
        return _NAN
    return jnp.dot(a, b) / na / nb


def push_past(past_grads, past_norms, past_count, grad, norm):
    """`deque.appendleft` on the past-gradient ring
    (reference `attack.py:868`)."""
    if past_grads.shape[0] == 0:
        return past_grads, past_norms, past_count
    past_grads = jnp.concatenate([grad[None, :], past_grads[:-1]])
    past_norms = jnp.concatenate([norm[None], past_norms[:-1]])
    past_count = jnp.minimum(past_count + 1, past_grads.shape[0])
    return past_grads, past_norms, past_count


def forensic_metrics(aux, G_honest):
    """In-graph forensic values from a GAR diagnostics aux
    (`ops/diag.py` schema) and the honest submission stack.

    Returns device scalars/vectors keyed for the driver: the scalar keys
    land in the study CSV verbatim (FORENSIC_COLUMNS), while `Sel mask`
    and `Worker dist` are per-worker vectors the host formats ('Sel
    workers') and feeds to the suspicion tracker (`obs/forensics.py`).
    """
    import jax.numpy as jnp  # local alias keeps the module top jax-free

    from byzantinemomentum_tpu.ops import diag as diag_mod

    n = aux["selection"].shape[0]
    dist = aux["dist"]
    _, dmed, _ = diag_mod.distance_summary(dist, rows=G_honest.shape[0])
    # Per-worker mean distance to the finite peers (suspicion z-scores);
    # a row with NO finite peer distance (fully corrupt) reads +inf
    offdiag = ~jnp.eye(n, dtype=bool)
    finite = jnp.isfinite(dist) & offdiag
    cnt = jnp.sum(finite.astype(jnp.float32), axis=1)
    mean_d = (jnp.sum(jnp.where(finite, dist, 0.0), axis=1)
              / jnp.maximum(cnt, 1.0))
    mean_d = jnp.where(cnt > 0, mean_d, jnp.inf)
    return {
        "Sel mask": aux["selection"],
        "Worker dist": mean_d,
        "Dist honest med": dmed,
        "Var/norm ratio": diag_mod.var_norm_ratio(G_honest),
        "Clip frac": jnp.mean(aux["trim_frac"]),
    }


def study_metrics(*, loss_avg, l2_origin, G_sampled, G_honest, G_attack,
                  grad_defense, accept_ratio, past_grads, past_norms,
                  past_count, momentum):
    """Compute the 17+5 metric values of one step
    (reference `attack.py:842-866`). Returns (metrics dict, new past ring)."""
    sampled_avg, sampled_na, sampled_mx, sampled_dev = avg_dev_max(G_sampled)
    honest_avg, honest_na, honest_mx, honest_dev = avg_dev_max(G_honest)
    attack_avg, attack_na, attack_mx, attack_dev = avg_dev_max(G_attack)
    defense_na = jnp.sqrt(jnp.sum(grad_defense * grad_defense))
    defense_mx = jnp.max(jnp.abs(grad_defense))

    P = past_grads.shape[0]
    if P > 0:
        has_past = past_count > 0
        cosin_sampled = jnp.where(
            has_past,
            jnp.dot(sampled_avg, past_grads[0]) / sampled_na / past_norms[0],
            _NAN)
        # mu * sum_i mu^i <sampled_avg, past_i> over the valid entries
        weights = momentum ** jnp.arange(P, dtype=jnp.float32)
        valid = (jnp.arange(P) < past_count).astype(jnp.float32)
        dots = past_grads @ sampled_avg
        curv_sampled = jnp.where(
            has_past, momentum * jnp.sum(weights * valid * dots), _NAN)
    else:
        cosin_sampled = _NAN
        curv_sampled = _NAN

    metrics = {
        "Average loss": loss_avg,
        "l2 from origin": l2_origin,
        "Sampled gradient deviation": sampled_dev,
        "Honest gradient deviation": honest_dev,
        "Attack gradient deviation": attack_dev,
        "Sampled gradient norm": sampled_na,
        "Honest gradient norm": honest_na,
        "Attack gradient norm": attack_na,
        "Defense gradient norm": defense_na,
        "Sampled max coordinate": sampled_mx,
        "Honest max coordinate": honest_mx,
        "Attack max coordinate": attack_mx,
        "Defense max coordinate": defense_mx,
        "Sampled-honest cosine": cosine(sampled_avg, sampled_na, honest_avg, honest_na),
        "Sampled-attack cosine": cosine(sampled_avg, sampled_na, attack_avg, attack_na),
        "Sampled-defense cosine": cosine(sampled_avg, sampled_na, grad_defense, defense_na),
        "Honest-attack cosine": cosine(honest_avg, honest_na, attack_avg, attack_na),
        "Honest-defense cosine": cosine(honest_avg, honest_na, grad_defense, defense_na),
        "Attack-defense cosine": cosine(attack_avg, attack_na, grad_defense, defense_na),
        "Sampled-prev cosine": cosin_sampled,
        "Sampled composite curvature": curv_sampled,
        "Attack acceptation ratio": accept_ratio,
    }
    new_past = push_past(past_grads, past_norms, past_count,
                         sampled_avg, sampled_na)
    return metrics, new_past
