"""Training engine: jitted per-step program + host-side state management.

This is the TPU-native redesign of the reference's 200-line training loop
(reference `attack.py:685-885`): the whole per-step computation — vmapped
honest gradients, clipping, momentum placement, attack synthesis, robust
aggregation, model update and the 24-column study metrics — compiles into a
single XLA program `train_step(state, xs, ys, lr) -> (state, metrics)`. The
host loop (see `cli/attack.py`) only samples batches, formats CSV rows and
handles milestones (eval/checkpoint/SIGINT), mirroring the reference's
division of labor with the device.
"""

from byzantinemomentum_tpu.engine import program
from byzantinemomentum_tpu.engine.config import EngineConfig
from byzantinemomentum_tpu.engine.state import TrainState
from byzantinemomentum_tpu.engine.step import Engine, build_engine
from byzantinemomentum_tpu.engine.metrics import (
    FAULT_COLUMNS, FORENSIC_COLUMNS, HEALTH_COLUMNS, RECOVERY_COLUMNS,
    STUDY_COLUMNS)

__all__ = ["EngineConfig", "TrainState", "Engine", "build_engine",
           "program",
           "FAULT_COLUMNS", "FORENSIC_COLUMNS", "HEALTH_COLUMNS",
           "RECOVERY_COLUMNS", "STUDY_COLUMNS"]
