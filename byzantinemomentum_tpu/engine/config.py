"""Static engine configuration — everything that shapes the compiled step.

All fields are trace-time constants: changing any of them rebuilds the XLA
program (the learning rate is deliberately NOT here — it is a runtime scalar
so the reference's per-step lr schedules don't retrigger compilation).
"""

import dataclasses

import jax.numpy as jnp

__all__ = ["EngineConfig", "DTYPES"]

# Accepted dtype spellings (reference `experiments/configuration.py:26-101`
# carries a torch dtype; bfloat16 is the TPU-native addition)
DTYPES = {
    "float32": jnp.float32, "f32": jnp.float32, "fp32": jnp.float32,
    "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
    "float16": jnp.float16, "f16": jnp.float16, "fp16": jnp.float16,
    "float64": jnp.float64, "f64": jnp.float64, "fp64": jnp.float64,
}


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Mirror of the reference's derived argument set
    (reference `attack.py:242-313`)."""

    nb_workers: int = 11          # --nb-workers
    nb_decl_byz: int = 4          # --nb-decl-byz (f declared)
    nb_real_byz: int = 0          # --nb-real-byz (f actually attacking)
    nb_for_study: int = 0         # --nb-for-study (0 = study disabled)
    nb_for_study_past: int = 1    # --nb-for-study-past (past-gradient ring)
    momentum: float = 0.9         # --momentum (mu)
    dampening: float = 0.0        # --dampening (lambda)
    nesterov: bool = False        # --momentum-nesterov
    momentum_at: str = "update"   # --momentum-at in {update, server, worker}
    weight_decay: float = 0.0     # --weight-decay (applied in the update)
    gradient_clip: float = None   # --gradient-clip (per-sampled-grad L2 cap)
    nb_local_steps: int = 1       # --nb-local-steps (multi-local-step SGD)
    dtype: str = "float32"        # --dtype: parameter/state/gradient dtype
    #                               (reference `configuration.py:26-101`)
    gars_per_call: bool = False   # --gars-per-call: re-draw the `--gars`
    #                               mixture GAR on EVERY defense invocation
    #                               (incl. inside an adaptive attack's line
    #                               search), the reference's semantics
    #                               (`attack.py:504-509`); default draws once
    #                               per step (documented divergence,
    #                               `engine/step.py`)
    compute_dtype: str = None     # --compute-dtype: forward/backward dtype;
    #                               None = same as `dtype`. Setting bf16 with
    #                               f32 params = TPU mixed precision (bf16
    #                               MXU matmuls, f32 master weights/momentum/
    #                               GAR space) — a capability beyond the
    #                               reference's single-dtype Configuration.
    grouped_workers: bool = True  # merged-batch grouped honest phase when
    #                               the model provides `apply_grouped`
    #                               (engine/step.py:_workers_grad_grouped);
    #                               same math as the vmapped path, ~2x
    #                               faster on TPU. False = always vmap
    #                               (--no-grouped-workers).
    fault_quarantine: bool = True  # degradation policy when a fault
    #                               schedule is attached (`faults/`):
    #                               quarantine non-finite submission rows
    #                               out of the aggregation and the quorum
    #                               (no effect without a schedule)
    fault_dynamic_quorum: bool = True  # recompute the effective (n, f)
    #                               the GAR runs with when workers are
    #                               absent (`faults/quorum.py`); False
    #                               keeps the declared f and only excludes
    #                               the absent rows
    gar_diagnostics: bool = False  # --gar-diagnostics: run the defense
    #                               through its in-jit diagnostics kernel
    #                               (`ops/diag.py` aux schema) and emit the
    #                               forensic study-CSV columns
    #                               (`engine/metrics.py::FORENSIC_COLUMNS`).
    #                               Trace-time switch: False compiles the
    #                               exact pre-diagnostics program (no
    #                               hot-path cost; `tests/test_diag.py`)
    health: bool = False          # --health: compute the in-jit tensor-
    #                               health vector (`engine/health.py`) and
    #                               emit the HEALTH_COLUMNS study columns
    #                               (norm histogram, Var ratio, update/
    #                               weight norms, non-finite counts).
    #                               Trace-time switch like gar_diagnostics:
    #                               False compiles the exact pre-health
    #                               program (byte-identical lowerings)

    def __post_init__(self):
        if self.momentum_at not in ("update", "server", "worker"):
            raise ValueError(f"Invalid momentum placement {self.momentum_at!r}")
        if self.dtype not in DTYPES:
            raise ValueError(
                f"Invalid dtype {self.dtype!r}; expected one of "
                f"{sorted(set(DTYPES))}")
        if self.compute_dtype is not None and self.compute_dtype not in DTYPES:
            raise ValueError(
                f"Invalid compute dtype {self.compute_dtype!r}; expected one "
                f"of {sorted(set(DTYPES))}")
        if self.nb_real_byz > self.nb_workers:
            raise ValueError(
                f"More real Byzantine workers ({self.nb_real_byz}) than total "
                f"workers ({self.nb_workers})")
        if self.nb_local_steps < 1:
            raise ValueError(
                f"Non-positive number of local steps {self.nb_local_steps}")

    @property
    def nb_honests(self):
        """Honest worker count = n - f_real (reference `attack.py:250`)."""
        return self.nb_workers - self.nb_real_byz

    @property
    def nb_sampled(self):
        """Gradients computed per step = max(honests, study extras)
        (reference `attack.py:764`)."""
        return max(self.nb_honests, self.nb_for_study)

    @property
    def study(self):
        return self.nb_for_study > 0

    @property
    def jnp_dtype(self):
        """Parameter/state dtype as a jnp dtype."""
        return DTYPES[self.dtype]

    @property
    def jnp_compute_dtype(self):
        """Forward/backward compute dtype as a jnp dtype."""
        return DTYPES[self.compute_dtype or self.dtype]
