"""The attack-vs-defense tournament: every attack x every GAR x
quarantine {on, off}, train and serve.

This is the repo's first result surface BEYOND the paper's own grid
(ROADMAP "close the defense loop" flagship): the paper fixes the attack
set and sweeps GARs/momentum; here the adversary adapts (ALIE z-margins,
EWMA-warm-up timing, framing, Sybil id-splitting, non-IID shards) and
the defense acts (quarantine, admission control), so each scoreboard
cell is one round of the actual game.

Scoreboard schema (`TOURNAMENT_r*.json`, rendered across rounds by
`scripts/bench_history.py`):

  train_cells   one row per (attack, gar, quarantine) from
                `ArenaCell.run`: final accuracy proxy (`final_err` —
                distance to the probe optimum), mean/steady-state
                aggregate error vs the uncorrupted honest mean,
                evicted honest/Byzantine counts, time-to-quarantine,
                reclaimed quorum.
  serve_cells   the Sybil cells from `arena/sybil.py::run_sybil_cell`:
                aggregate shift sustained with admission {on, off},
                detection rate, honest blast radius.
  summary       the acceptance digests: which selection GARs quarantine
                -on strictly dominates on steady-state aggregate error
                against EVERY adaptive attack, total honest evictions
                (framing rows must show zero), Sybil detection.

The grid runs on the CPU-cheap probe engine (`arena/loop.py`) — one
compiled step per (attack, gar) cell, shared verbatim by the on/off
runs and every mask update (the zero-recompile contract
`run(warm_recompile_check=True)` asserts through
`analysis/contracts.py`).
"""

from byzantinemomentum_tpu.arena.loop import ArenaCell
from byzantinemomentum_tpu.arena.sybil import run_sybil_cell
from byzantinemomentum_tpu.attacks import attacks as attack_registry

__all__ = ["ADAPTIVE_ATTACKS", "SELECTION_GARS", "train_roster",
           "run_tournament"]

# The adaptive half of the red team — the attacks that read the defense
# (the acceptance's dominance digest quantifies quarantine against
# these; the label below must match the roster's cell labels).
# `mimic` (attacker byte-copies a victim's row, `attacks/mimic.py`)
# rides the grid through the registry but stays OFF this list: its rows
# are honest-valued, so it never biases the aggregate — its acceptance
# metric is the zero-honest-eviction regression (dedup keeps the
# victim), not agg-error dominance.
ADAPTIVE_ATTACKS = ("alie", "alie-warmup", "framing", "alie+noniid")

# Selection-family GARs (the rules whose per-row choices the suspicion
# machinery can observe sharpest — the dominance claim targets these).
SELECTION_GARS = ("krum", "bulyan", "brute", "aksel", "cge")

# Label-skew level of the non-IID roster entry: worker optima fan out
# 1.5 honest-sigma from the population optimum, violating the i.i.d.
# variance assumption every GAR bound is stated under.
NONIID_SKEW = 1.5


def train_roster():
    """[(label, attack, attack_args, skew)] — every runnable registered
    attack (the template registration deliberately declines its own
    check) plus the non-IID honest-data mode riding the in-envelope
    attacker."""
    roster = [(name, name, {}, 0.0)
              for name in sorted(attack_registry) if name != "template"]
    roster.append(("alie+noniid", "alie", {}, NONIID_SKEW))
    return roster


def run_tournament(*, gars=None, roster=None, steps=80, seed=0, n=11,
                   f_decl=3, f_real=3, d=32, serve_requests=30,
                   serve_gar="krum", recompile_check=False, log=None):
    """Run the grid; returns the scoreboard dict (see module docstring).

    `recompile_check` asserts the zero-recompile contract on the first
    train cell (the tournament smoke's acceptance hook); `log` is an
    optional `print`-like progress callback.
    """
    import jax

    if gars is None:
        from byzantinemomentum_tpu.analysis.lattice import CELL_GARS
        gars = CELL_GARS
    roster = train_roster() if roster is None else roster
    say = log if log is not None else (lambda *_: None)

    train_cells = []
    checked = False
    for gar in gars:
        for label, attack, attack_args, skew in roster:
            cell = ArenaCell(gar, attack, n=n, f_decl=f_decl,
                             f_real=f_real, d=d, attack_args=attack_args)
            rows = []
            for quarantine in (True, False):
                row = cell.run(
                    quarantine=quarantine, steps=steps, seed=seed,
                    skew=skew,
                    warm_recompile_check=recompile_check and not checked)
                checked = True
                row["attack"] = label
                row["skew"] = skew
                rows.append(row)
                train_cells.append(row)
            say(f"  {gar:>8} x {label:<14} on/off agg_err_last10 = "
                f"{rows[0]['agg_err_last10']:.3f}/"
                f"{rows[1]['agg_err_last10']:.3f}  "
                f"evicted h/b = {rows[0]['evicted_honest']}/"
                f"{rows[0]['evicted_byz']}")

    serve_cells = []
    for admission in (True, False):
        row = run_sybil_cell(gar=serve_gar, admission=admission,
                             requests=serve_requests, f=2, seed=seed)
        serve_cells.append(row)
        say(f"  serve sybil admission={admission}: "
            f"tail shift {row['agg_shift_tail']:.3f}, "
            f"detection {row['detection_rate']:.2f}")

    scoreboard = {
        "kind": "tournament",
        "backend": jax.default_backend(),
        "config": {"n": n, "f_decl": f_decl, "f_real": f_real, "d": d,
                   "steps": steps, "seed": seed,
                   "noniid_skew": NONIID_SKEW,
                   "gars": list(gars),
                   "attacks": [label for label, *_ in roster]},
        "train_cells": train_cells,
        "serve_cells": serve_cells,
        "summary": _summarize(train_cells, serve_cells),
    }
    return scoreboard


def _summarize(train_cells, serve_cells):
    """The acceptance digests over the raw cells."""
    by_key = {(c["gar"], c["attack"], c["quarantine"]): c
              for c in train_cells}
    gars = sorted({c["gar"] for c in train_cells})
    adaptive = [a for a in ADAPTIVE_ATTACKS
                if any(c["attack"] == a for c in train_cells)]

    dominated = []
    for gar in gars:
        wins = []
        for attack in adaptive:
            on = by_key.get((gar, attack, True))
            off = by_key.get((gar, attack, False))
            if on is None or off is None:
                break
            wins.append(on["agg_err_last10"] < off["agg_err_last10"])
        if wins and all(wins):
            dominated.append(gar)

    framing_honest = sum(c["evicted_honest"] for c in train_cells
                         if c["attack"] == "framing" and c["quarantine"])
    sybil = {}
    for row in serve_cells:
        key = "on" if row["admission"] else "off"
        sybil[f"shift_tail_{key}"] = row["agg_shift_tail"]
        if row["admission"]:
            sybil["detection_rate"] = row["detection_rate"]
            sybil["honest_masked"] = row["honest_masked"]

    return {
        "dominance_metric": "agg_err_last10",
        "adaptive_attacks": adaptive,
        "selection_gars_dominated": [g for g in dominated
                                     if g in SELECTION_GARS],
        "gars_dominated": dominated,
        "framing_honest_evictions": framing_honest,
        "honest_evictions_total": sum(c["evicted_honest"]
                                      for c in train_cells),
        "byz_evictions_total": sum(c["evicted_byz"]
                                   for c in train_cells),
        "sybil": sybil,
    }
