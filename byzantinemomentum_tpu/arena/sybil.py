"""Sybil serve attack — one perturbation split across many client ids.

The per-client suspicion store (`obs/forensics.py`) is exactly as strong
as its keying assumption: one client, one history. A Sybil adversary
buys `k` fresh client ids and splits one attack vector across them —
each row carries only `scale / k` of the perturbation, so every
per-client statistic (selection rate, distance z) stays deep inside the
honest envelope while the COALITION carries the full `scale` and, being
`k > f` rows, sits outside the GAR's per-request tolerance contract.

The defense that catches it is cohort-level, not client-level: the split
shards are mutually near-identical (they all encode the same direction),
which the store's collusion channel reads straight off the serve aux's
pairwise-distance matrix — distinct client ids closer to each other than
`COLLUSION_FRAC` of the cohort's median distance. With admission control
on (`serve/admission.py`), those ids' rows are masked out of the
aggregate once their collusion EWMA crosses the threshold; with it off,
the verdicts ride the responses and nothing stops the shift — the
regression pair `tests/test_serve.py` pins.

`run_sybil_cell` is the tournament's serve-mode cell runner: it replays
the same request stream against admission {on, off} and reports the
aggregate shift each sustains plus the detection bookkeeping.
"""

import numpy as np

__all__ = ["sybil_cohort", "run_sybil_cell"]


def sybil_cohort(rng, *, n_honest, k, d, direction, scale, sigma=1.0,
                 shard_jitter=0.02):
    """One request's `(matrix, client_ids)`: `n_honest` honest rows of
    `N(0, sigma^2)` under stable ids `h<i>`, plus `k` Sybil rows under
    ids `s<j>` — each the honest-looking base point `scale/k` along
    `direction`, with `shard_jitter * sigma` of per-shard noise (the
    knob the adversary turns against duplicate detection)."""
    honest = sigma * rng.standard_normal((n_honest, d)).astype(np.float32)
    base = sigma * 0.1 * rng.standard_normal(d).astype(np.float32)
    shard = base[None, :] + (scale / k) * direction[None, :]
    sybil = (shard
             + shard_jitter * sigma
             * rng.standard_normal((k, d)).astype(np.float32))
    matrix = np.concatenate([honest, sybil.astype(np.float32)])
    ids = tuple(f"h{i}" for i in range(n_honest)) + tuple(
        f"s{j}" for j in range(k))
    return matrix, ids


def run_sybil_cell(*, gar="krum", admission=True, requests=30, n_honest=8,
                   k=6, d=32, f=2, scale=6.0, sigma=1.0, shard_jitter=0.02,
                   seed=0, service_kwargs=None):
    """One serve-mode tournament cell: the Sybil stream against a live
    `AggregationService` with admission control on or off.

    Returns the scoreboard row: mean aggregate shift relative to the
    same stream's honest-rows-only aggregate (the quantity the coalition
    is trying to move), the Sybil detection rate (flagged `s*` ids /
    k at the end), honest ids caught in the blast radius (must be 0),
    and the admission counters.
    """
    from byzantinemomentum_tpu.serve import AggregationService

    kwargs = dict(service_kwargs or {})
    kwargs.setdefault("max_batch", 4)
    kwargs.setdefault("max_delay_ms", 1.0)
    if admission:
        kwargs.setdefault("admission", {"mode": "mask"})
    else:
        # The collusion channel still OBSERVES (verdicts ride responses)
        # so the off-cell measures pure detection without enforcement
        from byzantinemomentum_tpu.serve.admission import ADMISSION_WEIGHTS
        kwargs.setdefault("suspicion", {"weights": ADMISSION_WEIGHTS})

    rng = np.random.default_rng(seed)
    direction = np.zeros(d, np.float32)
    direction[0] = 1.0
    shifts, honest_baseline_shifts = [], []
    with AggregationService(**kwargs) as svc:
        last = None
        for _ in range(requests):
            matrix, ids = sybil_cohort(
                rng, n_honest=n_honest, k=k, d=d, direction=direction,
                scale=scale, sigma=sigma, shard_jitter=shard_jitter)
            last = svc.aggregate(matrix, gar=gar, f=f, client_ids=ids,
                                 diagnostics=True, timeout=30.0)
            honest_only = svc.aggregate(matrix[:n_honest], gar=gar, f=f,
                                        timeout=30.0)
            shift = np.linalg.norm(np.asarray(last.aggregate)
                                   - np.asarray(honest_only.aggregate))
            shifts.append(float(shift))
            honest_baseline_shifts.append(
                float(np.linalg.norm(np.asarray(honest_only.aggregate))))
        stats = svc.stats()
        verdicts = last.verdicts or {}
        flagged = {c for c, v in verdicts.items()
                   if v["suspect"] or v.get("collusion", 0.0) >= 0.5}
        admission_info = last.admission or {}
    sybil_ids = {f"s{j}" for j in range(k)}
    honest_ids = {f"h{i}" for i in range(n_honest)}
    masked_final = {c for c, a in admission_info.items()
                    if a["action"] == "mask"}
    # Steady state: the last third of the stream, after the store warmed
    tail = max(len(shifts) // 3, 1)
    return {
        "mode": "serve-sybil", "gar": gar, "admission": bool(admission),
        "requests": requests, "k_sybil": k, "n_honest": n_honest,
        "scale": scale, "shard_jitter": shard_jitter,
        "agg_shift_mean": round(float(np.mean(shifts)), 6),
        "agg_shift_tail": round(float(np.mean(shifts[-tail:])), 6),
        "detection_rate": round(len(flagged & sybil_ids) / k, 4),
        "honest_flagged": len(flagged & honest_ids),
        "honest_masked": len(masked_final & honest_ids),
        "sybil_masked_final": len(masked_final & sybil_ids),
        "masked_rows_total": stats["admission"]["masked_rows"],
    }
