"""The attack-vs-defense arena — the closed defense loop and the
tournament that earns it.

PR 4/8 built the sensors (in-jit GAR diagnostics, per-worker/per-client
EWMA suspicion) but nothing ever *acted* on a verdict. This package
closes the loop and then stress-tests it:

* `quarantine.py` — `QuarantinePolicy`: suspicion verdicts become an
  ACTIVE MASK fed to the masked-quorum GAR kernels (`faults/quorum.py`)
  as a runtime operand, so evictions re-use the compiled program (zero
  retrace — asserted in the tournament smoke) and the effective quorum
  `f_eff` shrinks in-jit with each eviction. Hysteresis, an eviction
  patience, a max-evictions budget and a keep-one collusion dedup keep a
  framing attack from turning the defense against honest workers.
* `loop.py` — the closed training loop: a probe engine (the
  `tests/test_engine.py` quadratic-probe technique — every trajectory is
  analytically checkable) with optional label-skewed non-IID worker
  shards, driven step by step with the policy's mask in the carry.
* `sybil.py` — the serve-side red team: one perturbation split across
  many client ids, under every per-client threshold
  (`obs/forensics.py::ClientSuspicionStore`), caught only by the
  cohort-level collusion channel + admission control
  (`serve/admission.py`).
* `tournament.py` — the grid runner: attack x GAR x quarantine {on, off}
  in train mode plus the serve-mode Sybil cells, emitting the
  machine-readable resilience scoreboard (`TOURNAMENT_r*.json`,
  rendered over rounds by `scripts/bench_history.py`).

The red team lives in `attacks/` (alie / alie-warmup / framing join the
paper's static roster through the same registry, with the new optional
state hook for the time-coupled ones).
"""

from byzantinemomentum_tpu.arena.quarantine import QuarantinePolicy

__all__ = ["QuarantinePolicy"]
