"""The closed defense loop: engine step + quarantine policy, end to end.

Train mode of the tournament (`arena/tournament.py`): a probe engine —
the `tests/test_engine.py` technique, a quadratic model whose per-worker
gradient is exactly `theta - mean(batch rows)` — runs the full
Byzantine-SGD step (honest phase, worker momentum, in-jit attack
synthesis against the live defense, masked-quorum aggregation) while a
host-side `QuarantinePolicy` turns each step's diagnostics into the next
step's active mask. The probe keeps every cell CPU-cheap (one cell is
~100 ms of XLA compile + tens of microsecond steps) while exercising the
real engine code paths: `Engine._phase_honest` / `_phase_update`, the
attack registry incl. the stateful hook, `faults/quorum.py` masked
kernels with dynamic `f_eff`, and `ops/diag.py::masked_generic_aux`.

Zero-recompile discipline: the step is compiled ONCE per (attack, GAR)
cell; the quarantine mask enters as a runtime bool[n] operand
(`quarantine_defense_kernel`), so quarantine {on, off} runs — and every
eviction within a run — share the same executable
(`analysis/contracts.py::assert_recompile_budget` holds this to zero in
the tournament smoke).

Non-IID honest data (`noniid_batches`): each worker's shard is "label
-skewed" — its batch rows draw from a worker-specific mean
`optimum + skew * sigma * dir_i` (dir_i a signed basis direction), the
mean-estimation analogue of a worker whose local class mix shifts its
local optimum. With skew > 0 the honest gradients are no longer i.i.d.,
the variance envelope the GARs assume widens, and an in-envelope attack
gets more room — the failure mode Karimireddy et al.'s bucketing line
studies (PAPERS.md).
"""

import numpy as np

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu import losses, ops
from byzantinemomentum_tpu.arena.quarantine import (
    QuarantinePolicy, quarantine_defense_kernel)
from byzantinemomentum_tpu.attacks import attacks as attack_registry
from byzantinemomentum_tpu.engine import EngineConfig, build_engine
from byzantinemomentum_tpu.models import ModelDef

__all__ = ["ArenaCell", "noniid_batches", "probe_model_def", "probe_loss"]


def probe_model_def(d):
    """Quadratic probe: output = batch, gradient of the loss below w.r.t.
    theta = theta - mean(batch rows) — fully analytic trajectories."""
    def init(key):
        return {"w": jnp.zeros((d,), jnp.float32)}, {}

    def apply(params, state, x, train=False, rng=None):
        return x, state

    return ModelDef("arena-probe", init, apply, (d,))


def probe_loss():
    """0.5 * ||theta - mean(batch)||^2 — the minimum sits at the data
    mean, so `||theta - optimum||` is the accuracy proxy."""
    return losses.Loss(
        lambda output, target, params:
        0.5 * jnp.sum((params - jnp.mean(output, axis=0)) ** 2))


def noniid_batches(rng, *, steps, workers, batch, optimum, sigma, skew):
    """f32[steps, S, B, d] honest data stream. Worker i's rows draw from
    `N(optimum + skew * sigma * dir_i, sigma^2)` — dir_i the signed basis
    direction `(-1)^i e_{i mod d}` — so `skew=0` is the i.i.d. grid and
    `skew>0` the label-skewed one (worker optima fan out around the true
    optimum; the population mean stays near `optimum` when S covers the
    directions evenly)."""
    d = optimum.shape[0]
    dirs = np.zeros((workers, d), np.float32)
    for i in range(workers):
        dirs[i, i % d] = 1.0 if i % 2 == 0 else -1.0
    means = optimum[None, :] + skew * sigma * dirs
    noise = rng.normal(size=(steps, workers, batch, d)).astype(np.float32)
    return means[None, :, None, :] + sigma * noise


class ArenaCell:
    """One (attack, GAR) train-mode cell: a compiled closed-loop step,
    runnable with quarantine on or off against the SAME executable.

    Args mirror the tournament grid: `n` workers of which `f_real`
    attack, `f_decl` declared; the probe dimension `d`; `attack_args`
    forwarded to the attack plugin.
    """

    def __init__(self, gar, attack, *, n=11, f_decl=3, f_real=3, d=32,
                 attack_args=None, gar_kwargs=None):
        if attack not in attack_registry:
            raise ValueError(f"Unknown attack {attack!r}")
        self.gar_name, self.attack_name = gar, attack
        self.n, self.f_decl, self.f_real, self.d = n, f_decl, f_real, d
        self.cfg = EngineConfig(
            nb_workers=n, nb_decl_byz=f_decl, nb_real_byz=f_real,
            nb_for_study=0, momentum=0.9, dampening=0.0,
            momentum_at="worker")
        self.engine = build_engine(
            cfg=self.cfg, model_def=probe_model_def(d), loss=probe_loss(),
            criterion=losses.Criterion("sigmoid"),
            defenses=[(ops.gars[gar], 1.0, dict(gar_kwargs or {}))],
            attack=attack_registry[attack],
            attack_kwargs=dict(attack_args or {}))
        self.step = self._build_step()

    def _build_step(self):
        engine = self.engine
        cfg = self.cfg
        kernel = quarantine_defense_kernel(
            ops.gars[self.gar_name], f=cfg.nb_decl_byz,
            kwargs=engine.defenses[0][2])

        def traced(state, xs, ys, lr, active, f_evicted):
            (rng, mix_key, G_sampled, loss_avg, net_state, new_mw,
             G_honest, _fault, new_fb) = engine._phase_honest(
                state, xs, ys, lr)

            def defense_fn(gradients, f):
                # Adaptive attacks line-search against the defense the
                # loop actually mounts: the masked kernel over the
                # policy's CURRENT active set (probes of another row
                # count fall back to the plain program)
                if gradients.shape[0] == active.shape[0]:
                    return kernel(gradients, active,
                                  f_evicted)["aggregate"]
                return engine._run_defense(
                    gradients, jax.random.uniform(mix_key))

            attack_state = state.attack_state
            if cfg.nb_real_byz > 0:
                with jax.named_scope("attack"):
                    if engine.attack.stateful:
                        G_attack, attack_state = engine.attack.unchecked(
                            G_honest, f_decl=cfg.nb_decl_byz,
                            f_real=cfg.nb_real_byz, defense=defense_fn,
                            state=attack_state, **engine.attack_kwargs)
                    else:
                        G_attack = engine.attack.unchecked(
                            G_honest, f_decl=cfg.nb_decl_byz,
                            f_real=cfg.nb_real_byz, defense=defense_fn,
                            **engine.attack_kwargs)
                    G_attack = G_attack.astype(G_honest.dtype)
            else:
                G_attack = jnp.zeros((0, engine.d), G_honest.dtype)

            G_all = jnp.concatenate([G_honest, G_attack])
            out = kernel(G_all, active, f_evicted)
            grad_defense = out.pop("aggregate").astype(G_honest.dtype)
            # The uncorrupted reference signal: what a fault-free
            # averaging server would apply this step
            ideal = jnp.mean(G_honest, axis=0)
            out["agg_err"] = jnp.sqrt(
                jnp.sum((grad_defense - ideal) ** 2))
            out["loss"] = loss_avg
            new_state, _ = engine._phase_update(
                state, rng, G_sampled, loss_avg, net_state, new_mw,
                G_honest, G_attack, grad_defense,
                jnp.float32(jnp.nan), lr, xs.shape[1],
                None, new_fb, None, attack_state)
            return new_state, out

        return jax.jit(traced, donate_argnums=(0,))

    # -------------------------------------------------------------- #

    def run(self, *, quarantine=True, steps=60, seed=0, batch=8,
            sigma=0.5, skew=0.0, lr=0.1, policy_kwargs=None,
            warm_recompile_check=False):
        """Drive the closed loop for `steps`; returns the scoreboard row.

        `warm_recompile_check` additionally asserts — via
        `analysis/contracts.py::assert_recompile_budget` — that three
        extra steps under a CHANGING active mask compile nothing: the
        eviction path re-uses the one compiled program.
        """
        n, h = self.n, self.cfg.nb_honests
        rng = np.random.default_rng(seed)
        optimum = np.ones(self.d, np.float32) / np.sqrt(self.d)
        data = noniid_batches(rng, steps=steps, workers=h, batch=batch,
                              optimum=optimum, sigma=sigma, skew=skew)
        ys = jnp.zeros((h, batch), jnp.float32)
        lr = jnp.float32(lr)

        policy = (QuarantinePolicy(n, self.f_decl, **(policy_kwargs or {}))
                  if quarantine else None)
        state = self.engine.init(jax.random.PRNGKey(seed))
        active = np.ones(n, dtype=bool)
        reclaimed = 0
        agg_errs, losses_seen = [], []
        for t in range(steps):
            state, out = self.step(state, jnp.asarray(data[t]), ys, lr,
                                   jnp.asarray(active),
                                   jnp.int32(reclaimed))
            host = jax.device_get(out)
            agg_errs.append(float(host["agg_err"]))
            losses_seen.append(float(host["loss"]))
            if policy is not None:
                active = policy.update(
                    t, host["selection"], distances=host["worker_dist"],
                    active=host["active"], dist_matrix=host["dist"])
                reclaimed = policy.f_reclaimed()

        if warm_recompile_check:
            from byzantinemomentum_tpu.analysis import contracts

            flip = {"i": 0}

            def warm_step():
                # A mask (and quorum credit) that CHANGES between calls
                # must not retrace
                mask = np.ones(n, dtype=bool)
                mask[n - 1 - (flip["i"] % 2)] = False
                flip["i"] += 1
                _state, out = self.step(
                    self.engine.init(jax.random.PRNGKey(7)),
                    jnp.asarray(data[0]), ys, lr, jnp.asarray(mask),
                    jnp.int32(flip["i"] % 2))
                return out["agg_err"]

            contracts.assert_recompile_budget(
                warm_step, steps=3, budget=0,
                label=f"arena {self.gar_name}/{self.attack_name}")

        theta = np.asarray(jax.device_get(state.theta))
        evicted = sorted(policy.evicted_at) if policy else []
        evicted_honest = [w for w in evicted if w < h]
        evicted_byz = [w for w in evicted if w >= h]
        ttq = (min(policy.evicted_at[w] for w in evicted_byz)
               if evicted_byz else None)
        return {
            "gar": self.gar_name, "attack": self.attack_name,
            "quarantine": bool(quarantine), "steps": steps,
            "final_err": round(float(np.linalg.norm(theta - optimum)), 6),
            "agg_err_mean": round(float(np.mean(agg_errs)), 6),
            "agg_err_last10": round(float(np.mean(agg_errs[-10:])), 6),
            "loss_last": round(losses_seen[-1], 6),
            "evicted_honest": len(evicted_honest),
            "evicted_byz": len(evicted_byz),
            "time_to_quarantine": ttq,
            "f_reclaimed": int(reclaimed),
            "active_final": int(np.sum(active)),
        }
