"""Suspicion-driven quarantine — verdicts become actions.

`QuarantinePolicy` folds each step's defense diagnostics into a
`SuspicionTracker` (`obs/forensics.py`, with the collusion channel
enabled) and maintains the ACTIVE MASK the next step's masked-quorum
aggregation runs with. The mask is a runtime operand of one compiled
program (`quarantine_defense_kernel` below — blessed as the
`<gar>/quarantine` lattice cells), so an eviction costs a bool flip, not
a retrace, and the dynamic quorum (`faults/quorum.py::effective_f`)
shrinks `f_eff` in-jit as workers leave.

Eviction is deliberately harder than suspicion (the framing-resistance
contract — a Byzantine coalition must not be able to spend its rows
getting honest workers evicted):

  statistical channel   blended suspicion must sit at or above
                        `evict_threshold` (ABOVE the tracker's suspect
                        threshold) for `patience` consecutive steps.
                        The statistical components a framer can push
                        onto a victim — selection deficit (weight w_sel)
                        and distance z (w_dist) — are weighted so that
                        even a victim starved to deficit 1 with an
                        elevated-but-honest z stays BELOW the threshold;
                        crossing it needs the saturated z-score a
                        genuinely distant row earns, or collusion mass.
  collusion channel     a near-duplicate cluster whose members' collusion
                        EWMA reaches `collusion_evict` is DEDUPLICATED:
                        every member is evicted except the one with the
                        lowest collusion history (ties keep the lowest
                        row index — honest rows precede attack rows in
                        the stacked matrix, and a mimicry-framed victim's
                        row is byte-identical to its copies anyway, so
                        the kept representative preserves the victim's
                        information regardless; the analysis is now
                        FIELDED as `attacks/mimic.py` and pinned by the
                        tournament's zero-honest-eviction regression).
                        Keeping one member also keeps the dedup sound
                        for an honest pair that briefly collides.
  budget                at most `max_evictions` workers (default: the
                        declared f) are ever out at once — the hard cap
                        on the blast radius of ANY policy failure.
  hysteresis            with `reinstate=True`, a statistically-evicted
                        worker whose suspicion falls to the tracker's
                        clear level for `patience` steps re-enters (and
                        frees budget); default off — an eviction is an
                        operator-visible event, not a flap.

Everything here is host-side numpy between steps (the same cadence as
the CSV flush); the in-jit half is the masked kernel the mask feeds.
"""

import numpy as np

from byzantinemomentum_tpu.obs import recorder
from byzantinemomentum_tpu.obs.forensics import SuspicionTracker

__all__ = ["QuarantinePolicy", "quarantine_defense_kernel",
           "DEFAULT_WEIGHTS"]

# (selection deficit, distance z, quarantine history, collusion) — chosen
# so the framable channels (deficit + z at honest levels) cannot reach
# the default evict_threshold on their own: a starved victim reads
# ~0.35 + 0.25 * z_honest/4 < 0.55 for z_honest < ~3.2 sigma, while a
# genuinely distant never-selected row saturates to 0.6 and a colluding
# cluster adds up to 0.3 of hard evidence.
DEFAULT_WEIGHTS = (0.35, 0.25, 0.10, 0.30)


class QuarantinePolicy:
    """The closed loop's actuator: suspicion in, active mask out.

    Args:
      nb_workers: rows in the stacked submission matrix (honest + byz).
      f_decl: declared Byzantine tolerance — the default eviction budget.
      evict_threshold: blended-suspicion level the statistical channel
        must hold for `patience` steps (must exceed the tracker's
        suspect `threshold`).
      patience: consecutive steps of evidence before an eviction (and,
        with `reinstate`, of calm before a re-entry).
      collusion_evict: collusion-EWMA level that triggers cluster dedup.
      max_evictions: hard cap on concurrently-evicted workers
        (None -> f_decl).
      reinstate: allow statistically-evicted workers back after calm.
      tracker: extra kwargs for the underlying `SuspicionTracker`
        (alpha/threshold/clear/weights/min_steps/collusion_frac).
    """

    def __init__(self, nb_workers, f_decl, *, evict_threshold=0.55,
                 patience=5, collusion_evict=0.8, max_evictions=None,
                 reinstate=False, tracker=None):
        kwargs = {"alpha": 0.1, "weights": DEFAULT_WEIGHTS, "min_steps": 10}
        kwargs.update(tracker or {})
        self.tracker = SuspicionTracker(nb_workers, **kwargs)
        if len(self.tracker.weights) != 4:
            raise ValueError(
                "QuarantinePolicy needs the 4-component tracker (the "
                "collusion channel); pass a 4-tuple of weights")
        if evict_threshold < self.tracker.threshold:
            raise ValueError(
                f"evict_threshold ({evict_threshold}) must not undercut "
                f"the suspect threshold ({self.tracker.threshold}) — "
                f"eviction is the stronger verdict")
        self.nb_workers = int(nb_workers)
        self.f_decl = int(f_decl)
        self.evict_threshold = float(evict_threshold)
        self.patience = int(patience)
        self.collusion_evict = float(collusion_evict)
        self.max_evictions = (self.f_decl if max_evictions is None
                              else int(max_evictions))
        self.reinstate = bool(reinstate)
        n = self.nb_workers
        self.evicted = np.zeros(n, dtype=bool)
        self.evicted_at = {}          # worker -> first eviction step
        self.evictions_total = 0
        self._streak = np.zeros(n, dtype=np.int64)
        self._calm = np.zeros(n, dtype=np.int64)
        self._by_collusion = np.zeros(n, dtype=bool)

    # -------------------------------------------------------------- #

    def mask(self):
        """The active mask for the NEXT step's masked aggregation."""
        return ~self.evicted

    def f_reclaimed(self):
        """Quorum credit for the masked kernels (`faults/quorum.py::
        masked_aggregate` `f_evicted`): evictions backed by COLLUSION
        evidence — a deduplicated copy of a kept row adds no adversarial
        dimension to the remaining stack, so the declared tolerance can
        shrink with it without under-provisioning. Statistical-channel
        evictions never reclaim (a framed honest victim's eviction must
        not lower the tolerance below the real attacker count)."""
        return int(np.sum(self.evicted & self._by_collusion))

    def update(self, step, selection, distances=None, active=None,
               dist_matrix=None):
        """Fold one step's diagnostics (the `quarantine_defense_kernel`
        outputs) and return the updated active mask.

        `active` is the step's POST-sanitize effective mask (evictions
        already excluded, NaN rows quarantined) — it feeds the tracker's
        quarantine-history channel.
        """
        susp = self.tracker.update(step, selection, distances=distances,
                                   active=active, dist_matrix=dist_matrix)
        if self.tracker.steps < self.tracker.min_steps:
            return self.mask()

        # Statistical channel: sustained blended suspicion
        hot = (susp >= self.evict_threshold) & ~self.evicted
        self._streak = np.where(hot, self._streak + 1, 0)
        candidates = [(float(susp[w]), int(w), "suspicion")
                      for w in np.nonzero(
                          (self._streak >= self.patience)
                          & ~self.evicted)[0]]

        # Collusion channel: dedup each saturated near-duplicate cluster,
        # keeping its lowest-collusion member (ties -> lowest index)
        coll = self.tracker.collusion
        saturated = (coll >= self.collusion_evict) & ~self.evicted
        for cluster in self._clusters(saturated):
            keep = min(cluster, key=lambda w: (coll[w], w))
            candidates.extend(
                (float(coll[w]), int(w), "collusion")
                for w in cluster if w != keep)

        # Strongest evidence first, within the global budget
        for score, worker, channel in sorted(candidates, reverse=True):
            if self.evicted[worker]:
                continue  # a worker can surface on both channels
            if int(self.evicted.sum()) >= self.max_evictions:
                break
            self.evicted[worker] = True
            # Collusion-backed evictions (the dedup channel, or a blended
            # eviction whose worker spent the majority of its recent
            # history in a near-duplicate cluster) reclaim quorum
            self._by_collusion[worker] = (channel == "collusion"
                                          or coll[worker] >= 0.5)
            self.evicted_at.setdefault(worker, int(step))
            self.evictions_total += 1
            self._streak[worker] = 0
            recorder.emit("quarantine_evict", worker=worker, step=int(step),
                          channel=channel, score=round(score, 4),
                          active=int((~self.evicted).sum()))

        if self.reinstate:
            calm = susp <= self.tracker.clear
            self._calm = np.where(calm, self._calm + 1, 0)
            back = (self.evicted & ~self._by_collusion
                    & (self._calm >= self.patience))
            for worker in np.nonzero(back)[0]:
                self.evicted[worker] = False
                self._calm[worker] = 0
                recorder.emit("quarantine_reinstate", worker=int(worker),
                              step=int(step),
                              suspicion=round(float(susp[worker]), 4))
        return self.mask()

    def _clusters(self, members):
        """Connected components of the tracker's last near-duplicate
        adjacency, restricted to `members`; singletons dropped (a lone
        saturated row with no current partner is stale evidence)."""
        partners = self.tracker.partners
        seen = np.zeros(self.nb_workers, dtype=bool)
        for start in np.nonzero(members)[0]:
            if seen[start]:
                continue
            stack, component = [int(start)], []
            seen[start] = True
            while stack:
                w = stack.pop()
                component.append(w)
                for nxt in np.nonzero(partners[w] & members & ~seen)[0]:
                    seen[nxt] = True
                    stack.append(int(nxt))
            if len(component) > 1:
                yield sorted(component)

    # -------------------------------------------------------------- #

    def summary(self):
        """JSON-safe snapshot (tournament scoreboard / report rows)."""
        return {
            "evicted": [int(w) for w in np.nonzero(self.evicted)[0]],
            "evictions_total": int(self.evictions_total),
            "evicted_at": {str(w): s for w, s in
                           sorted(self.evicted_at.items())},
            "budget": self.max_evictions,
            "f_reclaimed": self.f_reclaimed(),
            "tracker": self.tracker.summary(),
        }


def quarantine_defense_kernel(gar, *, f, kwargs=None, dynamic=True):
    """The closed loop's per-step defense program AT THE QUARANTINE CALL
    SITE — the traceable program the `<gar>/quarantine` lattice cells
    fingerprint
    (`analysis/lattice.py`): NaN-sanitize composed over the policy mask,
    the masked-quorum aggregate with dynamic `f_eff`
    (`faults/quorum.py::masked_aggregate`), and the rule-agnostic serve
    aux (`ops/diag.py::masked_generic_aux`) whose selection /
    worker-distance / distance-matrix outputs are exactly what
    `QuarantinePolicy.update` consumes.

    `(G: f32[n, d], active: bool[n], f_evicted: i32[]) -> dict` —
    `active` and `f_evicted` are RUNTIME operands: mask updates (and the
    quorum credit for confirmed-duplicate evictions, `masked_aggregate`'s
    `f_evicted`) re-use this one compiled program between steps — the
    zero-recompile contract the tournament smoke asserts.
    """
    from byzantinemomentum_tpu.faults import quorum, sanitize
    from byzantinemomentum_tpu.ops import diag

    kwargs = {} if kwargs is None else kwargs

    def program(G, active, f_evicted):
        active_eff, _ = sanitize.quarantine(G, active)
        aggregate, f_eff = quorum.masked_aggregate(
            gar, G, active_eff, f_decl=f, dynamic=dynamic,
            f_evicted=f_evicted, **kwargs)
        aux = diag.masked_generic_aux(G, aggregate, active_eff, f_eff)
        return {"aggregate": aggregate, "f_eff": f_eff,
                "active": active_eff, "selection": aux["selection"],
                "worker_dist": aux["worker_dist"], "dist": aux["dist"]}

    return program
