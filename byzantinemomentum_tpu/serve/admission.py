"""Admission control — the first defense the aggregation service
actually MOUNTS (until now suspicion verdicts only rode responses).

At submit time the service peeks each row's client verdict
(`obs/forensics.py::ClientSuspicionStore.verdict` — read-only, no EWMA
advance) and applies the configured action to the rows of clients the
store currently distrusts:

  mask        the row enters the packed batch INACTIVE: the traced-count
              masked kernels exclude it and the effective quorum
              recomputes (`faults/quorum.py`) — the row is rejected from
              the aggregate but the request still answers, with the
              rejection recorded in the response's admission provenance.
  downweight  the row is blended toward the mean of the cohort's
              admitted rows (`weight * row + (1 - weight) * mean`): the
              client keeps a (shrunk) vote while its evidence
              accumulates — the soft action for low-confidence verdicts.

Two evidence channels gate a row, mirroring the train-side quarantine
policy (`arena/quarantine.py`):

  suspect     the store's hysteresis verdict (blended EWMA suspicion
              over `min_obs` observations) — the per-client statistical
              channel.
  collusion   the store's near-duplicate EWMA at `collusion_threshold`
              or above after `collusion_min_obs` observations — the
              COHORT-level channel, and the one that catches Sybil
              traffic: one perturbation split across many fresh client
              ids keeps every per-client statistic unremarkable, but the
              split shards are mutually near-identical across distinct
              ids (`arena/sybil.py` is the red team for exactly this).

Safety: at most `max_frac` of a cohort is ever masked (weakest evidence
readmitted first) — an admission false-positive storm must not disable
the GAR's own robustness by shrinking the quorum below usefulness.
"""

import numpy as np

__all__ = ["AdmissionPolicy", "ADMISSION_WEIGHTS"]

# Store weights that enable the collusion channel (the 4-component form
# of `obs/forensics.py` — same shape as the train-side quarantine
# policy's DEFAULT_WEIGHTS).
ADMISSION_WEIGHTS = (0.35, 0.25, 0.10, 0.30)


class AdmissionPolicy:
    """The service's row-admission rule.

    Args:
      mode: "mask" (reject rows from the aggregate) or "downweight"
        (blend toward the admitted cohort mean).
      collusion_threshold: collusion-EWMA level that flags a client.
      collusion_min_obs: observations before the collusion channel may
        flag (below the store's own `min_obs` — coordinated duplicates
        are harder evidence than statistics, so they act sooner).
      downweight: surviving weight of a downweighted row.
      max_frac: largest fraction of a cohort the policy may mask.
    """

    def __init__(self, mode="mask", *, collusion_threshold=0.5,
                 collusion_min_obs=3, downweight=0.25, max_frac=0.5):
        if mode not in ("mask", "downweight"):
            raise ValueError(
                f"Unknown admission mode {mode!r}; expected 'mask' or "
                f"'downweight'")
        if not 0.0 <= downweight <= 1.0:
            raise ValueError(
                f"Expected a downweight in [0, 1], got {downweight}")
        if not 0.0 < max_frac <= 1.0:
            raise ValueError(
                f"Expected max_frac in (0, 1], got {max_frac}")
        self.mode = mode
        self.collusion_threshold = float(collusion_threshold)
        self.collusion_min_obs = int(collusion_min_obs)
        self.downweight = float(downweight)
        self.max_frac = float(max_frac)

    def decide(self, client_ids, store):
        """Per-row admission decision for one cohort.

        Returns `(admitted: bool[n], flagged: {client: reason})` —
        `admitted` is False only in "mask" mode (downweighting keeps the
        row active); `flagged` carries the verdict provenance either way.
        """
        n = len(client_ids)
        admitted = np.ones(n, dtype=bool)
        flagged = {}
        evidence = []  # (score, row) for the max_frac readmission order
        for i, client in enumerate(client_ids):
            verdict = store.verdict(client)
            if verdict is None:
                continue
            reason = None
            if (verdict["collusion"] >= self.collusion_threshold
                    and verdict["observations"] >= self.collusion_min_obs):
                reason = "collusion"
            elif verdict["suspect"]:
                reason = "suspect"
            if reason is not None:
                flagged[str(client)] = {
                    "reason": reason, "action": self.mode,
                    "suspicion": verdict["suspicion"],
                    "collusion": verdict["collusion"]}
                evidence.append(
                    (max(verdict["collusion"], verdict["suspicion"]), i))
        if self.mode == "mask" and evidence:
            budget = int(self.max_frac * n)
            evidence.sort(reverse=True)
            for rank, (_, row) in enumerate(evidence):
                if rank < budget:
                    admitted[row] = False
                else:  # weakest evidence re-admitted under the cap
                    flagged[str(client_ids[row])]["action"] = "readmitted"
        return admitted, flagged

    def apply(self, matrix, admitted, flagged, client_ids):
        """Transform the request payload per the decisions (called once
        at submit time, before packing): "mask" leaves the matrix alone
        (the packer drops the rows from the active set); "downweight"
        blends flagged rows toward the mean of the unflagged ones."""
        if self.mode != "downweight" or not flagged:
            return matrix
        flagged_rows = np.array(
            [str(c) in flagged for c in client_ids], dtype=bool)
        if flagged_rows.all():
            return matrix  # nothing trustworthy to blend toward
        center = matrix[~flagged_rows].mean(axis=0)
        out = matrix.copy()
        out[flagged_rows] = (self.downweight * matrix[flagged_rows]
                             + (1.0 - self.downweight) * center[None, :])
        return out
