"""Persistent compiled GAR programs for the aggregation service.

A served aggregation is one device program over a *cell*:

    (gar, n-bucket, f, d, diagnostics)

Request row counts are rounded UP to a small set of shape buckets and the
padding rows are masked out through the PR 1 masked-quorum GAR variants
(`faults/quorum.py::masked_aggregate` — inactive rows never select, never
average, and the effective Byzantine tolerance is recomputed from the
traced active count), so steady-state traffic over mixed n never
recompiles: every request lands on one of the bucket programs compiled at
warm-up. Only the GARs with TRUE masked kernels (`average`, `median`,
`trmean`, `krum` and their `native-` tiers) take padded buckets; the rest
fall back to the documented NaN-routing contract, which is only correct
while `absent + byzantine <= f` — more padding than that would break the
rule's guarantee — so those rules get EXACT cells (`n_bucket == n`: one
compile per distinct n, still cached and persistent).

The batch axis is bucketed the same way: concurrent same-cell requests
pack along a leading request axis (`vmap` over the per-request program)
whose length rounds up to a power of two, padding slots repeating the
first request's payload (their outputs are dropped — repeating real data
keeps the padded lanes numerically tame). One compiled program therefore
serves every (n <= bucket, batch <= bucket) combination of its cell.

Dispatch is async — the executable call returns before the device
finishes, and the service resolves caller futures on device-ready.
(PR 8 additionally requested `donate_argnums` on the packed matrix; the
BMT-H03 structural gate showed the request was inert — no program output
matches the `(B, N, d)` buffer's shape, so jax drops the aliasing and
warns on donation-capable backends. The dead request is gone; the
lattice cell `serve/...` pins the no-aliasing layout, and the engine's
update cell pins the contract where donation IS honored.)

Diagnostics cells additionally return the serve aux
(`ops/diag.py::masked_generic_aux`): per-row scores, selection mass and
mean finite pairwise distance — the inputs of the per-client suspicion
store. The masked aggregate stays authoritative either way (the PR 4
fault-step discipline).
"""

import threading

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu import ops, utils
from byzantinemomentum_tpu.faults import quorum
from byzantinemomentum_tpu.obs import recorder
from byzantinemomentum_tpu.ops import diag

__all__ = ["Cell", "ProgramCache", "OversizeRequest", "N_BUCKETS",
           "MASKED_GARS", "batch_bucket", "row_bucket"]

# Row-count shape buckets: requests round up to the smallest bucket >= n.
# The ladder is geometric so at most 2x rows are ever padded, and capped
# where the fused Pallas pipeline caps (`ops/pallas_gar.py::MAX_ROWS`).
N_BUCKETS = (4, 8, 16, 32, 64)

# GARs with exact masked-quorum kernels (`faults/quorum.py` dispatch):
# these aggregate the active subset EXACTLY regardless of how many padded
# rows ride along, so they are the rules that take padded buckets.
MASKED_GARS = frozenset({"average", "median", "trmean", "krum"})


class OversizeRequest(utils.UserException):
    """The request's row count exceeds every configured shape bucket."""


def _base_name(name):
    return name[len("native-"):] if name.startswith("native-") else name


def row_bucket(gar_name, n, buckets=N_BUCKETS):
    """The bucketed row count for a request of `n` rows: the smallest
    bucket >= n for the masked-family GARs, `n` itself (an exact cell)
    for rules whose padding contract would not hold. Raises
    `OversizeRequest` beyond the largest bucket."""
    if n < 1:
        raise utils.UserException(f"Expected at least one row, got {n}")
    if n > buckets[-1]:
        raise OversizeRequest(
            f"Request of {n} rows exceeds the largest shape bucket "
            f"({buckets[-1]}); shard the cohort or raise the bucket ladder")
    if _base_name(gar_name) not in MASKED_GARS:
        return n
    for b in buckets:
        if n <= b:
            return b
    raise OversizeRequest(f"No bucket holds {n} rows")  # unreachable


def batch_bucket(b, max_batch):
    """Round a packed batch size up to a power of two <= max_batch."""
    out = 1
    while out < b and out < max_batch:
        out *= 2
    return out


class Cell(tuple):
    """Hashable program-cache key `(gar, n_bucket, f, d, diagnostics)`."""

    __slots__ = ()

    def __new__(cls, gar, n_bucket, f, d, diagnostics):
        return tuple.__new__(cls, (str(gar), int(n_bucket), int(f), int(d),
                                   bool(diagnostics)))

    gar = property(lambda self: self[0])
    n_bucket = property(lambda self: self[1])
    f = property(lambda self: self[2])
    d = property(lambda self: self[3])
    diagnostics = property(lambda self: self[4])

    def __repr__(self):
        return (f"Cell({self.gar}, n={self.n_bucket}, f={self.f}, "
                f"d={self.d}, diag={self.diagnostics})")


def _build(cell):
    """Compile-ready program for one cell: `vmap` of the per-request
    masked aggregation along the leading request axis. Inputs
    `(G: f32[B, N, d], active: bool[B, N])`, outputs a dict of stacked
    per-request results. No donation: no output matches the packed
    matrix's shape, so a `donate_argnums` request could never alias
    (BMT-H03 — the lattice cell pins this layout)."""
    gar = ops.gars[cell.gar]
    f, diagnostics = cell.f, cell.diagnostics

    def one(G, active):
        agg, f_eff = quorum.masked_aggregate(gar, G, active, f_decl=f)
        out = {"aggregate": agg, "f_eff": f_eff}
        if diagnostics:
            aux = diag.masked_generic_aux(G, agg, active, f_eff)
            out["scores"] = aux["scores"]
            out["selection"] = aux["selection"]
            out["worker_dist"] = aux["worker_dist"]
        return out

    return jax.jit(jax.vmap(one))


class ProgramCache:
    """The persistent compiled-program store, keyed by cell.

    One jitted callable per cell serves every batch bucket (jit re-lowers
    per concrete batch shape under the same wrapper); `get` counts
    hits/misses per `(cell, batch_bucket)` — the unit that actually
    compiles — through the active obs recorder (`serve_program_hit` /
    `serve_program_miss` counters), so a warm serving loop's zero-compile
    claim is observable, and `analysis/contracts.py::
    assert_recompile_budget` can hold it to zero at the XLA level.

    Thread-safe: the service's caller threads (warm-up) and the
    microbatch flusher both reach `get`.
    """

    def __init__(self, buckets=N_BUCKETS):
        self.buckets = tuple(sorted(buckets))
        self._programs = {}
        self._warm = set()     # (cell, batch_bucket) pairs seen
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def cell(self, gar, n, f, d, diagnostics):
        """The cell a request of `n` rows lands on (bucketing the rows)."""
        if gar not in ops.gars:
            raise utils.UserException(
                f"Unknown aggregation rule {gar!r}; registered: "
                f"{', '.join(sorted(ops.gars))}")
        return Cell(gar, row_bucket(gar, n, self.buckets), f, d, diagnostics)

    def get(self, cell, batch):
        """The compiled program for `cell`, counting a hit/miss for the
        `(cell, batch)` shape about to run (`batch` is the already-
        bucketed leading-axis length the caller packed to)."""
        with self._lock:
            program = self._programs.get(cell)
            if program is None:
                program = self._programs[cell] = _build(cell)
            key = (cell, int(batch))
            if key in self._warm:
                self.hits += 1
                hit = True
            else:
                self._warm.add(key)
                self.misses += 1
                hit = False
        recorder.counter("serve_program_hit" if hit else "serve_program_miss")
        return program

    def stats(self):
        with self._lock:
            return {"cells": len(self._programs), "hits": self.hits,
                    "misses": self.misses,
                    "programs": len(self._warm)}
