"""Persistent compiled GAR programs for the aggregation service.

A served aggregation is one device program over a *cell*:

    (gar, n-bucket, f, d-bucket, diagnostics)

Shape buckets collapse the compile lattice on BOTH data axes:

* ROWS — request row counts round UP a small geometric ladder and the
  padding rows are masked out through the traced-count masked-quorum GAR
  kernels (`faults/quorum.py::masked_aggregate`): inactive rows never
  select, never average, and the effective Byzantine tolerance is
  recomputed from the traced active count. Since PR 10 EVERY registered
  rule has a true traced-count kernel (bulyan's stage-1 scan runs inert
  padded rounds, brute enumerates over the active subset with a
  worst-case-sized rank space, phocas/meamed/aksel/cge turn their static
  slice bounds into rank predicates), so every rule takes padded
  buckets. The one exception is brute at an infeasible declared rank
  space (`ops/brute.py::masked_rank_space` — the masked program must
  provision `C(n_bucket, f)` subsets statically): those requests get an
  EXACT row cell (`n_bucket == n`), still cached and persistent, with the
  reason pinned in `row_bucket`.

* COLUMNS — request dimensions round UP the `D_BUCKETS` ladder (then by
  doubling) with ZERO padding, and the aggregate is sliced back to the
  request's true width. Zero columns are exact for every registered rule
  — the per-rule proof lives in `D_PAD_EXACT` below — so heterogeneous
  model sizes stop compiling per d. A rule whose proof ever fails routes
  to exact-d (`col_bucket` consults the registry); today none does.

Steady-state traffic over mixed (n, d) therefore never recompiles: every
request lands on one of the bucket programs compiled at warm-up, and
requests of DIFFERENT raw shapes that share a cell microbatch together.

The batch axis is bucketed the same way: concurrent same-cell requests
pack along a leading request axis (`vmap` over the per-request program)
whose length rounds up to a power of two, padding slots repeating the
first request's payload (their outputs are dropped — repeating real data
keeps the padded lanes numerically tame). One compiled program therefore
serves every (n <= bucket, d <= bucket, batch <= bucket) combination of
its cell.

Dispatch is async — the executable call returns before the device
finishes, and the service resolves caller futures on device-ready.
(PR 8 additionally requested `donate_argnums` on the packed matrix; the
BMT-H03 structural gate showed the request was inert — no program output
matches the `(B, N, D)` buffer's shape, so jax drops the aliasing and
warns on donation-capable backends. The dead request is gone; the
lattice cell `serve/...` pins the no-aliasing layout, and the engine's
update cell pins the contract where donation IS honored.)

Diagnostics cells additionally return the serve aux
(`ops/diag.py::masked_generic_aux`): per-row scores, selection mass and
mean finite pairwise distance — the inputs of the per-client suspicion
store. The masked aggregate stays authoritative either way (the PR 4
fault-step discipline).
"""

import threading

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu import ops, utils
from byzantinemomentum_tpu.faults import quorum
from byzantinemomentum_tpu.obs import recorder
from byzantinemomentum_tpu.ops import brute as brute_mod, diag
from byzantinemomentum_tpu.utils.locking import NamedLock

__all__ = ["Cell", "ProgramCache", "OversizeRequest", "N_BUCKETS",
           "D_BUCKETS", "MASKED_GARS", "D_PAD_EXACT", "batch_bucket",
           "row_bucket", "col_bucket"]

# Row-count shape buckets: requests round up to the smallest bucket >= n.
# The ladder is geometric so at most 2x rows are ever padded, and capped
# where the fused Pallas pipeline caps (`ops/pallas_gar.py::MAX_ROWS`).
N_BUCKETS = (4, 8, 16, 32, 64)

# Column (model-dimension) shape buckets: the ladder covers the common
# request range, then extends by doubling — every d lands on a warm
# program at the cost of < 2x padded FLOPs. No upper cap: a big model is
# a legitimate client, it just pays its own (cached) compile.
D_BUCKETS = (32, 64, 128, 256, 512, 1024)

# Every first-tier registered rule has a traced-count masked kernel
# (`faults/quorum.py` dispatch), so every rule takes padded row buckets.
# Kept as an explicit registry (not "everything") so a future rule
# without a masked kernel degrades to exact row cells instead of
# silently serving a wrong contract.
MASKED_GARS = frozenset({"average", "median", "trmean", "krum", "bulyan",
                         "brute", "phocas", "meamed", "aksel", "cge"})

# The per-rule d-padding exactness proof: appending ZERO columns (and
# slicing the aggregate back) must not change any real coordinate of the
# output. The shared lemmas:
#   (L1) squared distances / norms / Gram entries gain only `+ 0` terms,
#        so every distance-derived ordering, score, selection and f_eff
#        is unchanged bit for bit;
#   (L2) coordinate-wise reductions (sort / median / trimmed mean /
#        closest-mean / row-weighted averages) act per column, so real
#        columns never see the padded ones;
#   (L3) the padded columns of the MASKED aggregate are exactly 0 for
#        every rule (means/medians/trims of all-zero active values, or a
#        weight vector hitting zero columns), so the serve aux's
#        distance-to-aggregate scores also gain only `+ 0` terms.
# Each entry cites the lemmas that close its proof; a rule that cannot
# be proven must map to False and is routed to exact-d by `col_bucket`.
D_PAD_EXACT = {
    "average": True,   # L2: per-column mean
    "median": True,    # L2: per-column sort + take
    "trmean": True,    # L2: per-column sort + rank-trimmed mean
    "phocas": True,    # L2 twice: trmean center, then closest-mean
    "meamed": True,    # L2 twice: median center, then closest-mean
    "krum": True,      # L1 scores/selection + L2 weighted row average
    "bulyan": True,    # L1 stage-1 scan + L2 stage-2 averaged median
    "aksel": True,     # L2 median center + L1 squared distances + L2 mean
    "cge": True,       # L1 norms + L2 mean
    "brute": True,     # L1 diameters (subset unchanged) + L2 mean
}


class OversizeRequest(utils.UserException):
    """The request's row count exceeds every configured shape bucket."""


def _base_name(name):
    return name[len("native-"):] if name.startswith("native-") else name


def row_bucket(gar_name, n, buckets=N_BUCKETS, f=None):
    """The bucketed row count for a request of `n` rows: the smallest
    bucket >= n whose masked program is buildable. Every registered rule
    has a traced-count masked kernel; the only unbuildable case is brute
    at a bucket whose worst-case subset enumeration `C(bucket, f)`
    exceeds `ops/brute.py::MASKED_MAX_SUBSETS` — such requests fall back
    to an EXACT row cell (n_bucket == n, one compile per distinct n,
    still cached; the exact cell itself may also be infeasible, in which
    case the quorum layer's NaN-routing fallback serves it). Raises
    `OversizeRequest` beyond the largest bucket."""
    if n < 1:
        raise utils.UserException(f"Expected at least one row, got {n}")
    if n > buckets[-1]:
        raise OversizeRequest(
            f"Request of {n} rows exceeds the largest shape bucket "
            f"({buckets[-1]}); shard the cohort or raise the bucket ladder")
    base = _base_name(gar_name)
    if base not in MASKED_GARS:
        return n
    for b in buckets:
        if n <= b:
            if base == "brute" and f is not None and (
                    brute_mod.masked_rank_space(b, f) is None):
                # Infeasible masked enumeration at this bucket: exact cell
                return n
            return b
    raise OversizeRequest(f"No bucket holds {n} rows")  # unreachable


def col_bucket(gar_name, d, buckets=D_BUCKETS):
    """The bucketed column count for a request of width `d`: the smallest
    ladder bucket >= d (doubling past the ladder top) for rules whose
    d-padding proof holds (`D_PAD_EXACT`), `d` itself — an exact-d cell —
    for any rule whose proof fails."""
    if d < 1:
        raise utils.UserException(f"Expected at least one column, got {d}")
    if not D_PAD_EXACT.get(_base_name(gar_name), False):
        return d
    for b in buckets:
        if d <= b:
            return b
    b = buckets[-1]
    while b < d:
        b *= 2
    return b


def batch_bucket(b, max_batch):
    """Round a packed batch size up to a power of two <= max_batch."""
    out = 1
    while out < b and out < max_batch:
        out *= 2
    return out


class Cell(tuple):
    """Hashable program-cache key `(gar, n_bucket, f, d_bucket,
    diagnostics)` — both shape coordinates are the BUCKETED (compiled)
    sizes; requests carry their raw (n, d) alongside."""

    __slots__ = ()

    def __new__(cls, gar, n_bucket, f, d_bucket, diagnostics):
        return tuple.__new__(cls, (str(gar), int(n_bucket), int(f),
                                   int(d_bucket), bool(diagnostics)))

    gar = property(lambda self: self[0])
    n_bucket = property(lambda self: self[1])
    f = property(lambda self: self[2])
    d = property(lambda self: self[3])
    d_bucket = property(lambda self: self[3])
    diagnostics = property(lambda self: self[4])

    def __repr__(self):
        return (f"Cell({self.gar}, n={self.n_bucket}, f={self.f}, "
                f"d={self.d_bucket}, diag={self.diagnostics})")


def _build(cell):
    """Compile-ready program for one cell: `vmap` of the per-request
    masked aggregation along the leading request axis. Inputs
    `(G: f32[B, N, D], active: bool[B, N])`, outputs a dict of stacked
    per-request results (aggregates at the bucketed width D — the
    resolver slices each back to its request's raw d). No donation: no
    output matches the packed matrix's shape, so a `donate_argnums`
    request could never alias (BMT-H03 — the lattice cell pins this
    layout)."""
    gar = ops.gars[cell.gar]
    f, diagnostics = cell.f, cell.diagnostics

    def one(G, active):
        agg, f_eff = quorum.masked_aggregate(gar, G, active, f_decl=f)
        out = {"aggregate": agg, "f_eff": f_eff}
        if diagnostics:
            aux = diag.masked_generic_aux(G, agg, active, f_eff)
            out["scores"] = aux["scores"]
            out["selection"] = aux["selection"]
            out["worker_dist"] = aux["worker_dist"]
            # The (N, N) pairwise matrix rides out too: the suspicion
            # store's collusion channel (Sybil detection) needs the
            # cohort geometry, not just per-row summaries — at bucket
            # sizes (N <= 64) it is noise next to the (N, D) payload
            out["dist"] = aux["dist"]
        return out

    return jax.jit(jax.vmap(one))


class ProgramCache:
    """The persistent compiled-program store, keyed by cell.

    One jitted callable per cell serves every batch bucket (jit re-lowers
    per concrete batch shape under the same wrapper); `get` counts
    hits/misses per `(cell, batch_bucket)` — the unit that actually
    compiles — through the active obs recorder (`serve_program_hit` /
    `serve_program_miss` counters), so a warm serving loop's zero-compile
    claim is observable, and `analysis/contracts.py::
    assert_recompile_budget` can hold it to zero at the XLA level.

    Thread-safe: the service's caller threads (warm-up) and the
    microbatch flusher both reach `get`.
    """

    def __init__(self, buckets=N_BUCKETS, d_buckets=D_BUCKETS):
        self.buckets = tuple(sorted(buckets))
        self.d_buckets = tuple(sorted(d_buckets))
        self._programs = {}
        self._warm = set()     # (cell, batch_bucket) pairs seen
        self._lock = NamedLock("programs.cache")
        self.hits = 0
        self.misses = 0

    def cell(self, gar, n, f, d, diagnostics):
        """The cell a request of raw shape `(n, d)` lands on (bucketing
        both axes)."""
        if gar not in ops.gars:
            raise utils.UserException(
                f"Unknown aggregation rule {gar!r}; registered: "
                f"{', '.join(sorted(ops.gars))}")
        return Cell(gar, row_bucket(gar, n, self.buckets, f=f), f,
                    col_bucket(gar, d, self.d_buckets), diagnostics)

    def get(self, cell, batch):
        """The compiled program for `cell`, counting a hit/miss for the
        `(cell, batch)` shape about to run (`batch` is the already-
        bucketed leading-axis length the caller packed to)."""
        with self._lock:
            program = self._programs.get(cell)
            if program is None:
                program = self._programs[cell] = _build(cell)
            key = (cell, int(batch))
            if key in self._warm:
                self.hits += 1
                hit = True
            else:
                self._warm.add(key)
                self.misses += 1
                hit = False
        recorder.counter("serve_program_hit" if hit else "serve_program_miss")
        return program

    def stats(self):
        with self._lock:
            return {"cells": len(self._programs), "hits": self.hits,
                    "misses": self.misses,
                    "programs": len(self._warm)}
