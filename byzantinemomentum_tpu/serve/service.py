"""The aggregation service: batched, cached, asynchronously-dispatched
Byzantine-resilient aggregation over the existing in-jit GAR machinery.

One `AggregationService` owns the three moving parts and wires them to
the telemetry substrate every other subsystem already uses:

  * a `ProgramCache` of persistent compiled programs per
    `(gar, n-bucket, f, d, diagnostics)` cell (`serve/programs.py`) —
    steady-state traffic never recompiles;
  * a `MicroBatcher` packing concurrent same-cell requests into one
    device program along a leading request axis, flushed by
    max-batch-size / max-delay, with async dispatch (`serve/batching.py`)
    — callers get futures resolved on device-ready, the host thread
    never blocks;
  * a `ClientSuspicionStore` (`obs/forensics.py`) folding each
    diagnostics cell's serve aux into client-id-keyed EWMA suspicion,
    whose verdicts ride back on each response.

Request tracing (`obs/trace/request.py`, on by default): every request
carries a `RequestTrace` whose monotonic span stamps — validate, queue
wait, pack, dispatch, resolver wake-up, device, resolve — tile the
measured submit→resolve latency; completed traces land in a bounded
ring buffer (`TraceBuffer`) whose per-phase p50/p99 summary rides
`stats()` and the SIGUSR1 snapshot (`write_trace_snapshot`), and the
trace record rides back on each response. `tracing=False` disables the
stamps entirely (the serve selfcheck measures and bounds the on/off
overhead).

Supervision follows the run pattern (`utils/jobs.py`): the service
writes the same atomic `heartbeat.json` the Jobs watchdog consumes (the
`step` field counts served requests, so a wedged device stalls the
signal and the watchdog's kill/retry applies unchanged), and counters /
gauges land in the run's `telemetry.jsonl` through the obs recorder.
"""

import pathlib
import threading
import time

import numpy as np

from byzantinemomentum_tpu import utils
from byzantinemomentum_tpu.obs import recorder
from byzantinemomentum_tpu.obs.forensics import ClientSuspicionStore
from byzantinemomentum_tpu.obs.heartbeat import write_heartbeat
from byzantinemomentum_tpu.obs.metrics import (LATENCY_MS_BOUNDS,
                                               OCCUPANCY_BOUNDS,
                                               MetricsRegistry,
                                               NullRegistry)
from byzantinemomentum_tpu.obs.trace import RequestTrace, TraceBuffer
from byzantinemomentum_tpu.serve.batching import MicroBatcher, ServeRequest
from byzantinemomentum_tpu.serve.programs import (
    N_BUCKETS, ProgramCache, batch_bucket)
from byzantinemomentum_tpu.utils.locking import NamedLock

__all__ = ["AggregationService", "AggregateResult"]


class AggregateResult:
    """One resolved aggregation response."""

    __slots__ = ("aggregate", "f_eff", "n", "cell", "verdicts",
                 "admission", "latency_ms", "trace")

    def __init__(self, aggregate, f_eff, n, cell, verdicts, latency_ms,
                 admission=None, trace=None):
        self.aggregate = aggregate    # np.f32[d] (raw request width)
        self.f_eff = f_eff            # effective Byzantine tolerance used
        self.n = n                    # submitted rows (pre-bucket)
        self.cell = cell              # the program cell served from
        self.verdicts = verdicts      # {client_id: verdict} | None
        self.admission = admission    # {client_id: decision} | None —
        #                               the submit-time admission-control
        #                               provenance (`serve/admission.py`)
        self.latency_ms = latency_ms  # submit -> resolve wall time
        self.trace = trace            # completed RequestTrace | None

    def as_dict(self):
        """JSON-safe view (the line-JSON front end's response body).
        The trace converts to its record dict HERE — on the serializing
        caller's clock, never the resolver thread's."""
        return {
            "aggregate": [float(x) for x in self.aggregate],
            "f_eff": int(self.f_eff),
            "n": self.n,
            "cell": {"gar": self.cell.gar, "n_bucket": self.cell.n_bucket,
                     "f": self.cell.f, "d_bucket": self.cell.d_bucket,
                     "diagnostics": self.cell.diagnostics},
            "verdicts": self.verdicts,
            "admission": self.admission,
            "latency_ms": round(self.latency_ms, 3),
            **({"trace": self.trace.as_dict()}
               if self.trace is not None else {}),
        }


class AggregationService:
    """Submit gradient/update cohorts, receive robust aggregates plus
    per-client suspicion verdicts.

    Args:
      max_batch: requests packed into one device program (per cell).
      max_delay_ms: longest a queued request waits for batch-mates.
      buckets: the row-count shape-bucket ladder (`serve/programs.py`).
      diagnostics: default for requests that don't say (diagnostics
        cells compute the serve aux and feed the suspicion store).
      directory: optional run directory — enables the heartbeat file
        (and a `Telemetry` recorder when none is active) so the Jobs
        watchdog can supervise the serving process like any run.
      heartbeat_interval: seconds between heartbeat writes (with a
        directory; the writer is a daemon thread).
      suspicion: kwargs forwarded to `ClientSuspicionStore`. With
        admission enabled and no explicit weights, the store runs the
        4-component form (`serve/admission.py::ADMISSION_WEIGHTS`) so
        the collusion/Sybil channel is live.
      admission: None (verdicts ride responses but gate nothing — the
        pre-admission behavior), an `AdmissionPolicy`, or a kwargs dict
        for one (`serve/admission.py`): suspect/colluding clients' rows
        are masked out of (or down-weighted in) the aggregate at submit
        time, with the decision provenance on the response.
      tracing: per-request span tracing (`obs/trace/request.py`). On by
        default — the stamps are a handful of monotonic-clock reads per
        request (overhead measured and bounded by the serve selfcheck's
        trace phase); `False` skips them entirely.
      trace_buffer: completed traces the in-memory ring keeps (the
        `stats`/SIGUSR1 summary window; old traces fall off).
      metrics: the process-local metrics registry (`obs/metrics`, r18) —
        the request/serve counters, the end-to-end and per-phase latency
        histograms and the batcher's depth/occupancy distributions all
        land here, and `{"op": "metrics"}` on the front end dumps it.
        `True` builds a fresh registry, `False` a `NullRegistry` (the
        paired-overhead baseline arm), or pass a registry instance.
    """

    def __init__(self, *, max_batch=8, max_delay_ms=2.0, buckets=N_BUCKETS,
                 diagnostics=True, directory=None, heartbeat_interval=2.0,
                 suspicion=None, admission=None, tracing=True,
                 trace_buffer=512, metrics=True):
        from byzantinemomentum_tpu.serve.admission import (
            ADMISSION_WEIGHTS, AdmissionPolicy)

        self.cache = ProgramCache(buckets=buckets)
        self.max_batch = int(max_batch)
        self.diagnostics = bool(diagnostics)
        self.tracing = bool(tracing)
        # The metrics plane (obs/metrics): instance-owned, never
        # process-global — a LocalFleet runs N services in ONE process
        # and each shard's numbers must stay its own. Hot-path handles
        # are bound once here; a bump is one per-metric lock + int add.
        if metrics is True:
            metrics = MetricsRegistry(source="serve")
        elif not metrics:
            metrics = NullRegistry()
        self.metrics = metrics
        self._m_requests = metrics.counter("serve_requests")
        self._m_served = metrics.counter("serve_served")
        self._m_rejected = metrics.counter("serve_rejected")
        self._m_masked = metrics.counter("serve_admission_masked")
        self._m_downweighted = metrics.counter(
            "serve_admission_downweighted")
        self._m_latency = metrics.histogram("serve_request_ms",
                                            bounds=LATENCY_MS_BOUNDS)
        self._m_occupancy = metrics.histogram("serve_batch_occupancy",
                                              bounds=OCCUPANCY_BOUNDS)
        self.traces = TraceBuffer(trace_buffer, metrics=metrics)
        # Stamped into every trace's meta (r19): the wire trace record
        # then names WHICH shard served, so the router's cross-process
        # join can cross-check routing against the shard's own identity
        self._trace_src = getattr(metrics, "source", None)
        if isinstance(admission, dict):
            admission = AdmissionPolicy(**admission)
        self.admission = admission
        suspicion = dict(suspicion or {})
        if admission is not None:
            suspicion.setdefault("weights", ADMISSION_WEIGHTS)
        self.suspicion = ClientSuspicionStore(**suspicion)
        self._suspicion_lock = NamedLock("service.suspicion")
        # One stats lock for the request/serve counters: they are bumped
        # from submitter (frontend handler) threads AND the resolver
        # thread and read by the heartbeat thread — `n += 1` is a
        # read-modify-write, so unguarded concurrent bumps lose updates
        # (BMT-T01; the schedule-harness regression in
        # tests/test_concurrency.py demonstrates the loss on the pre-fix
        # pattern). `stats()` snapshots under the same lock so one
        # payload is internally coherent.
        self._stats_lock = NamedLock("service.stats")
        self._requests = 0
        self._served = 0
        self._rejected = 0
        self._admission_masked = 0
        self._admission_downweighted = 0
        self._closed = False
        self._telemetry = None
        self.directory = None
        if directory is not None:
            self.directory = pathlib.Path(directory)
            self.directory.mkdir(parents=True, exist_ok=True)
            if recorder.active() is None:
                from byzantinemomentum_tpu.obs.recorder import Telemetry
                self._telemetry = recorder.activate(Telemetry(self.directory))
        self.batcher = MicroBatcher(self._dispatch, self._resolve,
                                    max_batch=max_batch,
                                    max_delay=max_delay_ms / 1000.0,
                                    metrics=metrics)
        self._beat_stop = threading.Event()
        self._beat_thread = None
        if self.directory is not None and heartbeat_interval:
            self._beat_thread = threading.Thread(
                target=self._beat_loop, args=(float(heartbeat_interval),),
                name="serve-heartbeat", daemon=True)
            self._beat_thread.start()
        recorder.emit("serve_start", max_batch=self.max_batch,
                      max_delay_ms=max_delay_ms,
                      buckets=list(self.cache.buckets))

    # ------------------------------------------------------------------ #
    # Submission API

    def submit(self, vectors, *, gar="krum", f=1, client_ids=None,
               diagnostics=None, trace_id=None, received_at=None):
        """Queue one aggregation; returns a `Future[AggregateResult]`.

        `vectors` is the (n, d) cohort matrix (array-like, one row per
        client submission); `client_ids` optionally names the rows so
        suspicion verdicts can ride back (requires a diagnostics cell).
        Invalid requests raise synchronously (`utils.UserException` /
        `OversizeRequest`) — the caller never holds a future that was
        doomed from the start. `trace_id` names the request's trace
        (auto-assigned when tracing is on and none is given);
        `received_at` is the frontend's monotonic receive stamp, opening
        a `parse` span before validation.
        """
        if self._closed:
            raise RuntimeError("AggregationService is closed")
        trace = None
        if self.tracing:
            trace = RequestTrace(trace_id)  # stamps `accept` at creation
            if received_at is not None:
                trace.stamp("recv", at=float(received_at))
        try:
            cell, matrix, client_ids = self._validate(
                vectors, gar, f, client_ids, diagnostics)
        except utils.UserException:
            with self._stats_lock:
                self._rejected += 1
            self._m_rejected.inc()
            recorder.counter("serve_rejected")
            raise
        n = matrix.shape[0]
        admitted, admission = None, None
        if self.admission is not None and client_ids is not None:
            with self._suspicion_lock:
                admitted, admission = self.admission.decide(
                    client_ids, self.suspicion)
            if admission:
                matrix = self.admission.apply(matrix, admitted, admission,
                                              client_ids)
                masked = int(n - admitted.sum())
                blended = sum(1 for a in admission.values()
                              if a["action"] == "downweight")
                with self._stats_lock:
                    self._admission_masked += masked
                    self._admission_downweighted += blended
                if masked:
                    self._m_masked.inc(masked)
                    recorder.counter("serve_admission_masked", masked)
                if blended:
                    self._m_downweighted.inc(blended)
                    recorder.counter("serve_admission_downweighted",
                                     blended)
        with self._stats_lock:
            self._requests += 1
        self._m_requests.inc()
        recorder.counter("serve_requests")
        if trace is not None:
            trace.meta = {"gar": cell.gar, "n": n, "d": int(matrix.shape[1])}
            if self._trace_src is not None:
                trace.meta["src"] = self._trace_src
        return self.batcher.submit(ServeRequest(cell, n, matrix, client_ids,
                                                admitted=admitted,
                                                admission=admission,
                                                trace=trace))

    def _validate(self, vectors, gar, f, client_ids, diagnostics):
        """Everything that can reject a request, in one place (every
        failure counts on the `serve_rejected` telemetry counter)."""
        matrix = np.asarray(vectors, dtype=np.float32)
        if matrix.ndim != 2:
            raise utils.UserException(
                f"Expected an (n, d) matrix of row submissions, got shape "
                f"{matrix.shape}")
        n, d = matrix.shape
        if diagnostics is None:
            diagnostics = self.diagnostics
        if client_ids is not None:
            client_ids = tuple(str(c) for c in client_ids)
            if len(client_ids) != n:
                raise utils.UserException(
                    f"Got {len(client_ids)} client ids for {n} rows")
            if not diagnostics:
                raise utils.UserException(
                    "Per-client verdicts need a diagnostics cell; pass "
                    "diagnostics=True (or drop client_ids)")
        cell = self.cache.cell(gar, n, f, d, bool(diagnostics))
        # The rule's own contract on the REQUEST rows (bucket padding only
        # ever relaxes static constraints — n_bucket >= n)
        from byzantinemomentum_tpu import ops
        message = ops.gars[gar].check(gradients=matrix, f=f)
        if message is not None:
            raise utils.UserException(
                f"Aggregation rule {gar!r} cannot serve this request: "
                f"{message}")
        return cell, matrix, client_ids

    def aggregate(self, vectors, timeout=None, **kwargs):
        """Synchronous `submit().result()` convenience."""
        return self.submit(vectors, **kwargs).result(timeout=timeout)

    def warmup(self, cells, batch_sizes=None):
        """Pre-compile (and pre-execute) the given `(gar, n, f, d,
        diagnostics)` request shapes at every batch bucket, so steady-state
        traffic meets a fully warm cache — raw (n, d) shapes are bucketed
        exactly as live requests are, so distinct raw shapes that share a
        cell warm it once. Drives the program cache directly (not the
        batcher) so exactly one program runs per `(cell, batch_bucket)`
        regardless of flush timing. Returns the number of programs
        executed."""
        import jax

        if batch_sizes is None:
            batch_sizes = []
            b = 1
            while b <= self.max_batch:
                batch_sizes.append(b)
                b *= 2
        count = 0
        seen = set()
        rng = np.random.default_rng(0)
        for gar, n, f, d, diagnostics in cells:
            cell = self.cache.cell(gar, n, f, d, bool(diagnostics))
            for b in batch_sizes:
                B = batch_bucket(b, self.max_batch)
                if (cell, B) in seen:
                    continue
                seen.add((cell, B))
                G = np.zeros((B, cell.n_bucket, cell.d_bucket),
                             dtype=np.float32)
                G[:, :n, :d] = rng.standard_normal((B, n, d))
                active = np.zeros((B, cell.n_bucket), dtype=bool)
                active[:, :n] = True
                program = self.cache.get(cell, B)
                jax.block_until_ready(
                    program(jax.device_put(G), jax.device_put(active)))
                count += 1
        return count

    # ------------------------------------------------------------------ #
    # Batch lifecycle (flusher/resolver threads)

    def _dispatch(self, cell, requests):
        """Pack one cell's batch and dispatch it asynchronously (flusher
        thread). Padding: rows beyond each request's n are inactive (the
        traced-count masked kernels ignore them), columns beyond each
        request's d are zero (exact for every rule — the
        `serve/programs.py::D_PAD_EXACT` proof); batch slots beyond the
        real requests repeat the first request's payload and are dropped
        at resolution. Requests of DIFFERENT raw (n, d) shapes pack into
        the same batch whenever they share a cell."""
        import jax

        N, D = cell.n_bucket, cell.d_bucket
        B = batch_bucket(len(requests), self.max_batch)
        G = np.zeros((B, N, D), dtype=np.float32)
        active = np.zeros((B, N), dtype=bool)
        for i, r in enumerate(requests):
            G[i, :r.n, :r.d] = r.matrix
            # Admission-masked rows stay INACTIVE: the traced-count
            # masked kernels exclude them and f_eff recomputes — the
            # same mechanism as the bucket padding rows
            active[i, :r.n] = True if r.admitted is None else r.admitted
        for i in range(len(requests), B):
            G[i], active[i] = G[0], active[0]
        self._m_occupancy.observe(len(requests) / B)
        if recorder.active() is not None:
            recorder.active().gauge("serve_batch_occupancy",
                                    len(requests) / B, cell=repr(cell))
        batch_stamps = next((r.trace.batch_stamps for r in requests
                             if r.trace is not None
                             and r.trace.batch_stamps is not None), None)
        if batch_stamps is not None:
            batch_stamps["packed"] = time.monotonic()
            batch_stamps["batch_size"] = len(requests)
            batch_stamps["batch_occupancy"] = len(requests) / B
        program = self.cache.get(cell, B)
        # Explicit device_put (the transfer-guard contract: the serving
        # hot loop performs no implicit host<->device transfers)
        out = program(jax.device_put(G), jax.device_put(active))
        if batch_stamps is not None:
            batch_stamps["dispatched"] = time.monotonic()
        return out

    def _resolve(self, out, requests):
        """Block until the batch leaves the device, then fulfill futures
        (resolver thread — the only place the host waits on the device).
        The device->host move is an EXPLICIT `jax.device_get`: the serve
        loop runs under the same transfer-guard contract as the engine
        step (`analysis/contracts.py::no_implicit_transfers`, held
        process-wide by the selfcheck)."""
        import jax

        host = jax.device_get(out)
        now = time.monotonic()
        for r in requests:
            if r.trace is not None and r.trace.batch_stamps is not None:
                r.trace.batch_stamps["device"] = now
                break  # shared dict: one store covers the batch
        # Batched suspicion fold: slice the aux OUTSIDE the lock (it is
        # cohort-local), then update the store once per BATCH under one
        # acquisition — submitter threads (admission `decide`) contend
        # on this lock, so per-request round-trips were resolve-span
        # latency. `observe_batch` keeps per-request fold order, so
        # verdicts are byte-identical to the sequential path.
        items, rows = [], []
        for i, r in enumerate(requests):
            if r.cell.diagnostics and r.client_ids is not None:
                items.append(dict(
                    client_ids=r.client_ids,
                    selection=host["selection"][i, :r.n],
                    distances=host["worker_dist"][i, :r.n],
                    active=r.admitted,
                    dist=(host["dist"][i, :r.n, :r.n]
                          if "dist" in host else None)))
                rows.append(i)
        if items:
            with self._suspicion_lock:
                folded = self.suspicion.observe_batch(items)
            batch_verdicts = dict(zip(rows, folded))
        else:
            batch_verdicts = {}
        for i, r in enumerate(requests):
            verdicts = batch_verdicts.get(i)
            done = time.monotonic()
            if r.trace is not None:
                # Hot path: stamp + ring append only — the dict/rounding
                # conversion happens lazily on whoever READS the trace
                # (response serialization, stats snapshot)
                r.trace.stamp("done", at=done)
                self.traces.add(r.trace)  # bmt: noqa[BMT-T01] TraceBuffer is internally locked (its own _lock serializes the ring)
            latency_ms = (done - r.t_submit) * 1000.0
            result = AggregateResult(
                aggregate=host["aggregate"][i, :r.d],
                f_eff=int(host["f_eff"][i]),
                n=r.n, cell=r.cell, verdicts=verdicts,
                admission=r.admission,
                latency_ms=latency_ms,
                trace=r.trace)
            with self._stats_lock:
                self._served += 1
            self._m_served.inc()
            self._m_latency.observe(latency_ms)
            if not r.future.done():
                r.future.set_result(result)

    # ------------------------------------------------------------------ #
    # Observability / lifecycle

    def stats(self):
        """Counter snapshot (the front end's `stats` op, the heartbeat
        payload, the load generator's occupancy report). The counters are
        read under the stats lock so one payload is coherent — `served`
        can never exceed `requests` within a snapshot."""
        with self._stats_lock:
            requests, served = self._requests, self._served
            rejected = self._rejected
            masked = self._admission_masked
            downweighted = self._admission_downweighted
        return {
            "requests": requests,
            "served": served,
            "rejected": rejected,
            "admission": {
                "enabled": self.admission is not None,
                "mode": getattr(self.admission, "mode", None),
                "masked_rows": masked,
                "downweighted_rows": downweighted,
            },
            "queue_depth": self.batcher.depth(),
            "metrics": {"enabled": self.metrics.enabled},
            "cache": self.cache.stats(),
            "suspicion": self.suspicion.summary(),
            "tracing": ({"enabled": True, **self.traces.summary()}
                        if self.tracing else {"enabled": False}),
        }

    def write_trace_snapshot(self, path=None):
        """Dump the trace ring buffer (summary + raw records) to a JSON
        file — the SIGUSR1 hook of the serving CLI. Default path:
        `traces-<completed>.json` in the service directory (CWD without
        one). Returns the path written."""
        import json

        payload = {"kind": "serve_traces", "written": time.time(),
                   "summary": self.traces.summary(),
                   "traces": self.traces.snapshot()}
        if path is None:
            base = self.directory or pathlib.Path(".")
            path = base / f"traces-{self.traces.completed}.json"
        path = pathlib.Path(path)
        path.write_text(json.dumps(payload, indent="\t") + "\n")
        recorder.emit("serve_trace_snapshot", path=str(path),
                      buffered=len(self.traces))
        return path

    def _beat_loop(self, interval):
        # First beat immediately: a supervisor adopting a fresh server
        # must see liveness before the first interval elapses
        self._write_heartbeat()
        while not self._beat_stop.wait(interval):
            self._write_heartbeat()

    def _write_heartbeat(self):
        if self.directory is None:
            return
        stats = self.stats()
        write_heartbeat(self.directory, {
            "step": stats["served"], "status": "serving", **stats})

    def close(self):
        """Drain in-flight work and stop the threads. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.batcher.close()
        self._beat_stop.set()
        if self._beat_thread is not None:
            self._beat_thread.join(timeout=2.0)
            self._write_heartbeat()
        recorder.emit("serve_stop", **{k: v for k, v in self.stats().items()
                                       if k in ("requests", "served",
                                                "rejected")})
        if self._telemetry is not None:
            recorder.deactivate()
            self._telemetry.close()
            self._telemetry = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
