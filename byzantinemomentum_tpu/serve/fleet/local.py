"""An in-process serve fleet on loopback: real shard sockets, real
router, no subprocesses.

`LocalFleet(shards=2)` builds N independent `AggregationService`s (each
with its OWN `ClientSuspicionStore` — the shard-local ownership the
process fleet has), binds each behind an `AggregationServer` on an
ephemeral loopback port, and routes through a real `FleetRouter`. The
wire path is byte-for-byte the production one (line JSON over TCP,
pipelined groups per shard connection); only process isolation is
simulated — which is exactly what the selfcheck, the unit tests and the
loadgen trace run need: route determinism, kill→restart→re-warm
semantics and router-path attribution, minus N jax warm-ups.

`kill(shard)` tears the shard's server+service down (the socket starts
refusing, the router's forwarder marks the arc dead on its next
connect); `restart(shard)` brings a FRESH service up on the SAME port —
the suspicion store starts empty, as a restarted process's would, so
returning clients re-warm from scratch.
"""

import json
import socket

from byzantinemomentum_tpu.obs.metrics import MetricsRegistry
from byzantinemomentum_tpu.serve.fleet.ring import DEFAULT_VNODES, \
    Membership
from byzantinemomentum_tpu.serve.fleet.router import FleetRouter, \
    RouterServer

__all__ = ["LocalFleet", "ask_socket", "fleet_socket"]


class LocalFleet:
    """N in-process shards + router. Use as a context manager."""

    def __init__(self, shards=2, *, vnodes=DEFAULT_VNODES,
                 on_dead="queue", max_parked=1024, router_server=False,
                 trace_buffer=512, service=None):
        from byzantinemomentum_tpu.serve.frontend import AggregationServer
        from byzantinemomentum_tpu.serve.service import AggregationService

        self._server_cls = AggregationServer
        self._service_cls = AggregationService
        self._service_kwargs = dict(service or {})
        self.membership = Membership(vnodes=vnodes)
        self.services = {}
        self.servers = {}
        for index in range(int(shards)):
            shard = f"shard-{index}"
            svc = AggregationService(**self._shard_kwargs(shard))
            server = AggregationServer(("127.0.0.1", 0), svc)
            server.serve_background()
            self.services[shard] = svc
            self.servers[shard] = server
            self.membership.bump("add", shard, host="127.0.0.1",
                                 port=server.port)
        self.router = FleetRouter(
            {s: (row["host"], row["port"])
             for s, row in self.membership.shards.items()},
            vnodes=vnodes, on_dead=on_dead, max_parked=max_parked,
            trace_buffer=trace_buffer,
            metrics=MetricsRegistry(source="router"))
        self.server = None
        if router_server:
            self.server = RouterServer(("127.0.0.1", 0), self.router)
            self.server.serve_background()

    # -------------------------------------------------------------- #

    def _shard_kwargs(self, shard):
        """Service kwargs for one shard: the registries must be
        INSTANCE-scoped with the shard's name as source — N services
        share this process, and a process-global registry would fold
        every shard's numbers into one stream before the scraper gets
        to merge (and label) them."""
        kwargs = dict(self._service_kwargs)
        if kwargs.get("metrics", True) is True:
            kwargs["metrics"] = MetricsRegistry(source=shard)
        return kwargs

    @property
    def shards(self):
        return tuple(sorted(self.services))

    def scrape_targets(self):
        """{name: (host, port)} of every live exposition port (shards +
        the router server when bound) — a `MetricsScraper`'s targets."""
        targets = {s: ("127.0.0.1", server.port)
                   for s, server in self.servers.items()}
        if self.server is not None:
            targets["router"] = ("127.0.0.1", self.server.port)
        return targets

    @property
    def port(self):
        """The router's TCP port (None without `router_server=True`)."""
        return None if self.server is None else self.server.port

    def owner(self, client):
        return self.router.owner(client)

    def ask(self, request):
        """One request dict through the router; returns the reply dict."""
        raw = json.dumps(request).encode("utf-8")
        return json.loads(self.router.handle_line(raw))

    def suspicion_clients(self, shard):
        """The client ids the shard's store currently holds (sorted)."""
        return tuple(self.services[shard].suspicion.clients())

    def kill(self, shard):
        """SIGKILL-shaped teardown: the shard stops answering NOW (close
        the server first so no farewell bytes reach the router), and the
        router finds out the way production does — a failed connect."""
        server = self.servers.pop(shard)
        server.shutdown()
        server.server_close()
        self.services.pop(shard).close()
        self.router.mark_dead(shard)

    def restart(self, shard):
        """A fresh service (EMPTY suspicion store) on the SAME port —
        ownership never moves; state does not survive, by design."""
        port = self.membership.shards[shard]["port"]
        svc = self._service_cls(**self._shard_kwargs(shard))
        server = self._server_cls(("127.0.0.1", port), svc)
        server.serve_background()
        self.services[shard] = svc
        self.servers[shard] = server
        self.membership.bump("alive", shard)
        self.router.mark_alive(shard)

    def set_tracing(self, on):
        """Flip the WHOLE fleet tracing plane at once — the router's
        splice AND every live shard's request tracing. The paired
        overhead arms of `ATTRIB_serve_fleet` toggle here so the off
        arm pays neither shard stamps nor the router-side reply
        parse."""
        self.router.tracing = bool(on)
        for svc in self.services.values():
            svc.tracing = bool(on)

    def close(self):
        self.router.close()
        if self.server is not None:
            self.server.shutdown()
            self.server.server_close()
        for shard in list(self.servers):
            server = self.servers.pop(shard)
            server.shutdown()
            server.server_close()
        for shard in list(self.services):
            self.services.pop(shard).close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def fleet_socket(host, port, timeout=30.0):
    """A connected line-JSON client socket to a router (or shard) —
    returns (socket, buffered rwb file pair). Caller closes both."""
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.settimeout(timeout)
    return sock, sock.makefile("rwb")


def ask_socket(files, request):
    """One request dict over an open line-JSON connection."""
    files.write(json.dumps(request).encode("utf-8") + b"\n")
    files.flush()
    line = files.readline()
    if not line:
        raise OSError("connection closed")
    return json.loads(line)
