"""The fleet's line-JSON router frontend: client → owner-shard forwarding.

One `FleetRouter` fronts N `AggregationService` shard processes. A
client connects to the router exactly as it would to a single-process
`AggregationServer` (one JSON object per line each way — the protocol is
unchanged); the router parses each line just enough to find its routing
key (`clients[0]`: a cohort shares its first client's owner, which is
what keeps whole cohorts shard-local), asks the consistent-hash ring for
the OWNER shard, and forwards the raw line bytes — no re-encode — over
that shard's connection. Replies pass back verbatim, so a shard-local
verdict is byte-identical to what the single-process path would emit.

Thread surface (the PR 14 covenant: every thread here has a schedule
model in `analysis/schedule.py` and passes the BMT-T gate):

* **connection threads** — one per client connection
  (`socketserver.ThreadingTCPServer`): parse, route, enqueue the line on
  the owner shard's `queue.Queue`, then block on the item's private
  reply queue (no lock held — the T04 rule). The enqueue is
  unconditional: liveness is read for POLICY, never as a send guard, so
  a kill landing between the check and the enqueue cannot lose a line
  (the `router_lost_forward_model` race, pinned schedule-clean).
* **forwarder threads** — one per shard, the shard connection's sole
  owner (sockets live in locals, never shared attributes). A forwarder
  drains its queue in pipelined groups: write every line, flush once,
  then read the replies in order (the shard frontend's per-connection
  writer thread guarantees in-order replies), so the shard's
  microbatcher sees concurrent requests and batches. Every item gets
  EXACTLY ONE disposition — replied, or errored — decided at a single
  point by its owning forwarder (the `router_double_resolve_model`
  fix): once any byte of a line hit the wire, a failure ERRORS the line
  rather than re-sending it, because a re-send could fold the same
  cohort into the shard's suspicion store twice and corrupt verdicts.
  Lines still queued behind a dead shard follow the `on_dead` policy:
  `"queue"` parks them until the launcher restarts the shard on its
  port (the arc revives, ownership never moved), `"error"` fails them
  fast. The parked line is BOUNDED (`max_parked`): past the cap a dead
  arc fails further lines fast instead of parking them — each parked
  line is a blocked client connection thread holding its buffers for
  up to `reply_timeout`, so an unbounded park under a flash crowd is a
  memory/thread amplifier, not patience. Rejections count in
  `stats()["parked_rejected"]`.
* **health watcher** — probes dead arcs with short-lived ping
  connections and revives them; under the `"error"` policy it is the
  only revival path for a trafficless shard.

Liveness changes go through `_set_liveness`, which calls the launcher's
hook (persist the versioned membership FIRST — `fleet.json` discipline)
before flipping the ring.

Tracing (PR 13 extension): each routed line stamps `recv` → `routed` →
`reply`, tiling the router-path latency into the `route` (parse + ring)
and `shard_rtt` (queue wait + forward + shard service time) legs that
`ATTRIB_serve_r16.json` records; `stats` carries the live summary.

Cross-process span join (r19): the shard's reply already carries its
per-phase `RequestTrace` record (`"trace"`, PR 13 wire protocol) — the
router used to drop it. With `tracing` on, the connection thread now
splices that record into its own envelope via `join_shard_trace`
(clock-free: shard durations nest under the router-measured
`shard_rtt`; the residual is wire + connection queue), lands the joined
record in a `TraceBuffer`, and counts the dominant hop onto
`router_critical_path_<hop>` registry counters — `stats()["joined"]`
and any metrics scrape answer "where is the convoy" live. A reply
without a parseable trace record degrades to the r16 opaque
`shard_rtt` row; the line is never severed over telemetry. The splice
parses the reply bytes ONLY on this branch (gated by a cheap
`b'"trace"' in reply` scan), so the forwarded bytes stay verbatim and
the tracing-off arm pays nothing — the paired-overhead budget in
`ATTRIB_serve_fleet_r19.json` holds the whole plane under 3%.
"""

import json
import queue
import socket
import socketserver
import threading
import time

from byzantinemomentum_tpu.obs.metrics import (LATENCY_MS_BOUNDS,
                                               NullRegistry)
from byzantinemomentum_tpu.obs.trace import JOINED_HOPS, ROUTER_PHASES, \
    TraceBuffer, join_shard_trace, percentile, phase_spans
from byzantinemomentum_tpu.serve.fleet.ring import DEFAULT_VNODES, HashRing
from byzantinemomentum_tpu.utils.locking import NamedLock

__all__ = ["FleetRouter", "RouterServer"]

# Lines written back-to-back per forwarder flush: bounds per-group reply
# latency while keeping the owner shard's microbatcher fed
_PIPELINE = 64

_JSON = json.JSONDecoder()


def _extract_trace(reply):
    """Parse ONLY the reply's top-level `"trace"` record out of the raw
    bytes: `raw_decode` at the key's value, so the splice never pays to
    re-parse the d-dimensional aggregate riding the same line — the
    on-arm join cost stays flat in d. A quoted `"trace"` that is not a
    key (next non-space char isn't `:`) is skipped; JSON string
    escaping means the byte sequence `"trace"` cannot hide INSIDE a
    string value, so a `:` match is a real key. Returns the decoded
    value or None (caller degrades to the opaque row)."""
    try:
        text = reply.decode("utf-8")
    except UnicodeDecodeError:
        return None
    pos = text.find('"trace"')
    while pos >= 0:
        cursor = pos + 7
        while cursor < len(text) and text[cursor] in " \t\r\n":
            cursor += 1
        if cursor < len(text) and text[cursor] == ":":
            cursor += 1
            while cursor < len(text) and text[cursor] in " \t\r\n":
                cursor += 1
            try:
                value, _ = _JSON.raw_decode(text, cursor)
            except ValueError:
                return None
            return value
        pos = text.find('"trace"', pos + 1)
    return None


class _Item:
    """One routed line: raw bytes in, exactly one disposition out."""

    __slots__ = ("raw", "reply_q", "stamps")

    def __init__(self, raw, stamps=None):
        self.raw = raw
        self.reply_q = queue.Queue(maxsize=1)
        self.stamps = stamps


class FleetRouter:
    """Consistent-hash router over `shards`: {shard id: (host, port)}."""

    def __init__(self, shards, *, vnodes=DEFAULT_VNODES, on_dead="queue",
                 max_parked=1024, reply_timeout=30.0, connect_timeout=2.0,
                 retry_interval=0.05, probe_interval=0.25,
                 trace_buffer=512, tracing=True, liveness_hook=None,
                 metrics=None):
        if on_dead not in ("queue", "error"):
            raise ValueError(f"on_dead must be 'queue' or 'error', "
                             f"got {on_dead!r}")
        if max_parked < 1:
            raise ValueError(f"max_parked must be >= 1, got {max_parked}")
        self.on_dead = on_dead
        self.max_parked = int(max_parked)
        self._addresses = {str(s): tuple(addr) for s, addr in shards.items()}
        self._ring = HashRing(sorted(self._addresses), vnodes=vnodes)
        self._reply_timeout = float(reply_timeout)
        self._connect_timeout = float(connect_timeout)
        self._retry_interval = float(retry_interval)
        self._probe_interval = float(probe_interval)
        # `liveness_hook(shard, alive)` runs BEFORE the ring flips (the
        # persist-before-change contract); it is called under the COLD
        # membership lock — never the hot ring lock — and must not call
        # back into the router.
        self._liveness_hook = liveness_hook
        # Lock split (BMT-L day-one fix): `router.ring` is the hot lock
        # `handle_line` takes per line; `router.membership` serializes
        # liveness transitions (dedupe + persist hook + flip), so the
        # hook's disk I/O can never convoy the request path. Order:
        # membership -> ring, and ring never takes anything inside it.
        self._lock = NamedLock("router.ring")
        self._membership = NamedLock("router.membership")
        self._closed = False
        self._wake = threading.Event()
        self._routed = {s: 0 for s in self._addresses}
        # Liveness epoch per arc: bumped on EVERY transition, so a
        # forwarder can tell "my idle connection predates a
        # kill+restart" and reconnect instead of erroring the first
        # post-restart line into a dead socket
        self._epochs = {s: 0 for s in self._addresses}
        self._errors = 0
        self._timeouts = 0
        self._parked_rejected = 0
        self._anon = 0
        # The metrics plane (obs/metrics): the router owns ITS registry
        # — shard internals stay shard-local, a scraper pulls each
        # process separately and merges. The counter names are the ones
        # DEFAULT_SERVE_SLOS folds as availability errors.
        self.metrics = metrics if metrics is not None else NullRegistry()
        self._m_routed = self.metrics.counter("router_routed")
        self._m_errors = self.metrics.counter("router_errors")
        self._m_timeouts = self.metrics.counter("router_timeouts")
        self._m_parked_rejected = self.metrics.counter(
            "router_parked_rejected")
        self._m_route = self.metrics.histogram("router_route_ms",
                                               bounds=LATENCY_MS_BOUNDS)
        self._m_rtt = self.metrics.histogram("router_shard_rtt_ms",
                                             bounds=LATENCY_MS_BOUNDS)
        self._trace_buffer = int(trace_buffer)
        self._spans = []  # bounded [(route_ms, shard_rtt_ms, total_ms)]
        # The span-join plane (r19). `tracing` gates ONLY the splice
        # (reply parse + joined ring + critical-path counters) — the
        # opaque route/shard_rtt rows above stay on either way, they
        # cost two clock reads per line. Critical-path counter handles
        # are pre-bound per joined hop so the hot path never takes the
        # registry lock.
        self.tracing = bool(tracing)
        self._joined = TraceBuffer(self._trace_buffer)
        self._m_critical = {
            hop: self.metrics.counter(f"router_critical_path_{hop}")
            for hop in JOINED_HOPS}
        self._queues = {s: queue.Queue() for s in self._addresses}
        self._forwarders = [
            threading.Thread(target=self._forward_loop, args=(s,),
                             name=f"fleet-forward-{s}", daemon=True)
            for s in sorted(self._addresses)]
        self._watcher = threading.Thread(target=self._watch_loop,
                                         name="fleet-health-watcher",
                                         daemon=True)
        for thread in self._forwarders:
            thread.start()
        self._watcher.start()

    # -------------------------------------------------------------- #
    # liveness

    def _is_closed(self):
        with self._lock:
            return self._closed

    def _set_liveness(self, shard, alive):
        """Flip one arc; persist-first via the hook; dedupes no-op
        flips so concurrent detectors (forwarder + watcher) record one
        transition. Returns True when the state actually changed.

        The transition serializes on `router.membership` end to end
        (check -> hook -> flip), so two detectors still produce exactly
        one persist and one flip — but the ring lock is only taken for
        the reads and the flip itself, and the hook's manifest fsync no
        longer runs under the lock every `handle_line` needs
        (`schedule.liveness_hook_model` pins the interleaving)."""
        with self._membership:
            with self._lock:
                if self._ring.alive(shard) == alive:
                    return False
            if self._liveness_hook is not None:
                self._liveness_hook(shard, alive)  # bmt: noqa[BMT-L03] persist-before-flip requires the hook inside the membership transition; membership is cold (liveness edges only) and the hook contract forbids calling back into the router
            with self._lock:
                if alive:
                    self._ring.mark_alive(shard)
                else:
                    self._ring.mark_dead(shard)
                self._epochs[shard] += 1
                return True

    def _epoch(self, shard):
        with self._lock:
            return self._epochs[shard]

    def mark_dead(self, shard):
        """Launcher-facing: the supervised process died."""
        return self._set_liveness(str(shard), False)

    def mark_alive(self, shard):
        """Launcher-facing: the shard restarted on its port."""
        return self._set_liveness(str(shard), True)

    def dead_shards(self):
        with self._lock:
            return self._ring.dead

    def owner(self, client):
        """Pure ownership (liveness-blind) — determinism probes."""
        return self._ring.owner(client)

    # -------------------------------------------------------------- #
    # the connection-thread path

    def handle_line(self, raw, received_at=None):
        """Route one client line; returns the reply BYTES (no newline).
        Called from connection threads."""
        received = time.monotonic() if received_at is None else received_at
        raw = raw.strip()
        try:
            request = json.loads(raw)
            if not isinstance(request, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as err:
            return self._error_bytes(f"invalid request line: {err}")
        op = request.get("op", "aggregate")
        if op == "ping":
            with self._lock:
                payload = {"ok": True, "op": "ping", "router": True,
                           "shards": len(self._addresses),
                           "alive": (len(self._addresses)
                                     - len(self._ring.dead))}
            return json.dumps(payload).encode("utf-8")
        if op == "stats":
            return json.dumps(self.stats()).encode("utf-8")
        if op == "metrics":
            # The router answers with ITS OWN registry, like every other
            # process: the puller scrapes router and shards separately
            # and does the merging itself (obs/metrics/scrape.py)
            return json.dumps({"ok": True,
                               "metrics": self.metrics.dump()}
                              ).encode("utf-8")
        clients = request.get("clients")
        if clients:
            key = str(clients[0])
        else:
            # No suspicion state to keep local: spread client-less
            # lines round-robin instead of hot-spotting one arc
            with self._lock:
                self._anon += 1
                key = f"anon:{self._anon}"
        with self._lock:
            shard = self._ring.owner(key)
            alive = self._ring.alive(shard)
            self._routed[shard] += 1
        self._m_routed.inc()
        if not alive and self.on_dead == "error":
            with self._lock:
                self._errors += 1
            self._m_errors.inc()
            return self._error_bytes(f"shard {shard} is dead "
                                     f"(on_dead=error)", shard=shard)
        if not alive and self._queues[shard].qsize() >= self.max_parked:
            # Bounded park: each parked line is a blocked connection
            # thread; past the cap the dead arc fails fast instead of
            # amplifying a flash crowd into unbounded queued memory
            with self._lock:
                self._parked_rejected += 1
            self._m_parked_rejected.inc()
            return self._error_bytes(
                f"shard {shard} is dead and its parked line is full "
                f"({self.max_parked} lines)", shard=shard)
        item = _Item(raw, stamps={"recv": received})
        item.stamps["routed"] = time.monotonic()
        self._queues[shard].put(item)
        try:
            reply = item.reply_q.get(timeout=self._reply_timeout)
        except queue.Empty:
            with self._lock:
                self._timeouts += 1
            self._m_timeouts.inc()
            return self._error_bytes(f"shard {shard} reply timeout "
                                     f"({self._reply_timeout}s)",
                                     shard=shard)
        item.stamps["reply"] = time.monotonic()
        self._record_trace(item.stamps, reply, shard)
        return reply

    def _error_bytes(self, message, **extra):
        return json.dumps({"ok": False, "error": f"router: {message}",
                           **extra}).encode("utf-8")

    def _join_reply(self, stamps, reply, shard):
        """Cross-process splice on the connection thread: pull the
        shard's trace record out of the reply bytes and nest it inside
        this line's router envelope. Any malformed/absent record
        returns None — the caller degrades to the opaque row."""
        if b'"trace"' not in reply:
            return None   # cheap scan: never json-parse untraced replies
        joined = join_shard_trace(stamps, _extract_trace(reply))
        if joined is not None:
            joined["shard"] = shard   # which arc served — skew analysis
        return joined

    def _record_trace(self, stamps, reply=None, shard=None):
        spans = phase_spans(stamps, ROUTER_PHASES)
        if spans is None:
            return
        total = (stamps["reply"] - stamps["recv"]) * 1000.0
        self._m_route.observe(spans["route"])
        self._m_rtt.observe(spans["shard_rtt"])
        if self.tracing and reply is not None:
            joined = self._join_reply(stamps, reply, shard)
            if joined is not None:
                # TraceBuffer.add and the counters are internally
                # locked — concurrent connection threads each land
                # their whole record (the router_splice schedule model
                # pins the unlocked variant losing records)
                self._m_critical[joined["dominant"]].inc()
                self._joined.add(joined)
        with self._lock:
            self._spans.append((spans["route"], spans["shard_rtt"], total))
            if len(self._spans) > self._trace_buffer:
                del self._spans[:len(self._spans) - self._trace_buffer]

    # -------------------------------------------------------------- #
    # the forwarder-thread path (sole owner of its shard connection)

    def _connect(self, shard):
        host, port = self._addresses[shard]
        sock = socket.create_connection((host, port),
                                        timeout=self._connect_timeout)
        sock.settimeout(self._reply_timeout)
        return sock, sock.makefile("rwb")

    @staticmethod
    def _close_sock(sock):
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _reply_error(self, item, message, shard=None):
        with self._lock:
            self._errors += 1
        self._m_errors.inc()
        item.reply_q.put(self._error_bytes(message, **(
            {"shard": shard} if shard is not None else {})))

    def _forward_loop(self, shard):
        q = self._queues[shard]
        sock = files = None
        epoch = None
        while True:
            item = q.get()
            if item is None:
                break
            if files is not None and self._epoch(shard) != epoch:
                # The arc transitioned (kill and/or restart) while this
                # connection sat idle: it points at a dead process.
                # Nothing of THIS batch touched the wire yet, so a
                # reconnect is safe — no double-observe possible.
                self._close_sock(sock)
                sock = files = None
            batch = [item]
            while len(batch) < _PIPELINE:
                try:
                    extra = q.get_nowait()
                except queue.Empty:
                    break
                if extra is None:
                    q.put(None)  # re-arm the shutdown sentinel
                    break
                batch.append(extra)
            # Ensure a connection. Under on_dead="queue" this retries
            # until the launcher restarts the shard (the batch PARKS —
            # nothing was sent, so a retry cannot double-observe);
            # under "error" the batch fails fast.
            while files is None:
                if self._is_closed():
                    for it in batch:
                        self._reply_error(it, "router is closing", shard)
                    batch = []
                    break
                try:
                    sock, files = self._connect(shard)
                    self._set_liveness(shard, True)
                    epoch = self._epoch(shard)
                except OSError as err:
                    self._set_liveness(shard, False)
                    if self.on_dead == "error":
                        for it in batch:
                            self._reply_error(
                                it, f"shard {shard} unreachable: {err}",
                                shard)
                        batch = []
                        break
                    # The batch PARKS on the dead arc: stamp when the
                    # park began so the replayed trace attributes its
                    # recovery wait as a `parked` hop instead of
                    # inflating `wire_residual` (r19). setdefault — the
                    # first failed attempt owns the stamp across
                    # retries.
                    parked_at = time.monotonic()
                    for it in batch:
                        if it.stamps is not None:
                            it.stamps.setdefault("parked", parked_at)
                    self._wake.wait(self._retry_interval)
            if not batch:
                continue
            # Close any park window: the arc is back and these lines
            # are about to replay. (reply_q.put/get below is the
            # happens-before edge that publishes both stamps to the
            # connection thread's splice.)
            unparked_at = time.monotonic()
            for it in batch:
                if it.stamps is not None and "parked" in it.stamps:
                    it.stamps.setdefault("unparked", unparked_at)
            try:
                for it in batch:
                    files.write(it.raw + b"\n")
                files.flush()
                for index, it in enumerate(batch):
                    reply = files.readline()
                    if not reply:
                        raise OSError("connection closed by shard")
                    it.reply_q.put(reply.rstrip(b"\n"))
                    batch[index] = None
            except OSError as err:
                # Past the first wire byte delivery is UNCERTAIN: a
                # re-send could fold the same cohort into the shard's
                # suspicion store twice (verdict corruption), so every
                # undisposed item of this group ERRORS — exactly one
                # disposition, owned here.
                self._close_sock(sock)
                sock = files = None
                self._set_liveness(shard, False)
                for it in batch:
                    if it is not None:
                        self._reply_error(
                            it, f"shard {shard} died mid-request: {err}",
                            shard)
        self._close_sock(sock)

    # -------------------------------------------------------------- #
    # the health-watcher thread

    def _probe(self, shard):
        try:
            sock, files = self._connect(shard)
        except OSError:
            return False
        try:
            files.write(b'{"op": "ping"}\n')
            files.flush()
            reply = files.readline()
            return bool(reply)
        except OSError:
            return False
        finally:
            self._close_sock(sock)

    def _watch_loop(self):
        while True:
            self._wake.wait(self._probe_interval)
            if self._is_closed():
                return
            for shard in self.dead_shards():
                if self._probe(shard):
                    self._set_liveness(shard, True)

    # -------------------------------------------------------------- #

    def stats(self):
        """Router-level stats + trace summary (shard internals stay
        shard-local: ask a shard's own `stats` op for its view)."""
        with self._lock:
            spans = list(self._spans)
            payload = {
                "ok": True, "op": "stats", "router": True,
                "on_dead": self.on_dead,
                "shards": {s: {"routed": self._routed[s],
                               "alive": self._ring.alive(s),
                               "address": list(self._addresses[s])}
                           for s in sorted(self._addresses)},
                "dead": list(self._ring.dead),
                "errors": self._errors,
                "timeouts": self._timeouts,
                "max_parked": self.max_parked,
                "parked_rejected": self._parked_rejected,
                "queued": {s: self._queues[s].qsize()
                           for s in sorted(self._addresses)},
            }
        if spans:
            payload["trace"] = {
                "traced": len(spans),
                "route": {"p50_ms": percentile([s[0] for s in spans], 50),
                          "p99_ms": percentile([s[0] for s in spans], 99)},
                "shard_rtt": {
                    "p50_ms": percentile([s[1] for s in spans], 50),
                    "p99_ms": percentile([s[1] for s in spans], 99)},
                "total": {"p50_ms": percentile([s[2] for s in spans], 50),
                          "p99_ms": percentile([s[2] for s in spans], 99)},
            }
        if len(self._joined):
            # The join-aware view: per-hop distributions + the
            # critical-path histogram over the joined window
            payload["joined"] = self._joined.summary()
        return payload

    def trace_spans(self):
        """[(route_ms, shard_rtt_ms, total_ms)] — the raw tiling rows
        the ATTRIB artifact aggregates."""
        with self._lock:
            return list(self._spans)

    def joined_records(self):
        """The joined cross-process trace records (oldest first) — the
        per-hop rows `ATTRIB_serve_fleet` aggregates. Each record:
        {"trace_id", "shard", "spans_ms": {hop: ms}, "total_ms",
        "dominant"}."""
        return self._joined.snapshot()

    @property
    def joined_completed(self):
        """Joined traces ever spliced (monotonic, ring-independent)."""
        return self._joined.completed

    def close(self, timeout=5.0):
        """Stop every thread; parked lines error. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._wake.set()
        for q in self._queues.values():
            q.put(None)
        for thread in self._forwarders:
            thread.join(timeout=timeout)
        self._watcher.join(timeout=timeout)
        # Anything a forwarder left parked gets its one disposition
        for shard, q in self._queues.items():
            while True:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    break
                if item is not None:
                    self._reply_error(item, "router closed", shard)


class _RouterHandler(socketserver.StreamRequestHandler):
    """One thread per client connection; the router does the work."""

    def handle(self):
        for raw in self.rfile:
            received_at = time.monotonic()
            try:
                reply = self.server.router.handle_line(raw, received_at)
            except Exception as err:  # bmt: noqa[BMT-E05] a failed route must answer its line, not sever every client on this connection
                reply = json.dumps({"ok": False,
                                    "error": f"router: {err}"}).encode()
            try:
                self.wfile.write(reply + b"\n")
                self.wfile.flush()
            except OSError:
                return  # client went away mid-reply


class RouterServer(socketserver.ThreadingTCPServer):
    """TCP front door for a `FleetRouter` (protocol-identical to
    `AggregationServer`, so clients cannot tell fleet from single)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, address, router):
        self.router = router
        super().__init__(address, _RouterHandler)

    @property
    def port(self):
        return self.server_address[1]

    def serve_background(self):
        thread = threading.Thread(target=self.serve_forever,
                                  name="fleet-router", daemon=True)
        thread.start()
        return thread
