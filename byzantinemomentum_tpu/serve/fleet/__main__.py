"""`python -m byzantinemomentum_tpu.serve.fleet` — launch the fleet.

The launched fleet carries the full r19 causal plane: the router
splices each shard's wire trace record into joined per-hop spans, and
SLO-burn / arc-death / failover edges drop atomic incident bundles
under `<result-directory>/incidents/` (disable with `--no-incidents`).
"""

import sys

from byzantinemomentum_tpu.serve.fleet.launcher import main

if __name__ == "__main__":
    sys.exit(main())
