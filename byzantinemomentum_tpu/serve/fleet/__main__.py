"""`python -m byzantinemomentum_tpu.serve.fleet` — launch the fleet."""

import sys

from byzantinemomentum_tpu.serve.fleet.launcher import main

if __name__ == "__main__":
    sys.exit(main())
