"""The serve-fleet launcher: N supervised shard processes + the router.

`python -m byzantinemomentum_tpu.serve.fleet --shards N ...` spawns N
independent `AggregationService` processes (`python -m
byzantinemomentum_tpu.serve`, one ephemeral pre-probed port each), runs
the consistent-hash `FleetRouter` in-process, and supervises the lot
with the `cluster/launcher.py` discipline:

* **ownership split** (the Ray model, PAPERS.md): this launcher decides
  LIVENESS — membership, versions, restarts; each shard decides STATE —
  its clients' suspicion store, admission, verdicts. Nothing here ever
  reads or moves suspicion state between shards.
* **persist-before-change** — every membership/liveness transition
  lands in the versioned `fleet.json` (atomic replace) BEFORE the ring
  flips or a process is spawned/restarted, so a crash replays a
  stale-but-consistent view, never a torn one.
* **orphan death** — every shard is spawned with `--parent-pipe` and
  its stdin held EXCLUSIVELY here: launcher death (any signal) closes
  the pipe and the shard's parent-watch thread exits the process.
* **one heartbeat** — per-shard atomic heartbeats
  (`shards/shard-<i>/heartbeat.json`) aggregate into the run's single
  top-level `heartbeat.json` (step = total served, monotonic), so
  `Jobs(seeds=(None,))` supervises a whole fleet through the same file
  a single-process run writes.
* **kill-safe failover** — a dead shard's arc is marked dead (persist
  first), the router queues or errors its lines per `--on-dead`, and
  the shard restarts on ITS port with a FRESH store: ownership never
  moves, and a returning client re-warms no faster than a fresh id.
* **one metrics plane** (`obs/metrics`, r18) — a `MetricsScraper`
  thread polls every shard's `{"op": "metrics"}` port each
  `--metrics-interval`, folds the in-process router registry in, merges
  bucket-wise and appends windowed snapshots to the run's
  `metrics.jsonl` ring; a `BurnRateEvaluator` watches the merged stream
  and lands `slo_burn`/`slo_ok` edges on the telemetry timeline. A dead
  shard is a GAP in the scrape (its counters stop moving), exactly as
  its traffic is.
* **incident bundles** (`obs/trace/incident.py`, r19) — every edge the
  fleet already detects (an `slo_burn` from the scraper, an arc death
  from the router's liveness hook, a failover restart from
  supervision) triggers an atomic snapshot of the evidence in flight —
  router trace summary incl. the joined critical path, the metrics
  window + SLO state, per-shard heartbeats, the membership version —
  into `incidents/incident-<n>.json`; teardown folds all per-process
  bundles into `incidents/fleet.json`. Triggers are non-blocking
  enqueues (the liveness hook runs under the router lock), captures
  happen on a dedicated worker.

Stdlib + ring/router + obs.heartbeat/metrics only — the launcher never
imports jax (the shards do, in their own processes).
"""

import argparse
import json
import os
import pathlib
import socket
import sys
import time

from byzantinemomentum_tpu.cluster.runtime import free_port
from byzantinemomentum_tpu.obs.health import load_blackbox
from byzantinemomentum_tpu.obs.heartbeat import read_heartbeat, \
    write_heartbeat
from byzantinemomentum_tpu.obs.metrics import BurnRateEvaluator, \
    MetricsRegistry, MetricsScraper
from byzantinemomentum_tpu.obs.trace import IncidentRecorder, \
    merge_fleet_incidents
from byzantinemomentum_tpu.serve.fleet.ring import DEFAULT_VNODES, \
    Membership, write_fleet_manifest
from byzantinemomentum_tpu.serve.fleet.router import FleetRouter, \
    RouterServer

__all__ = ["FleetLauncher", "main", "process_commandline"]

# Repo root on the shards' PYTHONPATH (the cluster-launcher idiom)
_PKG_ROOT = pathlib.Path(__file__).resolve().parents[3]

SHARDS_DIRNAME = "shards"


def process_commandline(argv=None):
    parser = argparse.ArgumentParser(prog="serve.fleet")
    add = parser.add_argument
    add("--shards", type=int, default=2,
        help="Shard count: one AggregationService process per shard")
    add("--result-directory", type=str, required=True)
    add("--host", type=str, default="127.0.0.1")
    add("--port", type=int, default=7700,
        help="Router port (0 picks an ephemeral one)")
    add("--vnodes", type=int, default=DEFAULT_VNODES)
    add("--on-dead", type=str, default="queue",
        choices=("queue", "error"),
        help="Dead-arc policy: park lines behind the restart, or fail "
             "them fast")
    add("--max-parked", type=int, default=1024,
        help="Parked-line bound per dead arc under --on-dead queue: "
             "past it further lines fail fast (each parked line is a "
             "blocked client connection thread)")
    add("--max-batch", type=int, default=8)
    add("--max-delay-ms", type=float, default=2.0)
    add("--no-diagnostics", action="store_true", default=False)
    add("--no-tracing", action="store_true", default=False)
    add("--heartbeat-interval", type=float, default=2.0)
    add("--metrics-interval", type=float, default=2.0,
        help="Seconds between metrics scrapes of the shard fleet "
             "(merged snapshots append to metrics.jsonl; 0 disables)")
    add("--no-incidents", action="store_true", default=False,
        help="Disable incident bundles (SLO burn / arc death / "
             "failover edges snapshot trace+metrics+membership into "
             "incidents/incident-<n>.json)")
    add("--poll", type=float, default=0.2,
        help="Supervision poll interval in seconds")
    add("--shard-retries", type=int, default=5,
        help="Restarts PER SHARD before the launcher gives up (the "
             "outer Jobs supervisor takes over with the same semantics)")
    add("--ready-timeout", type=float, default=120.0,
        help="Seconds to wait for a spawned shard to answer ping")
    add("--warmup", action="append", default=None,
        help="gar:n:d:f spec compiled by every shard before it serves "
             "(repeatable)")
    add("--seed", type=int, default=1,
        help="Accepted for Jobs-supervisor compatibility")
    add("--device", type=str, default="auto",
        help="Accepted for Jobs-supervisor compatibility")
    add("--auto-resume", action="store_true", default=False,
        help="Accepted for Jobs-supervisor compatibility (shards are "
             "stateless: a relaunch IS a resume)")
    return parser.parse_args(sys.argv[1:] if argv is None else argv)


def _ping(host, port, timeout=1.0):
    """One short-lived ping round-trip; False on any failure."""
    try:
        with socket.create_connection((host, port),
                                      timeout=timeout) as sock:
            sock.settimeout(timeout)
            files = sock.makefile("rwb")
            files.write(b'{"op": "ping"}\n')
            files.flush()
            return bool(files.readline())
    except OSError:
        return False


class FleetLauncher:
    """The supervised fleet: shard processes, membership, router."""

    def __init__(self, args):
        self.args = args
        self.resdir = pathlib.Path(args.result_directory).resolve()
        self.shards_dir = self.resdir / SHARDS_DIRNAME
        self.shards_dir.mkdir(parents=True, exist_ok=True)
        self.host = args.host
        self.membership = Membership(vnodes=args.vnodes)
        self.procs = {}      # shard id -> Popen
        self.restarts = {}   # shard id -> count
        self.router = None
        self.server = None
        self.scraper = None
        self.incidents = None

    # -------------------------------------------------------------- #
    # incident capture (r19): edge events snapshot the evidence that
    # is otherwise rotating out of per-process rings

    def _metrics_context(self):
        if self.scraper is None:
            return {"enabled": False}
        snapshot = self.scraper.last_snapshot or {}
        out = {"t": snapshot.get("t"),
               "reached": snapshot.get("reached"),
               "missed": snapshot.get("missed")}
        merged = (snapshot.get("merged") or {}).get("metrics") or {}
        out["counters"] = {
            name: cell.get("value") for name, cell in merged.items()
            if isinstance(cell, dict) and cell.get("type") == "counter"}
        if self.scraper.evaluator is not None:
            out["slo"] = self.scraper.evaluator.summary()
        return out

    def _health_context(self):
        beats = {}
        for shard in sorted(self.membership.shards):
            beat = read_heartbeat(self.shards_dir / shard)
            if beat is not None:
                beats[shard] = {key: beat.get(key)
                                for key in ("step", "status", "updated")}
        context = {"heartbeats": beats}
        blackbox = load_blackbox(self.resdir)
        if blackbox is not None:
            context["blackbox"] = blackbox
        return context

    def _membership_context(self):
        return {"version": self.membership.version,
                "shards": len(self.membership.shards),
                "dead": sorted(self.router.dead_shards())
                if self.router else [],
                "restarts": dict(self.restarts)}

    def _make_incidents(self):
        return IncidentRecorder(self.resdir, source="launcher",
                                providers={
                                    "trace": lambda: self.router.stats(),
                                    "metrics": self._metrics_context,
                                    "health": self._health_context,
                                    "membership": self._membership_context,
                                }).start()

    def _on_slo_event(self, name, event):
        """Scraper-thread edge observer: a burn edge IS an incident."""
        if name == "slo_burn" and self.incidents is not None:
            self.incidents.trigger("slo_burn", **event)

    # -------------------------------------------------------------- #

    def _persist(self):
        write_fleet_manifest(self.resdir, self.membership,
                             router={"host": self.host,
                                     "port": (self.server.port
                                              if self.server else None),
                                     "pid": os.getpid(),
                                     "on_dead": self.args.on_dead})

    def _liveness_hook(self, shard, alive):
        """Router-detected transitions: version + persist BEFORE the
        ring flips (called under the router lock; no router calls)."""
        self.membership.bump("alive" if alive else "dead", shard)
        self._persist()
        if not alive and self.incidents is not None:
            # trigger() only enqueues — the capture worker snapshots
            # strictly outside this (router-held) lock context
            self.incidents.trigger("arc_dead", shard=shard,
                                   ring_version=self.membership.version)

    def _shard_cmd(self, shard, port):
        args = self.args
        cmd = [sys.executable, "-m", "byzantinemomentum_tpu.serve",
               "--host", self.host, "--port", str(port),
               "--parent-pipe",
               "--result-directory", str(self.shards_dir / shard),
               "--max-batch", str(args.max_batch),
               "--max-delay-ms", str(args.max_delay_ms),
               "--heartbeat-interval", str(args.heartbeat_interval)]
        if args.no_diagnostics:
            cmd.append("--no-diagnostics")
        if args.no_tracing:
            cmd.append("--no-tracing")
        for spec in args.warmup or ():
            cmd += ["--warmup", spec]
        return cmd

    def _spawn(self, shard, port):
        import subprocess

        (self.shards_dir / shard).mkdir(parents=True, exist_ok=True)
        out = (self.shards_dir / f"{shard}.out.log").open("ab")
        err = (self.shards_dir / f"{shard}.err.log").open("ab")
        env = dict(os.environ)
        env["PYTHONPATH"] = (str(_PKG_ROOT) + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        proc = subprocess.Popen(self._shard_cmd(shard, port),
                                stdin=subprocess.PIPE, stdout=out,
                                stderr=err, cwd=str(_PKG_ROOT), env=env)
        out.close()
        err.close()
        self.procs[shard] = proc
        self.membership.shards[shard]["pid"] = proc.pid
        self._persist()
        return proc

    def _wait_ready(self, shard, deadline):
        port = self.membership.shards[shard]["port"]
        while time.monotonic() < deadline:
            if _ping(self.host, port):
                return True
            if self.procs[shard].poll() is not None:
                return False
            time.sleep(0.1)
        return False

    # -------------------------------------------------------------- #

    def launch(self):
        """Membership first (persisted), then processes, then router."""
        for index in range(self.args.shards):
            shard = f"shard-{index}"
            self.membership.bump("add", shard, host=self.host,
                                 port=free_port())
        self._persist()
        for shard in sorted(self.membership.shards):
            self._spawn(shard, self.membership.shards[shard]["port"])
            self.restarts[shard] = 0
        deadline = time.monotonic() + self.args.ready_timeout
        for shard in sorted(self.membership.shards):
            if not self._wait_ready(shard, deadline):
                raise RuntimeError(f"{shard} never became ready "
                                   f"(see {self.shards_dir}/{shard}.err.log)")
        self.router = FleetRouter(
            {s: (row["host"], row["port"])
             for s, row in self.membership.shards.items()},
            vnodes=self.args.vnodes, on_dead=self.args.on_dead,
            max_parked=self.args.max_parked,
            liveness_hook=self._liveness_hook,
            metrics=MetricsRegistry(source="router"))
        self.server = RouterServer((self.host, self.args.port), self.router)
        self.server.serve_background()
        if not getattr(self.args, "no_incidents", False):
            self.incidents = self._make_incidents()
        if getattr(self.args, "metrics_interval", 0) > 0:
            # The pull plane: shards are TCP targets (their frontends
            # answer the metrics op), the in-process router registry
            # folds in as `local`, and the merged snapshots + SLO burn
            # edges land next to heartbeat.json
            self.scraper = MetricsScraper(
                {s: (row["host"], row["port"])
                 for s, row in self.membership.shards.items()},
                self.resdir, interval=self.args.metrics_interval,
                local=self.router.metrics,
                evaluator=BurnRateEvaluator(),
                on_event=self._on_slo_event).start()
        self._persist()  # now the manifest names the router's real port
        return self.server.port

    def aggregate_heartbeat(self, status="serving"):
        """Join the per-shard heartbeats into the run's single
        `heartbeat.json` — step is TOTAL SERVED (monotonic across
        restarts only while shards live; a restarted shard restarts its
        count, so the watchdog key is the max-over-time the Jobs
        signature already tolerates)."""
        served = 0
        alive = []
        shard_steps = {}
        for shard in sorted(self.membership.shards):
            beat = read_heartbeat(self.shards_dir / shard)
            if beat is None:
                continue
            step = beat.get("step")
            if isinstance(step, (int, float)):
                served += int(step)
                shard_steps[shard] = int(step)
            if beat.get("status") == "serving":
                alive.append(shard)
        write_heartbeat(self.resdir, {
            "step": served, "status": status,
            "shards": len(self.membership.shards),
            "shards_alive": len(alive), "shard_steps": shard_steps,
            "ring_version": self.membership.version,
            "dead": list(self.router.dead_shards()) if self.router else []})

    def supervise_once(self):
        """One poll: restart dead shards (persist-first), refresh the
        aggregated heartbeat. Returns the shards restarted this poll."""
        restarted = []
        for shard, proc in list(self.procs.items()):
            if proc.poll() is None:
                continue
            if proc.stdin is not None:
                try:
                    proc.stdin.close()
                except OSError:
                    pass
            self.restarts[shard] += 1
            if self.restarts[shard] > self.args.shard_retries:
                raise RuntimeError(
                    f"{shard} exceeded --shard-retries="
                    f"{self.args.shard_retries}")
            # Dead BEFORE restart, both persisted: the manifest's
            # history shows the arc go dark, then revive — on the SAME
            # port, so ownership (and every other client's suspicion
            # history) never moves
            self.router.mark_dead(shard)
            self._spawn(shard, self.membership.shards[shard]["port"])
            deadline = time.monotonic() + self.args.ready_timeout
            if not self._wait_ready(shard, deadline):
                raise RuntimeError(f"{shard} did not come back after a "
                                   f"restart")
            self.router.mark_alive(shard)
            restarted.append(shard)
            if self.incidents is not None:
                self.incidents.trigger(
                    "failover", shard=shard,
                    restarts=self.restarts[shard],
                    ring_version=self.membership.version)
        self.aggregate_heartbeat()
        return restarted

    def teardown(self):
        if self.scraper is not None:
            self.scraper.stop()
        if self.incidents is not None:
            # Drain queued triggers first, then fold every per-process
            # bundle (launcher + shards) into the fleet-scope index
            self.incidents.stop()
            merge_fleet_incidents(self.resdir)
            self.incidents = None
        if self.server is not None:
            self.server.shutdown()
            self.server.server_close()
        if self.router is not None:
            self.router.close()
        for proc in self.procs.values():
            if proc.poll() is None:
                try:
                    proc.kill()
                except OSError:
                    pass
        for proc in self.procs.values():
            try:
                proc.wait(timeout=10)
            except Exception:  # bmt: noqa[BMT-E05] kill-then-wait failing means the OS is reaping it; teardown must not raise
                pass
            if proc.stdin is not None:
                try:
                    proc.stdin.close()
                except OSError:
                    pass


def main(argv=None):
    args = process_commandline(argv)
    if args.shards < 1:
        print("fleet: need at least one shard")
        return 2
    launcher = FleetLauncher(args)
    # A live signal BEFORE the slow part (N shard spawns, each a jax
    # import + warmup) so an outer Jobs watchdog never kills a fleet
    # for starting up
    write_heartbeat(launcher.resdir,
                    {"step": None, "status": "launching",
                     "shards": args.shards})
    try:
        port = launcher.launch()
    except (RuntimeError, OSError) as err:
        print(f"fleet: launch failed: {err}")
        launcher.teardown()
        return 1
    print("fleet: " + json.dumps(
        {"router": f"{args.host}:{port}", "shards": args.shards,
         "ports": {s: row["port"]
                   for s, row in launcher.membership.shards.items()},
         "on_dead": args.on_dead,
         "ring_version": launcher.membership.version}), flush=True)
    try:
        while True:
            time.sleep(max(args.poll, 0.01))
            launcher.supervise_once()
    except KeyboardInterrupt:
        pass
    except RuntimeError as err:
        print(f"fleet: {err}")
        launcher.teardown()
        launcher.aggregate_heartbeat(status="failed")
        return 1
    launcher.teardown()
    launcher.aggregate_heartbeat(status="stopped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
