"""Consistent-hash ring: the fleet's client → shard ownership map.

Every client id hashes onto a 64-bit circle; each shard contributes
`vnodes` virtual points (sha1 of ``"shard:vnode"`` — NEVER the builtin
``hash()``, whose per-process ``PYTHONHASHSEED`` salt would give every
router process a different ring). A client is OWNED by the shard whose
virtual point is first clockwise from the client's hash. Ownership is
the suspicion-locality contract: the owner's `ClientSuspicionStore` is
the only one that ever sees the client, so verdicts are byte-identical
to a single-process store fed the same substream.

Two properties the unit battery (tests/test_fleet.py) pins:

* **determinism** — the same (shards, vnodes) build the same ring in
  every process; routing is a pure function of the membership snapshot.
* **minimal remap** — removing K of N shards remaps only the clients
  the dead shards owned: an expected (and asserted) fraction of at most
  (K+1)/N, while every other client keeps its owner (and therefore its
  suspicion history).

Liveness is deliberately SEPARATE from ownership (the Ray split the
PAPERS.md annotation adopts: the launcher decides liveness, the owner
decides state): `mark_dead`/`mark_alive` flip a shard's arc without
moving any client, because a killed shard restarts on the same port and
resumes owning exactly its old arc — with a fresh store, so a returning
client re-warms no faster than a fresh id. `owner()` ignores liveness;
`route()` consults it and reports a dead owner to the router's policy
instead of silently failing clients over (which would leak suspicion
state across shards).

Membership is VERSIONED and persisted before any change takes effect:
`Membership.bump` appends a history record and `write_fleet_manifest`
lands it atomically (tmp + fsync + `os.replace`, the heartbeat/manifest
discipline) BEFORE the launcher or router acts on the new view, so a
crash replays at worst a stale-but-consistent ring, never a torn one.
Stdlib only — no jax, no numpy — so the router and launcher never
initialize a backend through this module.
"""

import bisect
import hashlib
import json
import os
import pathlib

__all__ = ["DEFAULT_VNODES", "FLEET_MANIFEST_NAME", "HashRing",
           "Membership", "hash_point", "read_fleet_manifest",
           "write_fleet_manifest"]

DEFAULT_VNODES = 64
FLEET_MANIFEST_NAME = "fleet.json"
_SPACE = 1 << 64


def hash_point(key):
    """Deterministic 64-bit circle position of `key` (sha1-derived:
    stable across processes, platforms and Python versions)."""
    digest = hashlib.sha1(str(key).encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big")


class HashRing:
    """The virtual-node circle over a set of shard ids.

    `shards` maps shard id -> alive flag; `owner(client)` is pure
    membership (stable under liveness flips), `route(client)` returns
    `(owner, alive)` so the caller applies its dead-arc policy.
    """

    def __init__(self, shards=(), *, vnodes=DEFAULT_VNODES):
        if vnodes < 1:
            raise ValueError(f"Expected vnodes >= 1, got {vnodes}")
        self.vnodes = int(vnodes)
        self._alive = {}      # shard id -> bool
        self._points = []     # sorted [(point, shard)]
        for shard in shards:
            self.add(shard)

    # -------------------------------------------------------------- #
    # membership

    def add(self, shard):
        shard = str(shard)
        if shard in self._alive:
            raise ValueError(f"shard {shard!r} is already on the ring")
        self._alive[shard] = True
        for v in range(self.vnodes):
            point = hash_point(f"{shard}:{v}")
            bisect.insort(self._points, (point, shard))

    def remove(self, shard):
        shard = str(shard)
        if shard not in self._alive:
            raise KeyError(shard)
        del self._alive[shard]
        self._points = [(p, s) for p, s in self._points if s != shard]

    # -------------------------------------------------------------- #
    # liveness (never moves ownership)

    def mark_dead(self, shard):
        if str(shard) not in self._alive:
            raise KeyError(shard)
        self._alive[str(shard)] = False

    def mark_alive(self, shard):
        if str(shard) not in self._alive:
            raise KeyError(shard)
        self._alive[str(shard)] = True

    def alive(self, shard):
        return bool(self._alive.get(str(shard), False))

    @property
    def shards(self):
        return tuple(sorted(self._alive))

    @property
    def dead(self):
        return tuple(sorted(s for s, a in self._alive.items() if not a))

    # -------------------------------------------------------------- #
    # routing

    def owner(self, client):
        """The shard owning `client` — pure membership, liveness-blind
        (a killed-and-restarting shard keeps its arc)."""
        if not self._points:
            raise LookupError("ring has no shards")
        point = hash_point(client) % _SPACE
        index = bisect.bisect_right(self._points, (point, "￿"))
        if index == len(self._points):
            index = 0
        return self._points[index][1]

    def route(self, client):
        """`(owner, alive)` — the router's dead-arc policy decides what
        a False means (queue behind the restart, or error the line)."""
        shard = self.owner(client)
        return shard, self._alive[shard]

    def spread(self, clients):
        """{shard: owned-client count} over `clients` (balance probe)."""
        counts = {shard: 0 for shard in self._alive}
        for client in clients:
            counts[self.owner(client)] += 1
        return counts


class Membership:
    """The versioned fleet view `fleet.json` persists.

    Every change appends a history record carrying the version AFTER the
    change — strictly monotonic, replayable: `Membership.replay` folds
    the history into the final shard set, and the unit battery asserts a
    replayed manifest reproduces the live ring exactly.
    """

    def __init__(self, *, vnodes=DEFAULT_VNODES):
        self.version = 0
        self.vnodes = int(vnodes)
        self.shards = {}   # shard id -> {"host", "port", "alive", "pid"}
        self.history = []  # [{"version", "change", "shard"}]

    def bump(self, change, shard, **fields):
        """Apply one membership/liveness change and version it. Valid
        `change`: add, remove, dead, alive."""
        shard = str(shard)
        if change == "add":
            if shard in self.shards:
                raise ValueError(f"shard {shard!r} already present")
            self.shards[shard] = {"alive": True, **fields}
        elif change == "remove":
            self.shards.pop(shard)
        elif change == "dead":
            self.shards[shard]["alive"] = False
            self.shards[shard].update(fields)
        elif change == "alive":
            self.shards[shard]["alive"] = True
            self.shards[shard].update(fields)
        else:
            raise ValueError(f"unknown membership change {change!r}")
        self.version += 1
        self.history.append({"version": self.version, "change": change,
                             "shard": shard})
        return self.version

    def ring(self):
        """The HashRing this membership describes."""
        ring = HashRing(sorted(self.shards), vnodes=self.vnodes)
        for shard, row in self.shards.items():
            if not row.get("alive", True):
                ring.mark_dead(shard)
        return ring

    def as_dict(self):
        return {"version": self.version, "vnodes": self.vnodes,
                "shards": {s: dict(row) for s, row in self.shards.items()},
                "history": [dict(h) for h in self.history]}

    @classmethod
    def from_dict(cls, payload):
        membership = cls(vnodes=payload.get("vnodes", DEFAULT_VNODES))
        membership.version = int(payload.get("version", 0))
        membership.shards = {str(s): dict(row) for s, row
                             in (payload.get("shards") or {}).items()}
        membership.history = [dict(h) for h in payload.get("history") or []]
        return membership

    @classmethod
    def replay(cls, payload):
        """Fold the manifest's HISTORY (not its snapshot) into a
        membership — the recovery-path proof that the persisted change
        log alone reconstructs the ring. Raises on a non-monotonic
        version sequence."""
        membership = cls(vnodes=payload.get("vnodes", DEFAULT_VNODES))
        for record in payload.get("history") or []:
            version = membership.bump(record["change"], record["shard"])
            if version != record["version"]:
                raise ValueError(
                    f"non-monotonic membership history: replayed version "
                    f"{version} but the record says {record['version']}")
        snapshot = payload.get("shards") or {}
        for shard, row in snapshot.items():
            membership.shards.setdefault(str(shard), {}).update(
                {k: v for k, v in row.items() if k != "alive"})
        return membership


def write_fleet_manifest(directory, membership, name=FLEET_MANIFEST_NAME,
                         **extra):
    """Atomically persist the membership (checkpoint discipline: tmp +
    fsync + replace) — called BEFORE the launcher/router act on a
    change, so a crash can replay a stale view but never a torn one."""
    directory = pathlib.Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    payload = membership.as_dict()
    payload.update(extra)
    path = directory / name
    tmp = path.with_name(path.name + ".tmp")
    with tmp.open("w", encoding="utf-8") as fd:
        fd.write(json.dumps(payload, indent="\t", sort_keys=True))
        fd.flush()
        os.fsync(fd.fileno())
    os.replace(tmp, path)
    return path


def read_fleet_manifest(directory, name=FLEET_MANIFEST_NAME):
    """The persisted manifest payload, or None when absent/torn."""
    try:
        return json.loads((pathlib.Path(directory) / name).read_text())
    except (OSError, ValueError):
        return None
