"""Sharded aggregation fleet: consistent-hash routing over N serve
processes, shard-local suspicion, kill-safe failover.

The single-process service (`serve/service.py`) tops out where one
resolver and one suspicion lock do (`BENCH_serve_r10.json`; queue wait
is 37% of p50 in `ATTRIB_serve_r13.json`). This package scales it OUT
instead of up, without touching the aggregation or suspicion math:

* `ring.py` — the consistent-hash ring (sha1 points, virtual nodes)
  and the versioned, persist-before-change `Membership` that owns it.
  Stdlib only; deterministic across processes.
* `router.py` — `FleetRouter`/`RouterServer`: the line-JSON frontend
  that maps each request's first client id onto its owner shard and
  pipelines groups down one connection per shard, with exactly-one
  disposition per line (queue-or-error on a dead arc, never re-send).
* `launcher.py` — N supervised shard processes under the
  `cluster/launcher.py` discipline: launcher-held stdin pipes (orphans
  die), per-shard heartbeats aggregated into one `heartbeat.json`
  (`Jobs(seeds=(None,))` supervises the fleet unchanged), membership
  persisted to `fleet.json` BEFORE any ring change.
* `local.py` — an in-process N-shard fleet on loopback for tests, the
  serve selfcheck and loadgen tracing (real sockets, no subprocesses).

Ownership follows the Ray split (PAPERS.md): the launcher/router decide
LIVENESS, each shard decides its clients' STATE — a shard owns its arc's
`ClientSuspicionStore` exactly, so fleet verdicts are byte-identical to
a single process fed the same per-shard substream, and a killed shard's
returning clients re-warm from scratch (no faster than a fresh id).
"""

from byzantinemomentum_tpu.serve.fleet.ring import (  # noqa: F401
    DEFAULT_VNODES,
    FLEET_MANIFEST_NAME,
    HashRing,
    Membership,
    hash_point,
    read_fleet_manifest,
    write_fleet_manifest,
)
from byzantinemomentum_tpu.serve.fleet.router import (  # noqa: F401
    FleetRouter,
    RouterServer,
)

__all__ = [
    "DEFAULT_VNODES", "FLEET_MANIFEST_NAME", "HashRing", "Membership",
    "hash_point", "read_fleet_manifest", "write_fleet_manifest",
    "FleetRouter", "RouterServer",
]
