"""Microbatching queue: pack concurrent same-cell requests into one
device program.

Requests enqueue per cell; a cell flushes when it holds `max_batch`
requests (immediately — the submitting thread notifies the flusher) or
when its oldest request has waited `max_delay` seconds, whichever comes
first. That is the classic max-batch-size / max-delay policy: an idle
service adds at most `max_delay` of latency, a saturated one packs full
batches, and p99 stays bounded by `max_delay` plus one program execution.

Two daemon threads, so the submitting host thread NEVER blocks on device
completion:

  flusher   picks due cells, hands each packed batch to the service's
            dispatch callback (which enqueues the device program
            asynchronously and returns its on-device outputs immediately),
            then passes the in-flight handle to the resolver.
  resolver  blocks on device-ready (the only thread that ever does),
            unpacks per-request results and fulfills the caller futures.

Callers hold `concurrent.futures.Future`s — `submit` returns before any
device work happens, and a future resolves exactly when its batch leaves
the device. Failures (a dispatch error, a poisoned batch) resolve the
affected futures with the exception instead of wedging callers.

Queue depth and batch occupancy land on the active obs recorder
(`serve_queue_depth` gauge, `serve_batches`/`serve_batched_requests`
counters) — the telemetry substrate every other subsystem already uses.
The depth gauge is emitted at every queue TRANSITION — submit (depth
after enqueue), flush (depth after the batch left) and resolver drain
(depth as a batch resolves) — so an idle-then-burst profile is visible
in the gauge sequence instead of only its flush-time residue.

The same transitions feed the metrics plane (`obs/metrics`, r18): a
`serve_queue_depth` registry gauge plus a `serve_queue_depth_dist`
histogram observed at the SAME edges with the SAME values — the gauge
edge stream in `telemetry.jsonl` and the registry's bucket counts are
two projections of one sequence, so folding the recorded edges into
the static ladder must reproduce the histogram exactly (pinned by a
cross-check test). Batch sizes land on `serve_batch_size`, and the
`serve_batches`/`serve_batched_requests` counters mirror onto registry
counters of the same names.

Request tracing (`obs/trace/request.py`): when a request carries a
`RequestTrace`, the batcher stamps the two hand-offs it owns — `flush`
(queue wait ends: the flusher picked the batch) and `resolver` (the
resolver thread picked the in-flight batch up, ending the dispatch→
resolver wake-up gap). Everything else is stamped by the service.
"""

import collections
import concurrent.futures
import queue
import threading
import time

from byzantinemomentum_tpu.obs import recorder
from byzantinemomentum_tpu.obs.metrics import DEPTH_BOUNDS, NullRegistry
from byzantinemomentum_tpu.utils.locking import NamedCondition

__all__ = ["ServeRequest", "MicroBatcher"]


class ServeRequest:
    """One enqueued aggregation: the packed payload plus its future.
    `n`/`d` are the RAW request shape (the cell's n_bucket/d_bucket are
    the compiled sizes); the packer pads up and the resolver slices
    back. `admitted`/`admission` carry the submit-time admission-control
    decisions (`serve/admission.py`): rows with `admitted` False pack as
    INACTIVE (the masked kernels reject them), and the flagged-client
    provenance rides back on the response. `trace` optionally carries
    the request's `RequestTrace` (`obs/trace`); when present its
    `submit` stamp is the same instant as `t_submit` so traced spans
    tile the measured latency."""

    __slots__ = ("cell", "n", "d", "matrix", "client_ids", "future",
                 "t_submit", "admitted", "admission", "trace")

    def __init__(self, cell, n, matrix, client_ids, admitted=None,
                 admission=None, trace=None):
        self.cell = cell
        self.n = int(n)
        self.d = int(matrix.shape[1])
        self.matrix = matrix          # np.f32[n, d] (host)
        self.client_ids = client_ids  # tuple[str] | None
        self.admitted = admitted      # bool[n] | None (None = all)
        self.admission = admission    # {client: decision} | None
        self.trace = trace            # RequestTrace | None
        self.future = concurrent.futures.Future()
        self.t_submit = time.monotonic()
        if trace is not None:
            trace.stamp("submit", at=self.t_submit)


class MicroBatcher:
    """Per-cell request queues + the flusher/resolver thread pair.

    Args:
      dispatch: `(cell, requests) -> handle` — pack and asynchronously
        dispatch one batch (called on the flusher thread; must not
        block on device completion).
      resolve: `(handle, requests) -> None` — block until device-ready
        and fulfill each request's future (called on the resolver
        thread).
      max_batch: flush a cell at this many queued requests.
      max_delay: seconds the oldest request of a cell may wait before
        its batch flushes regardless of occupancy.
      metrics: the owning service's `MetricsRegistry` (None = no-op
        `NullRegistry`) — queue depth gauge + distribution, batch-size
        histogram and the batch counters land there.
    """

    def __init__(self, dispatch, resolve, *, max_batch=8, max_delay=0.002,
                 metrics=None):
        if max_batch < 1:
            raise ValueError(f"Expected max_batch >= 1, got {max_batch}")
        if max_delay < 0:
            raise ValueError(f"Expected max_delay >= 0, got {max_delay}")
        self._dispatch = dispatch
        self._resolve = resolve
        self.max_batch = int(max_batch)
        self.max_delay = float(max_delay)
        metrics = metrics if metrics is not None else NullRegistry()
        self._m_depth = metrics.gauge("serve_queue_depth")
        self._m_depth_dist = metrics.histogram("serve_queue_depth_dist",
                                               bounds=DEPTH_BOUNDS)
        self._m_batches = metrics.counter("serve_batches")
        self._m_batched = metrics.counter("serve_batched_requests")
        self._m_batch_size = metrics.histogram("serve_batch_size",
                                               bounds=DEPTH_BOUNDS)
        self._queues = collections.OrderedDict()  # cell -> deque[request]
        self._cond = NamedCondition("batcher.cond")  # bmt: noqa[BMT-L06] the batcher handoff is pinned end-to-end by tests/test_serve.py's deterministic drain paths (single condition, no second lock)
        self._inflight = queue.Queue()
        self._closed = False
        self._flusher = threading.Thread(target=self._flush_loop,
                                         name="serve-flusher", daemon=True)
        self._resolver = threading.Thread(target=self._resolve_loop,
                                          name="serve-resolver", daemon=True)
        self._flusher.start()
        self._resolver.start()

    # ------------------------------------------------------------------ #

    def submit(self, request):
        """Enqueue one request; returns its future immediately."""
        with self._cond:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._queues.setdefault(request.cell, collections.deque()
                                    ).append(request)
            depth = sum(len(q) for q in self._queues.values())
            self._cond.notify()
        if request.trace is not None:
            request.trace.depth_at_submit = depth
        # Depth on SUBMIT, not only on flush: an idle-then-burst queue
        # build-up is otherwise invisible (the gauge would only record
        # the post-flush residue)
        self._m_depth.set(depth)  # bmt: noqa[BMT-T01] Gauge is internally locked (its own _lock serializes set/snapshot); the attribute binds once in __init__
        self._m_depth_dist.observe(depth)
        if recorder.active() is not None:
            recorder.active().gauge("serve_queue_depth", depth,
                                    edge="submit")
        return request.future

    def depth(self):
        """Requests currently queued (not yet dispatched)."""
        with self._cond:
            return sum(len(q) for q in self._queues.values())

    # ------------------------------------------------------------------ #
    # Flusher: pick due cells, dispatch, hand off to the resolver

    def _due(self, now):
        """(requests, depth_after) of the most urgent due cell, or None.
        A cell is due when full (>= max_batch) or its oldest request aged
        past max_delay; fullness beats age so a saturated cell drains in
        whole batches."""
        due_cell = None
        for cell, q in self._queues.items():
            if not q:
                continue
            if len(q) >= self.max_batch:
                due_cell = cell
                break
            if now - q[0].t_submit >= self.max_delay and due_cell is None:
                due_cell = cell
        if due_cell is None:
            return None
        q = self._queues[due_cell]
        batch = [q.popleft() for _ in range(min(len(q), self.max_batch))]
        if not q:
            del self._queues[due_cell]
        return batch, sum(len(qq) for qq in self._queues.values())

    def _next_deadline(self, now):
        """Seconds until the earliest max-delay expiry (None = no queue)."""
        oldest = None
        for q in self._queues.values():
            if q and (oldest is None or q[0].t_submit < oldest):
                oldest = q[0].t_submit
        if oldest is None:
            return None
        return max(0.0, oldest + self.max_delay - now)

    def _flush_loop(self):
        while True:
            with self._cond:
                while True:
                    if self._closed and not self._queues:
                        return
                    picked = self._due(time.monotonic())
                    if picked is not None:
                        break
                    timeout = self._next_deadline(time.monotonic())
                    self._cond.wait(timeout=timeout)
                batch, depth_after = picked
            # One shared stamp dict per batch: every hand-off below this
            # point is batch-granular, so traced requests reference it
            # instead of each paying five timestamped stores
            batch_stamps = None
            for r in batch:
                if r.trace is not None:
                    if batch_stamps is None:
                        batch_stamps = {"flush": time.monotonic()}
                    r.trace.batch_stamps = batch_stamps
            self._m_batches.inc()
            self._m_batched.inc(len(batch))
            self._m_batch_size.observe(len(batch))
            self._m_depth.set(depth_after)  # bmt: noqa[BMT-T01] Gauge is internally locked; the attribute binds once in __init__
            self._m_depth_dist.observe(depth_after)
            recorder.counter("serve_batches")
            recorder.counter("serve_batched_requests", len(batch))
            if recorder.active() is not None:
                recorder.active().gauge("serve_queue_depth", depth_after,
                                        edge="flush")
            try:
                handle = self._dispatch(batch[0].cell, batch)
            except Exception as err:  # bmt: noqa[BMT-E05] one poisoned batch must fail its own futures, not kill the flusher serving every other caller
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(err)
                continue
            self._inflight.put((handle, batch))

    # ------------------------------------------------------------------ #
    # Resolver: the only thread that blocks on the device

    def _resolve_loop(self):
        while True:
            item = self._inflight.get()
            if item is None:
                return
            handle, batch = item
            t_wake = time.monotonic()
            for r in batch:
                if r.trace is not None and r.trace.batch_stamps is not None:
                    r.trace.batch_stamps["resolver"] = t_wake
                    break  # shared dict: one store covers the batch
            try:
                self._resolve(handle, batch)
            except Exception as err:  # bmt: noqa[BMT-E05] a failed resolution must fail its own futures, not kill the resolver thread behind every in-flight batch
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(err)
            # Depth on resolver DRAIN: with submit/flush above, every
            # queue transition lands on the gauge, so a depth timeline
            # can be read straight off the telemetry
            depth = self.depth()
            self._m_depth.set(depth)  # bmt: noqa[BMT-T01] Gauge is internally locked; the attribute binds once in __init__
            self._m_depth_dist.observe(depth)
            if recorder.active() is not None:
                recorder.active().gauge("serve_queue_depth", depth,
                                        edge="drain")

    # ------------------------------------------------------------------ #

    def close(self, timeout=5.0):
        """Drain the queues, stop both threads. Idempotent."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._flusher.join(timeout=timeout)
        self._inflight.put(None)
        self._resolver.join(timeout=timeout)
