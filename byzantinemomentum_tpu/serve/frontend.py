"""Line-JSON socket front end for the aggregation service (stdlib only).

Protocol: newline-delimited JSON over TCP, one object per line, one
response line per request line, in order:

  {"op": "aggregate", "vectors": [[...], ...], "gar": "krum", "f": 1,
   "clients": ["c0", ...], "diagnostics": true, "trace": "req-17"}
      -> {"ok": true, "aggregate": [...], "f_eff": 1, "n": 11,
          "cell": {...}, "verdicts": {...}, "latency_ms": 3.2,
          "trace": {"trace_id": "req-17", "spans_ms": {...}, ...}}
  {"op": "stats"}   -> {"ok": true, "stats": {...}}
  {"op": "metrics"} -> {"ok": true, "metrics": {...}}   (registry dump)
  {"op": "ping"}    -> {"ok": true, "op": "ping"}

Errors answer `{"ok": false, "error": "..."}` on the same line slot; a
malformed line never kills the connection, let alone the server. Each
connection gets its own handler thread (`ThreadingTCPServer`) plus a
per-connection WRITER thread: the reader submits each line without
blocking on its result and hands the future down an in-order reply
queue the writer drains — so one connection can hold many requests in
flight (the fleet router pipelines whole groups down a single shard
connection) and the microbatcher still packs them into shared device
programs. Replies stay strictly in request order; a client that sends
one line and waits sees exactly the old behavior.

Trace-id propagation (`obs/trace/request.py`): an optional `"trace"`
field (string or number) names the request's trace; with tracing on the
completed span record rides back under the response's `"trace"` key,
its `parse` span opened at the instant the raw line arrived (stamped
BEFORE the JSON decode, so client-visible decode cost is attributed).
Absent ids are auto-assigned server-side; a malformed id (object/array)
answers an error on its line slot without severing the connection.

The response's trace record is also the substrate of the fleet-scope
span JOIN (r19): it carries only DURATIONS from this process's
monotonic clock —

  {"trace_id": "req-17",
   "spans_ms": {"parse": .., "validate": .., "queue": .., "pack": ..,
                "dispatch": .., "resolver_wake": .., "device": ..,
                "resolve": ..},
   "total_ms": .., "depth_at_submit": .., "batch_size": ..,
   "batch_occupancy": .., "gar": .., "n": .., "d": .., "src": "shard-2"}

— never wall-clock timestamps, so the fleet router can nest them
clock-free inside its own measured `shard_rtt` envelope
(`join_shard_trace`); `src` names the serving shard (the service's
metrics source) so the join can cross-check routing against the
shard's own identity. A frontend running with tracing off simply omits
the key and the router degrades to its opaque row — the record is
telemetry, never load-bearing protocol.
"""

import json
import queue
import socketserver
import threading
import time

from byzantinemomentum_tpu import utils

__all__ = ["AggregationServer", "serve_forever"]


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        service = self.server.service
        # In-order reply lane: the reader thread (this one) enqueues a
        # dict (already-answered op/error) or a Future per line; the
        # writer resolves and writes them in request order, so replies
        # pipeline without ever reordering
        replies = queue.Queue()
        writer = threading.Thread(target=self._write_loop, args=(replies,),  # bmt: noqa[BMT-L06] per-connection writer drains one reply queue then exits; ordering is pinned by the queue itself (single producer, single consumer)
                                  name="serve-conn-writer", daemon=True)
        writer.start()
        try:
            for raw in self.rfile:
                received_at = time.monotonic()  # before the JSON decode:
                #                                 parse cost is attributed
                line = raw.strip()
                if not line:
                    continue
                try:
                    replies.put(self._one(service, json.loads(line),
                                          received_at))
                except (ValueError, KeyError, TypeError,
                        utils.UserException) as err:
                    replies.put({"ok": False, "error": str(err)})
                except Exception as err:  # bmt: noqa[BMT-E05] a failed request must answer its line, not sever every client on this connection
                    replies.put({"ok": False,
                                 "error": f"{type(err).__name__}: {err}"})
        finally:
            replies.put(None)
            writer.join()

    def _write_loop(self, replies):
        """Drain the reply lane in order; a future blocks only its own
        line (later futures keep computing underneath)."""
        broken = False
        while True:
            entry = replies.get()
            if entry is None:
                return
            if not isinstance(entry, dict):
                try:
                    entry = {"ok": True, **entry.result().as_dict()}
                except utils.UserException as err:
                    entry = {"ok": False, "error": str(err)}
                except Exception as err:  # bmt: noqa[BMT-E05] a failed request must answer its line, not sever every client on this connection
                    entry = {"ok": False,
                             "error": f"{type(err).__name__}: {err}"}
            if broken:
                continue  # client hung up: keep draining to the sentinel
            try:
                self.wfile.write(json.dumps(entry).encode("utf-8") + b"\n")
                self.wfile.flush()
            except OSError:
                broken = True

    @staticmethod
    def _one(service, request, received_at=None):
        """One parsed line -> an answered dict (ops) or the request's
        Future (aggregate) for the writer to resolve in order."""
        if not isinstance(request, dict):
            raise ValueError("expected a JSON object per line")
        op = request.get("op", "aggregate")
        if op == "ping":
            return {"ok": True, "op": "ping"}
        if op == "stats":
            return {"ok": True, "stats": service.stats()}
        if op == "metrics":
            # The metrics-plane exposition verb (obs/metrics): the
            # scraper pulls this shard's registry dump and does the
            # merging ITSELF — no push path, no aggregation here
            return {"ok": True, "metrics": service.metrics.dump()}
        if op != "aggregate":
            raise ValueError(f"unknown op {op!r}")
        trace_id = request.get("trace")
        if trace_id is not None and not isinstance(trace_id, (str, int,
                                                              float)):
            # A malformed id answers an error on ITS line slot (the
            # handler catches ValueError); the connection lives on
            raise ValueError(
                f"trace id must be a string or number, got "
                f"{type(trace_id).__name__}")
        vectors = request["vectors"]
        return service.submit(
            vectors,
            gar=request.get("gar", "krum"),
            f=int(request.get("f", 1)),
            client_ids=request.get("clients"),
            diagnostics=request.get("diagnostics"),
            trace_id=trace_id, received_at=received_at)


class AggregationServer(socketserver.ThreadingTCPServer):
    """TCP server bound to an `AggregationService`."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, address, service):
        super().__init__(address, _Handler)
        self.service = service

    @property
    def port(self):
        return self.server_address[1]

    def serve_background(self):
        """Serve on a daemon thread; returns the thread (the caller owns
        shutdown through `server.shutdown()`)."""
        thread = threading.Thread(target=self.serve_forever,
                                  name="serve-frontend", daemon=True)
        thread.start()
        return thread


def serve_forever(service, host="127.0.0.1", port=0):
    """Blocking convenience: bind and serve until interrupted. Returns
    the server (mostly useful when `port=0` picked an ephemeral port —
    read it back before blocking via `AggregationServer` directly)."""
    with AggregationServer((host, port), service) as server:
        server.serve_forever()
    return server
