"""Line-JSON socket front end for the aggregation service (stdlib only).

Protocol: newline-delimited JSON over TCP, one object per line, one
response line per request line, in order:

  {"op": "aggregate", "vectors": [[...], ...], "gar": "krum", "f": 1,
   "clients": ["c0", ...], "diagnostics": true, "trace": "req-17"}
      -> {"ok": true, "aggregate": [...], "f_eff": 1, "n": 11,
          "cell": {...}, "verdicts": {...}, "latency_ms": 3.2,
          "trace": {"trace_id": "req-17", "spans_ms": {...}, ...}}
  {"op": "stats"}   -> {"ok": true, "stats": {...}}
  {"op": "ping"}    -> {"ok": true, "op": "ping"}

Errors answer `{"ok": false, "error": "..."}` on the same line slot; a
malformed line never kills the connection, let alone the server. Each
connection gets its own handler thread (`ThreadingTCPServer`), and the
handler blocks on ITS request's future only — the service's dispatch
stays batched and asynchronous underneath, so concurrent connections
pack into shared device programs.

Trace-id propagation (`obs/trace/request.py`): an optional `"trace"`
field (string or number) names the request's trace; with tracing on the
completed span record rides back under the response's `"trace"` key,
its `parse` span opened at the instant the raw line arrived (stamped
BEFORE the JSON decode, so client-visible decode cost is attributed).
Absent ids are auto-assigned server-side; a malformed id (object/array)
answers an error on its line slot without severing the connection.
"""

import json
import socketserver
import threading
import time

from byzantinemomentum_tpu import utils

__all__ = ["AggregationServer", "serve_forever"]


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        service = self.server.service
        for raw in self.rfile:
            received_at = time.monotonic()  # before the JSON decode:
            #                                 parse cost is attributed
            line = raw.strip()
            if not line:
                continue
            try:
                response = self._one(service, json.loads(line),
                                     received_at)
            except (ValueError, KeyError, TypeError,
                    utils.UserException) as err:
                response = {"ok": False, "error": str(err)}
            except Exception as err:  # bmt: noqa[BMT-E05] a failed request must answer its line, not sever every client on this connection
                response = {"ok": False,
                            "error": f"{type(err).__name__}: {err}"}
            try:
                self.wfile.write(json.dumps(response).encode("utf-8")
                                 + b"\n")
                self.wfile.flush()
            except OSError:
                return  # client hung up mid-response

    @staticmethod
    def _one(service, request, received_at=None):
        if not isinstance(request, dict):
            raise ValueError("expected a JSON object per line")
        op = request.get("op", "aggregate")
        if op == "ping":
            return {"ok": True, "op": "ping"}
        if op == "stats":
            return {"ok": True, "stats": service.stats()}
        if op != "aggregate":
            raise ValueError(f"unknown op {op!r}")
        trace_id = request.get("trace")
        if trace_id is not None and not isinstance(trace_id, (str, int,
                                                              float)):
            # A malformed id answers an error on ITS line slot (the
            # handler catches ValueError); the connection lives on
            raise ValueError(
                f"trace id must be a string or number, got "
                f"{type(trace_id).__name__}")
        vectors = request["vectors"]
        future = service.submit(
            vectors,
            gar=request.get("gar", "krum"),
            f=int(request.get("f", 1)),
            client_ids=request.get("clients"),
            diagnostics=request.get("diagnostics"),
            trace_id=trace_id, received_at=received_at)
        result = future.result()
        return {"ok": True, **result.as_dict()}


class AggregationServer(socketserver.ThreadingTCPServer):
    """TCP server bound to an `AggregationService`."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, address, service):
        super().__init__(address, _Handler)
        self.service = service

    @property
    def port(self):
        return self.server_address[1]

    def serve_background(self):
        """Serve on a daemon thread; returns the thread (the caller owns
        shutdown through `server.shutdown()`)."""
        thread = threading.Thread(target=self.serve_forever,
                                  name="serve-frontend", daemon=True)
        thread.start()
        return thread


def serve_forever(service, host="127.0.0.1", port=0):
    """Blocking convenience: bind and serve until interrupted. Returns
    the server (mostly useful when `port=0` picked an ephemeral port —
    read it back before blocking via `AggregationServer` directly)."""
    with AggregationServer((host, port), service) as server:
        server.serve_forever()
    return server
