"""Aggregation-as-a-service: the streaming, batched GAR scoring engine.

The millions-of-users story for this codebase (ROADMAP
"Aggregation-as-a-service"): clients submit gradient/update cohorts, the
service returns Byzantine-resilient aggregates plus per-client suspicion
verdicts. Everything device-side reuses the existing in-jit machinery —
the masked-quorum GAR variants (PR 1) absorb shape-bucket padding, the
serve aux rides the PR 4 diagnostics substrate, telemetry and heartbeats
are the PR 3 obs stack — so there is no forked "serving copy" of any
kernel to drift (Sculley et al.'s hidden-debt warning, PAPERS.md).

Layers (one module each):

  programs   persistent compiled program cache per
             `(gar, n-bucket, f, d-bucket, diagnostics)` cell; request
             (n, d) rounds up a two-axis shape-bucket ladder — padded
             rows masked out in-jit by the traced-count masked kernels,
             padded columns zero (exact per rule, `D_PAD_EXACT`).
  batching   microbatch queue (max-batch / max-delay flush) packing
             concurrent same-cell requests along a leading `vmap` axis;
             async dispatch, futures on device-ready.
  service    `AggregationService` — the in-process API tying cache +
             batcher + the client-keyed suspicion store + heartbeats.
  frontend   line-JSON TCP front end (stdlib `socketserver`).
  __main__   CLI: `python -m byzantinemomentum_tpu.serve` serves;
             `--selfcheck` proves the zero-recompile warm loop, the
             suspicion path and a socket round-trip (the CI smoke).

Load is measured the production way by `scripts/serve_loadgen.py`
(open-loop Poisson arrivals, p50/p99 + aggregations/s, machine-readable
`BENCH_serve.json` gated by `scripts/bench_compare.py`).
"""

from byzantinemomentum_tpu.serve.programs import (   # noqa: F401
    Cell, D_BUCKETS, D_PAD_EXACT, MASKED_GARS, N_BUCKETS, OversizeRequest,
    ProgramCache)
from byzantinemomentum_tpu.serve.batching import MicroBatcher  # noqa: F401
from byzantinemomentum_tpu.serve.service import (    # noqa: F401
    AggregateResult, AggregationService)

__all__ = ["AggregationService", "AggregateResult", "Cell", "MicroBatcher",
           "ProgramCache", "OversizeRequest", "MASKED_GARS", "N_BUCKETS",
           "D_BUCKETS", "D_PAD_EXACT"]
