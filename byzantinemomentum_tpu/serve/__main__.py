"""CLI for the aggregation service.

    python -m byzantinemomentum_tpu.serve --port 7600 \
        --result-directory results-serve
    python -m byzantinemomentum_tpu.serve --selfcheck

Serving mode binds the line-JSON front end and blocks; with a result
directory it writes the same heartbeat/telemetry a training run does, so
`utils/jobs.py` can supervise the server exactly like a run (watchdog on
the heartbeat, kill + retry on stall). The Jobs-dispatched flags
(`--seed`, `--device`) are accepted for that reason: a seed seeds the
selfcheck's synthetic traffic, the device string is advisory.

`--selfcheck` is the CI smoke (`scripts/run_test_tiers.py` serve tier):
it proves, in-process and in seconds, that (1) a warm serving loop
compiles ZERO new programs across 100+ mixed-cell requests
(`analysis/contracts.py::assert_recompile_budget`), (2) warm
HETEROGENEOUS traffic — one rule per kernel family (Gram-selection,
stage-1 scan, subset enumeration, coordinate-wise), each spanning >= 3
raw row counts AND >= 3 raw widths — also compiles ZERO programs (the
two-axis bucket ladder's whole point: novel raw (n, d) shapes land on
warm bucket programs), (3) a planted outlier client's suspicion rises
and its verdict rides the response, (4) the socket front end answers
ping/aggregate/stats over a real TCP connection, and (5) the trace
phase (`obs/trace`): warm requests yield traces whose span sum tiles
the client-measured end-to-end latency within tolerance, and the
tracing-on vs tracing-off throughput overhead is measured, printed
(`serve trace: {...}`, recorded by the tier harness) and bounded,
(6) the fleet phase (`serve/fleet`): a 2-shard in-process ring holds
route determinism, shard-exact suspicion ownership and the
kill/restart re-warm bound (`serve fleet: {...}`), and (7) the
fleet-scope causal plane (r19): routed requests produce JOINED traces
— shard spans spliced under the router envelope — whose spans tile the
router-measured wall (`serve fleet trace: {...}`), and a planted SLO
burn captures an incident bundle whose replayed causal story prints as
a parseable `incident: {...}` line.

A live serving process answers SIGUSR1 with a trace-ring snapshot
(`traces-<completed>.json` in the result directory) — the serve twin of
the driver's SIGUSR1 profiler window.
"""

import argparse
import json
import sys

import numpy as np

__all__ = ["main", "selfcheck", "HETERO_FAMILIES"]

# The selfcheck's mixed-cell traffic: three GARs, mixed row counts
# (bucketed and exact), mixed f/d, diagnostics on and off.
SELFCHECK_CELLS = (
    ("krum", 11, 2, 64, True),
    ("krum", 7, 1, 64, True),
    ("median", 5, 1, 32, True),
    ("trmean", 9, 2, 64, False),
)

# Heterogeneous-(n, d) traffic: one rule per kernel FAMILY, each family
# serving >= 3 distinct raw n and >= 3 distinct raw d values — the raw
# shapes deliberately share buckets (n -> 16/8, d -> 128) so the whole
# grid lands on a handful of warm programs.
HETERO_FAMILIES = (
    # (gar, f, raw row counts, raw widths)
    ("krum", 2, (9, 11, 13), (96, 120, 128)),    # Gram-selection family
    ("bulyan", 1, (9, 11, 13), (96, 120, 128)),  # stage-1 scan family
    ("brute", 1, (5, 6, 7), (96, 120, 128)),     # subset-enumeration family
    ("trmean", 2, (9, 11, 13), (96, 120, 128)),  # coordinate-wise family
)


def selfcheck(seed=1, requests=120, verbose=True):
    """Run the three proofs; returns the stats payload (raises on
    failure). Kept importable so tests can run it in-process."""
    from byzantinemomentum_tpu.analysis import contracts
    from byzantinemomentum_tpu.serve import AggregationService
    from byzantinemomentum_tpu.serve.frontend import AggregationServer

    rng = np.random.default_rng(seed)
    service = AggregationService(max_batch=8, max_delay_ms=5.0)
    try:
        compiled = service.warmup(SELFCHECK_CELLS)
        if verbose:
            print(f"serve selfcheck: warmed {compiled} programs over "
                  f"{len(SELFCHECK_CELLS)} cells", flush=True)

        # (1) the warm loop never recompiles across mixed-cell traffic,
        # and performs no implicit host<->device transfer anywhere — the
        # guard is PROCESS-scoped because the dispatch (device_put + call)
        # and the device wait (device_get) happen on the microbatcher's
        # flusher/resolver daemon threads, not this one
        group = max(1, requests // 10)

        def step():
            futures = []
            for k in range(group):
                gar, n, f, d, diag = SELFCHECK_CELLS[k % len(SELFCHECK_CELLS)]
                cohort = rng.standard_normal((n, d)).astype(np.float32)
                clients = ([f"client-{i}" for i in range(n)] if diag
                           else None)
                futures.append(service.submit(
                    cohort, gar=gar, f=f, client_ids=clients,
                    diagnostics=diag))
            for fut in futures:
                fut.result(timeout=30)

        with contracts.record_lock_edges() as lock_edges:
            with contracts.no_implicit_transfers(scope="process"):
                contracts.assert_recompile_budget(
                    step, steps=10, budget=0,
                    label=f"warm serving loop ({10 * group} mixed-cell "
                          f"requests)")
        if verbose:
            print(f"serve selfcheck: {10 * group} warm requests, "
                  f"0 recompiles, 0 implicit transfers", flush=True)
        # (1b) every lock-order edge the warm window actually exercised
        # must be in the static lock-order graph (BMT-L runtime
        # cross-check): an uncovered edge means either the sweep cannot
        # see an acquisition site or a code path inverted the blessed
        # hierarchy — both are bugs, not noise
        checked_edges = contracts.assert_lock_edges_subset(lock_edges)
        if verbose:
            print(f"serve selfcheck: {checked_edges} runtime lock-order "
                  f"edge(s), all within the static graph", flush=True)

        # (2) heterogeneous-(n, d) traffic: every kernel family, >= 3 raw
        # n and >= 3 raw d each, ZERO compiles once the bucket programs
        # are warm — the two-axis ladder acceptance
        hetero_cells = [(gar, n, f, d, False)
                        for gar, f, ns, ds in HETERO_FAMILIES
                        for n in ns for d in ds]
        compiled = service.warmup(hetero_cells)
        if verbose:
            print(f"serve selfcheck: warmed {compiled} hetero bucket "
                  f"programs for {len(hetero_cells)} raw (n, d) shapes",
                  flush=True)

        def hetero_step():
            futures = []
            for gar, f, ns, ds in HETERO_FAMILIES:
                for n in ns:
                    for d in ds:
                        cohort = rng.standard_normal((n, d)).astype(
                            np.float32)
                        futures.append(service.submit(
                            cohort, gar=gar, f=f, diagnostics=False))
            for fut in futures:
                fut.result(timeout=60)

        hetero_requests = 3 * len(hetero_cells)
        contracts.assert_recompile_budget(
            hetero_step, steps=3, budget=0,
            label=f"warm heterogeneous-(n, d) traffic "
                  f"({hetero_requests} requests over "
                  f"{len(HETERO_FAMILIES)} rule families)")
        if verbose:
            print(f"serve selfcheck: {hetero_requests} warm heterogeneous "
                  f"requests across {len(HETERO_FAMILIES)} families "
                  f"(>=3 raw n x >=3 raw d each), 0 recompiles",
                  flush=True)

        # (3) a planted outlier client gets flagged, verdict on response
        n, d, f = 11, 64, 2
        verdict = None
        for _ in range(30):
            cohort = rng.standard_normal((n, d)).astype(np.float32)
            cohort[0] += 40.0  # the outlier every honest row disagrees with
            clients = ["evil"] + [f"honest-{i}" for i in range(n - 1)]
            result = service.aggregate(cohort, gar="krum", f=f,
                                       client_ids=clients, timeout=30)
            verdict = result.verdicts["evil"]
        honest = result.verdicts["honest-0"]
        if not (verdict["suspicion"] > honest["suspicion"]
                and verdict["suspect"]):
            raise AssertionError(
                f"planted outlier not flagged: evil={verdict} "
                f"honest={honest}")
        if verbose:
            print(f"serve selfcheck: outlier flagged "
                  f"(suspicion {verdict['suspicion']:.2f} vs honest "
                  f"{honest['suspicion']:.2f})", flush=True)

        # (4) the socket front end round-trips
        import socket
        with AggregationServer(("127.0.0.1", 0), service) as server:
            server.serve_background()
            with socket.create_connection(("127.0.0.1", server.port),
                                          timeout=10) as conn:
                fd = conn.makefile("rwb")
                cohort = rng.standard_normal((7, 32)).astype(np.float32)
                for request in (
                        {"op": "ping"},
                        {"op": "aggregate", "gar": "median", "f": 1,
                         "vectors": cohort.tolist(),
                         "clients": [f"s{i}" for i in range(7)]},
                        {"op": "stats"}):
                    fd.write(json.dumps(request).encode() + b"\n")
                    fd.flush()
                    response = json.loads(fd.readline())
                    if not response.get("ok"):
                        raise AssertionError(
                            f"socket round-trip failed: {response}")
            server.shutdown()
        if verbose:
            print("serve selfcheck: socket front end ok", flush=True)

        # (5) trace phase (obs/trace): warm traced requests must tile
        # the end-to-end latency the CLIENT measures, and tracing must
        # not cost meaningful throughput (the stamps are a handful of
        # monotonic-clock reads per request)
        import time

        gar, n, f, d, _ = SELFCHECK_CELLS[0]  # warm since phase (1)
        walls, sums = [], []
        for _ in range(24):
            cohort = rng.standard_normal((n, d)).astype(np.float32)
            t0 = time.monotonic()
            result = service.aggregate(cohort, gar=gar, f=f,
                                       diagnostics=True, timeout=30)
            walls.append((time.monotonic() - t0) * 1000.0)
            sums.append(sum(result.trace.spans_ms().values()))
        tile_error = abs(sum(sums) - sum(walls)) / max(sum(walls), 1e-9)
        if tile_error > 0.20:
            raise AssertionError(
                f"trace spans do not tile the measured latency: span sum "
                f"mean {sum(sums) / len(sums):.3f} ms vs client wall mean "
                f"{sum(walls) / len(walls):.3f} ms "
                f"({tile_error * 100:.1f}% off)")

        def _rate(count=96):
            best = None
            for _ in range(3):
                t0 = time.monotonic()
                futures = [service.submit(
                    rng.standard_normal((n, d)).astype(np.float32),
                    gar=gar, f=f, diagnostics=True)
                    for _ in range(count)]
                for fut in futures:
                    fut.result(timeout=60)
                rate = count / (time.monotonic() - t0)
                best = rate if best is None else max(best, rate)
            return best

        rate_on = _rate()
        service.tracing = False
        try:
            rate_off = _rate()
        finally:
            service.tracing = True
        overhead = max(0.0, 1.0 - rate_on / rate_off)
        trace_line = {
            "requests": len(walls),
            "tile_error_frac": round(tile_error, 4),
            "agg_per_sec_tracing_on": round(rate_on, 1),
            "agg_per_sec_tracing_off": round(rate_off, 1),
            "overhead_frac": round(overhead, 4),
        }
        print(f"serve trace: {json.dumps(trace_line)}", flush=True)
        if overhead > 0.25:
            # Generous CI bound (1-core hosts jitter); the committed
            # ATTRIB_serve artifact holds the real <= 3% measurement
            raise AssertionError(
                f"tracing overhead {overhead * 100:.1f}% exceeds the "
                f"25% selfcheck bound")
        if verbose:
            print(f"serve selfcheck: trace spans tile latency "
                  f"({tile_error * 100:.2f}% off), tracing overhead "
                  f"{overhead * 100:.2f}%", flush=True)

        # (6) fleet phase (serve/fleet): a 2-shard in-process ring —
        # route determinism, shard-EXACT suspicion ownership, kill →
        # readmit → re-warm bound, and zero recompiles on the routed
        # warm path. Real router + real shard sockets; only process
        # isolation is simulated (each shard still owns its own store).
        from byzantinemomentum_tpu.serve.fleet.local import LocalFleet

        gar, n, f, d = "median", 5, 1, 32
        with LocalFleet(2, service={"max_batch": 4,
                                    "max_delay_ms": 2.0}) as fleet:
            for svc in fleet.services.values():
                svc.warmup([(gar, n, f, d, True), (gar, n, f, d, False)])
            bases = [f"fleet-{i}" for i in range(16)]
            cohorts = {b: [b] + [f"{b}.{j}" for j in range(1, n)]
                       for b in bases}
            owners = {b: fleet.owner(b) for b in bases}
            if owners != {b: fleet.owner(b) for b in bases}:
                raise AssertionError("ring ownership is not deterministic")
            if set(owners.values()) != set(fleet.shards):
                raise AssertionError(
                    f"16 cohorts landed on {sorted(set(owners.values()))} "
                    f"only — the ring is not spreading")

            def ask(clients, diagnostics=True):
                cohort = rng.standard_normal((n, d)).astype(np.float32)
                request = {"op": "aggregate", "gar": gar, "f": f,
                           "vectors": cohort.tolist(),
                           "diagnostics": diagnostics}
                if clients is not None:
                    request["clients"] = clients
                reply = fleet.ask(request)
                if not reply.get("ok"):
                    raise AssertionError(f"fleet route failed: {reply}")
                return reply

            def fleet_step():
                for base in bases:
                    ask(cohorts[base])

            contracts.assert_recompile_budget(
                fleet_step, steps=3, budget=0,
                label="warm routed fleet traffic (2 shards)")

            # Ownership is EXACT: each shard's store holds the union of
            # the cohorts whose routing key it owns, and nothing else
            expected = {s: set() for s in fleet.shards}
            for base in bases:
                expected[owners[base]].update(cohorts[base])
            for shard in fleet.shards:
                got = set(fleet.suspicion_clients(shard))
                if got != expected[shard]:
                    raise AssertionError(
                        f"{shard} store drifted from its arc: "
                        f"unexpected={sorted(got - expected[shard])} "
                        f"missing={sorted(expected[shard] - got)}")

            # Routed vs direct throughput, one request in flight each
            # (what the router's two extra socket hops cost); the tier
            # harness records fleet_speedup from the printed line
            count = 48
            t0 = time.monotonic()
            for k in range(count):
                ask(None, diagnostics=False)
            fleet_rate = count / (time.monotonic() - t0)
            svc = fleet.services[fleet.shards[0]]
            t0 = time.monotonic()
            for k in range(count):
                svc.aggregate(rng.standard_normal((n, d)).astype(
                    np.float32), gar=gar, f=f, diagnostics=False,
                    timeout=30)
            direct_rate = count / (time.monotonic() - t0)

            # Kill-safe failover: the victim restarts on ITS port with
            # an EMPTY store — the returning cohort re-warms exactly as
            # fast as a brand-new id (no resurrection channel), and the
            # survivor's counts advance uncorrupted
            victim = owners[bases[0]]
            survivor_base = next(b for b in bases if owners[b] != victim)
            before = ask(cohorts[survivor_base])["verdicts"][
                survivor_base]["observations"]
            fleet.kill(victim)
            fleet.restart(victim)
            returning = ask(cohorts[bases[0]])["verdicts"][
                bases[0]]["observations"]
            k = 0
            while fleet.owner(f"newcomer-{k}") != victim:
                k += 1
            newcomer = f"newcomer-{k}"
            fresh = ask([newcomer] + [f"{newcomer}.{j}"
                                      for j in range(1, n)])["verdicts"][
                newcomer]["observations"]
            if returning != fresh:
                raise AssertionError(
                    f"returning client re-warmed faster than a fresh id "
                    f"after the {victim} restart: returning came back at "
                    f"{returning} observations, fresh starts at {fresh}")
            after = ask(cohorts[survivor_base])["verdicts"][
                survivor_base]["observations"]
            if after != before + 1:
                raise AssertionError(
                    f"survivor verdicts corrupted by the {victim} "
                    f"failover: {survivor_base} observations {before} -> "
                    f"{after} (expected {before + 1})")
            fleet_line = {
                "shards": len(fleet.shards), "requests": 3 * len(bases),
                "fleet_agg_per_sec": round(fleet_rate, 1),
                "direct_agg_per_sec": round(direct_rate, 1),
                "fleet_speedup": round(fleet_rate / direct_rate, 3),
                "killed": victim, "rewarm_observations": returning,
                "fresh_observations": fresh,
            }
        print(f"serve fleet: {json.dumps(fleet_line)}", flush=True)
        if verbose:
            print(f"serve fleet: 2-shard ring ok — ownership exact, "
                  f"{victim} kill/restart re-warm bound holds, routed "
                  f"rate {fleet_rate:.0f}/s vs direct "
                  f"{direct_rate:.0f}/s", flush=True)

        # (7) fleet-scope causal plane (r19): the cross-process span
        # join — shard spans spliced under the router envelope — must
        # tile the router-measured wall, and a planted SLO burn must
        # freeze an incident bundle obs_report can replay. Both halves
        # print machine-parseable lines the tier harness records.
        import pathlib
        import tempfile

        from byzantinemomentum_tpu.obs.metrics import SLO, \
            BurnRateEvaluator
        from byzantinemomentum_tpu.obs.trace import (IncidentRecorder,
                                                     render_incidents)
        from byzantinemomentum_tpu.serve.fleet.local import (ask_socket,
                                                             fleet_socket)

        gar, n, f, d = "median", 5, 1, 32
        with LocalFleet(2, router_server=True,
                        service={"max_batch": 4,
                                 "max_delay_ms": 2.0}) as fleet:
            for svc in fleet.services.values():
                svc.warmup([(gar, n, f, d, True)])
            sock, files = fleet_socket("127.0.0.1", fleet.port,
                                       timeout=30)
            try:
                for k in range(24):
                    base = f"jt-{k}"
                    reply = ask_socket(files, {
                        "op": "aggregate", "gar": gar, "f": f,
                        "vectors": rng.standard_normal((n, d)).astype(
                            np.float32).tolist(),
                        "clients": [base] + [f"{base}.{j}"
                                             for j in range(1, n)]})
                    if not reply.get("ok"):
                        raise AssertionError(
                            f"fleet-trace request failed: {reply}")
            finally:
                sock.close()
            records = fleet.router.joined_records()
            if len(records) < 20:
                raise AssertionError(
                    f"span join landed only {len(records)}/24 records")
            tile_errors = [abs(sum(r["spans_ms"].values())
                               - r["total_ms"]) / r["total_ms"]
                           for r in records if r["total_ms"] > 0]
            join_tile = sum(tile_errors) / max(len(tile_errors), 1)
            critical = {}
            for record in records:
                hop = record.get("dominant")
                if hop:
                    critical[hop] = critical.get(hop, 0) + 1
            join_line = {
                "joined": len(records),
                "tile_error_frac": round(join_tile, 4),
                "critical_path": dict(sorted(critical.items(),
                                             key=lambda kv: -kv[1])),
            }
            print(f"serve fleet trace: {json.dumps(join_line)}",
                  flush=True)
            if join_tile > 0.15:
                raise AssertionError(
                    f"joined spans do not tile the router wall: mean "
                    f"error {join_tile * 100:.1f}% > 15%")

        # The planted burn: a synthetic snapshot stream trips the
        # availability SLO (200 rejects in one window), the burn edge
        # captures a bundle, and the replay names the causal story
        def snap(t, total, bad):
            return {"t": t, "merged": {"metrics": {
                "bad_requests": {"type": "counter", "value": bad},
                "all_requests": {"type": "counter", "value": total}}}}

        slo = SLO("selfcheck-availability", objective=0.999,
                  total="all_requests", bad=("bad_requests",),
                  fast_s=30.0, slow_s=300.0, burn_threshold=10.0)
        evaluator = BurnRateEvaluator([slo])
        burns = []
        for t, total, bad in ((0.0, 0, 0), (10.0, 400, 0),
                              (20.0, 800, 200)):
            burns += [e for e in evaluator.observe(snap(t, total, bad))
                      if e["event"] == "slo_burn"]
        if not burns:
            raise AssertionError("planted SLO burn never fired")
        with tempfile.TemporaryDirectory() as tmp:
            recorder = IncidentRecorder(
                pathlib.Path(tmp), source="selfcheck",
                providers={
                    "trace": lambda: {"critical_path": critical},
                    "membership": lambda: {"version": 1, "dead": []}})
            event = dict(burns[0])
            bundle_path = recorder.capture(event.pop("event"), event)
            if bundle_path is None:
                raise AssertionError("incident capture hit its own "
                                     "cooldown on the first bundle")
            bundle = json.loads(pathlib.Path(bundle_path).read_text())
            story = render_incidents(tmp)
            if not any("story:" in line for line in story):
                raise AssertionError(
                    f"incident replay produced no story: {story}")
            incident_line = {
                "reason": bundle["reason"],
                "slo": bundle["data"].get("slo"),
                "burn_fast": bundle["data"].get("burn_fast"),
                "evidence": sorted(bundle["context"]),
                "story": next(line.split("story:", 1)[1].strip()
                              for line in story if "story:" in line),
            }
            print(f"incident: {json.dumps(incident_line)}", flush=True)
        if verbose:
            print(f"serve selfcheck: span join tiles the router wall "
                  f"({join_tile * 100:.2f}% off over {len(records)} "
                  f"joined records), planted burn -> replayable "
                  f"incident bundle", flush=True)

        stats = service.stats()
        stats["lock_edges"] = checked_edges
    finally:
        service.close()
    return stats


def _watch_parent():
    """Die with the launcher (`cluster/host.py` discipline): the fleet
    launcher holds the write end of our stdin pipe and NEVER writes, so
    EOF means the launcher is gone — whatever killed it. Raw `os.read`
    on fd 0, not `sys.stdin.buffer`: a buffered reader's internal lock
    can abort interpreter shutdown from a daemon thread."""
    import os
    import threading

    def watch():
        try:
            while os.read(0, 4096):
                pass
        except OSError:
            pass
        os._exit(3)

    threading.Thread(target=watch, name="parent-watch",  # bmt: noqa[BMT-L06] lock-free parent-death watch: blocks on pipe EOF then os._exit — it shares no state to interleave
                     daemon=True).start()


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m byzantinemomentum_tpu.serve",
        description="Aggregation-as-a-service: batched Byzantine-resilient "
                    "aggregation over a line-JSON socket")
    parser.add_argument("--selfcheck", action="store_true",
                        help="run the CI smoke (warm-loop recompile budget, "
                             "suspicion path, socket round-trip) and exit")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7600,
                        help="TCP port (0 = ephemeral)")
    parser.add_argument("--max-batch", type=int, default=8)
    parser.add_argument("--max-delay-ms", type=float, default=2.0)
    parser.add_argument("--no-diagnostics", action="store_true",
                        help="default new requests to diagnostics=False")
    parser.add_argument("--no-tracing", action="store_true",
                        help="disable per-request span tracing "
                             "(obs/trace; on by default)")
    parser.add_argument("--trace-buffer", type=int, default=512,
                        help="completed traces the in-memory ring keeps "
                             "(the stats/SIGUSR1 summary window)")
    parser.add_argument("--heartbeat-interval", type=float, default=2.0)
    parser.add_argument("--no-metrics", action="store_true",
                        help="disable the metrics registry (obs/metrics; "
                             "on by default — the paired-overhead "
                             "baseline arm)")
    parser.add_argument("--result-directory", default=None,
                        help="run directory for heartbeat.json + "
                             "telemetry.jsonl (enables Jobs supervision)")
    parser.add_argument("--seed", type=int, default=1,
                        help="selfcheck traffic seed (Jobs-compatible)")
    parser.add_argument("--device", default=None,
                        help="advisory device string (Jobs-compatible)")
    parser.add_argument("--parent-pipe", action="store_true",
                        help="exit when stdin hits EOF — the fleet "
                             "launcher holds the write end of our stdin "
                             "pipe, so a dead launcher (any signal) takes "
                             "its shards with it instead of leaking "
                             "orphan servers on bound ports")
    parser.add_argument("--warmup", action="append", default=None,
                        metavar="GAR:N:D:F",
                        help="pre-compile this request shape (diagnostics "
                             "cell) before binding the port; repeatable — "
                             "the fleet launcher warms every shard so the "
                             "readiness ping means 'warm', not 'bound'")
    args = parser.parse_args(argv)

    if args.parent_pipe:
        _watch_parent()

    if args.selfcheck:
        try:
            stats = selfcheck(seed=args.seed)
        except Exception as err:  # bmt: noqa[BMT-E05] the smoke's contract is an exit code + one readable line, whatever layer failed
            print(f"serve selfcheck: FAILED — {type(err).__name__}: {err}")
            return 1
        print(f"serve selfcheck: ok {json.dumps(stats)}")
        return 0

    from byzantinemomentum_tpu.serve import AggregationService
    from byzantinemomentum_tpu.serve.frontend import AggregationServer

    # Tail-latency knob: the default 5 ms GIL switch interval lets one
    # packing slice stall the submitter/handler threads for more than the
    # whole max-delay budget; 1 ms keeps scheduler jitter out of p99
    sys.setswitchinterval(0.001)
    # Metrics source = the result directory's basename: fleet shards run
    # with --result-directory shards/shard-<i>, so the merged fleet
    # payload's `sources` list names each contributing shard
    import pathlib

    from byzantinemomentum_tpu.obs.metrics import MetricsRegistry
    if args.no_metrics:
        metrics = False
    else:
        source = (pathlib.Path(args.result_directory).name
                  if args.result_directory else "serve")
        metrics = MetricsRegistry(source=source)
    service = AggregationService(
        max_batch=args.max_batch, max_delay_ms=args.max_delay_ms,
        diagnostics=not args.no_diagnostics,
        directory=args.result_directory,
        heartbeat_interval=args.heartbeat_interval,
        tracing=not args.no_tracing, trace_buffer=args.trace_buffer,
        metrics=metrics)
    if args.warmup:
        cells = []
        for spec in args.warmup:
            parts = spec.split(":")
            if len(parts) != 4:
                parser.error(f"--warmup expects GAR:N:D:F, got {spec!r}")
            gar, n, d, f = parts
            cells.append((gar, int(n), int(f), int(d), True))
        compiled = service.warmup(cells)
        print(f"serve: warmed {compiled} programs over {len(cells)} "
              f"request shapes", flush=True)
    # SIGUSR1 -> trace-ring snapshot (the serve twin of the driver's
    # SIGUSR1 profiler window): a live server dumps its completed-trace
    # buffer + per-phase summary without restarting or pausing
    import signal

    def _on_usr1(signum, frame):
        try:
            path = service.write_trace_snapshot()
            print(f"serve: SIGUSR1 trace snapshot -> {path}", flush=True)
        except OSError as err:
            print(f"serve: SIGUSR1 snapshot failed: {err}", flush=True)

    try:
        signal.signal(signal.SIGUSR1, _on_usr1)
    except (ValueError, AttributeError, OSError):
        pass  # non-main thread / platform without SIGUSR1: snapshot via stats
    try:
        with AggregationServer((args.host, args.port), service) as server:
            print(f"serving aggregation on {args.host}:{server.port} "
                  f"(max_batch={args.max_batch}, "
                  f"max_delay={args.max_delay_ms}ms)", flush=True)
            try:
                server.serve_forever()
            except KeyboardInterrupt:
                pass
    finally:
        service.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
