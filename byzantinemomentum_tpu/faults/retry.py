"""Retry with exponential backoff — the host-side degradation primitive.

The in-graph half of the fault subsystem masks bad submissions; this is
the other half, for the host I/O paths (dataset downloads, and any future
storage/RPC boundary): bounded retries with exponential backoff, after
which the caller's own degrade path (disk probe, synthetic fallback)
takes over. Transient-only by construction — the default `retry_on` is
`OSError` (network stalls, resets, timeouts), so content errors like a
checksum mismatch propagate immediately instead of being retried into
the same failure.
"""

import time

# Host-only telemetry hooks (obs.recorder imports no jax): every retry is
# a resilience event worth a spot on the run's timeline
from byzantinemomentum_tpu.obs import recorder as _obs

__all__ = ["with_backoff"]


def with_backoff(fn, *, attempts=3, base_delay=1.0, retry_on=(OSError,),
                 on_retry=None, sleep=time.sleep):
    """Call `fn()` up to `attempts` times, sleeping `base_delay * 2**i`
    between tries; re-raises the last error once the budget is spent.

    `on_retry(attempt, delay, error)` observes each retry (logging);
    `sleep` is injectable for tests. Each retry also bumps the active
    telemetry recorder's `retry_attempts` counter and records a `retry`
    event (no-ops outside an instrumented run).
    """
    if attempts < 1:
        raise ValueError(f"Non-positive attempt count {attempts}")
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as err:
            if attempt + 1 >= attempts:
                raise
            delay = base_delay * (2.0 ** attempt)
            _obs.counter("retry_attempts")
            _obs.emit("retry", attempt=attempt + 1, delay=delay,
                      error=str(err))
            if on_retry is not None:
                on_retry(attempt, delay, err)
            if delay > 0:
                sleep(delay)
