"""Declarative fault plans — *system* faults as first-class, serializable
data.

The `attacks/` package models adversarial workers (Byzantine rows
synthesized in-graph); this module models the faults real deployments
actually see: stragglers, dropped workers, corrupted/NaN gradient shards,
duplicated submissions, devices lost mid-run. A `FaultPlan` declares them
per step and per worker, round-trips through JSON, and compiles
(`faults/schedule.py`) into dense per-step mask arrays applied inside the
jitted training step (`faults/inject.py`).

Determinism contract: a plan is data, not a process — the same plan always
injects the same faults at the same steps into the same workers.
`FaultPlan.generate` derives a concrete event list from per-kind rates and
a seed (numpy `RandomState`), so randomized chaos runs are exactly
reproducible from `(rates, seed)`.

Worker indexing: faults address workers by their row in the stacked
submission matrix — honest workers are rows `0..h-1`, Byzantine rows (when
an `--attack` runs alongside the plan) follow. Submission-mutating faults
(straggler / corruption / duplication) only make sense on honest rows;
`drop_worker` and `device_loss` may target any row.
"""

import dataclasses
import json
import pathlib

__all__ = ["FaultEvent", "FaultPolicy", "FaultPlan", "KINDS", "MODES",
           "SYSTEM_KINDS", "straggler", "drop_worker", "corrupt_gradient",
           "duplicate_submission", "device_loss", "straggle"]

# Fault taxonomy. `device_loss` is the permanent form of `drop_worker`:
# from its step on, the worker never submits again (no duration).
KINDS = ("straggler", "drop_worker", "corrupt_gradient",
         "duplicate_submission", "device_loss")

# corrupt_gradient modes: all-NaN shard, all-zero shard, or a scaled
# (exploding/vanishing) shard.
MODES = ("nan", "zero", "scale")

# Kinds a plan may carry at SYSTEM scope (`cluster/chaos.py`): there,
# `worker` indexes a HOST process of a multi-controller fleet,
# `device_loss` means SIGKILL — real lost hardware, not a masked row —
# and `straggle` means SIGSTOP now / SIGCONT after `window_s` wall-clock
# seconds: a host that is alive-but-not-stepping, the failure mode the
# launcher's straggler policy (`cluster/straggler.py`) must distinguish
# from a corpse. The in-step kinds (straggler/corruption/duplication)
# have no system analogue; `validate_system` refuses them — and
# `validate` refuses the system kinds in-step — so a plan cannot
# silently mean two different things.
SYSTEM_KINDS = ("device_loss", "straggle")


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One fault: `kind` hits `worker` for steps `[step, step + duration)`.

    Field use per kind:
      straggler            — `duration` is the delay window: the worker keeps
                             resubmitting its last pre-window gradient, so
                             staleness grows with the window length.
      drop_worker          — absent for `duration` steps; the degradation
                             policy shrinks the effective quorum.
      corrupt_gradient     — submission mangled per `mode` (`scale` uses
                             `scale`).
      duplicate_submission — submits a byte-copy of worker `source`'s fresh
                             gradient instead of its own.
      device_loss          — permanently gone from `step` on (`duration`
                             ignored).
      straggle             — SYSTEM scope only: the host is SIGSTOP'd when
                             the fleet reaches `step` and SIGCONT'd
                             `window_s` wall-clock seconds later (steps are
                             meaningless to a stopped process, so the
                             window is time, not `duration`).
    """

    kind: str
    worker: int
    step: int
    duration: int = 1
    mode: str = "nan"
    scale: float = 10.0
    source: int = 0
    window_s: float = 0.0

    def __post_init__(self):
        if self.kind not in KINDS and self.kind not in SYSTEM_KINDS:
            raise ValueError(
                f"Unknown fault kind {self.kind!r}; expected one of "
                f"{KINDS + tuple(k for k in SYSTEM_KINDS if k not in KINDS)}")
        if self.kind == "straggle" and self.window_s <= 0:
            raise ValueError(
                f"straggle needs a positive wall-clock window_s, got "
                f"{self.window_s}")
        if self.worker < 0:
            raise ValueError(f"Negative worker index {self.worker}")
        if self.step < 0:
            raise ValueError(f"Negative fault step {self.step}")
        if self.duration < 1:
            raise ValueError(f"Non-positive fault duration {self.duration}")
        if self.kind == "corrupt_gradient" and self.mode not in MODES:
            raise ValueError(
                f"Unknown corruption mode {self.mode!r}; expected one of "
                f"{MODES}")
        if self.kind == "duplicate_submission" and self.source < 0:
            raise ValueError(f"Negative source worker {self.source}")

    @property
    def end(self):
        """First step no longer affected (device_loss never ends)."""
        return self.step + (1 if self.kind == "device_loss"
                            else self.duration)


# Constructor helpers — the declarative surface mirroring the fault
# taxonomy names (`plan = FaultPlan(events=(drop_worker(3, step=10), ...))`).

def straggler(worker, step, delay_steps=1):
    """Worker resubmits its pre-`step` gradient for `delay_steps` steps."""
    return FaultEvent("straggler", worker, step, duration=delay_steps)


def drop_worker(worker, step, duration=1):
    """Worker is absent (no submission) for `duration` steps."""
    return FaultEvent("drop_worker", worker, step, duration=duration)


def corrupt_gradient(worker, step, mode="nan", scale=10.0, duration=1):
    """Worker's submission is corrupted (`nan`, `zero`, or `scale`)."""
    return FaultEvent("corrupt_gradient", worker, step, duration=duration,
                      mode=mode, scale=scale)


def duplicate_submission(worker, step, source, duration=1):
    """Worker submits a copy of `source`'s fresh gradient."""
    return FaultEvent("duplicate_submission", worker, step,
                      duration=duration, source=source)


def device_loss(worker, step):
    """Worker is permanently lost from `step` on."""
    return FaultEvent("device_loss", worker, step)


def straggle(host, step, window_s):
    """SYSTEM scope: host SIGSTOP'd at `step`, SIGCONT'd `window_s`
    seconds later."""
    return FaultEvent("straggle", host, step, window_s=float(window_s))


@dataclasses.dataclass(frozen=True)
class FaultPolicy:
    """How the engine degrades when faults (or fault-like inputs) appear.

    `nan_quarantine` and `dynamic_quorum` are trace-time switches (they
    become `EngineConfig.fault_quarantine` / `.fault_dynamic_quorum`); the
    `fetch_*` knobs parameterize the data-download retry/backoff path
    (`data/sources.py:_fetch`).
    """

    nan_quarantine: bool = True   # mask non-finite submission rows out of
    #                               the aggregation (and out of the quorum)
    dynamic_quorum: bool = True   # recompute the effective (n, f) the GAR
    #                               runs with when workers are absent
    fetch_attempts: int = 3       # data-download attempts before degrading
    fetch_backoff: float = 1.0    # base backoff seconds (doubles per retry)
    fetch_timeout: float = 60.0   # per-connection stall timeout seconds

    def __post_init__(self):
        if self.fetch_attempts < 1:
            raise ValueError(
                f"Non-positive fetch attempts {self.fetch_attempts}")
        if self.fetch_backoff < 0 or self.fetch_timeout <= 0:
            raise ValueError(
                f"Invalid fetch backoff/timeout "
                f"({self.fetch_backoff}, {self.fetch_timeout})")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A complete fault scenario: events + degradation policy + seed."""

    events: tuple = ()
    policy: FaultPolicy = dataclasses.field(default_factory=FaultPolicy)
    seed: int = 0

    def __post_init__(self):
        # Normalize: accept lists/dicts from JSON land, store tuples of
        # FaultEvent (hashable, so a plan can key caches)
        events = tuple(
            e if isinstance(e, FaultEvent) else FaultEvent(**e)
            for e in self.events)
        object.__setattr__(self, "events", events)
        if not isinstance(self.policy, FaultPolicy):
            object.__setattr__(self, "policy", FaultPolicy(**self.policy))

    @property
    def horizon(self):
        """First step with no scheduled (non-permanent) fault activity."""
        return max((e.end for e in self.events), default=0)

    def validate(self, nb_workers, nb_honests):
        """None if the plan fits an (n = nb_workers, h = nb_honests) run,
        else a human-readable refusal (CLI contract, like `GAR.check`)."""
        for e in self.events:
            if e.kind not in KINDS:
                return (f"fault {e.kind!r} only exists at SYSTEM scope "
                        f"(a jitted step cannot SIGSTOP a host); in-step "
                        f"plans may only use {'/'.join(KINDS)}")
            if e.worker >= nb_workers:
                return (f"fault {e.kind!r} targets worker {e.worker} but the "
                        f"run has only {nb_workers} workers")
            mutating = e.kind in ("straggler", "corrupt_gradient",
                                  "duplicate_submission")
            if mutating and e.worker >= nb_honests:
                return (f"fault {e.kind!r} mutates worker {e.worker}'s "
                        f"submission, but rows >= {nb_honests} are "
                        f"attack-synthesized (only drop_worker/device_loss "
                        f"may target them)")
            if e.kind == "duplicate_submission":
                if e.source >= nb_honests:
                    return (f"duplicate_submission copies worker {e.source}, "
                            f"but only rows < {nb_honests} hold honest "
                            f"submissions")
                if e.source == e.worker:
                    return (f"duplicate_submission on worker {e.worker} "
                            f"copies itself (a no-op; refusing a plan that "
                            f"cannot mean what it says)")
        return None

    def validate_system(self, nb_hosts):
        """None if the plan can drive HOST-scope chaos on an
        `nb_hosts`-process fleet (`cluster/chaos.py::SystemFaultDriver`),
        else a human-readable refusal. At system scope `worker` indexes a
        host and only `SYSTEM_KINDS` are meaningful (a SIGKILL has no
        'corrupted submission' analogue)."""
        for e in self.events:
            if e.kind not in SYSTEM_KINDS:
                return (f"fault {e.kind!r} has no system-scope meaning; a "
                        f"host-level plan may only use "
                        f"{'/'.join(SYSTEM_KINDS)}")
            if e.worker >= nb_hosts:
                return (f"system fault targets host {e.worker} but the "
                        f"fleet has only {nb_hosts} hosts")
            if e.worker == 0 and nb_hosts > 1:
                # Host 0 runs the jax.distributed coordinator service:
                # killing it wedges the SURVIVORS' collectives inside the
                # runtime rather than failing them — the launcher's
                # teardown still recovers, but the plan should say what it
                # means (kill a non-coordinator host, or a 1-host fleet)
                return ("system fault targets host 0 (the distributed "
                        "coordinator); target a non-coordinator host so "
                        "the survivors' failure mode is peer loss, not "
                        "coordinator loss")
        return None

    # ------------------------------------------------------------------ #
    # JSON round-trip

    def to_dict(self):
        return {
            "events": [dataclasses.asdict(e) for e in self.events],
            "policy": dataclasses.asdict(self.policy),
            "seed": self.seed,
        }

    @classmethod
    def from_dict(cls, data):
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"Unknown fault-plan fields {sorted(unknown)}; expected a "
                f"subset of {sorted(known)}")
        return cls(**data)

    def to_json(self, **kwargs):
        kwargs.setdefault("indent", "\t")
        return json.dumps(self.to_dict(), **kwargs)

    @classmethod
    def from_json(cls, text):
        return cls.from_dict(json.loads(text))

    def save(self, path):
        path = pathlib.Path(path)
        path.write_text(self.to_json() + "\n")
        return path

    @classmethod
    def load(cls, path):
        return cls.from_json(pathlib.Path(path).read_text())

    # ------------------------------------------------------------------ #
    # Seeded generation (reproducible chaos)

    @classmethod
    def generate(cls, *, nb_workers, nb_steps, rates, seed=0,
                 policy=None, nb_honests=None, max_scale=100.0):
        """Expand per-kind fault `rates` into a concrete, deterministic plan.

        `rates`: dict kind -> per-worker-per-step probability. Every draw
        comes from `numpy.random.RandomState(seed)` in a fixed iteration
        order (kind-major, then step, then worker), so `(rates, seed)`
        fully determines the plan — rerunning yields byte-identical JSON.
        Submission-mutating kinds only target rows < `nb_honests`
        (default: all of `nb_workers`).
        """
        import numpy as np

        h = nb_workers if nb_honests is None else nb_honests
        rng = np.random.RandomState(seed)
        events = []
        for kind in KINDS:
            rate = rates.get(kind, 0.0)
            if not rate:
                continue
            rows = nb_workers if kind in ("drop_worker", "device_loss") else h
            hits = rng.random_sample((nb_steps, rows)) < rate
            for step, worker in zip(*np.nonzero(hits)):
                step, worker = int(step), int(worker)
                if kind == "straggler":
                    events.append(straggler(
                        worker, step, delay_steps=int(rng.randint(1, 4))))
                elif kind == "drop_worker":
                    events.append(drop_worker(worker, step))
                elif kind == "corrupt_gradient":
                    mode = MODES[int(rng.randint(len(MODES)))]
                    events.append(corrupt_gradient(
                        worker, step, mode=mode,
                        scale=float(rng.uniform(0.0, max_scale))))
                elif kind == "duplicate_submission":
                    if h < 2:
                        continue
                    source = int(rng.randint(h - 1))
                    events.append(duplicate_submission(
                        worker, step, source=source + (source >= worker)))
                else:  # device_loss: first hit wins, later ones are moot
                    events.append(device_loss(worker, step))
        return cls(events=tuple(events), policy=policy or FaultPolicy(),
                   seed=seed)
