"""Compilation of a `FaultPlan` into dense per-step mask arrays.

The plan is declarative (a list of events); the jitted training step needs
O(1) lookups by step index. `build_schedule` lowers the events into small
host-side numpy arrays of shape `(horizon + 1, rows)` — row `horizon` is
all-neutral, and the in-graph lookup clamps the step index to it, so every
step beyond the plan's horizon reads "no fault" without a branch. Permanent
`device_loss` events live in a separate `(n,)` first-lost-step vector
(compared against the step counter directly) so they persist past the
horizon.

The arrays enter the XLA program as constants at trace time: a few KB for
realistic plans, nothing on the hot path but `jnp.take` of one row.
"""

import typing

import numpy as np

__all__ = ["FaultSchedule", "StepFaults", "build_schedule"]

# "Never lost" sentinel for the device-loss vector: any real step compares
# strictly below it. int32 to match the step counter's dtype.
NEVER = np.iinfo(np.int32).max


class StepFaults(typing.NamedTuple):
    """One step's traced fault row set (see `FaultSchedule.step_faults`)."""

    stale: typing.Any      # bool[h] — submit the buffered stale gradient
    nan: typing.Any        # bool[h] — submission replaced by NaN
    zero: typing.Any       # bool[h] — submission replaced by zeros
    scale: typing.Any      # f32[h]  — submission multiplier (1 = clean)
    dup: typing.Any        # i32[h]  — source row to copy, -1 = own
    drop: typing.Any       # bool[n] — absent this step (incl. device loss)


class FaultSchedule:
    """Host-side compiled form of a `FaultPlan` (see module docstring)."""

    def __init__(self, plan, nb_workers, nb_honests):
        message = plan.validate(nb_workers, nb_honests)
        if message is not None:
            raise ValueError(f"Invalid fault plan: {message}")
        n, h = nb_workers, nb_honests
        T = plan.horizon
        self.plan = plan
        self.nb_workers = n
        self.nb_honests = h
        self.horizon = T
        self.stale = np.zeros((T + 1, h), bool)
        self.nan = np.zeros((T + 1, h), bool)
        self.zero = np.zeros((T + 1, h), bool)
        self.scale = np.ones((T + 1, h), np.float32)
        self.dup = np.full((T + 1, h), -1, np.int32)
        self.drop = np.zeros((T + 1, n), bool)
        self.lost_from = np.full((n,), NEVER, np.int32)
        for e in plan.events:
            steps = slice(e.step, e.end)  # rows T.. stay neutral by clamp
            if e.kind == "straggler":
                self.stale[steps, e.worker] = True
            elif e.kind == "drop_worker":
                self.drop[steps, e.worker] = True
            elif e.kind == "corrupt_gradient":
                if e.mode == "nan":
                    self.nan[steps, e.worker] = True
                elif e.mode == "zero":
                    self.zero[steps, e.worker] = True
                else:
                    self.scale[steps, e.worker] *= e.scale
            elif e.kind == "duplicate_submission":
                self.dup[steps, e.worker] = e.source
            else:  # device_loss
                self.lost_from[e.worker] = min(
                    int(self.lost_from[e.worker]), e.step)

    @property
    def has_stale(self):
        """Whether the engine must carry the per-worker stale-gradient
        buffer in `TrainState` (allocated only when a straggler exists)."""
        return bool(self.stale.any())

    def step_faults(self, step):
        """The step's fault rows as traced arrays (`step`: traced i32).

        Steps past the horizon read the all-neutral row `horizon`;
        device loss is folded into `drop` by comparing `step` against the
        first-lost vector, so it persists beyond the horizon.
        """
        import jax.numpy as jnp

        t = jnp.minimum(step, self.horizon)
        row = lambda a: jnp.take(jnp.asarray(a), t, axis=0)  # noqa: E731
        drop = row(self.drop) | (step >= jnp.asarray(self.lost_from))
        return StepFaults(stale=row(self.stale), nan=row(self.nan),
                          zero=row(self.zero), scale=row(self.scale),
                          dup=row(self.dup), drop=drop)


def build_schedule(plan, *, nb_workers, nb_honests):
    """Compile `plan`, or return None for a plan with no events — the
    engine treats None as "no fault machinery at all", so an empty plan
    compiles to exactly the fault-free program (zero overhead)."""
    if plan is None or not plan.events:
        return None
    return FaultSchedule(plan, nb_workers, nb_honests)
