"""In-graph fault injection — the hook the training step calls between the
honest phase and the defense.

Everything here is shape-static `jnp.where` masking over the stacked
`(h, d)` honest-submission matrix: no host round-trips, no dynamic shapes,
no gathers beyond one row-indexed `take` for duplications. The per-step
fault rows come from the compiled schedule (`faults/schedule.py`) indexed
by the traced step counter, so the same compiled program serves every step
of the plan.

Application order per worker (matching how the faults compose physically):

  1. duplication  — the worker ships a copy of another worker's *fresh*
                    gradient (it happens at submission time, before any
                    transport corruption);
  2. staleness    — a straggler's submission is its buffered pre-window
                    gradient (overriding this step's fresh/duplicated row);
  3. corruption   — scale / zero / NaN mangle whatever was submitted;
  4. absence      — drop/device-loss rows are reported in the active mask
                    (the degradation policy excludes them from the quorum;
                    the row's content no longer matters).
"""

import jax.numpy as jnp

__all__ = ["inject"]


def inject(schedule, step, G_honest, fault_buffer):
    """Apply `schedule`'s faults for `step` to the honest submissions.

    Args:
      schedule: `FaultSchedule`.
      step: traced i32 step counter.
      G_honest: f32[h, d] — the honest rows about to feed the defense.
      fault_buffer: f32[h, d] per-worker last fresh submission (shape
        (0, d) when the plan has no stragglers — then it passes through
        untouched).

    Returns:
      (G_faulted, new_buffer, active: bool[n], injected: i32) — the mangled
      submission stack, the updated stale buffer, the full-n active mask
      (honest rows then attack rows; absent rows False) and the number of
      fault conditions live this step (the `Faults injected` metric).
    """
    sf = schedule.step_faults(step)
    h = G_honest.shape[0]
    G = G_honest

    # 1. duplication: take() needs an in-range index even for the -1
    # "own row" sentinel — clip, then select on the sentinel mask
    dup_on = sf.dup >= 0
    src = jnp.clip(sf.dup, 0, h - 1)
    G = jnp.where(dup_on[:, None], jnp.take(G_honest, src, axis=0), G)

    # 2. staleness (buffer only exists when the plan has stragglers):
    # submit the buffered gradient; refresh the buffer from the CLEAN rows
    # only, so a multi-step window keeps replaying the pre-window gradient
    if schedule.has_stale:
        G = jnp.where(sf.stale[:, None], fault_buffer, G)
        new_buffer = jnp.where(sf.stale[:, None], fault_buffer, G_honest)
    else:
        new_buffer = fault_buffer

    # 3. corruption
    G = G * sf.scale[:, None].astype(G.dtype)
    G = jnp.where(sf.zero[:, None], jnp.zeros((), G.dtype), G)
    G = jnp.where(sf.nan[:, None], jnp.asarray(jnp.nan, G.dtype), G)

    # 4. absence — over the full n rows (attack rows can be dropped too)
    active = ~sf.drop

    injected = (
        jnp.sum(sf.stale) + jnp.sum(sf.drop) + jnp.sum(sf.nan)
        + jnp.sum(sf.zero) + jnp.sum(sf.scale != 1.0) + jnp.sum(dup_on)
    ).astype(jnp.int32)
    return G, new_buffer, active, injected
