"""Dynamic quorum — the degradation policy that keeps GAR guarantees
meaningful when workers are absent.

Every GAR kernel is compiled for a static `(n, f)` contract; when the fault
subsystem drops workers (or quarantines corrupt rows), the *effective*
row count `n_eff = sum(active)` is a traced value. This module recomputes
the effective Byzantine tolerance `f_eff` each step — the declared `f`
clamped to the GAR's own breakdown ceiling at the shrunken `n_eff` — and
dispatches to masked kernel variants (`ops/_common.py`, `ops/krum.py`)
that aggregate over the active subset with those traced counts.

Every registered first-tier rule now has a TRACED-COUNT masked kernel
(average/median/trmean via `ops/_common.py`, krum via `ops/krum.py`,
bulyan/brute/phocas/meamed/aksel/cge via their own modules) — each static
slice bound turned into a rank predicate against the traced counts, each
fixed-length loop run with inert padded iterations — so the aggregation
service can serve ANY rule from a padded shape bucket
(`serve/programs.py`) and degraded fault steps recompute the quorum for
every rule instead of only four. The single exception is brute at an
infeasible declared rank space (`ops/brute.py::masked_rank_space` — the
traced-count enumeration must provision the static worst case
`C(n, f_decl)`), which keeps the historical fallback: inactive rows are
routed to NaN, which every kernel already treats as worst-case
(sort-last values, +inf distances), and the static declared `f` absorbs
them as long as `absent + byzantine <= f` — the documented (weaker)
contract, now reachable only on that one route.
"""

import jax.numpy as jnp

from byzantinemomentum_tpu.ops import (
    _common, aksel as aksel_mod, brute as brute_mod, bulyan as bulyan_mod,
    cge as cge_mod, krum as krum_mod, trmean as trmean_mod)

__all__ = ["effective_f", "masked_aggregate"]

# Breakdown ceilings: the largest f each rule tolerates at a given n
# (matching the rules' own `check` contracts: krum needs n >= 2f+3, bulyan
# n >= 4f+3, the trimmed family n >= 2f+1). The default is the generic
# minority bound.
_F_CEILING = {
    "krum": lambda n: (n - 3) // 2,
    "bulyan": lambda n: (n - 3) // 4,
    "brute": lambda n: (n - 1) // 2,
    "trmean": lambda n: (n - 1) // 2,
    "phocas": lambda n: (n - 1) // 2,
    "meamed": lambda n: (n - 1) // 2,
}


def _base_name(name):
    """Strip the compiled-tier prefix: `native-krum` shares krum's math."""
    return name[len("native-"):] if name.startswith("native-") else name


def effective_f(gar_name, n_eff, f_decl):
    """Traced effective Byzantine tolerance: the declared `f` clamped to
    the GAR's breakdown ceiling at the (traced) effective row count."""
    ceiling = _F_CEILING.get(_base_name(gar_name), lambda n: (n - 1) // 2)
    return jnp.clip(jnp.minimum(f_decl, ceiling(n_eff)), 0, None).astype(
        jnp.int32)


def masked_aggregate(gar, gradients, active, *, f_decl, dynamic=True,
                     f_evicted=None, **kwargs):
    """Aggregate the active rows of `gradients` with `gar`.

    Args:
      gar: a registered `GAR` (or an engine facade exposing `.name` /
        `.unchecked`, e.g. the d-sharded wrapper).
      gradients: f32[n, d] stacked submissions.
      active: bool[n] — rows present this step.
      f_decl: static declared Byzantine count.
      dynamic: recompute the effective quorum (False = keep the declared
        `f`, only excluding the absent rows from the aggregation).
      f_evicted: optional traced i32 — Byzantine rows the caller has
        already CONFIRMED and excluded from `active` (the quarantine
        loop's collusion-deduplicated evictions, `arena/quarantine.py`).
        They are subtracted from the declared tolerance before the
        clamp, so evicting a confirmed attacker does not ALSO shrink the
        selection width the remaining rows aggregate with (a Krum over
        n_eff rows at the un-credited f would drop `2 * evictions`
        selected rows' worth of variance reduction). The static `f_decl`
        still provisions every worst-case bound (brute's rank space,
        scan lengths) — the credit only moves the traced `f_eff`.
      kwargs: the GAR's registered plugin args.

    Returns:
      (f32[d] aggregate, i32[] effective f actually used) — the latter
      feeds the `Quorum f` metric column.
    """
    name = _base_name(gar.name)
    n_eff = jnp.sum(active.astype(jnp.int32))
    f_claim = (jnp.maximum(
        jnp.asarray(f_decl, jnp.int32)
        - jnp.asarray(f_evicted, jnp.int32), 0)
        if f_evicted is not None else f_decl)
    f_eff = (effective_f(name, n_eff, f_claim) if dynamic
             else jnp.asarray(f_claim, jnp.int32))

    if name == "average":
        return _common.masked_mean(gradients, active, n_eff), f_eff
    if name == "median":
        return _common.masked_lower_median(gradients, active, n_eff), f_eff
    if name == "trmean":
        return _common.masked_trmean(gradients, active, f_eff, n_eff), f_eff
    if name == "krum":
        dist = _common.pairwise_distances(
            gradients, method=kwargs.get("method", "dot"))
        w = krum_mod.selection_weights_masked(
            dist, active, n_eff, f_eff, kwargs.get("m")).astype(
                gradients.dtype)
        # Zero the inactive rows so a dropped worker's garbage (NaN row)
        # cannot poison the weighted average's masked path
        kept = jnp.where(active[:, None], gradients,
                         jnp.zeros((), gradients.dtype))
        return _common.weighted_rows_mean(w, kept), f_eff
    if name == "bulyan":
        return bulyan_mod.aggregate_masked(
            gradients, active, n_eff, f_eff, kwargs.get("m"),
            method=kwargs.get("method", "dot")), f_eff
    if name == "phocas":
        return trmean_mod.masked_phocas(gradients, active, n_eff,
                                        f_eff), f_eff
    if name == "meamed":
        return trmean_mod.masked_meamed(gradients, active, n_eff,
                                        f_eff), f_eff
    if name == "aksel":
        return aksel_mod.aggregate_masked(
            gradients, active, n_eff, f_eff,
            mode=kwargs.get("mode", "mid")), f_eff
    if name == "cge":
        return cge_mod.aggregate_masked(gradients, active, n_eff,
                                        f_eff), f_eff
    if (name == "brute" and brute_mod.masked_rank_space(
            gradients.shape[0], f_decl) is not None):
        return brute_mod.aggregate_masked(
            gradients, active, n_eff, f_eff, f_decl,
            method=kwargs.get("method", "dot")), f_eff

    # Fallback — brute beyond its feasible masked rank space, and any
    # unregistered/template rule: inactive rows become NaN — every
    # kernel's documented worst-case routing (sort-last, +inf distances) —
    # and the static declared f absorbs them (correct while
    # absent + byzantine <= f_decl)
    routed = jnp.where(active[:, None], gradients,
                       jnp.asarray(jnp.nan, gradients.dtype))
    return (gar.unchecked(routed, f=f_decl, **kwargs),
            jnp.asarray(f_decl, jnp.int32))
