"""Dynamic quorum — the degradation policy that keeps GAR guarantees
meaningful when workers are absent.

Every GAR kernel is compiled for a static `(n, f)` contract; when the fault
subsystem drops workers (or quarantines corrupt rows), the *effective*
row count `n_eff = sum(active)` is a traced value. This module recomputes
the effective Byzantine tolerance `f_eff` each step — the declared `f`
clamped to the GAR's own breakdown ceiling at the shrunken `n_eff` — and
dispatches to masked kernel variants (`ops/_common.py`, `ops/krum.py`)
that aggregate over the active subset with those traced counts.

GARs without a masked variant degrade gracefully instead of wrongly:
inactive rows are routed to NaN, which every kernel in this framework
already treats as worst-case (sort-last values, +inf distances), and the
static declared `f` keeps absorbing them as long as
`absent + byzantine <= f` — the documented fallback contract.
"""

import jax.numpy as jnp

from byzantinemomentum_tpu.ops import _common, krum as krum_mod

__all__ = ["effective_f", "masked_aggregate"]

# Breakdown ceilings: the largest f each rule tolerates at a given n
# (matching the rules' own `check` contracts: krum needs n >= 2f+3, bulyan
# n >= 4f+3, the trimmed family n >= 2f+1). The default is the generic
# minority bound.
_F_CEILING = {
    "krum": lambda n: (n - 3) // 2,
    "bulyan": lambda n: (n - 3) // 4,
    "brute": lambda n: (n - 1) // 2,
    "trmean": lambda n: (n - 1) // 2,
    "phocas": lambda n: (n - 1) // 2,
    "meamed": lambda n: (n - 1) // 2,
}


def _base_name(name):
    """Strip the compiled-tier prefix: `native-krum` shares krum's math."""
    return name[len("native-"):] if name.startswith("native-") else name


def effective_f(gar_name, n_eff, f_decl):
    """Traced effective Byzantine tolerance: the declared `f` clamped to
    the GAR's breakdown ceiling at the (traced) effective row count."""
    ceiling = _F_CEILING.get(_base_name(gar_name), lambda n: (n - 1) // 2)
    return jnp.clip(jnp.minimum(f_decl, ceiling(n_eff)), 0, None).astype(
        jnp.int32)


def masked_aggregate(gar, gradients, active, *, f_decl, dynamic=True,
                     **kwargs):
    """Aggregate the active rows of `gradients` with `gar`.

    Args:
      gar: a registered `GAR` (or an engine facade exposing `.name` /
        `.unchecked`, e.g. the d-sharded wrapper).
      gradients: f32[n, d] stacked submissions.
      active: bool[n] — rows present this step.
      f_decl: static declared Byzantine count.
      dynamic: recompute the effective quorum (False = keep the declared
        `f`, only excluding the absent rows from the aggregation).
      kwargs: the GAR's registered plugin args.

    Returns:
      (f32[d] aggregate, i32[] effective f actually used) — the latter
      feeds the `Quorum f` metric column.
    """
    name = _base_name(gar.name)
    n_eff = jnp.sum(active.astype(jnp.int32))
    f_eff = (effective_f(name, n_eff, f_decl) if dynamic
             else jnp.asarray(f_decl, jnp.int32))

    if name == "average":
        return _common.masked_mean(gradients, active, n_eff), f_eff
    if name == "median":
        return _common.masked_lower_median(gradients, active, n_eff), f_eff
    if name == "trmean":
        return _common.masked_trmean(gradients, active, f_eff, n_eff), f_eff
    if name == "krum":
        dist = _common.pairwise_distances(
            gradients, method=kwargs.get("method", "dot"))
        w = krum_mod.selection_weights_masked(
            dist, active, n_eff, f_eff, kwargs.get("m")).astype(
                gradients.dtype)
        # Zero the inactive rows so a dropped worker's garbage (NaN row)
        # cannot poison the weighted average's masked path
        kept = jnp.where(active[:, None], gradients,
                         jnp.zeros((), gradients.dtype))
        return _common.weighted_rows_mean(w, kept), f_eff

    # Fallback: inactive rows become NaN — every kernel's documented
    # worst-case routing (sort-last, +inf distances) — and the static
    # declared f absorbs them (correct while absent + byzantine <= f_decl)
    routed = jnp.where(active[:, None], gradients,
                       jnp.asarray(jnp.nan, gradients.dtype))
    return (gar.unchecked(routed, f=f_decl, **kwargs),
            jnp.asarray(f_decl, jnp.int32))
