"""NaN-quarantine: detection and masking of numerically-corrupt rows.

The GAR kernels are NaN-*resilient* (non-finite coordinates sort last /
map to +inf distances, `ops/_common.py`) — they survive corrupt rows but
still count them toward `n`. Quarantine is the stronger degradation-policy
response: detect the corrupt rows (the `attacks/nan.py` emission pattern,
generalized to any non-finite shard by `attacks.nan.detect`) and remove
them from the active set, so the dynamic-quorum layer (`faults/quorum.py`)
aggregates over genuinely healthy submissions with a matching effective
`(n, f)`.
"""

from byzantinemomentum_tpu.attacks.nan import detect as corrupt_rows

__all__ = ["corrupt_rows", "quarantine"]


def quarantine(gradients, active):
    """Mask numerically-corrupt rows out of `active`.

    `gradients: f32[n, d]`, `active: bool[n]` -> `(bool[n], i32[])`: the
    shrunk active mask and the number of rows newly quarantined (already-
    inactive corrupt rows — e.g. dropped workers whose row is garbage —
    are not double-counted).
    """
    import jax.numpy as jnp

    bad = corrupt_rows(gradients)
    return active & ~bad, jnp.sum((active & bad).astype(jnp.int32))
