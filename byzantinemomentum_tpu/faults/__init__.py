"""Fault injection & resilience — system faults as first-class, testable
behavior.

The paper's premise is surviving adversarial workers; this package covers
the other half of "Byzantine" that real systems meet first: stragglers,
dropped workers, corrupted/NaN gradient shards, duplicated submissions,
devices lost mid-run. Three layers:

* **declaration** (`plan.py`) — `FaultPlan`: per-step, per-worker fault
  events plus a degradation `FaultPolicy`, JSON round-trippable and fully
  deterministic (seeded generation for randomized chaos runs);
* **injection** (`schedule.py`, `inject.py`) — the plan compiles to dense
  per-step masks applied to the stacked gradient batch INSIDE the jitted
  step, before aggregation: pure `jnp.where` masking, no host round-trips,
  and a `None` schedule (empty plan) compiles to the exact fault-free
  program;
* **degradation policy** (`quorum.py`, `sanitize.py`, `retry.py`) —
  dynamic quorum (the GAR runs with the effective `(n, f)` of the workers
  actually present), NaN-quarantine (corrupt rows detected via the
  generalized `attacks/nan.py` predicate and masked out), and
  retry/backoff for the host data-fetch path.

Driver surface: `cli/attack.py --fault-plan plan.json`; the study CSV
gains `Faults injected` / `Workers active` / `Quorum f` columns so
`study.py` can plot accuracy against fault pressure.

This module keeps its imports host-only (no jax): `FaultPlan` authoring,
JSON handling and the retry helper work in contexts where the accelerator
stack must not initialize (dataset download paths, plan tooling). The
jax-facing halves live in the submodules the engine imports directly.
"""

from byzantinemomentum_tpu.faults.plan import (
    FaultEvent,
    FaultPlan,
    FaultPolicy,
    corrupt_gradient,
    device_loss,
    drop_worker,
    duplicate_submission,
    straggler,
)
from byzantinemomentum_tpu.faults.retry import with_backoff

__all__ = ["FaultEvent", "FaultPlan", "FaultPolicy", "build_schedule",
           "corrupt_gradient", "device_loss", "drop_worker",
           "duplicate_submission", "straggler", "with_backoff"]


def build_schedule(plan, *, nb_workers, nb_honests):
    """Compile a `FaultPlan` for an (n, h) run — None for an empty plan
    (the engine's zero-overhead contract). Lazy import: the schedule half
    touches jax."""
    from byzantinemomentum_tpu.faults import schedule as _schedule
    return _schedule.build_schedule(plan, nb_workers=nb_workers,
                                    nb_honests=nb_honests)
