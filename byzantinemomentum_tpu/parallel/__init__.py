"""Multi-chip execution: device meshes, sharding specs and distributed GAR
kernels.

The reference has NO distributed backend (SURVEY.md §2.8: its "workers" are
an in-process loop, its only transport a `.to(device)` move,
reference `attack.py:811-815`). The TPU-native equivalent built here is the
real thing: the `(n, d)` gradient matrix lives sharded across a
`jax.sharding.Mesh`, and "communication" is XLA collectives over ICI —

* **worker axis** (data parallel over simulated workers): per-worker batches
  and gradients shard along `n`; the aggregation gathers rows, which XLA
  lowers to an all-gather on ICI.
* **model axis** (the `d` dimension, for models too large for one chip):
  coordinate-wise GARs (median/trmean/phocas/meamed) shard trivially along
  `d`; pairwise-distance GARs (krum/bulyan/brute) compute per-shard partial
  Gram matrices and `psum` them over the model axis (`sharded.py`) — the
  distance matrix is tiny (n x n), so only the reduction crosses chips.

DCN enters only at the experiment-grid level (`tools.Jobs`-style scheduling
of independent runs across hosts), exactly where the reference used
process-level parallelism (reference `tools/jobs.py:148-191`).
"""

from byzantinemomentum_tpu.parallel.mesh import make_mesh, mesh_axes
from byzantinemomentum_tpu.parallel.ring import (
    dense_attention,
    ring_attention,
    ulysses_attention,
)
from byzantinemomentum_tpu.parallel.sharded import (
    global_batch,
    global_train_state,
    host_to_global,
    pairwise_distances_sharded,
    shard_defense_list,
    shard_defenses,
    shard_gar,
    shard_gar_diag,
    sharded_eval_many,
    sharded_state_spec,
    sharded_train_multi,
    sharded_train_step,
)

__all__ = ["global_batch", "global_train_state", "host_to_global",
           "make_mesh", "mesh_axes", "pairwise_distances_sharded",
           "shard_defense_list", "shard_defenses", "shard_gar",
           "shard_gar_diag", "sharded_eval_many",
           "sharded_state_spec", "sharded_train_step",
           "sharded_train_multi",
           "dense_attention", "ring_attention", "ulysses_attention"]
