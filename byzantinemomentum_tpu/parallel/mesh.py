"""Device-mesh construction.

Axis naming convention used across the framework:
  "workers" — the simulated-worker axis (data parallel): batches and the
              (n, d) gradient matrix shard along it.
  "model"   — the flat-parameter axis (d): parameters, momentum buffers and
              gradient columns shard along it for large models.
"""

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "mesh_axes", "shard_map"]

WORKERS, MODEL = "workers", "model"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """`jax.shard_map` across jax versions.

    Recent jax exposes the primitive at the top level with the `check_vma`
    spelling; the releases this framework must also run on only ship
    `jax.experimental.shard_map.shard_map`, where the same knob is named
    `check_rep`. Every shard-mapped kernel in the framework goes through
    this wrapper so a jax downgrade degrades nothing but the spelling.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


def mesh_axes():
    return (WORKERS, MODEL)


def make_mesh(n_devices=None, *, model_parallel=1, devices=None):
    """Build a (workers, model) `Mesh` over the available devices.

    Args:
      n_devices: number of devices to use (default: all).
      model_parallel: size of the model axis; the worker axis gets the rest.
      devices: explicit device list (default: `jax.devices()`).
    Returns:
      `jax.sharding.Mesh` with axes ("workers", "model").
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if model_parallel < 1:
        raise ValueError(
            f"Non-positive model-parallel size {model_parallel}")
    if n_devices is not None:
        if n_devices < 1:
            raise ValueError(f"Non-positive device count {n_devices}")
        if n_devices > len(devices):
            raise ValueError(
                f"Requested {n_devices} devices but only {len(devices)} are "
                f"available")
        devices = devices[:n_devices]
    n = len(devices)
    if n % model_parallel != 0:
        raise ValueError(
            f"Device count {n} is not divisible by model_parallel="
            f"{model_parallel}")
    grid = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (WORKERS, MODEL))
