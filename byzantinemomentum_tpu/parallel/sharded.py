"""Sharded execution: distributed GAR kernels and the multi-chip training
step.

Design recipe (the scaling-book pattern): annotate shardings on the jitted
step and let XLA insert the collectives. Two explicit `shard_map` kernels
are provided for the cases where the communication pattern is worth pinning
by hand:

* `pairwise_distances_sharded` — the O(n²·d) distance computation behind
  krum/bulyan/brute with `d` sharded over the "model" axis: each chip forms
  its partial Gram matrix on the MXU and a single `psum` of the tiny (n, n)
  result crosses ICI (instead of all-gathering the (n, d) matrix).
* `shard_gar` — coordinate-wise GARs (median/trmean/phocas/meamed/average)
  run on each chip's d-slice with NO communication at all (Pallas sorting
  networks stay alive per shard via `pallas_sort.allowed()`);
  selection-based GARs (krum/bulyan/brute) reuse the psum distances, then
  every chip applies the (replicated, tiny) selection to its local slice.

The sharded training step swaps the engine's defenses for these kernels at
trace time (`shard_defenses`), so `--mesh` runs take the explicit
distributed path for every registered GAR the kernels cover.

Fault injection composes with the mesh: the engine's injection hook and
degradation policy (`faults/`) are part of the traced step, so `--mesh`
runs inject the same masks. On fault steps the masked dynamic-quorum
kernels (plain jnp, `faults/quorum.py`) are partitioned by the jit
propagator rather than these hand-written shard_map kernels — correctness
first; hand-sharding the (rare) degraded steps is future work. The
`_ShardedGar` facade keeps the GAR name visible so the quorum layer's
per-rule dispatch still applies, and the unsupported-GAR fallback routes
through its padded `.unchecked`.
"""

import contextlib

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from byzantinemomentum_tpu.engine.state import TrainState
from byzantinemomentum_tpu.ops import pallas_sort
from byzantinemomentum_tpu.parallel.mesh import MODEL, WORKERS, shard_map

__all__ = ["global_batch", "global_train_state", "host_to_global",
           "pairwise_distances_sharded", "shard_defense_list",
           "shard_defenses", "shard_gar", "shard_gar_diag",
           "sharded_eval_many", "sharded_state_spec", "sharded_train_step",
           "sharded_train_multi", "COORDINATE_WISE"]

# GARs that act independently per coordinate: they shard over `d` with zero
# communication (SURVEY.md §5.7: "coordinate-wise GARs shard trivially over
# d; pairwise-distance GARs need a psum over d-shards").
COORDINATE_WISE = frozenset(
    {"average", "median", "trmean", "phocas", "meamed", "native-median"})


def pairwise_distances_sharded(g, mesh):
    """All-pairs Euclidean distances of the rows of `g: f32[n, d]` with `d`
    sharded along the mesh's "model" axis.

    Per shard: partial row-norms and partial Gram matrix (one MXU matmul),
    then one `psum` of (n,) + (n, n) over ICI. Semantics match
    `ops._common.pairwise_distances` ('dot' method): non-finite -> +inf,
    +inf diagonal.
    """
    return shard_map(
        _psum_pairwise, mesh=mesh,
        in_specs=P(None, MODEL), out_specs=P(None, None))(g)


def _psum_pairwise(g_local):
    """Shard-local body of the distributed pairwise-distance kernel: the
    partial Gram on this d-slice (one streamed Pallas pass where supported,
    else one MXU matmul), psum over the model axis; the shared
    `(n, n)` post-processing (`ops._common.distances_from_sq_gram`) keeps
    the semantics identical to the single-device path."""
    from byzantinemomentum_tpu.ops import _common, pallas_gar

    with pallas_sort.allowed():
        if pallas_gar.supported(g_local):
            # Fused tier (`ops/pallas_gar.py`): the partial Gram
            # accumulates in VMEM over d-tiles of this shard's slice —
            # legal here because shard_map operands are manual per-device
            # shards even while the outer trace holds `disabled()`
            part = pallas_gar.sq_gram(g_local)
        else:
            # precision=HIGHEST as in `ops._common.pairwise_distances`:
            # TPU matmuls default to bf16-decomposed passes, and these
            # distances feed selection orderings that must match the
            # single-device path
            part = jnp.matmul(g_local, g_local.T,
                              precision=jax.lax.Precision.HIGHEST)
    gram = jax.lax.psum(part, MODEL)
    return _common.distances_from_sq_gram(gram)


def shard_gar(gar, mesh, *, f, **kwargs):
    """Wrap a registered GAR into a d-sharded callable `(G) -> f32[d]`.

    Coordinate-wise rules run shard-locally. Selection-based rules
    (krum/bulyan/brute) compute the psum'd distance matrix, derive the
    (replicated, tiny) selection, and apply it to the local d-slice — the
    (n, d) matrix itself never crosses ICI.

    Every shard-local body runs under `pallas_sort.allowed()`: operands
    inside `shard_map` are manual per-device shards, so the Pallas sorting
    networks are legal here even while the surrounding multi-device trace
    holds `pallas_sort.disabled()`.
    """
    if gar.name in COORDINATE_WISE:
        def kernel(g_local):
            with pallas_sort.allowed():
                return gar.unchecked(g_local, f=f, **kwargs)
        # check_vma=False: the Pallas out_shapes inside carry no
        # varying-mesh-axes annotation
        return shard_map(kernel, mesh=mesh, in_specs=P(None, MODEL),
                         out_specs=P(MODEL), check_vma=False)

    if gar.name in ("krum", "native-krum"):
        from byzantinemomentum_tpu.ops import _common, krum as krum_mod

        def kernel(g_local):
            # Global distances via one psum'd Gram; the (replicated) weight
            # vector then averages the local d-slice — single source of
            # truth for selection in `ops/krum.py:selection_weights`.
            # Non-finite propagation is per coordinate, hence d-local.
            dist = _psum_pairwise(g_local)
            w = krum_mod.selection_weights(
                dist, f, kwargs.get("m")).astype(g_local.dtype)
            # The psum'd distances certify WHOLE rows finite, which covers
            # this shard's columns; under `allowed()` the averaging takes
            # the streamed fused kernel per shard (`ops/pallas_gar.py`)
            with pallas_sort.allowed():
                return _common.weighted_rows_mean(
                    w, g_local,
                    all_finite=_common.all_finite_from_dist(dist))

        # check_vma=False: the Pallas out_shapes inside carry no
        # varying-mesh-axes annotation
        return shard_map(kernel, mesh=mesh, in_specs=P(None, MODEL),
                         out_specs=P(MODEL), check_vma=False)

    if gar.name in ("bulyan", "native-bulyan"):
        from byzantinemomentum_tpu.ops import _common, bulyan as bulyan_mod

        def kernel(g_local):
            # Stage 1 (reference `aggregators/bulyan.py:63-76`): global
            # distances via one psum'd Gram, replicated score-scan selection
            # (`ops/bulyan.py:selection_weights`), then one d-local
            # (rounds, n) @ (n, d_shard) matmul
            dist = _psum_pairwise(g_local)
            W = bulyan_mod.selection_weights(dist, f, kwargs.get("m"))
            with pallas_sort.allowed():
                from byzantinemomentum_tpu.ops import pallas_gar
                if pallas_gar.supported(g_local):
                    # Fully-fused d-local tail: stage-1 averages and the
                    # stage-2 averaged median in one streamed read of the
                    # shard slice (`ops/pallas_gar.py`)
                    return pallas_gar.selected_median_mean(
                        W, g_local, W.shape[0] - 2 * f)
                sel = _common.weighted_rows_mean(
                    W.astype(g_local.dtype), g_local,
                    all_finite=_common.all_finite_from_dist(dist))
                # Stage 2 (reference `bulyan.py:77-84`): coordinate-wise
                # averaged median — d-local, Pallas-fused where supported
                return _common.averaged_median(sel, sel.shape[0] - 2 * f)

        # check_vma=False: the Pallas out_shapes inside carry no
        # varying-mesh-axes annotation
        return shard_map(kernel, mesh=mesh, in_specs=P(None, MODEL),
                         out_specs=P(MODEL), check_vma=False)

    if gar.name in ("brute", "native-brute"):
        from byzantinemomentum_tpu.ops import brute as brute_mod

        def kernel(g_local):
            # Streaming subset enumeration runs on the replicated psum'd
            # (n, n) distances (reference `aggregators/brute.py:32-68`);
            # only the masked mean touches the local d-slice
            n = g_local.shape[0]
            dist = _psum_pairwise(g_local)
            mask = brute_mod.best_subset_mask_from_dist(dist, f)
            with pallas_sort.allowed():
                from byzantinemomentum_tpu.ops import pallas_gar
                if pallas_gar.supported(g_local):
                    return pallas_gar.masked_rows_mean(mask, g_local, n - f)
            kept = jnp.where(mask[:, None], g_local, 0)
            return jnp.sum(kept, axis=0) / (n - f)

        # check_vma=False: older jax's conservative check_rep cannot track
        # replication through the subset-enumeration lax.scan (the psum'd
        # operands ARE replicated; the newer check_vma verifier agrees)
        return shard_map(kernel, mesh=mesh, in_specs=P(None, MODEL),
                         out_specs=P(MODEL), check_vma=False)

    # Fallback: replicate (correct for any GAR; no d-sharding win)
    def kernel_replicated(g):
        return gar.unchecked(g, f=f, **kwargs)
    return kernel_replicated


def shard_gar_diag(gar, mesh, *, f, **kwargs):
    """d-sharded DIAGNOSTICS kernel builder for rules with a native
    sharded aux. Returns `fn(G_padded, d_real) -> (aggregate, aux)` —
    `d_real` is the pre-padding width (static at trace time; the facade
    threads it) — or None for rules that keep `_generic_diagnose`.

    Selection rules (krum/bulyan/brute): the aux psums the SAME distance
    Gram the aggregate already needs, so diagnostics under `--mesh` cost
    one (n, n) collective total — exactly like the single-device kernels
    share their distance matrix between aggregate and aux
    (`ops/krum.py::diagnose` etc.). Every aux component is a function of
    the replicated psum'd distances alone — only the aggregate touches
    the d axis — so the aux leaves the shard_map replicated (`P()`
    out-specs) and matches the unsharded native aux up to
    Gram-accumulation rounding (oracle-tested in `tests/test_lattice.py`).
    Zero-padded d columns (the facade's divisibility padding) contribute
    nothing to any distance, so these rules ignore `d_real`.

    Coordinate-wise rules (trmean/phocas/meamed — the ROADMAP "lattice
    rung 1" — and, since the PR 11 round, median): trim fractions are
    per-coordinate MEANS, so the sharded aux sums d-LOCAL partial
    quantities and psums them with shard widths accounted: each shard
    counts its kept coordinates and squared deviations over its REAL
    columns only (a global-column-index mask derived from `d_real`
    excludes the divisibility padding, whose all-zero columns would
    otherwise count as universally kept), one tupled psum carries
    `(Gram, dev², kept-counts)` across ICI, and the replicated totals
    divide by the true width. Median's "kept" is its was-median
    indicator (`ops/median.py::diagnose` — the sharded aux retires the
    generic geometry fallback the ROADMAP's lattice rung 3 pointed at).
    Oracle-tested against the unsharded native aux
    (`tests/test_lattice.py`).
    """
    name = gar.name

    if name in ("trmean", "phocas", "meamed", "median", "native-median"):
        base = name[len("native-"):] if name.startswith("native-") else name
        return _coord_diag_builder(base, gar, mesh, f=f, **kwargs)

    if name in ("krum", "native-krum"):
        from byzantinemomentum_tpu.ops import (
            _common, diag, krum as krum_mod)

        def kernel(g_local):
            n = g_local.shape[0]
            m = kwargs.get("m")
            m_eff = n - f - 2 if m is None else m
            dist = _psum_pairwise(g_local)
            w = krum_mod.selection_weights(dist, f, m)
            with pallas_sort.allowed():
                agg = _common.weighted_rows_mean(
                    w.astype(g_local.dtype), g_local,
                    all_finite=_common.all_finite_from_dist(dist))
            return agg, diag.make_aux(
                n, scores=krum_mod.scores_from_dist(dist, f),
                selection=w * m_eff, dist=dist)

    elif name in ("bulyan", "native-bulyan"):
        from byzantinemomentum_tpu.ops import (
            _common, bulyan as bulyan_mod, diag, pallas_gar)

        def kernel(g_local):
            n = g_local.shape[0]
            m = kwargs.get("m")
            m_scores = n - f - 2 if m is None else m
            dist = _psum_pairwise(g_local)
            W = bulyan_mod.selection_weights(dist, f, m)
            rounds = W.shape[0]
            with pallas_sort.allowed():
                if pallas_gar.supported(g_local):
                    agg = pallas_gar.selected_median_mean(
                        W, g_local, rounds - 2 * f)
                else:
                    sel = _common.weighted_rows_mean(
                        W.astype(g_local.dtype), g_local,
                        all_finite=_common.all_finite_from_dist(dist))
                    agg = _common.averaged_median(sel, rounds - 2 * f)
            scores = jnp.sum(jnp.sort(dist, axis=1)[:, :m_scores], axis=1)
            mass = jnp.sum((W > 0).astype(jnp.float32), axis=0) / rounds
            return agg, diag.make_aux(n, scores=scores, selection=mass,
                                      dist=dist)

    elif name in ("brute", "native-brute"):
        from byzantinemomentum_tpu.ops import (
            brute as brute_mod, diag, pallas_gar)

        def kernel(g_local):
            n = g_local.shape[0]
            dist = _psum_pairwise(g_local)
            mask = brute_mod.best_subset_mask_from_dist(dist, f)
            with pallas_sort.allowed():
                if pallas_gar.supported(g_local):
                    agg = pallas_gar.masked_rows_mean(mask, g_local, n - f)
                else:
                    kept = jnp.where(mask[:, None], g_local, 0)
                    agg = jnp.sum(kept, axis=0) / (n - f)
            in_subset = mask[None, :] & ~jnp.eye(n, dtype=bool)
            scores = jnp.max(jnp.where(in_subset, dist, -jnp.inf), axis=1)
            return agg, diag.make_aux(
                n, scores=scores, selection=mask.astype(jnp.float32),
                dist=dist)

    else:
        return None

    aux_specs = {"scores": P(), "selection": P(), "dist": P(),
                 "trim_frac": P()}
    # check_vma=False: the Pallas out_shapes inside carry no varying-
    # mesh-axes annotation, and the replicated aux rides the psum'd Gram
    mapped = shard_map(kernel, mesh=mesh, in_specs=P(None, MODEL),
                       out_specs=(P(MODEL), aux_specs), check_vma=False)
    return lambda g, d_real: mapped(g)  # distance aux: padding-invariant


def _coord_diag_builder(name, gar, mesh, *, f, **kwargs):
    """Native d-sharded diagnostics for the coordinate-wise trim rules:
    shard-local aggregate + kept-mask, width-aware partial sums, ONE
    tupled psum (`(Gram, dev², kept-counts)` — the collective census the
    lattice pins), replicated aux. See `shard_gar_diag`."""
    from byzantinemomentum_tpu.ops import _common, diag, trmean as trmean_mod

    def fn(g, d_real):
        def kernel(g_local):
            n = g_local.shape[0]
            width = g_local.shape[1]
            with pallas_sort.allowed():
                if name == "median":
                    # Coordinate-wise ops are exact per d-shard; "kept"
                    # is the was-median indicator (NaN rows compare
                    # False, exactly as the unsharded native aux)
                    agg = _common.lower_median(g_local)
                    kept = g_local == agg[None, :]
                elif name == "trmean":
                    agg = trmean_mod.trmean(g_local, f)
                    kept = diag.rank_kept_mask(g_local, f)
                elif name == "phocas":
                    center = trmean_mod.trmean(g_local, f)
                    agg = _common.closest_mean(g_local, center, n - f)
                    dev_c = jnp.abs(g_local - center[None, :])
                    kept = diag.rank_kept_mask(dev_c, f, n_low=0,
                                               n_high=n - f)
                else:  # meamed
                    center = _common.lower_median(g_local)
                    agg = _common.closest_mean(g_local, center, n - f)
                    dev_c = jnp.abs(g_local - center[None, :])
                    kept = diag.rank_kept_mask(dev_c, f, n_low=0,
                                               n_high=n - f)
            # Real-column mask: the facade's divisibility padding lives in
            # the LAST shard's tail; its all-zero columns must not count
            # toward any per-coordinate mean
            start = jax.lax.axis_index(MODEL).astype(jnp.int32) * width
            real = (start + jnp.arange(width, dtype=jnp.int32)) < d_real
            kept_part = jnp.sum((kept & real[None, :]).astype(jnp.float32),
                                axis=1)
            dev = g_local - agg[None, :]
            # Padded columns deviate by exactly 0 (zero data, zero
            # aggregate), so the score partials need no real-mask
            dev2_part = jnp.sum(dev * dev, axis=1)
            gram_part = jnp.matmul(g_local, g_local.T,
                                   precision=jax.lax.Precision.HIGHEST)
            gram, dev2, kept_count = jax.lax.psum(
                (gram_part, dev2_part, kept_part), MODEL)
            scores = _common.sanitize_inf(jnp.sqrt(dev2))
            trim = 1.0 - kept_count / d_real
            aux = diag.make_aux(
                n, scores=scores,
                selection=jnp.ones((n,), jnp.float32),
                dist=_common.distances_from_sq_gram(gram),
                trim_frac=trim)
            return agg, aux

        aux_specs = {"scores": P(), "selection": P(), "dist": P(),
                     "trim_frac": P()}
        # check_vma=False: Pallas out_shapes carry no varying-mesh-axes
        # annotation, and the replicated aux rides the tupled psum
        return shard_map(kernel, mesh=mesh, in_specs=P(None, MODEL),
                         out_specs=(P(MODEL), aux_specs),
                         check_vma=False)(g)

    return fn


# ------------------------------------------------------------------------- #
# Multi-controller (multi-process) support: the jit + shardings recipe
# below is already multi-process-ready — the same compiled program runs on
# every process of a `jax.distributed` fleet — but each process only holds
# its *addressable* shards, so host-side values (freshly initialized
# state, sampled batches) must be lifted into global `jax.Array`s before
# they can feed a global-mesh program. Every process calls these with the
# SAME host values (the cluster runtime's determinism contract:
# same seed -> same init, same sampler stream -> same batch), and
# `jax.make_array_from_callback` materializes only the shards this
# process owns.

def host_to_global(mesh, host_tree, spec_tree):
    """Lift a host-value pytree into global arrays on `mesh` according to
    a matching pytree of `PartitionSpec`s (leaves that are specs, e.g.
    `sharded_state_spec`'s output)."""
    import numpy as np

    shardings = jax.tree.map(lambda p: NamedSharding(mesh, p), spec_tree,
                             is_leaf=lambda x: isinstance(x, P))

    def put(leaf, sharding):
        arr = np.asarray(leaf)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx, a=arr: a[idx])

    return jax.tree.map(put, host_tree, shardings)


def global_train_state(mesh, state):
    """A `TrainState` (freshly initialized or checkpoint-restored on this
    host) as global arrays laid out per `sharded_state_spec` — the input
    the multi-process `sharded_train_step` consumes. On a
    (workers=N, model=1) cluster mesh every buffer is fully replicated,
    so `jax.device_get` on the OUTPUT state works from any process (what
    checkpointing and the study CSV read)."""
    return host_to_global(mesh, jax.device_get(state),
                          sharded_state_spec(state))


def global_batch(mesh, array, spec=P(WORKERS)):
    """One host-sampled batch as a global array sharded per `spec`
    (default: rows along the workers axis, the training-step layout)."""
    return host_to_global(mesh, array, spec)


def sharded_state_spec(state):
    """PartitionSpecs for a `TrainState` on a (workers, model) mesh: all
    d-dimensional buffers shard along "model"; scalars/counters/PRNG
    replicate. (BatchNorm state replicates — it is tiny.)"""
    d = state.theta.shape
    return TrainState(
        theta=P(MODEL),
        net_state=jax.tree.map(lambda _: P(), state.net_state),
        opt_state=jax.tree.map(
            lambda leaf: P(MODEL) if getattr(leaf, "shape", None) == d else P(),
            state.opt_state),
        momentum_server=P(MODEL),
        momentum_workers=P(None, MODEL),
        origin=P(MODEL) if state.origin.ndim else P(),
        past_grads=P(None, MODEL),
        past_norms=P(),
        past_count=P(),
        steps=P(),
        datapoints=P(),
        rng=P(),
        # The straggler-fault stale buffer (`faults/inject.py`) is (h, d):
        # d-sharded like every flat-parameter-space buffer
        fault_buffer=P(None, MODEL),
        # Adaptive-attack history (tiny counter pytrees): replicated
        attack_state=jax.tree.map(lambda _: P(), state.attack_state),
    )


class _ShardedGar:
    """Engine-facing facade over `shard_gar`/`shard_gar_diag` kernels.

    `.unchecked` ignores the call-site f/kwargs (already bound into the
    kernel) and pads the d axis up to a multiple of the model-axis size —
    zero columns leave every distance, score and coordinate-wise reduction
    of the real columns unchanged, and are sliced back off. Selection
    metadata (`influence`) stays on the original GAR object. `.diagnosed`
    (the `--gar-diagnostics` path) runs the NATIVE psum'd-Gram diagnostics
    kernel where one exists (krum/bulyan/brute — the aux psums the same
    distance Gram as the aggregate and matches the unsharded native aux);
    other rules take the generic geometry fallback around the sharded
    kernel.
    """

    def __init__(self, inner, fn, axis_size, diag_fn=None):
        self.name = inner.name
        self.influence = inner.influence
        self._fn = fn
        self._diag_fn = diag_fn
        self._axis_size = axis_size

    def _padded(self, gradients):
        d = gradients.shape[1]
        pad = (-d) % self._axis_size
        if pad:
            gradients = jnp.pad(gradients, ((0, 0), (0, pad)))
        return gradients, d, pad

    def diagnosed(self, gradients, **kwargs):
        if self._diag_fn is None:
            from byzantinemomentum_tpu.ops import _generic_diagnose
            return _generic_diagnose(self.unchecked, gradients, **kwargs)
        gradients, d, pad = self._padded(gradients)
        # The builder gets the PRE-padding width: coordinate-wise aux
        # normalizes its per-coordinate means by the true d
        agg, aux = self._diag_fn(gradients, d)
        return (agg[:d] if pad else agg), aux

    def unchecked(self, gradients, **_kwargs):
        gradients, d, pad = self._padded(gradients)
        out = self._fn(gradients)
        return out[:d] if pad else out


def shard_defense_list(defenses, mesh, *, f):
    """A defense list with every GAR rebuilt as an explicit d-sharded
    `shard_gar` kernel (krum/bulyan/brute ride the psum'd Gram and carry
    native psum'd-Gram diagnostics; coordinate-wise rules keep their
    Pallas kernels per shard) — the sharding axis of the program builder
    (`engine/program.py::shard_axis`)."""
    axis_size = mesh.shape[MODEL]
    return [
        (_ShardedGar(gar, shard_gar(gar, mesh, f=f, **kw), axis_size,
                     diag_fn=shard_gar_diag(gar, mesh, f=f, **kw)), fc, kw)
        for gar, fc, kw in defenses
    ]


def shard_defenses(engine, mesh):
    """`shard_defense_list` over the engine's defense list."""
    return shard_defense_list(engine.defenses, mesh,
                              f=engine.cfg.nb_decl_byz)


@contextlib.contextmanager
def _defenses_overridden(engine, defenses):
    saved = engine.defenses
    engine.defenses = defenses
    try:
        yield
    finally:
        engine.defenses = saved


def _sharded_step_builder(step_fn, mesh, state_example, batch_spec,
                          engine=None, replicate_metrics=False):
    """Shared sharding setup for the single- and multi-step programs.

    The traced function is wrapped in `pallas_sort.disabled()`: Mosaic
    kernels cannot be auto-partitioned by the jit sharding propagator. The
    defense calls are the exception — when `engine` is given they are
    swapped for explicit `shard_gar` kernels, whose `shard_map` bodies are
    manual partitions where Pallas is legal again (`pallas_sort.allowed()`).
    """
    # Function-level import: engine.step pulls in the model registry, whose
    # transformer module imports this package (circular at module scope)
    from byzantinemomentum_tpu.engine.step import grouped_sharded

    spec = sharded_state_spec(state_example)
    state_shardings = jax.tree.map(
        lambda p: NamedSharding(mesh, p), spec,
        is_leaf=lambda x: isinstance(x, P))
    batch_sharding = NamedSharding(mesh, batch_spec)
    lr_sharding = NamedSharding(mesh, P())

    wrapped = shard_defenses(engine, mesh) if engine is not None else None

    def traced(*args):
        ctx = (_defenses_overridden(engine, wrapped) if wrapped is not None
               else contextlib.nullcontext())
        # grouped_sharded: the jit propagator cannot batch-shard the
        # channel-group honest phase on its own, so the engine traces it as
        # an explicit `shard_map` over the workers axis — each shard runs
        # the merged grouped program on its local workers (vmap fallback
        # for models without `apply_grouped` or non-dividing worker axes)
        with ctx, pallas_sort.disabled(), grouped_sharded(mesh):
            return step_fn(*args)

    # Single-process runs leave the metrics layout to the compiler; a
    # multi-process fleet pins them REPLICATED so every process can read
    # the study metrics off its own addressable shard (`jax.device_get`
    # on a partially-addressable array would fail)
    metrics_sharding = (NamedSharding(mesh, P()) if replicate_metrics
                        else None)
    return jax.jit(
        traced,
        in_shardings=(state_shardings, batch_sharding, batch_sharding,
                      lr_sharding),
        out_shardings=(state_shardings, metrics_sharding),
        donate_argnums=(0,))


def sharded_train_step(engine, mesh, state_example, replicate_metrics=False):
    """Compile the engine's training step for a multi-chip mesh.

    Batches shard along "workers" (each chip computes its workers' gradients
    — the reference's sequential honest phase, now data-parallel across
    chips); parameters and momentum shard along "model". The GAR runs as an
    explicit `shard_gar` kernel (psum'd Gram for selection rules, shard-local
    Pallas for coordinate-wise rules); XLA inserts the all-gather of gradient
    rows feeding it and the collectives for the d-sharded update.

    `replicate_metrics` pins the metrics output replicated — required on a
    multi-process mesh, where every controller reads them
    (`byzantinemomentum_tpu/cluster/host.py`).

    Returns `step(state, xs, ys, lr) -> (state, metrics)` — a drop-in for
    `engine.train_step`.
    """
    return _sharded_step_builder(engine._train_step, mesh, state_example,
                                 P(WORKERS), engine=engine,
                                 replicate_metrics=replicate_metrics)


def sharded_eval_many(engine, mesh, state_example):
    """Milestone evaluation over the mesh: test batches shard along
    "workers" on their batch axis (each chip scores its slice of every
    rep; the tiny `[#correct, #samples]` accumulator is psum'd by XLA), and
    theta stays in its d-sharded layout instead of gathering onto one
    device. Drop-in for `engine.eval_many`.
    """
    spec = sharded_state_spec(state_example)
    theta_sh = NamedSharding(mesh, P(MODEL))
    ns_sh = jax.tree.map(lambda p: NamedSharding(mesh, p), spec.net_state,
                         is_leaf=lambda x: isinstance(x, P))
    batch_sh = NamedSharding(mesh, P(None, WORKERS))
    jitted = jax.jit(
        engine._eval_many,
        in_shardings=(theta_sh, ns_sh, batch_sh, batch_sh),
        out_shardings=NamedSharding(mesh, P()))
    workers_ax = mesh.shape[WORKERS]

    def call(theta, net_state, xs, ys):
        if xs.shape[1] % workers_ax:
            raise ValueError(
                f"Sharded evaluation requires the test batch size "
                f"({xs.shape[1]}) to divide evenly over the {workers_ax}-way "
                f"worker axis; use engine.eval_many instead")
        return jitted(theta, net_state, xs, ys)

    return call


def sharded_train_multi(engine, mesh, state_example):
    """Multi-chip version of `engine.train_multi`: M fused steps per
    dispatch (`lax.scan`) with the same shardings as `sharded_train_step` —
    batches `xs: [M, S, B, ...]` shard along "workers" on their S axis.

    Returns `step(state, xs, ys, lrs) -> (state, stacked metrics)`.
    """
    return _sharded_step_builder(engine._train_multi, mesh, state_example,
                                 P(None, WORKERS), engine=engine)
