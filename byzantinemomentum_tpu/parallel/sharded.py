"""Sharded execution: distributed GAR kernels and the multi-chip training
step.

Design recipe (the scaling-book pattern): annotate shardings on the jitted
step and let XLA insert the collectives. Two explicit `shard_map` kernels
are provided for the cases where the communication pattern is worth pinning
by hand:

* `pairwise_distances_sharded` — the O(n²·d) distance computation behind
  krum/bulyan/brute with `d` sharded over the "model" axis: each chip forms
  its partial Gram matrix on the MXU and a single `psum` of the tiny (n, n)
  result crosses ICI (instead of all-gathering the (n, d) matrix).
* `shard_gar` — coordinate-wise GARs (median/trmean/phocas/meamed/average)
  run on each chip's d-slice with NO communication at all; selection-based
  GARs (krum) reuse the psum distances, then every chip applies the
  (replicated, tiny) selection to its local slice.
"""

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from byzantinemomentum_tpu.engine.state import TrainState
from byzantinemomentum_tpu.parallel.mesh import MODEL, WORKERS

__all__ = ["pairwise_distances_sharded", "shard_gar", "sharded_state_spec",
           "sharded_train_step", "sharded_train_multi", "COORDINATE_WISE"]

# GARs that act independently per coordinate: they shard over `d` with zero
# communication (SURVEY.md §5.7: "coordinate-wise GARs shard trivially over
# d; pairwise-distance GARs need a psum over d-shards").
COORDINATE_WISE = frozenset(
    {"average", "median", "trmean", "phocas", "meamed", "native-median"})


def pairwise_distances_sharded(g, mesh):
    """All-pairs Euclidean distances of the rows of `g: f32[n, d]` with `d`
    sharded along the mesh's "model" axis.

    Per shard: partial row-norms and partial Gram matrix (one MXU matmul),
    then one `psum` of (n,) + (n, n) over ICI. Semantics match
    `ops._common.pairwise_distances` ('dot' method): non-finite -> +inf,
    +inf diagonal.
    """
    return shard_map(
        _psum_pairwise, mesh=mesh,
        in_specs=P(None, MODEL), out_specs=P(None, None))(g)


def _psum_pairwise(g_local):
    """Shard-local body of the distributed pairwise-distance kernel: partial
    row-norms + partial Gram on this d-slice, psum over the model axis.
    (Single source of truth — the semantics must match
    `ops._common.pairwise_distances`.)"""
    sq = jax.lax.psum(jnp.sum(g_local * g_local, axis=1), MODEL)
    gram = jax.lax.psum(g_local @ g_local.T, MODEL)
    d2 = jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * gram, 0.0)
    d2 = jnp.where(jnp.isfinite(d2), d2, jnp.inf)
    n = g_local.shape[0]
    d2 = jnp.where(jnp.eye(n, dtype=bool), jnp.inf, d2)
    return jnp.sqrt(d2)


def shard_gar(gar, mesh, *, f, **kwargs):
    """Wrap a registered GAR into a d-sharded callable `(G) -> f32[d]`.

    Coordinate-wise rules run shard-locally. Krum-family rules compute the
    psum'd distance matrix, derive the (replicated) selection, and average
    the selected rows locally per shard.
    """
    if gar.name in COORDINATE_WISE:
        def kernel(g_local):
            return gar.unchecked(g_local, f=f, **kwargs)
        return shard_map(kernel, mesh=mesh,
                         in_specs=P(None, MODEL), out_specs=P(MODEL))

    if gar.name in ("krum", "native-krum"):
        def kernel(g_local):
            n = g_local.shape[0]
            dist = _psum_pairwise(g_local)
            scores = jnp.sum(jnp.sort(dist, axis=1)[:, :n - f - 1], axis=1)
            m = kwargs.get("m") or n - f - 2
            sel = jnp.argsort(scores, stable=True)[:m]
            return jnp.mean(g_local[sel], axis=0)

        return shard_map(kernel, mesh=mesh,
                         in_specs=P(None, MODEL), out_specs=P(MODEL))

    # Fallback: replicate (correct for any GAR; no d-sharding win)
    def kernel_replicated(g):
        return gar.unchecked(g, f=f, **kwargs)
    return kernel_replicated


def sharded_state_spec(state):
    """PartitionSpecs for a `TrainState` on a (workers, model) mesh: all
    d-dimensional buffers shard along "model"; scalars/counters/PRNG
    replicate. (BatchNorm state replicates — it is tiny.)"""
    d = state.theta.shape
    return TrainState(
        theta=P(MODEL),
        net_state=jax.tree.map(lambda _: P(), state.net_state),
        opt_state=jax.tree.map(
            lambda leaf: P(MODEL) if getattr(leaf, "shape", None) == d else P(),
            state.opt_state),
        momentum_server=P(MODEL),
        momentum_workers=P(None, MODEL),
        origin=P(MODEL) if state.origin.ndim else P(),
        past_grads=P(None, MODEL),
        past_norms=P(),
        past_count=P(),
        steps=P(),
        datapoints=P(),
        rng=P(),
    )


def _sharded_step_builder(step_fn, mesh, state_example, batch_spec):
    """Shared sharding setup for the single- and multi-step programs.

    The traced function is wrapped in `pallas_sort.disabled()`: Mosaic
    kernels cannot be auto-partitioned by the jit sharding propagator, so a
    multi-device trace must take the coordinate-wise GARs' jnp fallbacks.
    """
    from byzantinemomentum_tpu.ops import pallas_sort

    spec = sharded_state_spec(state_example)
    state_shardings = jax.tree.map(
        lambda p: NamedSharding(mesh, p), spec,
        is_leaf=lambda x: isinstance(x, P))
    batch_sharding = NamedSharding(mesh, batch_spec)
    lr_sharding = NamedSharding(mesh, P())

    def traced(*args):
        with pallas_sort.disabled():
            return step_fn(*args)

    return jax.jit(
        traced,
        in_shardings=(state_shardings, batch_sharding, batch_sharding,
                      lr_sharding),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,))


def sharded_train_step(engine, mesh, state_example):
    """Compile the engine's training step for a multi-chip mesh.

    Batches shard along "workers" (each chip computes its workers' gradients
    — the reference's sequential honest phase, now data-parallel across
    chips); parameters and momentum shard along "model". XLA inserts the
    all-gather of gradient rows feeding the GAR and the collectives for the
    d-sharded update.

    Returns `step(state, xs, ys, lr) -> (state, metrics)` — a drop-in for
    `engine.train_step`.
    """
    return _sharded_step_builder(engine._train_step, mesh, state_example,
                                 P(WORKERS))


def sharded_train_multi(engine, mesh, state_example):
    """Multi-chip version of `engine.train_multi`: M fused steps per
    dispatch (`lax.scan`) with the same shardings as `sharded_train_step` —
    batches `xs: [M, S, B, ...]` shard along "workers" on their S axis.

    Returns `step(state, xs, ys, lrs) -> (state, stacked metrics)`.
    """
    return _sharded_step_builder(engine._train_multi, mesh, state_example,
                                 P(None, WORKERS))
