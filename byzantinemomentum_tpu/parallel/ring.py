"""Sequence/context parallelism: ring attention and Ulysses-style
all-to-all attention over a mesh "seq" axis.

The reference has no attention models at all (SURVEY.md §5.7), but
long-context scaling is a first-class axis of this framework: the
transformer family (`models/transformer.py`) can run with its sequence
dimension sharded across chips, using either

* **ring attention** (`ring_attention`) — K/V blocks rotate around the ring
  via `lax.ppermute` while each chip holds its Q chunk, accumulating the
  exact softmax with the online (max, sum) rescaling trick. Communication
  per step: one (B, H, Lc, Dh) block to the ring neighbor — bandwidth
  optimal over ICI, memory O(L/p) per chip.
* **Ulysses / all-to-all** (`ulysses_attention`) — `lax.all_to_all` swaps
  the head and sequence axes so each chip computes full-sequence attention
  for H/p heads, then swaps back. One collective in, one out; requires
  heads % p == 0.

Both are exact (not approximations) and are verified against dense local
attention in `tests/test_ring.py`.
"""

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "ulysses_attention", "dense_attention"]

_NEG = -1e30  # large-negative mask value (avoids -inf NaN propagation)


def _axis_size(axis_name):
    """`lax.axis_size` across jax versions (older releases lack it;
    `psum(1, axis)` constant-folds to the same concrete int)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def dense_attention(q, k, v, *, causal=True, base=0, key_mask=None):
    """Plain softmax attention `[B, H, L, Dh]` (single-device reference).

    `base` offsets the query positions relative to the key positions —
    used by the ring kernel for cross-block causal masks. `key_mask`
    (bool[Lk], True = usable) excludes key positions from the softmax —
    the dense counterpart of the ring kernel's `drop_blocks` peer-loss
    degradation, and its differential-test oracle.
    """
    dh = q.shape[-1]
    # Softmax statistics in f32 regardless of the input dtype (the usual
    # flash-attention accumulator rule); output cast back to q.dtype
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(
        jnp.float32(dh))
    if causal:
        qpos = base + jnp.arange(q.shape[2])[:, None]
        kpos = jnp.arange(k.shape[2])[None, :]
        scores = jnp.where(qpos >= kpos, scores, _NEG)
    if key_mask is not None:
        scores = jnp.where(key_mask[None, None, None, :], scores, _NEG)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v).astype(q.dtype)


def ring_attention(q, k, v, axis_name, *, causal=True, drop_blocks=None):
    """Exact blockwise attention with the sequence sharded over `axis_name`.

    Inputs are the LOCAL chunks `[B, H, Lc, Dh]` of the `[B, H, L, Dh]`
    arrays (L = p * Lc, chunk i holding positions [i*Lc, (i+1)*Lc)). Must
    run inside `shard_map` over a mesh with axis `axis_name`.

    Online-softmax accumulation: for each of the p ring steps, the chip
    scores its Q chunk against the currently-held K/V block, rescales its
    running (output, max, normalizer) triple, and forwards the block to the
    next ring neighbor via `ppermute`.

    `drop_blocks` (bool[p], True = lost) is the fault-injection hook
    (`faults/`, multi-host chaos testing): K/V blocks originating on a
    "lost" ring participant are excluded from the accumulation — the
    surviving chips compute exact softmax attention over the remaining
    positions (the oracle is `dense_attention` with the matching
    `key_mask`), instead of deadlocking or poisoning the statistics. A
    query whose every visible block is dropped degrades to a zero output
    (the normalizer floor below).
    """
    p = _axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, h, lc, dh = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(dh))
    perm = [(j, (j + 1) % p) for j in range(p)]

    qpos = me * lc + jnp.arange(lc)  # global positions of local queries

    def body(i, carry):
        o, m, l, k_cur, v_cur = carry
        src = (me - i) % p  # ring step i holds the block that started at src
        kpos = src * lc + jnp.arange(lc)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur) * scale
        if causal:
            mask = qpos[:, None] >= kpos[None, :]
        else:
            mask = jnp.ones((lc, lc), bool)
        if drop_blocks is not None:
            mask = mask & ~jnp.take(jnp.asarray(drop_blocks), src)
        scores = jnp.where(mask, scores, _NEG)
        block_max = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, block_max)
        alpha = jnp.exp(m - m_new)
        probs = jnp.where(mask, jnp.exp(scores - m_new[..., None]), 0.0)
        l = l * alpha + jnp.sum(probs, axis=-1)
        o = o * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", probs, v_cur)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return o, m_new, l, k_next, v_next

    # Derived from q (not fresh constants) so the shard_map varying-axis
    # checker sees the carry as device-varying from the start. Accumulators
    # are f32 whatever the input dtype (the body's f32 `scale` promotes the
    # statistics, so a low-precision carry would change type across
    # iterations); the output is cast back at the end.
    o0 = jnp.zeros_like(q, dtype=jnp.float32)
    m0 = jnp.full_like(q[..., 0], _NEG, dtype=jnp.float32)
    l0 = jnp.zeros_like(q[..., 0], dtype=jnp.float32)
    o, m, l, _, _ = lax.fori_loop(0, p, body, (o0, m0, l0, k, v))
    return (o / jnp.maximum(l, 1e-20)[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, *, causal=True):
    """All-to-all sequence parallelism (Ulysses): swap the sharded axis from
    sequence to heads, run full-sequence dense attention on H/p local heads,
    swap back. Inputs/outputs: local `[B, H, Lc, Dh]` chunks inside
    `shard_map`; requires `H % p == 0`.
    """
    p = _axis_size(axis_name)
    if q.shape[1] % p != 0:
        raise ValueError(
            f"ulysses_attention requires heads ({q.shape[1]}) divisible by "
            f"the sequence-axis size ({p})")

    def to_heads(x):
        # [B, H, Lc, Dh] -> [B, H/p, L, Dh]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_seq(x):
        # [B, H/p, L, Dh] -> [B, H, Lc, Dh]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    out = dense_attention(to_heads(q), to_heads(k), to_heads(v),
                          causal=causal)
    return to_seq(out)
