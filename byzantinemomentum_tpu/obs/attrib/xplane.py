"""xplane trace parsing — the core of `scripts/trace_opstats.py`, promoted
into a library the attribution pipeline (and that script) share.

A `jax.profiler` trace directory holds one `*.xplane.pb` per capture under
`plugins/profile/<ts>/`. The proto (`tensorflow.tsl.profiler.protobuf.
xplane_pb2`) is a forest of *planes* (one per device, plus the host), each
holding *lines* (threads/streams) of timestamped events whose names and
stats reference per-plane metadata tables. Two layouts matter here:

* **TPU** — device planes named `/device:TPU:<n>` with an `"XLA Ops"` line;
  each event is one HLO op execution, and its stats usually carry the HLO
  metadata scope path (`tf_op`) the compiler recorded.
* **CPU** — one `/host:CPU` plane whose `tf_XLATfrtCpuClient/...` thread
  lines carry the HLO op executions (events named by HLO *instruction*,
  with `hlo_module`/`program_id` stats but no scope path — phase identity
  comes from joining against the compiled module's text, `phases.py`).

Protobuf backend: this library parses with whatever backend the process
already has — the default (upb) parses raw xplanes fine and ~35x faster
than pure python, which matters because the CPU runtime traces every
intra-op thread-pool sub-task (a conv-heavy chunk reaches hundreds of
MB). The historic pure-python forcing (the tensorboard profile plugin's
converter is broken against this image's TF build) lives only in the
`scripts/trace_opstats.py` CLI, where the original workaround shipped; a
parse failure here names the env knob.
"""

import glob
import os
import pathlib

__all__ = ["OpEvent", "load_xspace", "find_xplane", "device_planes",
           "op_events", "aggregate_ops", "window_span"]

# Substrings identifying lines/planes that carry HLO op executions
_TPU_OPS_LINE = "XLA Ops"
_CPU_EXEC_LINE_PREFIX = "tf_XLA"
# Event-stat keys that may carry the HLO-metadata scope path on device
# traces (tensorboard's converter calls it tf_op)
_SCOPE_STATS = ("tf_op", "tf_op_name", "hlo_op_name")
# Thread-line events that are executor bookkeeping, not HLO ops
_NON_OPS = ("ThreadpoolListener", "ThunkExecutor", "ParseArguments")


class OpEvent:
    """One HLO op execution: name, duration (ms), optional scope path and
    module, plus the raw [start, end) ps timestamps for span math."""

    __slots__ = ("name", "dur_ms", "scope", "module", "start_ps", "end_ps")

    def __init__(self, name, dur_ms, scope=None, module=None,
                 start_ps=0, end_ps=0):
        self.name = name
        self.dur_ms = dur_ms
        self.scope = scope
        self.module = module
        self.start_ps = start_ps
        self.end_ps = end_ps

    def __repr__(self):
        return (f"OpEvent({self.name!r}, {self.dur_ms:.4f}ms, "
                f"scope={self.scope!r})")


def find_xplane(trace_dir):
    """Newest `*.xplane.pb` under a `start_trace` directory (None when the
    capture never completed)."""
    pattern = os.path.join(str(trace_dir), "plugins/profile/*/*.xplane.pb")
    paths = sorted(glob.glob(pattern))
    return pathlib.Path(paths[-1]) if paths else None


# Refuse to parse captures above this size (override: BMT_XPLANE_MAX_MB).
# Oversized windows — one that caught an XLA compile, or a CPU capture of
# a conv-heavy program (the CPU runtime traces every intra-op thread-pool
# sub-task: one big conv/copy is thousands of events per execution) —
# would stall the caller for minutes and gigabytes; a live training run
# must degrade to a warning instead. Raising the cap is an explicit
# opt-in to that cost.
_MAX_XPLANE_MB = 128.0


def load_xspace(trace_dir):
    """Parse the trace directory's newest xplane into an `XSpace` proto.

    Raises FileNotFoundError when no capture exists, ImportError when the
    xplane proto bindings are absent (no TF in the environment), and
    ValueError for captures past the size cap — all conditions the caller
    decides how to degrade on.
    """
    path = pathlib.Path(trace_dir)
    if path.is_file():
        xplane = path
    else:
        xplane = find_xplane(path)
        if xplane is None:
            raise FileNotFoundError(
                f"no *.xplane.pb under {str(path)!r} — did stop_trace() "
                f"run?")
    size_mb = xplane.stat().st_size / 2**20
    cap_mb = float(os.environ.get("BMT_XPLANE_MAX_MB", _MAX_XPLANE_MB))
    if size_mb > cap_mb:
        raise ValueError(
            f"{str(xplane)!r} is {size_mb:.0f} MB (cap {cap_mb:.0f} MB, "
            f"BMT_XPLANE_MAX_MB overrides) — a window this size traced a "
            f"compile or a while-loop-heavy program (e.g. an adaptive "
            f"attack's line search on the CPU backend)")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    space = xplane_pb2.XSpace()
    try:
        space.ParseFromString(xplane.read_bytes())
    except Exception as err:  # bmt: noqa[BMT-E05] protobuf backends raise backend-specific decode errors; re-raise with the known workaround named
        raise ValueError(
            f"cannot parse {str(xplane)!r} under this protobuf backend "
            f"({err}); retry with "
            f"PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python") from err
    return space


def device_planes(space):
    """The planes carrying HLO op executions, most specific first:
    `/device:*` planes when present (TPU/GPU), else the `/host:CPU`
    plane (the CPU backend runs its thunks on host threads)."""
    planes = [p for p in space.planes if p.name.startswith("/device:")]
    if planes:
        return planes
    return [p for p in space.planes if p.name == "/host:CPU"]


def _stat_value(stat, stat_meta):
    """A stat's value: strings come back as-is; `ref_value` indirects into
    the plane's stat-metadata table (how the CPU runtime interns HLO op
    and module names)."""
    if stat.str_value:
        return stat.str_value
    if stat.ref_value:
        meta = stat_meta.get(stat.ref_value)
        return meta.name if meta is not None else None
    for field in ("int64_value", "uint64_value", "double_value"):
        value = getattr(stat, field)
        if value:
            return value
    return None


def _event_stats(event, stat_meta):
    """{stat name: value} of one event."""
    out = {}
    for stat in event.stats:
        meta = stat_meta.get(stat.metadata_id)
        if meta is None:
            continue
        out[meta.name] = _stat_value(stat, stat_meta)
    return out


def _op_lines(plane):
    """The plane's lines whose events are HLO op executions."""
    lines = list(plane.lines)
    named = {line.name: line for line in lines}
    if _TPU_OPS_LINE in named:
        return [named[_TPU_OPS_LINE]]
    return [line for line in lines
            if line.name.startswith(_CPU_EXEC_LINE_PREFIX)]


def op_events(space, planes=None):
    """Every HLO op execution in the trace, as `OpEvent`s.

    `planes`: restrict to planes whose name contains this string (e.g.
    `"/device:TPU:0"`); default = every device plane (`device_planes`).
    """
    if planes is not None:
        selected = [p for p in space.planes if planes in p.name]
    else:
        selected = device_planes(space)
    out = []
    for plane in selected:
        event_meta = dict(plane.event_metadata.items())
        stat_meta = dict(plane.stat_metadata.items())
        for line in _op_lines(plane):
            line_start = line.timestamp_ns * 1000  # -> ps
            for event in line.events:
                meta = event_meta.get(event.metadata_id)
                name = meta.name if meta is not None else ""
                if not name or any(name.startswith(p) for p in _NON_OPS):
                    continue
                stats = _event_stats(event, stat_meta)
                scope = None
                for key in _SCOPE_STATS:
                    value = stats.get(key)
                    if isinstance(value, str) and value:
                        scope = value
                        break
                start = line_start + event.offset_ps
                out.append(OpEvent(
                    name=name,
                    dur_ms=event.duration_ps / 1e9,
                    scope=scope,
                    module=stats.get("hlo_module"),
                    start_ps=start,
                    end_ps=start + event.duration_ps,
                ))
    return out


def aggregate_ops(space_or_dir, planes=None):
    """Per-op totals `{name: (total_ms, count)}` — the
    `scripts/trace_opstats.py` aggregation, as a library call. Accepts a
    trace directory/path or an already-parsed XSpace."""
    space = (space_or_dir if hasattr(space_or_dir, "planes")
             else load_xspace(space_or_dir))
    totals = {}
    for event in op_events(space, planes=planes):
        ms, count = totals.get(event.name, (0.0, 0))
        totals[event.name] = (ms + event.dur_ms, count + 1)
    return totals


def window_span(events):
    """(busy_ms, span_ms) of a list of `OpEvent`s: busy is the union of
    the event intervals (overlapping executor threads do not double-count),
    span is last-end minus first-start — their difference is the time the
    device(s) sat idle waiting on the host inside the traced window."""
    if not events:
        return 0.0, 0.0
    intervals = sorted((e.start_ps, e.end_ps) for e in events)
    busy_ps = 0
    cur_start, cur_end = intervals[0]
    for start, end in intervals[1:]:
        if start > cur_end:
            busy_ps += cur_end - cur_start
            cur_start, cur_end = start, end
        else:
            cur_end = max(cur_end, end)
    busy_ps += cur_end - cur_start
    span_ps = max(e.end_ps for e in events) - intervals[0][0]
    return busy_ps / 1e9, span_ps / 1e9
