"""Phase-attributed device profiling (PR 6).

Turns one-off trace archaeology (`PERF_NOTES.md`'s hand-transcribed
numbers) into a first-class per-run observability layer:

* **xplane** (`xplane.py`) — the profiler-trace parsing core promoted out
  of `scripts/trace_opstats.py` (that script is now a thin CLI over it):
  per-HLO-op events/durations on TPU `"XLA Ops"` lines and CPU
  `TfrtCpuClient` thread lines alike, with a size cap
  (`BMT_XPLANE_MAX_MB`) so a mis-captured window degrades to a warning
  instead of stalling a live run.
* **phases** (`phases.py`) — scope-path -> engine-phase extraction (the
  `jax.named_scope` annotations in `engine/step.py`: `honest`, `attack`,
  `gar`/`gar_masked`/`gar_diag`, `update`, `metrics`), the instruction ->
  scope join for CPU traces (compiled-module text), and the MXU /
  relayout / memory op-class bucketer.
* **attribution** (`attribution.py`) — the per-run `attribution.json`
  builder: per-phase ms/step, MFU and distance-to-floor (the
  `obs/perf.py` logical-FLOP recipe), relayout ms and host-gap fraction.

Driver surface: `cli/attack.py --attribution` captures a deterministic
warm-up-then-one-chunk window and attributes it; the SIGUSR1 live window
auto-attributes too. `scripts/bench_compare.py` gates attribution
artifacts so relayout/host-gap regrowth fails CI instead of silently
eating a packing win.

Import discipline: like the rest of `obs/`, nothing here imports jax (or
the xplane proto) at module scope.
"""

from byzantinemomentum_tpu.obs.attrib.attribution import (  # noqa: F401
    ATTRIBUTION_NAME,
    attribute_trace,
    load_attribution,
    write_attribution,
)
from byzantinemomentum_tpu.obs.attrib.phases import (  # noqa: F401
    OP_CLASSES,
    PHASES,
    op_class_of,
    phase_of,
    scope_map_from_hlo,
)
from byzantinemomentum_tpu.obs.attrib import xplane  # noqa: F401

__all__ = [
    "ATTRIBUTION_NAME", "attribute_trace", "load_attribution",
    "write_attribution", "OP_CLASSES", "PHASES", "op_class_of", "phase_of",
    "scope_map_from_hlo", "xplane",
]
