"""Build one run's `attribution.json` out of a profiler trace window.

The artifact answers, per step, the questions PERF_NOTES.md used to answer
by hand-driving `scripts/trace_opstats.py` and transcribing prose: where
the device time goes by engine phase (`honest`/`attack`/`gar*`/`update`/
`metrics`, from the `jax.named_scope` annotations), how much of it is
relayout data movement (the r5 packing win's regression mode), how long
the device sat idle on the host inside the window, and how far the step
is from its MXU floor.

Schema (all times ms/step, every field present — `null` when unknown):

    {"kind": "attribution", "backend": ..., "device_kind": ...,
     "steps": N, "phases": {phase: {"ms": float, "ops": int}},
     "op_classes": {"mxu"|"relayout"|"memory": float},
     "device_ms": float,        # union of device-op intervals
     "host_gap_ms": float,      # window span - device busy
     "host_gap_fraction": float,
     "total_ms": float,         # device_ms + host_gap_ms == span/steps
     "unattributed_ms": float,  # device ops with no phase identity
     "flops_per_step": float|null, "peak_flops": float|null,
     "mfu": float|null, "mxu_floor_ms": float|null,
     "distance_to_floor": float|null}   # total_ms / mxu_floor_ms

The phase dict always carries every engine phase plus `"other"` (device
ops outside any named scope) and `"host"` (the gap), so
`sum(p["ms"]) == total_ms` — the invariant the acceptance test checks
against the telemetry `device_step_ms` gauge.
"""

import json
import pathlib

from byzantinemomentum_tpu.obs.attrib import phases as phases_mod
from byzantinemomentum_tpu.obs.attrib import xplane

__all__ = ["ATTRIBUTION_NAME", "attribute_trace", "write_attribution",
           "load_attribution"]

ATTRIBUTION_NAME = "attribution.json"


def attribute_trace(trace_dir, steps, *, hlo_text=None, flops_per_step=None,
                    peak_flops=None, backend=None, device_kind=None,
                    planes=None):
    """Attribute one captured trace window to phases and op classes.

    Args:
      trace_dir: the directory passed to `jax.profiler.start_trace` (or a
        direct `.xplane.pb` path, or a parsed XSpace).
      steps: training steps the traced window executed (divides totals).
      hlo_text: optimized HLO text of the traced program
        (`compiled.as_text()`) — the instruction->scope join for backends
        whose traces carry no scope stat (CPU). TPU traces attribute from
        their own `tf_op` stats and may pass None.
      flops_per_step / peak_flops: the `obs/perf.py` logical-FLOP recipe
        and chip peak; both optional (MFU/floor fields go null).
    """
    steps = max(1, int(steps))
    space = (trace_dir if hasattr(trace_dir, "planes")
             else xplane.load_xspace(trace_dir))
    events = xplane.op_events(space, planes=planes)
    scope_map = phases_mod.scope_map_from_hlo(hlo_text) if hlo_text else {}
    # Fallback join by instruction BASE name (`dot.7` -> `dot`): numeric
    # suffixes drift between the traced compilation and a re-lowered copy
    # of the program; a base name maps to a phase only while every
    # same-base instruction agrees (ambiguity -> unattributed, never a
    # silent mis-bucket).
    _AMBIG = object()
    base_phase = {}
    for name, scope in scope_map.items():
        base = name.split(".", 1)[0]
        phase = phases_mod.phase_of(scope)
        if base_phase.setdefault(base, phase) != phase:
            base_phase[base] = _AMBIG

    phase_ms = {name: 0.0 for name in phases_mod.PHASES}
    phase_ms["other"] = 0.0
    phase_ops = {name: 0 for name in phase_ms}
    class_ms = {name: 0.0 for name in phases_mod.OP_CLASSES}
    unattributed = 0.0
    for event in events:
        scope = event.scope or scope_map.get(event.name)
        phase = phases_mod.phase_of(scope)
        if phase is None and scope is None:
            fallback = base_phase.get(event.name.split(".", 1)[0])
            if fallback is not _AMBIG:
                phase = fallback
        if phase is None:
            phase = "other"
            unattributed += event.dur_ms
        phase_ms[phase] += event.dur_ms
        phase_ops[phase] += 1
        class_ms[phases_mod.op_class_of(event.name)] += event.dur_ms

    busy_ms, span_ms = xplane.window_span(events)
    host_gap_ms = max(0.0, span_ms - busy_ms)
    # The union of intervals (busy) is what the device actually worked;
    # overlapping executor threads can make the naive duration sum exceed
    # it — scale the per-phase buckets so they tile the busy time and the
    # artifact's invariant sum(phases) == total holds exactly.
    raw_total = sum(phase_ms.values())
    scale = (busy_ms / raw_total) if raw_total > 0 else 0.0
    phase_ms = {k: v * scale for k, v in phase_ms.items()}
    class_ms = {k: v * scale for k, v in class_ms.items()}
    unattributed *= scale

    per_step = lambda ms: ms / steps  # noqa: E731

    phases_out = {
        name: {"ms": per_step(ms), "ops": phase_ops[name]}
        for name, ms in phase_ms.items()
    }
    phases_out["host"] = {"ms": per_step(host_gap_ms), "ops": 0}
    device_ms = per_step(busy_ms)
    total_ms = per_step(busy_ms + host_gap_ms)

    mfu = None
    mxu_floor_ms = None
    distance = None
    if flops_per_step and peak_flops:
        mxu_floor_ms = float(flops_per_step) / float(peak_flops) * 1e3
        if total_ms > 0:
            mfu = mxu_floor_ms / total_ms
            distance = total_ms / mxu_floor_ms
    return {
        "kind": "attribution",
        "backend": backend,
        "device_kind": device_kind,
        "steps": steps,
        "phases": phases_out,
        "op_classes": {k: per_step(v) for k, v in class_ms.items()},
        "device_ms": device_ms,
        "host_gap_ms": per_step(host_gap_ms),
        "host_gap_fraction": (host_gap_ms / (busy_ms + host_gap_ms)
                              if busy_ms + host_gap_ms > 0 else 0.0),
        "total_ms": total_ms,
        "unattributed_ms": per_step(unattributed),
        "flops_per_step": flops_per_step,
        "peak_flops": peak_flops,
        "mfu": mfu,
        "mxu_floor_ms": mxu_floor_ms,
        "distance_to_floor": distance,
    }


def write_attribution(directory, attribution, name=ATTRIBUTION_NAME):
    """Write the artifact (stable key order for diffable artifacts)."""
    path = pathlib.Path(directory) / name
    path.write_text(json.dumps(attribution, indent=2, sort_keys=True)
                    + "\n")
    return path


def load_attribution(directory, name=ATTRIBUTION_NAME):
    """The run's attribution artifact, or None when absent/torn."""
    path = pathlib.Path(directory)
    if path.is_dir():
        path = path / name
    try:
        data = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None
