"""Phase and op-class bucketing of HLO op executions.

Phase identity comes from the `jax.named_scope` annotations the engine
wraps its phases in (`engine/step.py`): the compiler threads the scope into
every instruction's HLO metadata `op_name`, so a path like

    jit(jitted)/jit(main)/while/body/honest/conv_general_dilated

attributes to `honest`. TPU traces carry that path per event (`tf_op`
stat); CPU traces do not, so `scope_map_from_hlo` rebuilds the
instruction-name -> scope join from the compiled module's text (the
optimized HLO keeps per-instruction `metadata={op_name="..."}`).

Attribution precedence is OUTERMOST-first: an adaptive attack's inner
line-search defense calls nest `attack/.../gar/...` and belong to the
attack (matching the PERF_NOTES convention "attack incl. its defense
call"); the server's own aggregation carries `gar` (or its `gar_masked` /
`gar_diag` variants) without an enclosing `attack`.

Op classes answer the *bandwidth-floor* questions independently of phase:
MXU work (convs/dots), `copy`/`reshape`/`transpose` relayouts (the r5
packing win's failure mode — regrowth is a regression), and everything
else (memory-bound fusions, reductions, RNG).
"""

import re

__all__ = ["PHASES", "OP_CLASSES", "phase_of", "op_class_of",
           "scope_map_from_hlo"]

# The engine's named scopes (engine/step.py), most specific first; the
# order only matters for documentation — matching is per path segment.
PHASES = ("honest", "attack", "gar_masked", "gar_diag", "gar", "update",
          "metrics")

OP_CLASSES = ("mxu", "relayout", "memory")

_PHASE_SET = frozenset(PHASES)

# HLO opcodes (and fusion-name stems) that run on the MXU
_MXU_STEMS = ("convolution", "conv", "dot", "cudnn", "gemm")
# Pure data-movement ops: the relayout budget (PERF_NOTES r5: conv-boundary
# copy/reshape chains were the ~5 ms/step failure mode packing removed)
_RELAYOUT_STEMS = ("copy", "reshape", "transpose", "bitcast")


def phase_of(scope):
    """The phase of one HLO-metadata scope path (None when no engine
    phase appears in it). Outermost match wins (see module docstring)."""
    if not scope:
        return None
    for segment in scope.split("/"):
        if segment in _PHASE_SET:
            return segment
    return None


def _stem(op_name):
    """`broadcast_add_fusion` -> its last meaningful stem tokens;
    `dot.7`/`copy.3` -> the opcode."""
    return re.split(r"[.\d]", op_name, maxsplit=1)[0].lower()


def op_class_of(op_name):
    """Coarse hardware class of one HLO op/fusion name: "mxu" for
    convs/dots, "relayout" for pure data movement, "memory" otherwise
    (elementwise/reduction fusions are bandwidth-bound on TPU)."""
    name = op_name.lower()
    stem = _stem(name)
    for needle in _RELAYOUT_STEMS:
        if stem.startswith(needle):
            return "relayout"
    for needle in _MXU_STEMS:
        if needle in name:
            return "mxu"
    return "memory"


# One optimized-HLO instruction line:  %copy.3 = f32[...] copy(...),
# ... metadata={op_name="jit(f)/honest/..." ...}
_INSTR = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*.*?"
    r"metadata=\{[^}]*?op_name=\"(?P<op_name>[^\"]*)\"")


def scope_map_from_hlo(hlo_text):
    """{instruction name: scope path} out of a compiled module's text
    (`compiled.as_text()`), the join CPU traces need (their events are
    named by HLO instruction with no scope stat).

    A fusion's own metadata carries ONE representative op_name; ops folded
    into it lose their identity — acceptable, because XLA fuses within a
    scope far more often than across (and the engine's phases are sized
    way above fusion granularity).
    """
    scopes = {}
    for line in hlo_text.splitlines():
        if "op_name=" not in line:
            continue
        m = _INSTR.match(line)
        if m:
            scopes[m.group("name")] = m.group("op_name")
    return scopes
