"""One-page text summary of a run directory's telemetry.

`render_report(run_dir)` digests `telemetry.jsonl` + `heartbeat.json` (+
`config.json` when present) into the questions an operator actually asks
of a run: is it alive, how fast is it going, what did compiles/checkpoints
cost, and did anything bad (fault, rollback, restart) happen on the
timeline. Pure stdlib — usable over any run directory, live or dead, with
no accelerator stack.

Entry points: `scripts/obs_report.py <run_dir>` and
`python -m byzantinemomentum_tpu.obs <run_dir>`.
"""

import argparse
import json
import pathlib
import time

from byzantinemomentum_tpu.obs.heartbeat import read_heartbeat
from byzantinemomentum_tpu.obs.recorder import load_records

__all__ = ["render_report", "main"]

# Events worth listing individually on the one-pager (the resilience +
# forensics timeline); everything else is summarized by count.
_TIMELINE_EVENTS = ("restart", "rollback", "divergence_giveup", "retry",
                    "checkpoint_invalid", "profiler_window", "attribution",
                    "run_start", "run_end", "suspect_worker",
                    "suspect_cleared", "serve_trace_snapshot",
                    "health_anomaly", "health_cleared", "health_flag",
                    "health_blackbox", "slo_burn", "slo_ok")


def _fmt_seconds(seconds):
    if seconds is None:
        return "?"
    if seconds < 1e-3:
        return f"{seconds * 1e6:.0f}us"
    if seconds < 1.0:
        return f"{seconds * 1e3:.1f}ms"
    if seconds < 120.0:
        return f"{seconds:.2f}s"
    return f"{seconds / 60.0:.1f}min"


def _stats(values):
    values = [float(v) for v in values]
    return (min(values), sum(values) / len(values), max(values))


def _load_attribution(run_dir):
    """The run's `attribution.json` (or None) — duplicated tiny reader so
    the report stays importable without the obs.attrib package loaded."""
    try:
        data = json.loads((pathlib.Path(run_dir)
                           / "attribution.json").read_text())
    except (OSError, ValueError):
        return None
    return data if (isinstance(data, dict)
                    and data.get("kind") == "attribution") else None


def _attribution_lines(att):
    """Render the "perf attribution" section: per-phase ms/step ranked by
    cost, the op-class split, and the floor distance when known."""
    lines = ["perf attribution: "
             f"{att.get('total_ms', 0.0):.3f} ms/step over "
             f"{att.get('steps', '?')} traced steps "
             f"(backend {att.get('backend', '?')})"]
    phases = att.get("phases") or {}
    ranked = sorted(phases.items(),
                    key=lambda kv: -float(kv[1].get("ms", 0.0)))
    for name, entry in ranked:
        ms = float(entry.get("ms", 0.0))
        if ms <= 0.0:
            continue
        total = float(att.get("total_ms") or 0.0)
        share = f" ({ms / total * 100.0:.1f}%)" if total > 0 else ""
        lines.append(f"  {name:<12} {ms:9.4f} ms/step{share}"
                     f"  x{entry.get('ops', 0)}")
    classes = att.get("op_classes") or {}
    if classes:
        lines.append("  op classes: " + ", ".join(
            f"{k}={float(v):.4f}ms" for k, v in sorted(classes.items())))
    extras = []
    if att.get("host_gap_fraction") is not None:
        extras.append(f"host gap {float(att['host_gap_fraction']) * 100:.1f}%")
    if att.get("mfu") is not None:
        extras.append(f"MFU {float(att['mfu']):.3f}")
    if att.get("distance_to_floor") is not None:
        extras.append(f"{float(att['distance_to_floor']):.1f}x off the "
                      f"MXU floor")
    if extras:
        lines.append("  " + ", ".join(extras))
    return lines


def render_report(run_dir):
    """The report as one string (trailing newline included)."""
    run_dir = pathlib.Path(run_dir)
    records = load_records(run_dir)
    heartbeat = read_heartbeat(run_dir)
    lines = [f"== Run report: {run_dir} =="]

    config = None
    try:
        config = json.loads((run_dir / "config.json").read_text())
    except (OSError, ValueError):
        pass  # absent or torn config.json: report without the summary line
    if config:
        keys = ("model", "dataset", "gar", "attack", "nb_workers",
                "nb_decl_byz", "nb_real_byz", "nb_steps")
        summary = ", ".join(f"{k}={config[k]}" for k in keys if k in config)
        lines.append(f"config: {summary}")

    if heartbeat is None:
        lines.append("heartbeat: (none)")
    else:
        age = time.time() - float(heartbeat.get("updated", 0.0))
        fields = [f"step {heartbeat.get('step', '?')}",
                  f"age {_fmt_seconds(age)}",
                  f"pid {heartbeat.get('pid', '?')}"]
        for key, unit in (("steps_per_sec", " steps/s"),
                          ("device_step_ms", " ms/step (device)"),
                          ("rss_mb", " MiB RSS"), ("mfu", " MFU")):
            value = heartbeat.get(key)
            if isinstance(value, (int, float)):
                fields.append(f"{value:.3g}{unit}")
        if heartbeat.get("status"):
            fields.append(f"status={heartbeat['status']}")
        lines.append("heartbeat: " + ", ".join(fields))

    # Perf attribution (obs/attrib): the per-phase view of the traced
    # chunk, read from the run's attribution.json artifact (rendered even
    # for telemetry-less directories — the artifact stands on its own)
    attribution = _load_attribution(run_dir)
    if attribution is not None:
        lines.extend(_attribution_lines(attribution))

    # Fleet health (obs/trace/fleet.py): cluster run dirs — a cluster
    # manifest or per-host telemetry streams — get the joined,
    # clock-aligned fleet timeline (fired faults, host deaths, liveness
    # transitions, agreed restarts as ordered events)
    from byzantinemomentum_tpu.obs.trace import render_fleet_report
    fleet_lines = render_fleet_report(run_dir)
    if fleet_lines:
        lines.extend(fleet_lines)

    # Incident bundles (obs/trace/incident.py): every SLO-burn /
    # arc-death / failover / straggler-kill capture in the directory
    # (process-local `incidents/` plus per-shard and per-host trees),
    # each replayed into its ordered causal story — burn edge ->
    # dominant hop -> membership — with the evidence cells it froze.
    # Rendered before the telemetry early-return: a fleet resdir holds
    # bundles without any top-level telemetry.jsonl
    from byzantinemomentum_tpu.obs.trace import render_incidents
    incident_lines = render_incidents(run_dir)
    if incident_lines:
        lines.extend(incident_lines)

    if not records:
        # A telemetry-less directory can still hold a flight recording
        # (e.g. a --no-telemetry run's blackbox): render it standalone
        from byzantinemomentum_tpu.obs.health import load_blackbox
        blackbox = load_blackbox(run_dir)
        if blackbox is not None:
            lines.append(f"health: blackbox [{blackbox.get('reason')}] "
                         f"ring x{len(blackbox.get('ring') or [])}")
        lines.append("telemetry: (no telemetry.jsonl)")
        return "\n".join(lines) + "\n"
    lines.append(f"telemetry: {len(records)} records")

    counters = {}
    for record in records:
        if record.get("kind") == "counter":
            counters[record.get("name")] = record.get("value")
    if counters:
        lines.append("counters: " + ", ".join(
            f"{name}={value}" for name, value in sorted(counters.items())))

    spans = {}
    for record in records:
        if record.get("kind") == "span" and "dur" in record:
            spans.setdefault(record.get("name"), []).append(record["dur"])
    if spans:
        lines.append("spans:")
        for name, durs in sorted(spans.items(),
                                 key=lambda kv: -sum(kv[1])):
            lo, mean, hi = _stats(durs)
            lines.append(f"  {name:<20} x{len(durs):<4} "
                         f"total {_fmt_seconds(sum(durs)):<8} "
                         f"mean {_fmt_seconds(mean):<8} "
                         f"max {_fmt_seconds(hi)}")

    gauges = {}
    for record in records:
        if record.get("kind") == "gauge" and "value" in record:
            gauges.setdefault(record.get("name"), []).append(record["value"])
    if gauges:
        lines.append("gauges:")
        for name, values in sorted(gauges.items()):
            lo, mean, hi = _stats(values)
            lines.append(f"  {name:<20} x{len(values):<4} "
                         f"min {lo:.4g}  mean {mean:.4g}  max {hi:.4g}")

    # Aggregation forensics (obs/forensics.py): the run's standing
    # suspects and suspicion scores, read from the final summary event,
    # plus the flag/clear edge counts
    summary = None
    edges = {"suspect_worker": 0, "suspect_cleared": 0}
    for record in records:
        if record.get("kind") != "event":
            continue
        if record.get("name") == "forensics_summary":
            summary = record.get("data") or {}
        elif record.get("name") in edges:
            edges[record["name"]] += 1
    if summary is not None or any(edges.values()):
        suspects = (summary or {}).get("suspects") or []
        parts = [f"suspects={suspects if suspects else 'none'}",
                 f"flagged x{edges['suspect_worker']}",
                 f"cleared x{edges['suspect_cleared']}"]
        scores = (summary or {}).get("suspicion")
        if scores:
            worst = max(range(len(scores)), key=lambda w: scores[w])
            parts.append(f"max suspicion {scores[worst]:.3g} "
                         f"(worker {worst})")
        lines.append("forensics: " + ", ".join(parts))

    # Numerics flight recorder (obs/health): the run's anomaly story from
    # the health_summary event + edge counts, and the blackbox dump's
    # coordinates when one was written
    health = None
    health_edges = {"health_anomaly": 0, "health_cleared": 0}
    for record in records:
        if record.get("kind") != "event":
            continue
        if record.get("name") == "health_summary":
            health = record.get("data") or {}
        elif record.get("name") in health_edges:
            health_edges[record["name"]] += 1
    from byzantinemomentum_tpu.obs.health import load_blackbox
    blackbox = load_blackbox(run_dir)
    if health is not None or any(health_edges.values()) \
            or blackbox is not None:
        parts = [f"anomalies x{health_edges['health_anomaly']}",
                 f"cleared x{health_edges['health_cleared']}"]
        source = health or (blackbox or {}).get("summary") or {}
        if source.get("var_ratio_ewma") is not None:
            parts.append(f"var/norm EWMA {source['var_ratio_ewma']:.3g}")
        last = source.get("last_anomaly")
        if last:
            parts.append(f"last anomaly {last.get('channel')}"
                         f"@{last.get('step')} ({last.get('rule')})")
        if blackbox is not None:
            parts.append(f"blackbox [{blackbox.get('reason')}] "
                         f"ring x{len(blackbox.get('ring') or [])}")
        lines.append("health: " + ", ".join(parts))

    # Metrics plane + SLOs (obs/metrics): replay the run's metrics.jsonl
    # ring through the burn-rate evaluator — the evaluator is pure in the
    # snapshot stream, so the replayed alert story matches what the live
    # scraper emitted — and render the per-objective summary block
    from byzantinemomentum_tpu.obs.metrics import (BurnRateEvaluator,
                                                   load_snapshots)
    snapshots = load_snapshots(run_dir)
    if snapshots:
        evaluator = BurnRateEvaluator()
        for snapshot in snapshots:
            evaluator.observe(snapshot)
        slo_summary = evaluator.summary()
        merged = (snapshots[-1].get("merged") or {}).get("metrics") or {}
        lines.append(f"metrics: {len(snapshots)} snapshot(s), "
                     f"{len(merged)} merged metric(s), "
                     f"slo burns x{slo_summary['burn_events']} "
                     f"ok x{slo_summary['ok_events']}")
        for row in slo_summary["slos"]:
            state = "ALERTING" if row["alerting"] else "ok"
            burns = ", ".join(
                f"{label} {row[f'burn_{label}']:.3g}"
                if row[f"burn_{label}"] is not None else f"{label} -"
                for label in ("fast", "slow"))
            lines.append(f"  slo {row['name']:<20} [{state}] "
                         f"burn {burns} "
                         f"(objective {row['objective']}, "
                         f"threshold {row['burn_threshold']})")

    timeline = [r for r in records if r.get("kind") == "event"
                and r.get("name") in _TIMELINE_EVENTS]
    if timeline:
        t0 = records[0].get("t", 0.0)
        lines.append("timeline:")
        for record in timeline[-20:]:
            offset = _fmt_seconds(max(0.0, record.get("t", t0) - t0))
            data = record.get("data") or {}
            extra = " ".join(f"{k}={v}" for k, v in sorted(data.items()))
            lines.append(f"  +{offset:<9} {record.get('name')}"
                         + (f"  {extra}" if extra else ""))

    other = {}
    for record in records:
        if (record.get("kind") == "event"
                and record.get("name") not in _TIMELINE_EVENTS):
            other[record.get("name")] = other.get(record.get("name"), 0) + 1
    if other:
        lines.append("other events: " + ", ".join(
            f"{name} x{count}" for name, count in sorted(other.items())))
    return "\n".join(lines) + "\n"


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="obs_report",
        description="Render a one-page text summary of a run directory's "
                    "telemetry (telemetry.jsonl + heartbeat.json)")
    parser.add_argument("run_dir", help="result directory of one run")
    args = parser.parse_args(argv)
    run_dir = pathlib.Path(args.run_dir)
    if not run_dir.is_dir():
        print(f"obs_report: {run_dir} is not a directory")
        return 1
    print(render_report(run_dir), end="")
    return 0
