"""Live performance measurement: sliding-window throughput, device-honest
chunk timing, host RSS, and the FLOP/MFU helpers shared with `bench.py`.

Only `StepTimer` (numpy) and `logical_flops` (jax, imported lazily) touch
array libraries; everything else is stdlib so the supervisor and report
tooling can import this module without initializing a backend.
"""

import collections
import time

from byzantinemomentum_tpu.utils.misc import AccumulatedTimedContext

__all__ = ["SlidingRate", "StepTimer", "host_rss_mb", "peak_flops", "mfu",
           "logical_flops", "PEAK_BF16_FLOPS"]


class SlidingRate:
    """Steps/s over a sliding wall-clock window.

    Fed (time, step) pairs every dispatch (cheap: no device sync), read at
    telemetry sample points. The window makes the gauge reflect *current*
    throughput — a mid-run slowdown (thermal, neighbor, tunnel) shows up
    within `window_s` instead of being averaged into the whole run.
    """

    def __init__(self, window_s=30.0):
        self.window_s = float(window_s)
        self._points = collections.deque()

    def update(self, steps, now=None):
        now = time.monotonic() if now is None else now
        self._points.append((now, int(steps)))
        floor = now - self.window_s
        while len(self._points) > 2 and self._points[0][0] < floor:
            self._points.popleft()

    def rate(self):
        """Current steps/s, or None before two points span the window."""
        if len(self._points) < 2:
            return None
        (t0, s0), (t1, s1) = self._points[0], self._points[-1]
        if t1 <= t0:
            return None
        return (s1 - s0) / (t1 - t0)


class StepTimer:
    """Device-honest timing of one dispatched chunk, built on
    `AccumulatedTimedContext`'s sync-barrier protocol: the barrier is a
    tiny device→host transfer of a token array (the state's step counter),
    which cannot complete before the device has executed everything
    enqueued — `block_until_ready` can lie on tunneled backends, a host
    copy cannot (see `bench.py`'s measurement notes).

    Usage per measured chunk:
        timer.start(pre_dispatch_token)   # drains the pipeline, starts
        ... dispatch the chunk ...
        dt = timer.stop(post_dispatch_token)  # waits for it, stops
    """

    def __init__(self, label="device chunk"):
        self._token = None
        self._ctx = AccumulatedTimedContext(label=label, sync=self._sync)
        self._last_total = 0.0

    def _sync(self):
        if self._token is not None:
            import numpy as np
            np.asarray(self._token)

    def start(self, token):
        self._token = token
        self._ctx.__enter__()

    def stop(self, token):
        """Seconds the chunk took on-device (wall time between the two
        drained barriers)."""
        self._token = token
        self._ctx.__exit__(None, None, None)
        self._token = None
        elapsed = self._ctx.total - self._last_total
        self._last_total = self._ctx.total
        return elapsed

    @property
    def total(self):
        return self._ctx.total


def host_rss_mb():
    """Resident-set size of this process in MiB (Linux `/proc` fast path,
    `resource` fallback), or None when neither source is readable."""
    try:
        with open("/proc/self/status", encoding="ascii") as fd:
            for line in fd:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0  # kB -> MiB
    except OSError:
        pass
    try:
        import resource
        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return float(rss_kb) / 1024.0
    except (ImportError, AttributeError, OSError, ValueError):
        return None  # no resource module / platform without ru_maxrss


# ------------------------------------------------------------------------- #
# FLOPs / MFU — the single source of truth bench.py quotes

# Peak bf16 matmul throughput per chip, FLOP/s (public spec sheets). MFU is
# quoted against the bf16 peak for every mode (conservative for f32, which
# the MXU runs via multi-pass bf16 decomposition).
PEAK_BF16_FLOPS = (
    ("v6", 918e12), ("v5p", 459e12), ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5", 459e12), ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
)


def peak_flops(device_kind):
    """Peak bf16 FLOP/s for a `jax.Device.device_kind` string (None for
    chips not in the table — e.g. the CPU backend, where MFU is not a
    meaningful quote)."""
    kind = str(device_kind).lower()
    for tag, peak in PEAK_BF16_FLOPS:
        if tag in kind:
            return peak
    return None


def mfu(flops_per_step, steps_per_sec, peak):
    """Model FLOPs utilization in [0, 1] (None when any input is unknown)."""
    if not flops_per_step or not steps_per_sec or not peak:
        return None
    return float(flops_per_step) * float(steps_per_sec) / float(peak)


def flops_of_compiled(compiled):
    """Per-step logical FLOPs out of a compiled program's
    `cost_analysis()` (None when the backend reports nothing). XLA counts
    a `lax.scan` body ONCE, so multi-step fused programs already report
    per-step FLOPs (verified in bench.py: the M-step program reports the
    same count as the single-step one)."""
    try:
        cost = compiled.cost_analysis()
        if cost:
            cost = cost[0] if isinstance(cost, (list, tuple)) else cost
            return float(cost.get("flops", 0.0)) or None
    except Exception:  # bmt: noqa[BMT-E05] cost_analysis raises backend-specific types; a missing FLOP estimate must never crash a run
        pass
    return None


def logical_flops(fn, *args):
    """Logical FLOPs per step of jit-compilable `fn(*args)` — the count
    behind the telemetry MFU gauge, same recipe as `bench.py`'s headline.
    Lowers and compiles a THROWAWAY copy of the program (lowering only
    inspects avals, so donated buffers are untouched); returns None on any
    failure — flop counting is an estimate, never worth crashing a run.
    """
    try:
        import jax
        lower = getattr(fn, "lower", None)
        if lower is None:
            lower = jax.jit(fn).lower
        return flops_of_compiled(lower(*args).compile())
    except Exception:  # bmt: noqa[BMT-E05] lowering/compiling the throwaway copy fails in backend-specific ways; FLOP counting is an estimate, never worth crashing a run
        return None
