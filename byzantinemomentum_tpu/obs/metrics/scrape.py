"""Pull-based metrics collection: scraper thread, on-disk snapshot ring,
and the launcher-side exposition endpoint.

The ownership discipline (module note in `registry.py`): every process
owns its registry and answers `{"op": "metrics"}` on its existing
line-JSON port; THIS module is the one place aggregation happens. A
`MetricsScraper` runs inside the supervising process (the serve-fleet
launcher, the cluster launcher), polls every child endpoint each
interval, merges the payloads bucket-wise (`merge_payloads`), and
appends one windowed snapshot per scrape to `metrics.jsonl` next to
`heartbeat.json` — then hands the merged snapshot to the SLO evaluator
(`slo.py`), whose `slo_burn`/`slo_ok` edges ride the active telemetry
recorder.

`metrics.jsonl` is a RING, not a log: past `max_lines` lines the file
is rewritten keeping the newest `max_lines // 2` snapshots, through the
tmp + fsync + `os.replace` door every other run artifact uses — a
reader never sees a half-rotated file, and a SIGKILL mid-append tears
at most the final line, which `load_snapshots` skips (the
`load_records` stance). The append-vs-rotate interleaving contract is
pinned by the `metrics_rotate*` models in `analysis/schedule.py`.

`MetricsEndpoint` is the launcher-side exposition server for cluster
runs: training hosts expose their numbers through heartbeats (files,
not sockets — they must not grow a listening port mid-step), so the
launcher folds those into ITS registry and serves the merged view on a
loopback line-JSON port, same verb, same payload schema as a serve
shard. One scrape protocol end to end.

Stdlib-only, like the rest of `obs`.
"""

import json
import os
import pathlib
import socket
import socketserver
import threading
import time

from byzantinemomentum_tpu.obs import recorder
from byzantinemomentum_tpu.obs.metrics.registry import merge_payloads
from byzantinemomentum_tpu.utils.locking import NamedLock

__all__ = ["METRICS_NAME", "append_snapshot", "load_snapshots",
           "scrape_target", "MetricsScraper", "MetricsEndpoint"]

METRICS_NAME = "metrics.jsonl"

# Ring bound: at the scrapers' seconds-scale cadence this holds hours of
# history while keeping the file re-read (report tooling, SLO replay)
# trivially cheap.
DEFAULT_MAX_LINES = 4096


def append_snapshot(directory, snapshot, *, max_lines=DEFAULT_MAX_LINES,
                    name=METRICS_NAME):
    """Append one snapshot line; rotate the ring once past `max_lines`
    (keep the newest half, atomically). Returns the path written. The
    caller serializes appends (the scraper is the only writer); rotation
    itself is crash-safe — `os.replace` lands whole or not at all."""
    directory = pathlib.Path(directory)
    path = directory / name
    line = json.dumps(snapshot, ensure_ascii=False,
                      separators=(",", ":")) + "\n"
    with path.open("a", encoding="utf-8") as fd:
        fd.write(line)
        fd.flush()
        os.fsync(fd.fileno())
    try:
        with path.open("r", encoding="utf-8") as fd:
            lines = fd.readlines()
    except OSError:
        return path
    if len(lines) > max_lines:
        keep = lines[-(max_lines // 2):]
        tmp = path.with_name(path.name + ".tmp")
        with tmp.open("w", encoding="utf-8") as fd:
            fd.writelines(keep)
            fd.flush()
            os.fsync(fd.fileno())
        os.replace(tmp, path)
    return path


def load_snapshots(path, name=METRICS_NAME):
    """Parse a `metrics.jsonl` (file path or run directory) into a list
    of snapshot dicts, oldest first, skipping unparsable lines — a
    SIGKILL can tear the final one. [] for a missing file."""
    path = pathlib.Path(path)
    if path.is_dir():
        path = path / name
    snapshots = []
    try:
        text = path.read_text(encoding="utf-8")
    except OSError:
        return snapshots
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            snapshot = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(snapshot, dict):
            snapshots.append(snapshot)
    return snapshots


def scrape_target(host, port, timeout=5.0):
    """One metrics pull over line JSON: returns the payload dict, or
    raises OSError/ValueError — the caller decides whether a dead
    target is an error or a gap (the scraper records it as a gap: a
    dead shard's counters simply stop contributing, exactly as its
    traffic did)."""
    with socket.create_connection((host, port), timeout=timeout) as sock:
        sock.settimeout(timeout)
        files = sock.makefile("rwb")
        try:
            files.write(json.dumps({"op": "metrics"}).encode("utf-8")
                        + b"\n")
            files.flush()
            line = files.readline()
        finally:
            files.close()
    if not line:
        raise OSError("connection closed before the metrics reply")
    reply = json.loads(line)
    if not (isinstance(reply, dict) and reply.get("ok")
            and isinstance(reply.get("metrics"), dict)):
        raise ValueError(f"not a metrics reply: {reply!r}")
    return reply["metrics"]


class MetricsScraper:
    """The supervising process's poll loop: scrape every target, merge,
    append one snapshot to the run directory's ring, feed the SLO
    evaluator, forward its edge events to the active recorder.

    `targets` maps name -> (host, port); `local` optionally adds the
    supervisor's own registry (the launcher's liveness/health fold) to
    every merge. `scrape_once()` is the loop body, public so tests and
    the selfcheck drive it deterministically without the thread."""

    def __init__(self, targets, directory, *, interval=2.0, local=None,
                 evaluator=None, on_event=None,
                 max_lines=DEFAULT_MAX_LINES, timeout=5.0):
        self.targets = dict(targets)
        self.directory = pathlib.Path(directory)
        self.interval = float(interval)
        self.local = local
        self.evaluator = evaluator
        # `on_event(name, event)` observes each evaluator edge (r19:
        # the launcher hangs incident-bundle capture here). Called on
        # the scraper thread, outside the scraper lock; exceptions are
        # swallowed — an observer must not take the scrape loop down.
        self.on_event = on_event
        self.max_lines = int(max_lines)
        self.timeout = float(timeout)
        self.scrapes = 0
        self.last_snapshot = None
        self._stop = threading.Event()
        self._thread = None
        # Guards the published pair (scrapes, last_snapshot) and the
        # thread start — NOT the disk append: the fsync'ing
        # `append_snapshot` runs outside it (BMT-L02 day-one fix,
        # pinned by `schedule.scrape_publish_model`).
        self._lock = NamedLock("scraper.publish")

    def scrape_once(self, now=None):
        """One scrape round; returns the snapshot appended (also kept
        as `last_snapshot`). Dead targets become gaps, not errors."""
        now = time.time() if now is None else float(now)
        payloads = []
        reached, missed = [], []
        for name in sorted(self.targets):
            host, port = self.targets[name]
            try:
                payloads.append(scrape_target(host, port,
                                              timeout=self.timeout))
                reached.append(name)
            except (OSError, ValueError):
                missed.append(name)
        if self.local is not None:
            payloads.append(self.local.dump())
        merged = merge_payloads(payloads) if payloads else None
        snapshot = {"t": now, "kind": "metrics_snapshot",
                    "targets": len(self.targets), "reached": reached,
                    "missed": missed, "merged": merged}
        # The append (fd write + fsync + possible rotation) stays OUT of
        # the lock: the scraper thread is the only writer of the ring
        # file, so only the published pair needs the critical section —
        # a `stats()`/`last_snapshot` reader never waits on the disk.
        append_snapshot(self.directory, snapshot,
                        max_lines=self.max_lines)
        with self._lock:
            self.scrapes += 1
            self.last_snapshot = snapshot
        if self.evaluator is not None and merged is not None:
            for event in self.evaluator.observe(snapshot):
                name = event.pop("event")
                recorder.emit(name, **event)
                if self.on_event is not None:
                    try:
                        self.on_event(name, event)
                    except Exception:  # bmt: noqa[BMT-E05] an edge observer (incident capture) must not kill the scrape loop every target depends on
                        pass
        return snapshot

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.scrape_once()
            except Exception:  # bmt: noqa[BMT-E05] the scraper must outlive any single bad scrape; the ring shows the gap
                pass

    def start(self):
        with self._lock:   # two starters must not both spawn (BMT-L05)
            if self._thread is None:
                self._thread = threading.Thread(target=self._loop,
                                                name="metrics-scraper",
                                                daemon=True)
                self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout + self.interval)
            self._thread = None


class _EndpointHandler(socketserver.StreamRequestHandler):
    def handle(self):
        while True:
            try:
                line = self.rfile.readline()
            except OSError:
                return
            if not line:
                return
            line = line.strip()
            if not line:
                continue
            try:
                request = json.loads(line)
                op = request.get("op") if isinstance(request, dict) \
                    else None
            except ValueError:
                op = None
            if op == "ping":
                reply = {"ok": True, "op": "ping"}
            elif op == "metrics":
                try:
                    reply = {"ok": True,
                             "metrics": self.server.provider()}
                except Exception as err:  # bmt: noqa[BMT-E05] a failed dump must answer the puller, not kill the endpoint
                    reply = {"ok": False, "error": str(err)}
            else:
                reply = {"ok": False,
                         "error": f"unknown op {op!r} (ping|metrics)"}
            try:
                self.wfile.write(json.dumps(reply).encode("utf-8")
                                 + b"\n")
                self.wfile.flush()
            except OSError:
                return


class MetricsEndpoint(socketserver.ThreadingTCPServer):
    """Loopback line-JSON exposition server: answers `ping` and
    `metrics` with whatever `provider()` returns (a registry's `dump`,
    or the scraper's latest merge). The cluster launcher's pull port."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, address, provider):
        self.provider = provider
        super().__init__(tuple(address), _EndpointHandler)

    @property
    def port(self):
        return self.server_address[1]

    def serve_background(self):
        thread = threading.Thread(target=self.serve_forever,
                                  name="metrics-endpoint", daemon=True)
        thread.start()
        return thread
