"""The fleet metrics plane: registry, exposition, scrape, SLOs.

One metrics contract for the whole many-process system (r18):

* **registry** (`registry.py`) — the process-local `MetricsRegistry`:
  monotonic counters, gauges and fixed-bucket streaming histograms
  (mergeable bucket arrays, exposition-time quantiles, no raw-sample
  retention), dumped as one deterministic schema-versioned payload.
* **scrape** (`scrape.py`) — the pull side: `scrape_target` speaks the
  `{"op": "metrics"}` verb on the existing line-JSON ports,
  `MetricsScraper` polls every child each interval, merges bucket-wise
  and appends windowed snapshots to the run directory's `metrics.jsonl`
  ring (torn-tail-tolerant, atomically rotated); `MetricsEndpoint` is
  the launcher-side exposition port for cluster runs.
* **slo** (`slo.py`) — declarative availability/latency objectives
  evaluated as multi-window burn rates over the merged stream, with
  `slo_burn`/`slo_ok` edges on the telemetry timeline and a summary
  block on the one-pager.

Import discipline: stdlib-only, like the rest of `obs`.
"""

from byzantinemomentum_tpu.obs.metrics.registry import (  # noqa: F401
    DEPTH_BOUNDS,
    LATENCY_MS_BOUNDS,
    METRICS_SCHEMA,
    OCCUPANCY_BOUNDS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    NullRegistry,
    merge_payloads,
    quantile_from_buckets,
)
from byzantinemomentum_tpu.obs.metrics.scrape import (  # noqa: F401
    METRICS_NAME,
    MetricsEndpoint,
    MetricsScraper,
    append_snapshot,
    load_snapshots,
    scrape_target,
)
from byzantinemomentum_tpu.obs.metrics.slo import (  # noqa: F401
    DEFAULT_SERVE_SLOS,
    SLO,
    BurnRateEvaluator,
    window_rates,
)

__all__ = [
    "DEPTH_BOUNDS", "LATENCY_MS_BOUNDS", "METRICS_SCHEMA",
    "OCCUPANCY_BOUNDS", "Counter",
    "Gauge", "Histogram", "MetricsRegistry", "NullRegistry",
    "merge_payloads", "quantile_from_buckets",
    "METRICS_NAME", "MetricsEndpoint", "MetricsScraper",
    "append_snapshot", "load_snapshots", "scrape_target",
    "DEFAULT_SERVE_SLOS", "SLO", "BurnRateEvaluator", "window_rates",
]
