"""Declarative SLOs over the merged metrics stream: multi-window
burn-rate alerting.

An SLO here is the standard error-budget formulation: an objective (the
fraction of requests that must be GOOD over a long compliance period)
turns into a budget (`1 - objective`), and the alerting question is not
"is the error rate nonzero" but "how fast is the budget burning". The
burn RATE over a window is `error_rate / budget` — burn 1.0 exhausts
the budget exactly at the period's end; burn 14.4 exhausts a 30-day
budget in 2 days. The multi-window discipline (Google SRE workbook)
fires only when BOTH a slow window and a fast window exceed the
threshold: the slow window proves the burn is sustained (no paging on a
single bad scrape), the fast window proves it is still happening (the
alert un-fires promptly once the bleeding stops).

Two objective kinds, both evaluated from counter/histogram DELTAS
between snapshots of the merged `metrics.jsonl` stream (totals are
cumulative since process start; a window's traffic is the difference
between its edge snapshots):

  availability   bad = sum of error counters, total = a request counter
  latency        bad = histogram observations ABOVE a threshold bucket
                 boundary (integer bucket arithmetic — the same
                 cumulative counts the quantiles use), total = the
                 histogram's count

`BurnRateEvaluator.observe(snapshot)` folds one snapshot and returns
edge events — `slo_burn` on entering alert, `slo_ok` on leaving — which
the scraper forwards to the telemetry recorder; `summary()` is the
`obs_report` block. Time comes from the snapshots themselves (`t`), so
replaying a recorded stream is deterministic.

Stdlib-only, like the rest of `obs`.
"""

from byzantinemomentum_tpu.obs.metrics.registry import METRICS_SCHEMA
from byzantinemomentum_tpu.utils.locking import NamedLock

__all__ = ["SLO", "BurnRateEvaluator", "DEFAULT_SERVE_SLOS",
           "window_rates"]


class SLO:
    """One declarative objective.

    kind         "availability" | "latency"
    objective    good fraction target (e.g. 0.999)
    total        counter name (availability) or histogram name (latency)
    bad          error counter names (availability only)
    threshold_ms latency bound; a histogram observation counts BAD when
                 its bucket's upper bound exceeds this (latency only —
                 pick a value ON the ladder to make the cut exact)
    fast_s/slow_s  the two burn windows, seconds
    burn_threshold thresholds both windows must exceed to fire
    """

    def __init__(self, name, *, kind="availability", objective=0.999,
                 total="serve_requests", bad=("serve_rejected",),
                 threshold_ms=None, fast_s=30.0, slow_s=300.0,
                 burn_threshold=10.0):
        if kind not in ("availability", "latency"):
            raise ValueError(f"SLO {name!r}: unknown kind {kind!r}")
        if not 0.0 < objective < 1.0:
            raise ValueError(f"SLO {name!r}: objective must be in (0, 1)")
        if kind == "latency" and threshold_ms is None:
            raise ValueError(f"SLO {name!r}: latency SLOs need "
                             f"threshold_ms")
        self.name = str(name)
        self.kind = kind
        self.objective = float(objective)
        self.total = str(total)
        self.bad = tuple(bad or ())
        self.threshold_ms = (None if threshold_ms is None
                             else float(threshold_ms))
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self.burn_threshold = float(burn_threshold)

    @property
    def budget(self):
        return 1.0 - self.objective

    def spec(self):
        """JSON-safe description (rides the summary block)."""
        out = {"name": self.name, "kind": self.kind,
               "objective": self.objective, "total": self.total,
               "fast_s": self.fast_s, "slow_s": self.slow_s,
               "burn_threshold": self.burn_threshold}
        if self.kind == "availability":
            out["bad"] = list(self.bad)
        else:
            out["threshold_ms"] = self.threshold_ms
        return out


# The serve fleet's default objectives: availability over the frontline
# request/reject counters, and a p-latency bound on the end-to-end
# request histogram. Window pair sized for the scraper's seconds-scale
# cadence (a production-minute deployment would scale both up together;
# the burn arithmetic is cadence-free).
DEFAULT_SERVE_SLOS = (
    SLO("serve-availability", kind="availability", objective=0.999,
        total="serve_requests",
        bad=("serve_rejected", "router_errors", "router_timeouts")),
    SLO("serve-latency", kind="latency", objective=0.99,
        total="serve_request_ms", threshold_ms=100.0),
)


def _counter(snapshot, name):
    cell = ((snapshot.get("merged") or {}).get("metrics") or {}).get(name)
    if isinstance(cell, dict) and cell.get("type") == "counter":
        return int(cell.get("value") or 0)
    return 0


def _latency_counts(snapshot, name, threshold_ms):
    """(total, bad) observation counts for a latency SLO: integer sums
    over the histogram's bucket array, BAD being every bucket whose
    upper bound (or the overflow bucket) lies above the threshold."""
    cell = ((snapshot.get("merged") or {}).get("metrics") or {}).get(name)
    if not (isinstance(cell, dict) and cell.get("type") == "histogram"):
        return 0, 0
    bounds = cell.get("bounds") or []
    counts = cell.get("counts") or []
    total = sum(int(c) for c in counts)
    bad = sum(int(c) for i, c in enumerate(counts)
              if i >= len(bounds) or float(bounds[i]) > threshold_ms)
    return total, bad


def _totals(snapshot, slo):
    if slo.kind == "latency":
        return _latency_counts(snapshot, slo.total, slo.threshold_ms)
    total = _counter(snapshot, slo.total)
    bad = sum(_counter(snapshot, name) for name in slo.bad)
    return total, bad


def window_rates(history, slo, now, *, detail=False):
    """`{fast: burn | None, slow: burn | None}` over a snapshot history
    (oldest first). Each window's burn is the bad/total DELTA rate
    between `now` and the oldest in-window snapshot, divided by the
    budget; None when the window has no earlier edge or no traffic.
    With `detail=True` returns `(burns, deltas)` where deltas carries
    each window's raw `{total, bad, span_s}` — the evidence an
    incident bundle wants next to the burn number (r19)."""
    burns = {}
    deltas = {}
    for label, window in (("fast", slo.fast_s), ("slow", slo.slow_s)):
        edge = None
        for snapshot in history:
            if now - float(snapshot.get("t", 0.0)) <= window:
                edge = snapshot
                break
        latest = history[-1] if history else None
        if edge is None or latest is None or edge is latest:
            burns[label] = None
            deltas[label] = None
            continue
        total0, bad0 = _totals(edge, slo)
        total1, bad1 = _totals(latest, slo)
        d_total, d_bad = total1 - total0, bad1 - bad0
        deltas[label] = {"total": d_total, "bad": max(d_bad, 0),
                         "span_s": round(
                             now - float(edge.get("t", 0.0)), 3)}
        if d_total <= 0:
            burns[label] = None
            continue
        burns[label] = (max(d_bad, 0) / d_total) / slo.budget
    return (burns, deltas) if detail else burns


class BurnRateEvaluator:
    """Folds merged snapshots into per-SLO alert state. Pure in the
    snapshot stream — time is read from each snapshot's `t`, so a
    recorded `metrics.jsonl` replays to the identical event sequence."""

    def __init__(self, slos=DEFAULT_SERVE_SLOS):
        self.slos = tuple(slos)
        self._history = []
        self._alerting = {slo.name: False for slo in self.slos}
        self.burn_events = 0
        self.ok_events = 0
        # `observe` folds on the scraper thread while `summary` reads
        # from report/selfcheck callers — the window + alert state is
        # cross-thread. Named so BMT-L reports say `slo.window`, not an
        # anonymous Lock address.
        self._lock = NamedLock("slo.window")

    def observe(self, snapshot):
        """Fold one snapshot; returns edge events (`slo_burn` on
        entering alert, `slo_ok` on leaving), each JSON-safe. The fold
        is pure host arithmetic over the bounded window — holding
        `slo.window` across it never waits on disk or network."""
        with self._lock:
            return self._observe(snapshot)

    def _observe(self, snapshot):
        now = float(snapshot.get("t", 0.0))
        self._history.append(snapshot)
        # Bound memory to the slow window (+ one pre-window edge so the
        # slow delta always has its earlier snapshot).
        horizon = max(slo.slow_s for slo in self.slos) if self.slos else 0
        while (len(self._history) > 2
               and now - float(self._history[1].get("t", 0.0)) > horizon):
            self._history.pop(0)
        events = []
        for slo in self.slos:
            burns, deltas = window_rates(self._history, slo, now,
                                         detail=True)
            fast, slow = burns["fast"], burns["slow"]
            firing = (fast is not None and slow is not None
                      and fast > slo.burn_threshold
                      and slow > slo.burn_threshold)
            was = self._alerting[slo.name]
            if firing and not was:
                self._alerting[slo.name] = True
                self.burn_events += 1
                # The burn edge carries its window deltas (raw bad/total
                # counts behind each burn number): when the edge
                # triggers an incident bundle, the evidence that tripped
                # the alert rides inside the bundle's `data` instead of
                # needing a metrics-history replay.
                events.append({"event": "slo_burn", "slo": slo.name,
                               "burn_fast": round(fast, 3),
                               "burn_slow": round(slow, 3),
                               "threshold": slo.burn_threshold,
                               "window_fast": deltas["fast"],
                               "window_slow": deltas["slow"], "t": now})
            elif was and not firing:
                self._alerting[slo.name] = False
                self.ok_events += 1
                events.append({"event": "slo_ok", "slo": slo.name,
                               "burn_fast": (None if fast is None
                                             else round(fast, 3)),
                               "burn_slow": (None if slow is None
                                             else round(slow, 3)),
                               "threshold": slo.burn_threshold, "t": now})
        return events

    def summary(self):
        """The `obs_report` SLO block: per-objective current burn and
        alert state, plus the lifetime edge counts."""
        with self._lock:
            return self._summary()

    def _summary(self):
        now = (float(self._history[-1].get("t", 0.0))
               if self._history else 0.0)
        rows = []
        for slo in self.slos:
            burns = (window_rates(self._history, slo, now)
                     if self._history else {"fast": None, "slow": None})
            rows.append({**slo.spec(),
                         "burn_fast": (None if burns["fast"] is None
                                       else round(burns["fast"], 3)),
                         "burn_slow": (None if burns["slow"] is None
                                       else round(burns["slow"], 3)),
                         "alerting": self._alerting[slo.name]})
        return {"schema": METRICS_SCHEMA, "slos": rows,
                "burn_events": self.burn_events,
                "ok_events": self.ok_events,
                "snapshots": len(self._history)}
