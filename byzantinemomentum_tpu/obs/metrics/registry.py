"""Process-local metrics registry: counters, gauges, streaming histograms.

The repo's numbers used to live in scattered, incompatible places —
`stats()` dicts, telemetry gauge streams, aggregated heartbeats, one-shot
artifacts — the exact "monitoring glue" decay Sculley et al. name
(PAPERS.md). This module is the single contract: every process owns ONE
`MetricsRegistry`, instruments bump it in-line, and an exposition call
(`dump()`) serializes the whole registry as one deterministic,
schema-versioned payload that a PULLER fetches over the existing
line-JSON protocols (`{"op": "metrics"}` on the serve frontend, the
fleet router, and the cluster launcher's endpoint). Aggregation is the
scraper's job (`obs/metrics/scrape.py`), never a push path — the Ray
ownership discipline applied to metrics.

Three metric kinds, all mergeable across processes:

  Counter    monotonic int total (`inc`); merges by addition.
  Gauge      last-set float (`set`); merges by addition — every gauge
             here is an extensive quantity (queue depth, alive-host
             count), so the fleet-wide value IS the sum.
  Histogram  fixed-bucket streaming distribution (`observe`): a static
             ladder of upper bounds + one overflow bucket, integer
             bucket counts, running count/sum/min/max. No raw samples
             are retained — memory is bounded by the ladder length —
             and merging is bucket-wise addition, which is associative
             and commutative, so a fleet scrape that merges N shard
             payloads reports the same quantiles as a single process
             that observed every sample (bit-for-bit: quantiles are
             computed from integer cumulative counts over the SAME
             static ladder, never from floats that could re-associate).

Quantiles (`Histogram.quantile`, and `quantile_from_buckets` for
payloads) are nearest-rank over the cumulative bucket counts, resolving
to the bucket's upper bound — the `obs/trace` percentile stance with
bounded memory. The overflow bucket resolves to the tracked max.

Locking: one `threading.Lock` per metric, held only for the few-field
update or the snapshot copy — submitter, resolver and scraper threads
interleave freely without a registry-wide convoy. The interleaving
contract (a scrape must never observe a torn multi-field histogram
update) is pinned by the `metrics_scrape*` models in
`analysis/schedule.py`.

Stdlib-only, like the rest of `obs`: host-side consumers (launchers,
report tooling) must import it without an accelerator stack.
"""

import bisect
import math
import threading

from byzantinemomentum_tpu.utils.locking import NamedLock

__all__ = ["METRICS_SCHEMA", "LATENCY_MS_BOUNDS", "DEPTH_BOUNDS",
           "OCCUPANCY_BOUNDS", "Counter", "Gauge", "Histogram",
           "MetricsRegistry", "NullRegistry", "merge_payloads",
           "quantile_from_buckets"]

# Version of the exposition payload; a merger refuses mixed schemas
# instead of silently mis-adding fields that changed meaning.
METRICS_SCHEMA = 1

# Default ladders. Latency buckets follow a coarse exponential sweep —
# sub-0.1 ms is scheduler noise on any host (bench_compare's serve
# floor), 5 s is past every serve timeout. Depth buckets stay exact
# through the microbatcher's realistic range (max_batch <= 32) and
# coarsen past it.
LATENCY_MS_BOUNDS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                     100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0)
DEPTH_BOUNDS = (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0,
                32.0, 48.0, 64.0, 96.0, 128.0, 256.0, 512.0)
# Fractions in [0, 1] (batch occupancy): eighths resolve every batch
# size the microbatcher's power-of-two bucket ladder can produce.
OCCUPANCY_BOUNDS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


class Counter:
    """Monotonic running total. `inc` rejects negative increments — a
    counter that can go down is a gauge wearing the wrong type, and the
    scraper's monotonicity contract (tests) depends on it."""

    kind = "counter"

    def __init__(self, name):
        self.name = name
        self._lock = NamedLock("metrics.counter")
        self._value = 0

    def inc(self, n=1):
        n = int(n)
        if n < 0:
            raise ValueError(f"counter {self.name!r}: negative inc {n}")
        with self._lock:
            self._value += n
            return self._value

    @property
    def value(self):
        with self._lock:
            return self._value

    def snapshot(self):
        with self._lock:
            return {"type": "counter", "value": self._value}


class Gauge:
    """Last-set extensive measurement (queue depth, alive hosts)."""

    kind = "gauge"

    def __init__(self, name):
        self.name = name
        self._lock = NamedLock("metrics.gauge")
        self._value = 0.0

    def set(self, value):
        with self._lock:
            self._value = float(value)

    def add(self, delta):
        with self._lock:
            self._value += float(delta)
            return self._value

    @property
    def value(self):
        with self._lock:
            return self._value

    def snapshot(self):
        with self._lock:
            return {"type": "gauge", "value": self._value}


def _bucket_index(bounds, value):
    """The bucket a value lands in: first bound >= value, else overflow."""
    return bisect.bisect_left(bounds, value)


def quantile_from_buckets(bounds, counts, q, maximum=None):
    """Nearest-rank quantile from a bucket array (payload-side twin of
    `Histogram.quantile`): the upper bound of the bucket holding the
    rank, the tracked `maximum` for the overflow bucket. None when
    empty. Deterministic in the integer counts alone — merged buckets
    yield bit-identical quantiles to the single-process fold."""
    total = sum(counts)
    if total == 0:
        return None
    q = min(max(float(q), 0.0), 1.0)
    rank = max(1, math.ceil(q * total))
    cumulative = 0
    for index, count in enumerate(counts):
        cumulative += count
        if cumulative >= rank:
            if index < len(bounds):
                return float(bounds[index])
            return float(maximum) if maximum is not None else None
    return float(maximum) if maximum is not None else None


class Histogram:
    """Fixed-bucket streaming histogram: bounded memory, mergeable."""

    kind = "histogram"

    def __init__(self, name, bounds=LATENCY_MS_BOUNDS):
        bounds = tuple(float(b) for b in bounds)
        if list(bounds) != sorted(set(bounds)):
            raise ValueError(f"histogram {name!r}: bounds must be "
                             f"strictly increasing, got {bounds}")
        self.name = name
        self.bounds = bounds
        self._lock = NamedLock("metrics.histogram")
        self._counts = [0] * (len(bounds) + 1)
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None

    def observe(self, value):
        value = float(value)
        index = _bucket_index(self.bounds, value)
        with self._lock:
            self._counts[index] += 1
            self._count += 1
            self._sum += value
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value

    @property
    def count(self):
        with self._lock:
            return self._count

    def quantile(self, q):
        with self._lock:
            counts, maximum = list(self._counts), self._max
        return quantile_from_buckets(self.bounds, counts, q, maximum)

    def snapshot(self):
        with self._lock:
            return {"type": "histogram", "bounds": list(self.bounds),
                    "counts": list(self._counts), "count": self._count,
                    "sum": self._sum, "min": self._min, "max": self._max}


class MetricsRegistry:
    """One process's (or subsystem's) named metrics + the exposition
    dump. Get-or-create accessors are idempotent and type-checked: the
    same name must always be the same kind (and, for histograms, the
    same ladder) — a name that changes shape would silently poison
    every merge downstream."""

    enabled = True

    def __init__(self, source=None):
        self.source = source
        self._lock = NamedLock("metrics.registry")
        self._metrics = {}

    def _get(self, name, factory, kind):
        name = str(name)
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = factory(name)
                self._metrics[name] = metric
        if metric.kind != kind:
            raise TypeError(f"metric {name!r} is a {metric.kind}, "
                            f"asked for as a {kind}")
        return metric

    def counter(self, name):
        return self._get(name, Counter, "counter")

    def gauge(self, name):
        return self._get(name, Gauge, "gauge")

    def histogram(self, name, bounds=LATENCY_MS_BOUNDS):
        metric = self._get(name, lambda n: Histogram(n, bounds),
                           "histogram")
        if metric.bounds != tuple(float(b) for b in bounds):
            raise ValueError(f"histogram {name!r} re-registered with a "
                             f"different ladder")
        return metric

    def dump(self):
        """The exposition payload: schema-versioned, metrics sorted by
        name — byte-stable for a fixed registry state, so snapshot
        diffs and merge parity checks are exact."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        payload = {"schema": METRICS_SCHEMA, "kind": "metrics",
                   "metrics": {name: metric.snapshot()
                               for name, metric in metrics}}
        if self.source is not None:
            payload["source"] = str(self.source)
        return payload


class NullRegistry:
    """The off switch: same surface, every operation a no-op — the
    paired overhead run's baseline arm, and the default for callers
    that opted out of metrics. `dump()` still answers (empty payload)
    so the exposition verb never errors on a metrics-off process."""

    enabled = False

    def __init__(self, source=None):
        self.source = source
        self._counter = _NullCounter()
        self._gauge = _NullGauge()
        self._histogram = _NullHistogram()

    def counter(self, name):
        return self._counter

    def gauge(self, name):
        return self._gauge

    def histogram(self, name, bounds=LATENCY_MS_BOUNDS):
        return self._histogram

    def dump(self):
        payload = {"schema": METRICS_SCHEMA, "kind": "metrics",
                   "metrics": {}}
        if self.source is not None:
            payload["source"] = str(self.source)
        return payload


class _NullCounter:
    kind = "counter"
    value = 0

    def inc(self, n=1):
        return 0


class _NullGauge:
    kind = "gauge"
    value = 0.0

    def set(self, value):
        pass

    def add(self, delta):
        return 0.0


class _NullHistogram:
    kind = "histogram"
    bounds = ()
    count = 0

    def observe(self, value):
        pass

    def quantile(self, q):
        return None


def merge_payloads(payloads):
    """Merge N exposition payloads into one: counters and gauges add,
    histograms add bucket-wise (same ladder required), min/max fold.
    Associative and commutative by construction — the fleet scrape's
    merge order can never change the reported distribution. Mixed
    schemas or mismatched histogram ladders raise: silently adding
    fields that changed meaning is how monitoring glue rots."""
    merged = {}
    sources = []
    for payload in payloads:
        if not isinstance(payload, dict) or payload.get("kind") != "metrics":
            raise ValueError("merge_payloads: not a metrics payload")
        if payload.get("schema") != METRICS_SCHEMA:
            raise ValueError(f"merge_payloads: schema "
                             f"{payload.get('schema')!r} != "
                             f"{METRICS_SCHEMA}")
        if payload.get("source") is not None:
            sources.append(str(payload["source"]))
        for name, cell in (payload.get("metrics") or {}).items():
            kind = cell.get("type")
            have = merged.get(name)
            if have is None:
                if kind == "histogram":
                    merged[name] = {"type": "histogram",
                                    "bounds": list(cell["bounds"]),
                                    "counts": list(cell["counts"]),
                                    "count": int(cell["count"]),
                                    "sum": float(cell["sum"]),
                                    "min": cell.get("min"),
                                    "max": cell.get("max")}
                else:
                    merged[name] = {"type": kind, "value": cell["value"]}
                continue
            if have["type"] != kind:
                raise ValueError(f"merge_payloads: metric {name!r} is a "
                                 f"{have['type']} in one payload, a "
                                 f"{kind} in another")
            if kind == "histogram":
                if have["bounds"] != list(cell["bounds"]):
                    raise ValueError(f"merge_payloads: histogram "
                                     f"{name!r} ladders differ")
                have["counts"] = [a + b for a, b in
                                  zip(have["counts"], cell["counts"])]
                have["count"] += int(cell["count"])
                have["sum"] += float(cell["sum"])
                for key, pick in (("min", min), ("max", max)):
                    theirs = cell.get(key)
                    if theirs is not None:
                        have[key] = (theirs if have[key] is None
                                     else pick(have[key], theirs))
            else:
                have["value"] = have["value"] + cell["value"]
    payload = {"schema": METRICS_SCHEMA, "kind": "metrics",
               "metrics": {name: merged[name] for name in sorted(merged)}}
    if sources:
        payload["sources"] = sorted(sources)
    return payload
