"""Runtime telemetry — structured spans/events, live perf counters and
supervisor-grade heartbeats.

The study CSV (`engine/metrics.py::STUDY_COLUMNS`) observes the *science*
(gradient norms, cosines, acceptation ratios); this package observes the
*system*: step latency, throughput, recompiles, checkpoint-write cost and
the resilience events (faults injected, rollbacks, restarts) that were
previously invisible or inferred indirectly (the `utils/jobs.py` watchdog
used to guess liveness from study-CSV mtime). Three pieces:

* **recorder** (`recorder.py`) — `Telemetry`: an append-only
  `telemetry.jsonl` per run holding spans (nested, wall-clock durations),
  events (point-in-time facts), monotonic counters and gauges, flushed per
  record so a SIGKILL loses at most the record being written. A
  module-level *active recorder* (`activate`/`emit`/`span`/`counter`) lets
  deep layers (`checkpoint.py`, `faults/`) land on the timeline without
  plumbing a handle through every call chain — all no-ops when inactive.
* **heartbeat** (`heartbeat.py`) — a single `heartbeat.json`, atomically
  replaced (tmp + fsync + `os.replace`, same discipline as
  `checkpoint.py`), with step, throughput, last-event summary and counter
  snapshot. The `Jobs` supervisor's watchdog consumes it instead of
  CSV-mtime guessing, making the kill decision signal-based.
* **perf** (`perf.py`) — sliding-window steps/s, device-honest chunk
  timing (an `AccumulatedTimedContext` whose sync barrier is a tiny
  device→host transfer), host RSS, the TPU bf16 peak-FLOPs table shared
  with `bench.py` and the logical-FLOP counter behind the MFU gauge.
* **attrib** (`attrib/`) — phase-attributed device profiling: xplane
  trace parsing (the `scripts/trace_opstats.py` core, promoted), the
  `jax.named_scope` phase join against `engine/step.py`'s annotations,
  MXU/memory/relayout op classes, and the per-run `attribution.json`
  artifact behind `cli/attack.py --attribution` (the SIGUSR1 live window
  auto-attributes too).
* **trace** (`trace/`) — request-scoped serve tracing (per-request span
  stamps from frontend parse to resolve, a bounded completed-trace ring
  behind `stats`/SIGUSR1 and the `ATTRIB_serve.json` artifact) and
  fleet-wide attribution (the launcher+host telemetry streams of a
  cluster run joined into one clock-aligned, causally-ordered timeline).
* **health** (`health/`) — the numerics flight recorder's host half:
  online SPC (EWMA + MAD z-scores, Western-Electric sustained-run
  rules) over the in-jit tensor-health stream (`engine/health.py`,
  `--health`), `health_anomaly`/`health_cleared` events, the
  early-warning rollback trigger (`--rollback-on-anomaly`) and the
  bounded `health_blackbox.json` post-mortem ring.
* **metrics** (`metrics/`) — the fleet metrics plane (r18): the
  process-local registry (counters / gauges / mergeable fixed-bucket
  histograms), the pull-based `{"op": "metrics"}` exposition verb on
  every line-JSON port, the supervising scraper + `metrics.jsonl`
  snapshot ring, and multi-window SLO burn-rate alerting
  (`slo_burn`/`slo_ok` on the timeline).
* **forensics** (`forensics.py`) — per-worker EWMA suspicion scores over
  the in-jit GAR diagnostics stream (`--gar-diagnostics`): selection-
  frequency deficit, distance z-score and NaN-quarantine history, with
  `suspect_worker`/`suspect_cleared` events landing on the timeline
  through the active-recorder API and a forensics section on the
  one-pager.

Driver surface: `cli/attack.py --telemetry[-interval]` (on by default when
a `--result-directory` exists), SIGUSR1 for an on-demand one-chunk
`jax.profiler` window on a live run. `scripts/obs_report.py` (and
`python -m byzantinemomentum_tpu.obs <run_dir>`) renders a one-page text
summary of any run directory; `python -m byzantinemomentum_tpu.obs
--selfcheck` is the CI smoke entry point.

Import discipline: nothing in this package imports jax at module scope
(`perf.logical_flops` imports it lazily), so host-only consumers — the
`Jobs` supervisor, report tooling, test harnesses — never initialize an
accelerator backend.
"""

from byzantinemomentum_tpu.obs.recorder import (  # noqa: F401
    TELEMETRY_NAME,
    Telemetry,
    activate,
    active,
    counter,
    deactivate,
    emit,
    install_compile_listener,
    load_records,
    span,
)
from byzantinemomentum_tpu.obs.forensics import (  # noqa: F401
    SuspicionTracker,
)
from byzantinemomentum_tpu.obs.heartbeat import (  # noqa: F401
    HEARTBEAT_NAME,
    HOSTS_DIRNAME,
    host_heartbeat_path,
    read_heartbeat,
    read_host_heartbeats,
    write_heartbeat,
    write_host_heartbeat,
)
from byzantinemomentum_tpu.obs.perf import (  # noqa: F401
    SlidingRate,
    StepTimer,
    flops_of_compiled,
    host_rss_mb,
    logical_flops,
    mfu,
    peak_flops,
)
from byzantinemomentum_tpu.obs import attrib  # noqa: F401
from byzantinemomentum_tpu.obs import health  # noqa: F401
from byzantinemomentum_tpu.obs import metrics  # noqa: F401
from byzantinemomentum_tpu.obs import trace  # noqa: F401
from byzantinemomentum_tpu.obs.health import (  # noqa: F401
    HealthMonitor,
    load_blackbox,
)

__all__ = [
    "TELEMETRY_NAME", "Telemetry", "activate", "active", "counter",
    "deactivate", "emit", "install_compile_listener", "load_records", "span",
    "HEARTBEAT_NAME", "HOSTS_DIRNAME", "host_heartbeat_path",
    "read_heartbeat", "read_host_heartbeats", "write_heartbeat",
    "write_host_heartbeat",
    "HealthMonitor", "SlidingRate", "StepTimer", "SuspicionTracker",
    "attrib", "health", "load_blackbox", "metrics", "trace",
    "flops_of_compiled", "host_rss_mb", "logical_flops", "mfu",
    "peak_flops",
]
