"""Host-side Byzantine forensics: per-worker EWMA suspicion scores.

The in-jit diagnostics path (`ops/diag.py`, threaded out through
`engine/step.py` as the `Sel mask`/`Worker dist` metric vectors) tells us
*what the GAR saw* on each step; this module folds those per-step
observations into a per-worker running suspicion score and lands
`suspect_worker` / `suspect_cleared` events on the run's telemetry
timeline (the PR 3 active-recorder API — no-ops without a recorder).

The score is a sum of three EWMA components, each normalized so "no
evidence" reads 0 and "consistent evidence" saturates toward its weight:

  selection deficit   how much less often the worker is selected than the
                      current average selection rate: EWMA of the 0/1
                      selected indicator, deficit = (mean_rate - rate) /
                      mean_rate, clipped to [0, 1]. An honest worker under
                      a working defense hovers near 0; an attacker that
                      Krum/Bulyan keeps rejecting saturates to ~1.
  distance z-score    how far the worker sits from the submission cloud:
                      z = (d_i - mean(d)) / std(d) over the per-worker
                      mean pairwise distances, clipped to [0, Z_CLIP] and
                      EWMA'd, then normalized by Z_CLIP. "A Little Is
                      Enough"-style attacks that live INSIDE honest
                      variance stay near 0 here — which is exactly why the
                      selection deficit is a separate component.
  quarantine history  EWMA of the worker's NaN-quarantine / inactive
                      indicator (`faults/sanitize.py` via the engine's
                      post-quarantine active mask, when a fault plan or
                      quarantine is live).
  collusion           OPTIONAL fourth component (a 4-tuple `weights`
                      enables it): EWMA of a near-duplicate indicator
                      read off the full pairwise-distance matrix —
                      workers whose rows sit closer than
                      `collusion_frac` of the cohort's median pairwise
                      distance to another row are colluding. This is the
                      channel that catches attacks the statistical
                      channels cannot: ALIE rows live INSIDE the honest
                      variance envelope (z ~ 0, selected often), but the
                      f attack rows are mutually (near-)identical — a
                      geometric signature honest i.i.d. noise at
                      realistic d essentially never produces. It is also
                      the only channel an adversary cannot aim at an
                      honest victim without byte-mimicking the victim's
                      own row (in which case deduplication keeps the
                      row's information — see `arena/quarantine.py`).

All weights sum to 1, so `suspicion` lives in [0, 1]. Crossing
`threshold` (rising edge) emits `suspect_worker`; falling back below
`clear` emits `suspect_cleared`. Pure stdlib + numpy on (n,) vectors —
at n <= 51 workers this is nanoseconds per step, paid only on the
host-side CSV flush path, never inside the compiled step.
"""

import numpy as np

from byzantinemomentum_tpu.obs import recorder

__all__ = ["SuspicionTracker", "ClientSuspicionStore", "Z_CLIP",
           "COLLUSION_FRAC", "collusion_partners"]

# Distance z-scores are clipped here before normalization: beyond ~4
# sigma, "farther" carries no additional information, and a single inf
# row must not destroy the EWMA.
Z_CLIP = 4.0

# Near-duplicate threshold, as a fraction of the cohort's median finite
# pairwise distance: honest i.i.d. rows sit ~sigma*sqrt(2d) apart (the
# median), while colluding copies differ only by whatever jitter the
# attacker dares to add — 0.2 leaves the adversary a factor-5 gap to
# cross before its rows blend into the honest cloud.
COLLUSION_FRAC = 0.2


def collusion_partners(dist, frac=COLLUSION_FRAC):
    """`bool[n, n]` near-duplicate adjacency from a pairwise-distance
    matrix (`ops/diag.py` aux convention: +inf diagonal, non-finite
    -> +inf): an edge where the finite off-diagonal distance is at most
    `frac` times the median finite off-diagonal distance. A fully
    degenerate cohort (median 0 — every row identical) keeps exact-zero
    edges, which is the honest reading of that cohort."""
    d = np.asarray(dist, dtype=np.float64)
    n = d.shape[0]
    offdiag = ~np.eye(n, dtype=bool)
    finite = np.isfinite(d) & offdiag
    if not finite.any():
        return np.zeros((n, n), dtype=bool)
    return finite & (d <= frac * float(np.median(d[finite])))


class SuspicionTracker:
    """Per-worker EWMA suspicion over a run's diagnostic step stream.

    Args:
      nb_workers: worker rows in the submission stack (honest + Byzantine).
      alpha: EWMA smoothing factor (weight of the newest observation).
      threshold: suspicion level whose rising edge emits `suspect_worker`.
      clear: level whose falling edge emits `suspect_cleared` (hysteresis:
        must be < threshold).
      weights: (selection, distance, quarantine) component weights —
        or a 4-tuple (selection, distance, quarantine, collusion) to
        enable the near-duplicate channel (fed by `update`'s
        `dist_matrix`); normalized to sum 1.
      min_steps: observations before any event fires (the first few steps'
        selection rates are pure noise).
      collusion_frac: near-duplicate threshold as a fraction of the
        cohort's median pairwise distance (`collusion_partners`).
    """

    def __init__(self, nb_workers, *, alpha=0.05, threshold=0.5, clear=0.25,
                 weights=(0.5, 0.3, 0.2), min_steps=10,
                 collusion_frac=COLLUSION_FRAC):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"EWMA alpha must be in (0, 1], got {alpha}")
        if not 0.0 <= clear < threshold:
            raise ValueError(
                f"Need 0 <= clear < threshold, got clear={clear} "
                f"threshold={threshold}")
        if len(weights) not in (3, 4):
            raise ValueError(
                f"Expected 3 (sel, dist, quarantine) or 4 (+ collusion) "
                f"component weights, got {len(weights)}")
        self.nb_workers = int(nb_workers)
        self.alpha = float(alpha)
        self.threshold = float(threshold)
        self.clear = float(clear)
        total = float(sum(weights))
        self.weights = tuple(float(w) / total for w in weights)
        self.min_steps = int(min_steps)
        self.collusion_frac = float(collusion_frac)
        self.steps = 0
        n = self.nb_workers
        self._sel_rate = np.zeros(n)      # EWMA of the selected indicator
        self._dist_z = np.zeros(n)        # EWMA of the clipped z-score
        self._quarantine = np.zeros(n)    # EWMA of the quarantined indicator
        self.collusion = np.zeros(n)      # EWMA of the near-duplicate flag
        self.partners = np.zeros((n, n), dtype=bool)  # last step's adjacency
        self.suspicion = np.zeros(n)
        self._suspect = np.zeros(n, dtype=bool)

    # -------------------------------------------------------------- #

    def _ewma(self, state, observation):
        return (1.0 - self.alpha) * state + self.alpha * observation

    def update(self, step, selection, distances=None, active=None,
               dist_matrix=None):
        """Fold one step's diagnostics into the scores.

        Args:
          step: the step number (stamped on emitted events).
          selection: (n,) selection mask/mass from the GAR aux (> 0 means
            the worker contributed to the aggregate).
          distances: optional (n,) per-worker mean pairwise distance
            (`Worker dist` metric); non-finite entries count as maximally
            far.
          active: optional (n,) post-quarantine active mask (1 = healthy);
            absent means nobody was quarantined this step.
          dist_matrix: optional (n, n) pairwise-distance matrix (the diag
            aux `dist`) feeding the collusion channel — only consumed
            when the tracker was built with a 4-tuple of weights.
        Returns:
          The (n,) suspicion array after the update.
        """
        n = self.nb_workers
        selection = np.asarray(selection, dtype=np.float64).reshape(n)
        selected = (selection > 0.0).astype(np.float64)
        self._sel_rate = self._ewma(self._sel_rate, selected)

        if distances is not None:
            d = np.asarray(distances, dtype=np.float64).reshape(n)
            finite = np.isfinite(d)
            if finite.any():
                mean = float(d[finite].mean())
                std = float(d[finite].std())
                z = np.full(n, Z_CLIP)
                if std > 0.0:
                    z[finite] = np.clip((d[finite] - mean) / std, 0.0, Z_CLIP)
                else:
                    z[finite] = 0.0
            else:
                z = np.full(n, Z_CLIP)
            self._dist_z = self._ewma(self._dist_z, z)

        quarantined = (np.zeros(n) if active is None
                       else 1.0 - (np.asarray(active, dtype=np.float64)
                                   .reshape(n) > 0.0))
        self._quarantine = self._ewma(self._quarantine, quarantined)

        if len(self.weights) == 4 and dist_matrix is not None:
            self.partners = collusion_partners(dist_matrix,
                                               self.collusion_frac)
            self.collusion = self._ewma(
                self.collusion, self.partners.any(axis=1).astype(np.float64))

        self.steps += 1
        mean_rate = float(self._sel_rate.mean())
        if mean_rate > 0.0:
            deficit = np.clip((mean_rate - self._sel_rate) / mean_rate,
                              0.0, 1.0)
        else:
            deficit = np.zeros(n)
        w_sel, w_dist, w_quar = self.weights[:3]
        self.suspicion = (w_sel * deficit
                          + w_dist * self._dist_z / Z_CLIP
                          + w_quar * self._quarantine)
        if len(self.weights) == 4:
            self.suspicion = self.suspicion + self.weights[3] * self.collusion
        self._emit_edges(step)
        return self.suspicion

    def _emit_edges(self, step):
        if self.steps < self.min_steps:
            return
        rising = (self.suspicion >= self.threshold) & ~self._suspect
        falling = (self.suspicion <= self.clear) & self._suspect
        for worker in np.nonzero(rising)[0]:
            self._suspect[worker] = True
            recorder.emit("suspect_worker", worker=int(worker), step=step,
                          suspicion=round(float(self.suspicion[worker]), 4),
                          sel_rate=round(float(self._sel_rate[worker]), 4))
        for worker in np.nonzero(falling)[0]:
            self._suspect[worker] = False
            recorder.emit("suspect_cleared", worker=int(worker), step=step,
                          suspicion=round(float(self.suspicion[worker]), 4))

    # -------------------------------------------------------------- #

    @property
    def suspects(self):
        """Currently-suspect worker indices (sorted list of ints)."""
        return [int(w) for w in np.nonzero(self._suspect)[0]]

    def max(self):
        """The current maximum suspicion score (the `Suspicion max` study
        column)."""
        return float(self.suspicion.max()) if self.nb_workers else 0.0

    def summary(self):
        """JSON-safe snapshot (heartbeat / report consumption)."""
        out = {
            "steps": self.steps,
            "suspects": self.suspects,
            "suspicion": [round(float(s), 4) for s in self.suspicion],
            "sel_rate": [round(float(r), 4) for r in self._sel_rate],
        }
        if len(self.weights) == 4:
            out["collusion"] = [round(float(c), 4) for c in self.collusion]
        return out


class ClientSuspicionStore:
    """`SuspicionTracker` promoted to a client-id-keyed map (the
    aggregation service, `serve/`).

    A training run has a fixed worker roster, so the tracker holds dense
    `(n,)` arrays; a service sees an OPEN population of client ids, each
    appearing in some requests and not others. This store keeps the same
    three EWMA components per client — selection indicator, clipped
    distance z-score, quarantine/inactive indicator — updated from each
    request's serve aux (selection mass + per-row mean distances,
    `ops/diag.py::masked_generic_aux`), with the z-score computed WITHIN
    the request cohort exactly as the tracker computes it within the
    worker roster. The selection deficit compares each client's EWMA rate
    against the mean rate over every currently-known client (the tracker's
    mean over workers, with the known population as the roster).

    Verdicts ride back on each response: `observe` returns
    `{client: {"suspicion", "suspect", "observations"}}` for the cohort.
    Threshold/clear hysteresis and the warm-up gate are per client;
    rising/falling edges emit `suspect_client` / `suspect_client_cleared`
    through the active recorder.

    Memory is bounded for millions-of-clients traffic: past `max_clients`
    the least-recently-observed client state is evicted (its history
    restarts if it returns — a cold client is warm-up-gated anyway).
    """

    def __init__(self, *, alpha=0.05, threshold=0.5, clear=0.25,
                 weights=(0.5, 0.3, 0.2), min_obs=10, max_clients=1_000_000,
                 collusion_frac=COLLUSION_FRAC):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"EWMA alpha must be in (0, 1], got {alpha}")
        if not 0.0 <= clear < threshold:
            raise ValueError(
                f"Need 0 <= clear < threshold, got clear={clear} "
                f"threshold={threshold}")
        if max_clients < 1:
            raise ValueError(f"Expected max_clients >= 1, got {max_clients}")
        if len(weights) not in (3, 4):
            raise ValueError(
                f"Expected 3 (sel, dist, quarantine) or 4 (+ collusion) "
                f"component weights, got {len(weights)}")
        self.alpha = float(alpha)
        self.threshold = float(threshold)
        self.clear = float(clear)
        total = float(sum(weights))
        self.weights = tuple(float(w) / total for w in weights)
        self.min_obs = int(min_obs)
        self.max_clients = int(max_clients)
        self.collusion_frac = float(collusion_frac)
        self.requests = 0
        # client -> [sel_rate, dist_z, quarantine, observations, suspect,
        #            collusion] (insertion order == recency order:
        # re-observed clients move to the end, so eviction pops the least
        # recently observed)
        self._state = {}

    def _ewma(self, state, observation):
        return (1.0 - self.alpha) * state + self.alpha * observation

    def observe(self, client_ids, selection, distances=None, active=None,
                step=None, dist=None):
        """Fold one request's serve aux into the per-client scores.

        Args:
          client_ids: cohort client ids, one per row.
          selection: (n,) selection mass (> 0 = contributed).
          distances: optional (n,) per-row mean pairwise distance
            (non-finite = maximally far, clipped to `Z_CLIP` sigma).
          active: optional (n,) request-level active/quarantine mask.
          step: optional sequence stamp for emitted events (defaults to
            the running request count).
          dist: optional (n, n) pairwise-distance matrix (the serve aux's
            `dist`) feeding the collusion channel — the Sybil defense:
            rows from DISTINCT client ids sitting nearer than
            `collusion_frac` of the cohort's median distance are a
            coordinated cluster (one perturbation split across many ids
            to stay under every per-client threshold). Only consumed
            with a 4-tuple of weights.
        Returns:
          {client_id: verdict dict} for the cohort, where a verdict is
          `{"suspicion": float, "suspect": bool, "observations": int,
          "collusion": float}`.
        """
        n = len(client_ids)
        selected = (np.asarray(selection, dtype=np.float64).reshape(n)
                    > 0.0).astype(np.float64)
        if distances is not None:
            d = np.asarray(distances, dtype=np.float64).reshape(n)
            finite = np.isfinite(d)
            z = np.full(n, Z_CLIP)
            if finite.any():
                std = float(d[finite].std())
                if std > 0.0:
                    z[finite] = np.clip(
                        (d[finite] - float(d[finite].mean())) / std,
                        0.0, Z_CLIP)
                else:
                    z[finite] = 0.0
        else:
            z = None
        quarantined = (np.zeros(n) if active is None
                       else 1.0 - (np.asarray(active, dtype=np.float64)
                                   .reshape(n) > 0.0))
        colluding = np.zeros(n)
        measured = np.ones(n, dtype=bool)
        if len(self.weights) == 4 and dist is not None:
            partners = collusion_partners(dist, self.collusion_frac)
            # Only cross-client edges are Sybil evidence: one client
            # resubmitting its own vector is noisy, not coordinated
            ids = list(client_ids)
            same = np.array([[a == b for b in ids] for a in ids], dtype=bool)
            colluding = (partners & ~same).any(axis=1).astype(np.float64)
            if active is not None:
                # An admission-masked (inactive) row was never measured —
                # its distances are the +inf routing, not geometry — so
                # its collusion EWMA HOLDS instead of decaying toward
                # innocence while it sits in quarantine
                measured = np.asarray(active, dtype=bool).reshape(n)

        self.requests += 1
        step = self.requests if step is None else step
        for i, client in enumerate(client_ids):
            state = self._state.pop(client, None)
            if state is None:
                state = [0.0, 0.0, 0.0, 0, False, 0.0]
            elif len(state) == 5:   # pre-collusion state layout
                state = state + [0.0]
            state[0] = self._ewma(state[0], selected[i])
            if z is not None:
                state[1] = self._ewma(state[1], z[i])
            state[2] = self._ewma(state[2], quarantined[i])
            state[3] += 1
            if measured[i]:
                state[5] = self._ewma(state[5], colluding[i])
            self._state[client] = state  # re-insert: most recent last

        mean_rate = (sum(s[0] for s in self._state.values())
                     / max(len(self._state), 1))
        verdicts = {}
        for client in client_ids:
            state = self._state[client]
            suspicion = self._score(state, mean_rate)
            obs, suspect = state[3], state[4]
            if obs >= self.min_obs:
                if suspicion >= self.threshold and not suspect:
                    state[4] = suspect = True
                    recorder.emit("suspect_client", client=str(client),
                                  step=step, suspicion=round(suspicion, 4),
                                  sel_rate=round(state[0], 4))
                elif suspicion <= self.clear and suspect:
                    state[4] = suspect = False
                    recorder.emit("suspect_client_cleared",
                                  client=str(client), step=step,
                                  suspicion=round(suspicion, 4))
            verdicts[client] = {"suspicion": round(float(suspicion), 4),
                                "suspect": bool(state[4]),
                                "observations": int(obs),
                                "collusion": round(float(state[5]), 4)}
        # Evict AFTER the verdicts so a cohort larger than the cap still
        # answers for every row of the request it just made
        while len(self._state) > self.max_clients:
            self._state.pop(next(iter(self._state)))
        return verdicts

    def observe_batch(self, items, step=None):
        """Fold one RESOLVED BATCH of requests, in submission order.

        `items` is a sequence of kwargs dicts for `observe` (client_ids,
        selection, distances, active, dist); returns the per-item
        verdict dicts in the same order. The verdicts are byte-identical
        to calling `observe` once per item: each item keeps ITS cohort's
        z-scores and sees the population mean selection rate as of ITS
        fold — the order-sensitive float arithmetic is part of the
        verdict contract (the equivalence test in tests/test_fleet.py
        pins it), so nothing is vectorized ACROSS items. What batching
        buys is at the caller: the service resolver acquires the
        suspicion lock ONCE per device batch and makes one call, instead
        of a lock round-trip per request — with admission `decide`
        contending on the same lock from every submitter thread, that
        moved the resolve span's p50 (`ATTRIB_serve_r16.json`). A batch
        is also atomic under that lock: an admission decision reads
        verdicts from between batches, never mid-fold.
        """
        return [self.observe(step=step, **item) for item in items]

    def _score(self, state, mean_rate):
        """The blended suspicion of one client state against the current
        population mean selection rate."""
        sel_rate, dist_z, quar = state[0], state[1], state[2]
        deficit = (min(max((mean_rate - sel_rate) / mean_rate, 0.0), 1.0)
                   if mean_rate > 0.0 else 0.0)
        w_sel, w_dist, w_quar = self.weights[:3]
        suspicion = (w_sel * deficit + w_dist * dist_z / Z_CLIP
                     + w_quar * quar)
        if len(self.weights) == 4:
            suspicion += self.weights[3] * state[5]
        return suspicion

    def verdict(self, client):
        """Read-only peek at one client's current verdict (None for a
        client the store has never observed) — the admission-control path
        (`serve/admission.py`) consults this at submit time WITHOUT
        advancing any EWMA or recency state."""
        state = self._state.get(client)
        if state is None:
            return None
        mean_rate = (sum(s[0] for s in self._state.values())
                     / max(len(self._state), 1))
        return {"suspicion": round(float(self._score(state, mean_rate)), 4),
                "suspect": bool(state[4]),
                "observations": int(state[3]),
                "collusion": round(float(state[5] if len(state) > 5
                                         else 0.0), 4)}

    @property
    def suspects(self):
        """Currently-suspect client ids (sorted)."""
        return sorted(str(c) for c, s in self._state.items() if s[4])

    def clients(self):
        """The client ids currently held (sorted) — the fleet's
        shard-ownership tests check a shard's store holds EXACTLY the
        clients the ring routes to it."""
        return sorted(str(c) for c in self._state)

    def __len__(self):
        return len(self._state)

    def summary(self):
        """JSON-safe snapshot (heartbeat / stats consumption)."""
        return {"requests": self.requests, "clients": len(self._state),
                "suspects": self.suspects}
