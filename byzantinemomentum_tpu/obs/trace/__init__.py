"""Request-scoped serve tracing and fleet-wide attribution.

PR 6's attribution machinery stops at training runs (per-phase ms/step
inside ONE compiled program); this package extends the profile-guided
discipline to the two surfaces that grew past it:

* **request** (`request.py`) — per-request tracing through the serving
  stack: every `ServeRequest` carries a trace id and monotonic-clock
  span stamps from frontend parse through admission, packing, queue
  wait, device dispatch and resolve, so the serve hot path's next wall
  (host-side packing? resolver wake-up? queue wait?) is *attributed*,
  not guessed. Completed traces land in a bounded in-memory ring buffer
  (`TraceBuffer`) whose per-phase p50/p99 summary rides `stats` and the
  SIGUSR1 snapshot; `scripts/serve_loadgen.py --trace` turns the stream
  into the `ATTRIB_serve.json` artifact `bench_compare.py` gates.
* **fleet** (`fleet.py`) — fleet-level attribution for cluster runs:
  the launcher's `telemetry.jsonl` and every host's
  `hosts/host-<i>.telemetry.jsonl` join into one causally-ordered fleet
  timeline (host clock offsets estimated from the launcher's heartbeat
  handshake — the launcher stamps each host heartbeat's `updated` field
  against its own clock on every poll, and the minimum skew over the
  run is the offset bound), with restarts, fired faults and liveness
  transitions as first-class timeline events. `obs_report` and
  `study.py` render it as the one-page fleet health view.
* **incident** (`incident.py`, r19) — SLO-triggered incident bundles:
  an edge event (`slo_burn`, router arc death/failover, a straggler
  kill) triggers an atomic snapshot of the evidence already resident in
  the process — trace ring, metrics-window deltas, health blackbox,
  membership version — into `incidents/incident-<n>.json`;
  `merge_fleet_incidents` folds the per-process bundles into one
  fleet-scope index, and `obs_report` replays each bundle into the
  ordered causal story (burn edge → dominant hop → arc event).

The cross-process span join (`join_shard_trace`) lives in `request.py`:
a shard's wire trace record nests clock-free inside the fleet router's
measured envelope, turning the opaque `shard_rtt` lump into per-hop
columns (`JOINED_HOPS`) with `dominant_hop` naming each trace's
critical path.

Import discipline: stdlib only at module scope (the obs contract) —
host-only consumers (the report, the launcher, test harnesses) never
initialize an accelerator backend through this package.
"""

from byzantinemomentum_tpu.obs.trace.request import (  # noqa: F401
    JOINED_HOPS,
    REQUEST_PHASES,
    ROUTER_PHASES,
    RequestTrace,
    TraceBuffer,
    dominant_hop,
    join_shard_trace,
    percentile,
    phase_spans,
)
from byzantinemomentum_tpu.obs.trace.incident import (  # noqa: F401
    INCIDENTS_DIRNAME,
    IncidentRecorder,
    load_incidents,
    merge_fleet_incidents,
    render_incidents,
)
from byzantinemomentum_tpu.obs.trace.fleet import (  # noqa: F401
    FLEET_TIMELINE_EVENTS,
    ClockOffsetTracker,
    estimate_offsets,
    fleet_timeline,
    load_fleet,
    render_fleet_report,
)

__all__ = [
    "JOINED_HOPS", "REQUEST_PHASES", "ROUTER_PHASES", "RequestTrace",
    "TraceBuffer", "dominant_hop", "join_shard_trace", "percentile",
    "phase_spans",
    "FLEET_TIMELINE_EVENTS", "ClockOffsetTracker", "estimate_offsets",
    "fleet_timeline", "load_fleet", "render_fleet_report",
    "INCIDENTS_DIRNAME", "IncidentRecorder", "load_incidents",
    "merge_fleet_incidents", "render_incidents",
]
