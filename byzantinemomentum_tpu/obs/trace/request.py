"""Per-request span tracing for the aggregation service.

One `RequestTrace` rides each `ServeRequest` through the serving stack,
stamping a monotonic clock at every hand-off. The stamps are cheap (one
`time.monotonic()` call and a dict store each — the measured overhead
budget is the serve selfcheck's trace phase), and the derived spans TILE
the measured request latency: summed, they must equal submit→resolve
wall time, so a latency regression always shows up in exactly one phase
instead of hiding between instruments.

Stamp points (writer in parentheses) and the spans between them:

  recv        (frontend)  the raw line arrived, before JSON parse
  accept      (service)   `submit()` entered
  submit      (service)   request enqueued (validation+admission done)
  flush       (batcher)   the flusher picked the request's batch
  packed      (service)   host-side numpy packing done
  dispatched  (service)   device_put + program call returned (async)
  resolver    (batcher)   the resolver thread picked the batch up
  device      (service)   `jax.device_get` returned (device done)
  done        (service)   this request's future about to resolve

  parse    = accept - recv        (frontend JSON decode; frontend only)
  validate = submit - accept      (validation + admission decision)
  queue    = flush - submit       (waiting for batch-mates / flusher)
  pack     = packed - flush       (host-side numpy packing)
  dispatch = dispatched - packed  (device_put + async program enqueue)
  resolver_wake = resolver - dispatched  (flusher→resolver hand-off)
  device   = device - resolver    (blocking on device completion)
  resolve  = done - device        (unpack, suspicion, future set)

`queue + pack + dispatch + resolver_wake + device + resolve` is the
request's submit→resolve latency; `parse`/`validate` sit before the
enqueue and are reported separately (a socket client pays them, the
in-process API pays only `validate`).

Completed traces land in a `TraceBuffer` — a bounded, thread-safe ring
(old traces fall off; the buffer can never grow a long-lived server's
heap) — whose `summary()` is the per-phase p50/p99 view served by
`stats` and the SIGUSR1 snapshot. When the buffer is handed a
`MetricsRegistry` (`obs/metrics`, r18), every completed trace also
feeds per-phase `serve_phase_<name>_ms` histograms — the ring summary
is a 512-trace window, the histograms are the process-lifetime
distribution the fleet scraper merges. Stdlib only: the obs import
discipline (no jax, no numpy) keeps every consumer host-only.

r19 adds the fleet-scope causal join: `join_shard_trace` splices a
shard's wire trace record (the `"trace"` key riding every response
since PR 13) into the router-measured envelope — clock-free, because
the shard contributes DURATIONS that nest under the router's
`shard_rtt`, never cross-host timestamps — and `dominant_hop` names
each trace's critical path, aggregated per-window by `summary()` and
process-lifetime by the `serve_critical_path_<hop>` registry counters.
"""

import collections
import itertools
import threading
import time

from byzantinemomentum_tpu.obs.metrics.registry import LATENCY_MS_BOUNDS
from byzantinemomentum_tpu.utils.locking import NamedLock

__all__ = ["JOINED_HOPS", "REQUEST_PHASES", "ROUTER_PHASES",
           "RequestTrace", "TraceBuffer", "dominant_hop",
           "join_shard_trace", "percentile", "phase_spans"]

# Span names in causal order: (phase, start stamp, end stamp). The first
# two phases precede the queue hand-off and are absent when the caller
# didn't stamp them (in-process submits have no `recv`).
REQUEST_PHASES = (
    ("parse", "recv", "accept"),
    ("validate", "accept", "submit"),
    ("queue", "submit", "flush"),
    ("pack", "flush", "packed"),
    ("dispatch", "packed", "dispatched"),
    ("resolver_wake", "dispatched", "resolver"),
    ("device", "resolver", "device"),
    ("resolve", "device", "done"),
)

# Phases whose sum IS the submit→resolve latency (the tiling contract)
LATENCY_PHASES = ("queue", "pack", "dispatch", "resolver_wake", "device",
                  "resolve")

# The fleet router's leg of the same contract (serve/fleet/router.py):
# `route` (line parse + ring lookup) and `shard_rtt` (owner-shard queue
# wait + forward + the shard's whole service time) are contiguous, so
# their sum tiles the router-path recv→reply latency exactly — the
# ATTRIB_serve r16 acceptance bound checks it like the service phases.
ROUTER_PHASES = (
    ("route", "recv", "routed"),
    ("shard_rtt", "routed", "reply"),
)

# Hop columns of a JOINED router+shard trace (`join_shard_trace`), in
# causal order. `route` is router-measured; `parked` is the dead-arc
# park window the forwarder stamps on replayed lines (r19); every
# `shard_*`/service hop is the shard's own monotonic duration spliced
# out of the wire record; `wire_residual` is what remains of the
# router-measured `shard_rtt` after the nested spans — forward/reply
# wire time plus the router's connection-queue wait, the only hop
# nobody times directly.
JOINED_HOPS = ("route", "parked", "wire_residual", "shard_frontend",
               "shard_queue", "pack", "dispatch", "resolver_wake",
               "device", "resolve")

# Shard-record phase -> joined hop column. `parse`+`validate` (frontend
# decode + admission) fold into one `shard_frontend` hop; `queue`
# surfaces as `shard_queue` — THE column the zipf hot-arc convoy lives
# in, opaque inside `shard_rtt` before r19.
_SHARD_HOP = {
    "parse": "shard_frontend",
    "validate": "shard_frontend",
    "queue": "shard_queue",
    "pack": "pack",
    "dispatch": "dispatch",
    "resolver_wake": "resolver_wake",
    "device": "device",
    "resolve": "resolve",
}


def phase_spans(stamps, phases):
    """{phase: ms} over a plain stamp dict for the given (phase, start,
    end) tuples — the RequestTrace span math for callers (the fleet
    router) whose stamp lifecycle doesn't fit the service pipeline.
    Returns None unless EVERY phase has both stamps (a partial router
    trace tiles nothing)."""
    spans = {}
    for phase, start, end in phases:
        t0, t1 = stamps.get(start), stamps.get(end)
        if t0 is None or t1 is None:
            return None
        spans[phase] = max(0.0, (t1 - t0) * 1000.0)
    return spans

def dominant_hop(spans):
    """The largest span of a {name: ms} dict (the trace's critical
    path, hop-granular). Ties break to the earliest-inserted name so
    the answer is deterministic; None on an empty dict."""
    best, best_ms = None, -1.0
    for name, ms in spans.items():
        if ms > best_ms:
            best, best_ms = name, ms
    return best


def join_shard_trace(stamps, shard_record):
    """Splice a shard's wire trace record into the router-measured
    envelope — the cross-process span join.

    Clock-free by construction: the shard's record carries DURATIONS
    from its own monotonic clock, never timestamps, so no cross-host
    clock comparison happens. The shard spans nest inside the
    router-measured `shard_rtt`; what the nesting leaves over —

        wire_residual = shard_rtt - parked - sum(shard spans)

    — is forward/reply wire time plus the router's connection queue,
    clamped >= 0 (a scheduler quantum can make the shard's own timers
    sum past the envelope by microseconds). A `parked`/`unparked` stamp
    pair (dead-arc replay, `--on-dead queue`) becomes its own hop so
    failover recovery latency is attributed instead of polluting the
    wire column.

    Returns the joined record:

        {"trace_id", "spans_ms": {hop: ms}, "total_ms", "dominant"}

    whose spans TILE the router's recv→reply wall (same contract as the
    service phases), or None when the router stamps are incomplete or
    the shard record is absent/malformed (non-dict, non-numeric or
    negative spans, no recognizable phase) — the caller degrades to the
    r16 opaque `shard_rtt` without severing the line."""
    router_spans = phase_spans(stamps, ROUTER_PHASES)
    if router_spans is None or not isinstance(shard_record, dict):
        return None
    shard_spans = shard_record.get("spans_ms")
    if not isinstance(shard_spans, dict):
        return None
    hops = {"route": router_spans["route"]}
    parked_ms = 0.0
    t0, t1 = stamps.get("parked"), stamps.get("unparked")
    if t0 is not None and t1 is not None:
        parked_ms = max(0.0, (t1 - t0) * 1000.0)
    if parked_ms > 0.0:
        hops["parked"] = parked_ms
    nested = 0.0
    recognized = False
    for phase, ms in shard_spans.items():
        hop = _SHARD_HOP.get(phase)
        if hop is None:
            continue   # unknown phases pass through (schema growth)
        if not isinstance(ms, (int, float)) or ms < 0.0:
            return None
        recognized = True
        hops[hop] = hops.get(hop, 0.0) + float(ms)
        nested += float(ms)
    if not recognized:
        return None
    hops["wire_residual"] = max(
        0.0, router_spans["shard_rtt"] - parked_ms - nested)
    record = {"spans_ms": {k: round(v, 4) for k, v in hops.items()},
              "total_ms": round(max(0.0, (stamps["reply"] - stamps["recv"])
                                    * 1000.0), 4),
              "dominant": dominant_hop(hops)}
    trace_id = shard_record.get("trace_id")
    if isinstance(trace_id, str):
        record["trace_id"] = trace_id
    return record


_ids = itertools.count(1)


class RequestTrace:
    """Monotonic-clock span stamps for one request's trip through the
    serving stack. Stamping is append-only and single-writer per stamp
    (each pipeline stage writes its own), so no lock is needed.

    Hot-path economics (the tracing-overhead budget is the selfcheck's
    trace phase and the committed ATTRIB_serve artifact): the per-batch
    hand-off stamps — flush, packed, dispatched, resolver, device — are
    IDENTICAL for every request of a batch, so the pipeline stamps them
    once into a shared `batch_stamps` dict each request references (one
    attribute store per request instead of five timestamped method
    calls); auto trace-id formatting is deferred to `as_dict()`."""

    __slots__ = ("_id", "stamps", "batch_stamps", "depth_at_submit",
                 "meta")

    def __init__(self, trace_id=None):
        # Explicit (wire) ids stringify up front; auto ids stay the bare
        # counter int until someone reads `trace_id`
        self._id = str(trace_id) if trace_id is not None else next(_ids)
        # Creation IS acceptance: the service constructs the trace on
        # `submit()` entry, so the accept stamp rides the constructor
        self.stamps = {"accept": time.monotonic()}
        self.batch_stamps = None      # shared per-batch stamp dict
        self.depth_at_submit = None   # queued requests when this one joined
        self.meta = None              # {gar, n, d} stamped at submit

    @property
    def trace_id(self):
        """The wire id (auto ids format lazily — never on the hot path)."""
        return self._id if isinstance(self._id, str) else f"t{self._id:08d}"

    @property
    def batch_size(self):
        return (self.batch_stamps or {}).get("batch_size")

    @property
    def batch_occupancy(self):
        return (self.batch_stamps or {}).get("batch_occupancy")

    def stamp(self, name, at=None):
        """Record stamp `name` now (or at the given monotonic time)."""
        self.stamps[name] = time.monotonic() if at is None else at

    def _stamp_at(self, name):
        value = self.stamps.get(name)
        if value is None and self.batch_stamps is not None:
            value = self.batch_stamps.get(name)
        return value

    def spans_ms(self):
        """{phase: ms} for every phase whose both stamps exist
        (per-request or shared batch stamps), in causal order. Negative
        spans are clamped to 0.0 (adjacent stamps taken on different
        threads can invert by scheduler quanta)."""
        spans = {}
        for phase, start, end in REQUEST_PHASES:
            t0, t1 = self._stamp_at(start), self._stamp_at(end)
            if t0 is not None and t1 is not None:
                spans[phase] = max(0.0, (t1 - t0) * 1000.0)
        return spans

    def total_ms(self):
        """submit→done wall time in ms (None before `done`)."""
        t0, t1 = self._stamp_at("submit"), self._stamp_at("done")
        if t0 is not None and t1 is not None:
            return max(0.0, (t1 - t0) * 1000.0)
        return None

    def as_dict(self):
        """The completed-trace record (ring buffer entry / response
        payload): spans in ms plus the queue/batch context."""
        record = {"trace_id": self.trace_id, "spans_ms": {
            k: round(v, 4) for k, v in self.spans_ms().items()}}
        total = self.total_ms()
        if total is not None:
            record["total_ms"] = round(total, 4)
        for key in ("depth_at_submit", "batch_size", "batch_occupancy"):
            value = getattr(self, key)
            if value is not None:
                record[key] = value
        if self.meta:
            record.update(self.meta)
        return record


def percentile(values, q):
    """Nearest-rank percentile of a non-empty sequence (stdlib-only — the
    obs package must not import numpy for a stats line)."""
    ordered = sorted(values)
    if not ordered:
        raise ValueError("percentile of an empty sequence")
    rank = max(0, min(len(ordered) - 1,
                      int(round(q / 100.0 * (len(ordered) - 1)))))
    return ordered[rank]


def _dist(values):
    """{p50, p99, mean, max} summary of a sample (rounded for JSON)."""
    return {
        "p50": round(percentile(values, 50), 4),
        "p99": round(percentile(values, 99), 4),
        "mean": round(sum(values) / len(values), 4),
        "max": round(max(values), 4),
    }


class TraceBuffer:
    """Bounded, thread-safe ring of completed traces.

    The resolver thread appends; `stats`/SIGUSR1 readers snapshot. The
    deque's maxlen is the bound — a long-lived server holds at most
    `maxlen` completed traces no matter how much traffic it serves.
    `add` is the serving hot path, so it stores the `RequestTrace`
    OBJECT (one lock + deque append); the dict conversion happens
    lazily at `snapshot()`/`summary()` time, on the reader's clock.

    `metrics` optionally feeds per-phase latency histograms
    (`serve_phase_<name>_ms`, the LATENCY_MS ladder) on every add —
    skipped entirely (no span math) when the registry is off, so the
    paired-overhead baseline arm pays nothing here."""

    def __init__(self, maxlen=512, *, metrics=None):
        if maxlen < 1:
            raise ValueError(f"Expected maxlen >= 1, got {maxlen}")
        self.maxlen = int(maxlen)
        self._ring = collections.deque(maxlen=self.maxlen)
        self._lock = NamedLock("trace.buffer")
        self._completed = 0
        self._metrics = (metrics if metrics is not None
                         and getattr(metrics, "enabled", False) else None)
        self._phase_hists = {}
        self._crit_counters = {}

    def _observe_phases(self, trace):
        spans = (trace.spans_ms() if isinstance(trace, RequestTrace)
                 else (trace.get("spans_ms") or {}))
        for phase, ms in spans.items():
            hist = self._phase_hists.get(phase)
            if hist is None:
                hist = self._metrics.histogram(
                    f"serve_phase_{phase}_ms", bounds=LATENCY_MS_BOUNDS)
                self._phase_hists[phase] = hist
            hist.observe(ms)
        # Critical-path extraction (r19): count the dominant phase onto
        # the registry so a scrape answers "where is the convoy" live
        # without replaying the ring
        hop = dominant_hop(spans)
        if hop is not None:
            counter = self._crit_counters.get(hop)
            if counter is None:
                # Registry `_get` is idempotent under its own lock, so a
                # concurrent first-observe races only on this cache slot
                # (last-wins with the SAME handle — benign)
                counter = self._metrics.counter(
                    f"serve_critical_path_{hop}")
                self._crit_counters[hop] = counter
            counter.inc()

    def add(self, trace):
        """Append one completed `RequestTrace` (or prebuilt record)."""
        if self._metrics is not None:
            self._observe_phases(trace)
        with self._lock:
            self._ring.append(trace)
            self._completed += 1

    def __len__(self):
        with self._lock:
            return len(self._ring)

    @property
    def completed(self):
        """Total traces ever completed (monotonic; the ring only holds
        the newest `maxlen` of them)."""
        with self._lock:
            return self._completed

    def snapshot(self):
        """The buffered traces as record dicts, oldest first (a copy —
        safe to mutate; conversion cost is paid here, never on the
        resolver thread)."""
        with self._lock:
            items = list(self._ring)
        return [t.as_dict() if isinstance(t, RequestTrace) else dict(t)
                for t in items]

    def summary(self):
        """Per-phase p50/p99/mean/max ms over the buffered traces, plus
        the queue-depth and batch-occupancy distributions — the `stats`
        payload's `tracing` section."""
        records = self.snapshot()
        out = {"completed": self.completed, "buffered": len(records),
               "maxlen": self.maxlen}
        if not records:
            return out
        phases = {}
        for record in records:
            for phase, ms in (record.get("spans_ms") or {}).items():
                phases.setdefault(phase, []).append(float(ms))
        out["phases_ms"] = {phase: _dist(values)
                           for phase, values in phases.items()}
        totals = [float(r["total_ms"]) for r in records
                  if isinstance(r.get("total_ms"), (int, float))]
        if totals:
            out["total_ms"] = _dist(totals)
        for key, label in (("depth_at_submit", "queue_depth"),
                           ("batch_size", "batch_size"),
                           ("batch_occupancy", "batch_occupancy")):
            values = [float(r[key]) for r in records
                      if isinstance(r.get(key), (int, float))]
            if values:
                out[label] = _dist(values)
        # Critical-path histogram over the window: how many traces each
        # hop/phase dominated. Joined records carry `dominant`
        # pre-computed (the router names it at splice time); plain
        # service records derive it here so the section exists for both.
        critical = {}
        for record in records:
            hop = record.get("dominant") or dominant_hop(
                record.get("spans_ms") or {})
            if hop is not None:
                critical[hop] = critical.get(hop, 0) + 1
        if critical:
            out["critical_path"] = dict(
                sorted(critical.items(), key=lambda kv: -kv[1]))
        return out
