"""Fleet-wide attribution: one causally-ordered timeline for a cluster
run directory.

A cluster run (`byzantinemomentum_tpu/cluster/`) leaves N+1 telemetry
streams behind: the launcher's `telemetry.jsonl` (fleet launches, fired
faults, host deaths, restart agreement, liveness transitions) and one
`hosts/host-<i>.telemetry.jsonl` per host (start/resume/end, per-step
progress gauges, checkpoint spans). Each stream is stamped with ITS
process's wall clock — joining them naively can order a host's step
AFTER the launcher observed the host dead. This module builds the joined
view the PR 12 runtime never had:

* **clock offsets** — the launcher estimates each host's clock skew from
  the heartbeat handshake it already performs: every supervision poll
  reads each host's atomic heartbeat, whose `updated` field is the
  host's clock at write time; `seen - updated` on the launcher's clock
  is `offset + delay` with transport/poll delay >= 0, so the MINIMUM
  over a run's polls is a one-sided offset estimate (the NTP argument,
  minus the return path). `ClockOffsetTracker` keeps the minimum; the
  launcher persists the estimates as one `clock_offsets` telemetry
  event at fleet teardown/end.
* **timeline** — `fleet_timeline(run_dir)` merges all streams with host
  timestamps shifted onto the launcher's clock (`t_host + offset`),
  sorted, each entry tagged with its `source` — so restart, fired-fault
  and liveness transitions read as one ordered story.
* **report** — `render_fleet_report(run_dir)` is the one-page fleet
  health view `obs_report` appends for cluster run dirs: the manifest
  summary (attempts, recoveries, status), per-host outcomes, clock
  offsets, and the ordered event timeline.

Stdlib only (the obs import discipline): the launcher and the report
tooling never initialize an accelerator backend through this module.
"""

import json
import pathlib
import re
import time

from byzantinemomentum_tpu.obs.heartbeat import HOSTS_DIRNAME
from byzantinemomentum_tpu.obs.recorder import load_records

__all__ = ["FLEET_TIMELINE_EVENTS", "HOST_TELEMETRY_PATTERN",
           "ClockOffsetTracker", "estimate_offsets", "fleet_timeline",
           "host_telemetry_path", "load_fleet", "render_fleet_report"]

# Events worth a line on the fleet timeline (everything else in the
# joined streams is summarized by count) — the launcher's supervision
# story plus each host's lifecycle marks.
FLEET_TIMELINE_EVENTS = (
    # launcher
    "cluster_start", "fleet_launch", "restart_agreed",
    "restart_disagreement", "fault_injected", "host_dead",
    "liveness_transition", "fleet_teardown", "wedge", "cluster_end",
    # hosts
    "host_start", "host_resume", "host_end", "restart", "rollback",
)

HOST_TELEMETRY_PATTERN = re.compile(r"host-(\d+)\.telemetry\.jsonl$")


def host_telemetry_path(run_dir, host_id):
    """Where host `host_id` of a cluster run writes its telemetry."""
    return (pathlib.Path(run_dir) / HOSTS_DIRNAME
            / f"host-{int(host_id)}.telemetry.jsonl")


class ClockOffsetTracker:
    """One-sided per-host clock-offset estimator over the launcher's
    heartbeat polls.

    `observe(host, host_wall, seen_wall)` folds one handshake sample:
    `seen_wall` (launcher clock, when the heartbeat was read) minus
    `host_wall` (host clock, the heartbeat's `updated` stamp) equals
    `offset + delay` with `delay >= 0` — the running MINIMUM over a
    fleet's polls is the tightest offset bound the one-way channel
    admits. `estimate()` maps host -> offset such that
    `t_launcher ~= t_host + offset`."""

    def __init__(self):
        self._min = {}
        self._samples = {}

    def observe(self, host, host_wall, seen_wall=None):
        if host_wall is None:
            return
        seen_wall = time.time() if seen_wall is None else seen_wall
        skew = float(seen_wall) - float(host_wall)
        host = int(host)
        current = self._min.get(host)
        if current is None or skew < current:
            self._min[host] = skew
        self._samples[host] = self._samples.get(host, 0) + 1

    def estimate(self):
        """{host: offset_seconds} (empty until the first observation)."""
        return dict(self._min)

    @property
    def samples(self):
        return dict(self._samples)

    def as_event_data(self):
        """The `clock_offsets` telemetry event payload the launcher
        persists (string keys: the record round-trips through JSON)."""
        return {"offsets": {str(h): round(o, 6)
                            for h, o in self._min.items()},
                "samples": {str(h): n for h, n in self._samples.items()}}


def load_fleet(run_dir):
    """All of a cluster run's telemetry streams:
    `{"launcher": [records], "hosts": {id: [records]}}` (empty lists for
    missing streams — a partially-recorded run still renders)."""
    run_dir = pathlib.Path(run_dir)
    hosts = {}
    hosts_dir = run_dir / HOSTS_DIRNAME
    if hosts_dir.is_dir():
        for path in sorted(hosts_dir.glob("host-*.telemetry.jsonl")):
            m = HOST_TELEMETRY_PATTERN.search(path.name)
            if m:
                hosts[int(m.group(1))] = load_records(path)
    return {"launcher": load_records(run_dir), "hosts": hosts}


def estimate_offsets(launcher_records):
    """{host: offset_seconds} from the newest `clock_offsets` event in a
    launcher telemetry stream (the tracker's persisted estimates).
    Missing event -> {} — hosts then merge unshifted, which is exact for
    same-machine fleets and a documented approximation otherwise."""
    offsets = {}
    for record in launcher_records:
        if record.get("kind") == "event" \
                and record.get("name") == "clock_offsets":
            data = (record.get("data") or {}).get("offsets") or {}
            parsed = {}
            for key, value in data.items():
                try:
                    parsed[int(key)] = float(value)
                except (TypeError, ValueError):
                    continue
            offsets = parsed  # newest event wins
    return offsets


def fleet_timeline(run_dir, *, events=FLEET_TIMELINE_EVENTS,
                   offsets=None):
    """The joined, causally-ordered fleet timeline.

    Returns a list of `{"t", "source", "name", "kind", "data"}` entries
    sorted by launcher-clock time: launcher records keep their stamps,
    host records are shifted by the per-host clock offset
    (`estimate_offsets` when not given). `events=None` keeps every
    event; the default keeps the supervision story
    (`FLEET_TIMELINE_EVENTS`). Span records (checkpoint save/load) ride
    along as entries with a `dur` field."""
    fleet = load_fleet(run_dir)
    if offsets is None:
        offsets = estimate_offsets(fleet["launcher"])

    entries = []

    def keep(record):
        if record.get("kind") == "span":
            return True
        if record.get("kind") != "event":
            return False
        return events is None or record.get("name") in events

    for record in fleet["launcher"]:
        if keep(record):
            entries.append({"t": float(record.get("t", 0.0)),
                            "source": "launcher",
                            "name": record.get("name"),
                            "kind": record.get("kind"),
                            "data": record.get("data") or {},
                            **({"dur": record["dur"]}
                               if "dur" in record else {})})
    for host, records in fleet["hosts"].items():
        shift = float(offsets.get(host, 0.0))
        for record in records:
            if keep(record):
                entries.append({"t": float(record.get("t", 0.0)) + shift,
                                "source": f"host-{host}",
                                "name": record.get("name"),
                                "kind": record.get("kind"),
                                "data": record.get("data") or {},
                                **({"dur": record["dur"]}
                                   if "dur" in record else {})})
    entries.sort(key=lambda e: e["t"])
    return entries


def host_progress(run_dir, *, offsets=None):
    """{host: [(t_launcher, step)]} from the hosts' per-step progress
    gauges (`host_step`), clock-shifted — the raw series behind
    `study.fleet_health`'s per-host lines."""
    fleet = load_fleet(run_dir)
    if offsets is None:
        offsets = estimate_offsets(fleet["launcher"])
    out = {}
    for host, records in fleet["hosts"].items():
        shift = float(offsets.get(host, 0.0))
        series = [(float(r.get("t", 0.0)) + shift, int(r["value"]))
                  for r in records
                  if r.get("kind") == "gauge" and r.get("name") == "host_step"
                  and isinstance(r.get("value"), (int, float))]
        if series:
            out[host] = series
    return out


def _fmt_offset(seconds):
    if seconds < 1e-3:
        return f"{seconds * 1e6:.0f}us"
    if seconds < 1.0:
        return f"{seconds * 1e3:.1f}ms"
    if seconds < 120.0:
        return f"{seconds:.2f}s"
    return f"{seconds / 60.0:.1f}min"


def render_fleet_report(run_dir, limit=40):
    """The fleet health view as text lines (the `obs_report` section for
    cluster run dirs). Empty list when the directory carries no cluster
    signal at all (no manifest, no host streams)."""
    run_dir = pathlib.Path(run_dir)
    try:
        manifest = json.loads((run_dir / "cluster.json").read_text())
        if not isinstance(manifest, dict):
            manifest = None
    except (OSError, ValueError):
        manifest = None
    fleet = load_fleet(run_dir)
    if manifest is None and not fleet["hosts"]:
        return []

    lines = []
    if manifest is not None:
        recoveries = manifest.get("recoveries") or []
        parts = [f"hosts={manifest.get('hosts')}",
                 f"status={manifest.get('status')}",
                 f"attempts={manifest.get('attempt')}",
                 f"recoveries={len(recoveries)}"]
        if manifest.get("restart_step") is not None:
            parts.append(f"restart_step={manifest['restart_step']}")
        if manifest.get("fired_faults"):
            parts.append(f"fired_faults={manifest['fired_faults']}")
        lines.append("fleet: " + ", ".join(parts))
        for rec in recoveries:
            lines.append(f"  recovery: host {rec.get('host')} died at step "
                         f"{rec.get('died_at_step')}, restarted from "
                         f"{rec.get('restart_step')} "
                         f"({rec.get('recovery_steps')} steps replayed)")

    offsets = estimate_offsets(fleet["launcher"])
    if offsets:
        lines.append("clock offsets (host -> launcher): " + ", ".join(
            f"host-{h} {_fmt_offset(abs(o))}"
            + ("" if o >= 0 else " ahead")
            for h, o in sorted(offsets.items())))

    timeline = fleet_timeline(run_dir, offsets=offsets)
    if timeline:
        t0 = timeline[0]["t"]
        lines.append(f"fleet timeline ({len(timeline)} entries"
                     + (f", last {limit}" if len(timeline) > limit else "")
                     + "):")
        for entry in timeline[-limit:]:
            offset = max(0.0, entry["t"] - t0)
            data = entry.get("data") or {}
            extra = " ".join(f"{k}={v}" for k, v in sorted(data.items())
                             if not isinstance(v, (dict, list)))
            dur = (f" [{entry['dur'] * 1e3:.1f}ms]"
                   if "dur" in entry else "")
            lines.append(f"  +{_fmt_offset(offset):<9} "
                         f"{entry['source']:<9} {entry['name']}{dur}"
                         + (f"  {extra}" if extra else ""))
    return lines
