"""SLO-triggered incident bundles: one atomic evidence file per edge.

The fleet already *has* the evidence when something goes wrong — the
router's joined trace ring knows the dominant hop, the metrics scraper
holds the window deltas that tripped the burn alert, the health
blackbox and the versioned membership file know which arc moved — but
it is scattered across per-process rings that keep rotating after the
incident. By the time a human looks, the interesting window fell off
the buffers. An `IncidentRecorder` fixes the decay: an edge event
(`slo_burn` from `obs/metrics/slo.py`, router arc death, a failover
restart, a straggler `KILLED`) triggers a capture that snapshots every
registered context provider and writes the lot as ONE atomic
`incidents/incident-<n>.json` bundle — the flight recorder dump, taken
at the instant of the edge, per process.

Design points, in the order they bit:

* **Triggers must be free.** The router liveness hook runs UNDER the
  router lock; a burn edge fires on the scraper thread mid-scrape.
  `trigger()` therefore only enqueues (a `queue.Queue.put`) and a
  daemon worker does the slow part — calling providers and fsyncing the
  bundle — strictly outside every caller lock.
* **Bundles are atomic and torn-tolerant.** Writes go through the
  heartbeat door (same-directory tmp → flush → fsync → `os.replace`),
  so a SIGKILL mid-write leaves whole bundles plus at most one orphan
  `.tmp` that `load_incidents` never reads. The reader still
  `json.loads` defensively and skips anything unparsable — readers
  never trust writers here.
* **The index is claimed under a lock.** Two concurrent captures must
  not both write `incident-<n>.json` for the same n (one bundle would
  silently vanish under `os.replace`) — the torn-bundle-write
  interleaving in `analysis/schedule.py::incident_bundle_model`, fixed
  by claiming `n` inside `_lock` before any I/O.
* **Evidence gathering never takes the fleet down.** A provider that
  raises contributes an `{"error": ...}` cell instead of killing the
  capture; the worker survives any single bad bundle.
* **Bounded, rate-limited.** A flapping burn edge cannot fill the disk:
  per-reason cooldown drops repeat captures inside `cooldown_s`, and
  the directory is a ring (`limit` newest bundles survive) like every
  other on-disk artifact in this repo.

Fleet scope: each process (launcher, every shard, the cluster
launcher) writes its OWN bundles under its result directory —
evidence-locality, the Ray-annotation discipline. At teardown the
launcher folds them into one ordered `incidents/fleet.json` index
(`merge_fleet_incidents`), and `obs_report` (`render_incidents`)
replays any bundle into the ordered causal story: burn edge → dominant
hop → arc/membership transition.

Stdlib only (the obs import discipline).
"""

import json
import os
import pathlib
import queue
import threading
import time

from byzantinemomentum_tpu.utils.locking import NamedLock

__all__ = ["INCIDENTS_DIRNAME", "IncidentRecorder", "load_incidents",
           "merge_fleet_incidents", "render_incidents"]

INCIDENTS_DIRNAME = "incidents"
FLEET_INDEX_NAME = "fleet.json"


class IncidentRecorder:
    """Edge-triggered capture of atomic evidence bundles.

    Args:
      directory: the process's result directory; bundles land in
        `<directory>/incidents/incident-<n>.json`.
      providers: {context name: zero-arg callable} — each capture calls
        every provider and stores its JSON-safe return under
        `context[name]` (an exception becomes an `{"error": ...}`
        cell). Typical providers: the trace-ring summary, the metrics
        window, the health blackbox, the membership version.
      limit: directory ring size — oldest bundles past it are deleted.
      cooldown_s: minimum seconds between captures of the SAME reason
        (a flapping edge dedupes to one bundle per window; drops count
        in `dropped`).
      source: stamped into each bundle (e.g. "launcher", "shard-2") so
        the fleet merge can attribute evidence to its process.
    """

    def __init__(self, directory, *, providers=None, limit=64,
                 cooldown_s=1.0, source=None):
        if limit < 1:
            raise ValueError(f"Expected limit >= 1, got {limit}")
        self.directory = pathlib.Path(directory) / INCIDENTS_DIRNAME
        self.providers = dict(providers or {})
        self.limit = int(limit)
        self.cooldown_s = float(cooldown_s)
        self.source = str(source) if source is not None else None
        self.captured = 0
        self.dropped = 0
        self._lock = NamedLock("incident.cooldown")
        self._n = self._next_index()
        self._last = {}   # reason -> monotonic time of last capture
        self._queue = queue.Queue()
        self._thread = None

    def _next_index(self):
        """Resume numbering past any bundle a previous incarnation of
        this process left behind (restarts must not overwrite
        evidence)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 1
        highest = 0
        for name in names:
            if name.startswith("incident-") and name.endswith(".json"):
                stem = name[len("incident-"):-len(".json")]
                if stem.isdigit():
                    highest = max(highest, int(stem))
        return highest + 1

    # -------------------------------------------------------------- #
    # the trigger side (any thread, any lock context)

    def start(self):
        """Start the capture worker. Idempotent; returns self."""
        with self._lock:   # two starters must not both spawn (BMT-L05)
            if self._thread is None:
                self._thread = threading.Thread(target=self._loop,
                                                name="incident-capture",
                                                daemon=True)
                self._thread.start()
        return self

    def trigger(self, reason, **data):
        """Request one capture. NON-BLOCKING and lock-free on the
        caller side — safe from the router's liveness hook (which runs
        under the router lock) and from the scraper thread. The worker
        snapshots the providers and writes the bundle."""
        self._queue.put((str(reason), data, time.time()))

    def _loop(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            reason, data, t = item
            try:
                self.capture(reason, data, t=t)
            except Exception:  # bmt: noqa[BMT-E05] evidence capture must outlive any single bad bundle — the worker serves every future edge
                pass

    # -------------------------------------------------------------- #
    # the capture side (worker thread; public for deterministic tests)

    def capture(self, reason, data=None, t=None):
        """Snapshot every provider and write one atomic bundle.
        Synchronous — tests and the selfcheck call it directly to skip
        the worker thread. Returns the bundle path, or None when the
        reason is inside its cooldown window."""
        reason = str(reason)
        now = time.monotonic()
        with self._lock:
            last = self._last.get(reason)
            if last is not None and now - last < self.cooldown_s:
                self.dropped += 1
                return None
            self._last[reason] = now
            # Claim the index BEFORE any I/O: concurrent captures with
            # distinct n can never collide on a filename, so no bundle
            # silently vanishes under os.replace (the
            # incident_bundle_model interleaving)
            n = self._n
            self._n += 1
        context = {}
        for name, provider in sorted(self.providers.items()):
            try:
                context[name] = provider()
            except Exception as err:  # bmt: noqa[BMT-E05] one broken provider forfeits its cell, not the whole bundle — and never the process that triggered
                context[name] = {"error": f"{type(err).__name__}: {err}"}
        bundle = {
            "kind": "incident",
            "n": n,
            "t": time.time() if t is None else float(t),
            "reason": reason,
            "data": dict(data or {}),
            "context": context,
        }
        if self.source is not None:
            bundle["source"] = self.source
        self.directory.mkdir(parents=True, exist_ok=True)
        path = self.directory / f"incident-{n}.json"
        # The heartbeat door: same-directory tmp, fsync, atomic rename.
        # A SIGKILL at any instant leaves whole bundles + at most one
        # orphan tmp the loader never reads.
        tmp = path.with_name(path.name + ".tmp")
        with tmp.open("w", encoding="utf-8") as fd:
            fd.write(json.dumps(bundle, ensure_ascii=False, indent=1))
            fd.write("\n")
            fd.flush()
            os.fsync(fd.fileno())
        os.replace(tmp, path)
        with self._lock:
            self.captured += 1
        self._prune()
        return path

    def _prune(self):
        """Ring the directory: delete the oldest bundles past `limit`."""
        try:
            names = [name for name in os.listdir(self.directory)
                     if name.startswith("incident-")
                     and name.endswith(".json")
                     and name[len("incident-"):-len(".json")].isdigit()]
        except OSError:
            return
        if len(names) <= self.limit:
            return
        names.sort(key=lambda s: int(s[len("incident-"):-len(".json")]))
        for name in names[:len(names) - self.limit]:
            try:
                os.unlink(self.directory / name)
            except OSError:
                pass

    def stop(self, timeout=5.0):
        """Drain queued triggers, stop the worker. Idempotent."""
        if self._thread is not None:
            self._queue.put(None)
            self._thread.join(timeout=timeout)
            self._thread = None

    def summary(self):
        with self._lock:
            return {"captured": self.captured, "dropped": self.dropped,
                    "next_n": self._n, "limit": self.limit}


# ------------------------------------------------------------------ #
# fleet-scope reading / merging


def _bundle_dirs(run_dir, fleet=True):
    run_dir = pathlib.Path(run_dir)
    dirs = [run_dir / INCIDENTS_DIRNAME]
    if fleet:
        dirs += sorted(run_dir.glob(f"shards/*/{INCIDENTS_DIRNAME}"))
        dirs += sorted(run_dir.glob(f"hosts/*/{INCIDENTS_DIRNAME}"))
    return dirs


def load_incidents(run_dir, *, fleet=True):
    """Every readable bundle under a run directory, ordered by
    (wall time, index). `fleet=True` also crawls per-process
    subdirectories (`shards/*/incidents`, `hosts/*/incidents`), tagging
    each bundle with its process when the writer didn't. Torn or
    half-written files are skipped — the atomic writer makes them
    near-impossible, but a reader never trusts that."""
    bundles = []
    run_dir = pathlib.Path(run_dir)
    for directory in _bundle_dirs(run_dir, fleet):
        if not directory.is_dir():
            continue
        source = (directory.parent.name
                  if directory.parent != run_dir else "launcher")
        for path in directory.glob("incident-*.json"):
            try:
                bundle = json.loads(path.read_text(encoding="utf-8"))
            except (OSError, ValueError):
                continue   # torn / unreadable: skip, never raise
            if not isinstance(bundle, dict):
                continue
            bundle.setdefault("source", source)
            bundles.append(bundle)
    bundles.sort(key=lambda b: (_num(b.get("t")), _num(b.get("n"))))
    return bundles


def _num(value, default=0.0):
    return float(value) if isinstance(value, (int, float)) else default


def merge_fleet_incidents(run_dir):
    """Launcher-side fleet merge: fold every per-process bundle into
    one ordered `incidents/fleet.json` index (atomic replace) so
    fleet-scope tooling reads one file instead of crawling process
    directories. Each row keeps the bundle headline — reason, source,
    time, the edge data, and the dominant hop if the trace context
    names one. Returns the index path, or None when no bundles
    exist."""
    bundles = load_incidents(run_dir, fleet=True)
    if not bundles:
        return None
    rows = []
    for bundle in bundles:
        row = {"n": bundle.get("n"), "t": bundle.get("t"),
               "reason": bundle.get("reason"),
               "source": bundle.get("source"),
               "data": bundle.get("data") or {}}
        hop = _dominant_from_bundle(bundle)
        if hop is not None:
            row["dominant_hop"] = hop
        rows.append(row)
    directory = pathlib.Path(run_dir) / INCIDENTS_DIRNAME
    directory.mkdir(parents=True, exist_ok=True)
    path = directory / FLEET_INDEX_NAME
    payload = {"kind": "incident_index", "incidents": len(rows),
               "rows": rows}
    tmp = path.with_name(path.name + ".tmp")
    with tmp.open("w", encoding="utf-8") as fd:
        fd.write(json.dumps(payload, ensure_ascii=False, indent=1))
        fd.write("\n")
        fd.flush()
        os.fsync(fd.fileno())
    os.replace(tmp, path)
    return path


# ------------------------------------------------------------------ #
# the report side: replay a bundle into the ordered causal story


def _critical_path_of(block):
    """Find a critical_path histogram anywhere useful in a trace
    context block (router stats carry it under `joined`)."""
    if not isinstance(block, dict):
        return None
    for candidate in (block.get("joined"), block.get("tracing"), block):
        if (isinstance(candidate, dict)
                and isinstance(candidate.get("critical_path"), dict)
                and candidate["critical_path"]):
            return candidate["critical_path"]
    return None


def _dominant_from_bundle(bundle):
    critical = _critical_path_of((bundle.get("context") or {}).get("trace"))
    if not critical:
        return None
    return max(critical, key=lambda hop: _num(critical.get(hop)))


def _story(bundle):
    """One bundle → the ordered causal story line:
    edge event → dominant hop → arc/membership transition."""
    data = bundle.get("data") or {}
    context = bundle.get("context") or {}
    reason = str(bundle.get("reason", "?"))
    if reason == "slo_burn":
        edge = (f"slo_burn[{data.get('slo', '?')}] "
                f"fast={_num(data.get('burn_fast')):.2f} "
                f"slow={_num(data.get('burn_slow')):.2f}")
    elif reason in ("arc_dead", "failover"):
        edge = f"{reason}[{data.get('shard', '?')}]"
    elif reason == "straggler_kill":
        edge = (f"straggler_kill[{data.get('host', '?')}] "
                f"{data.get('straggler_reason', data.get('why', ''))}"
                ).rstrip()
    else:
        edge = reason
    parts = [edge]
    critical = _critical_path_of(context.get("trace"))
    if critical:
        hop = max(critical, key=lambda h: _num(critical.get(h)))
        total = sum(int(_num(v)) for v in critical.values())
        parts.append(f"dominant hop {hop} ({int(_num(critical[hop]))}"
                     f"/{total} traces)")
    membership = context.get("membership")
    if isinstance(membership, dict) and membership:
        dead = membership.get("dead")
        arc = (f"membership v{membership.get('version', '?')}"
               + (f" dead={list(dead)}" if dead else " all arcs alive"))
        parts.append(arc)
    return " -> ".join(parts)


def render_incidents(run_dir, *, limit=8):
    """The `obs_report` incidents section: every bundle of the run
    (newest `limit`), each replayed into its one-line causal story plus
    the evidence cells the bundle captured. Returns a list of lines
    (empty when the run recorded no incidents)."""
    bundles = load_incidents(run_dir, fleet=True)
    if not bundles:
        return []
    t0 = _num(bundles[0].get("t"))
    sources = {}
    for bundle in bundles:
        source = str(bundle.get("source", "?"))
        sources[source] = sources.get(source, 0) + 1
    lines = [f"incidents: {len(bundles)} bundle"
             f"{'s' if len(bundles) != 1 else ''} ("
             + ", ".join(f"{n} {src}" for src, n in sorted(sources.items()))
             + ")"]
    for bundle in bundles[-limit:]:
        n = bundle.get("n", "?")
        source = bundle.get("source", "?")
        dt = _num(bundle.get("t")) - t0
        lines.append(f"  incident-{n} [{source}] t+{dt:.1f}s "
                     f"{bundle.get('reason', '?')}")
        lines.append(f"    story: {_story(bundle)}")
        context = bundle.get("context") or {}
        missing = [name for name, cell in sorted(context.items())
                   if isinstance(cell, dict) and "error" in cell]
        present = [name for name in sorted(context)
                   if name not in missing]
        if present:
            lines.append(f"    evidence: {', '.join(present)}"
                         + (f" (failed: {', '.join(missing)})"
                            if missing else ""))
    if len(bundles) > limit:
        lines.append(f"  ... {len(bundles) - limit} older bundle(s) "
                     f"not shown")
    return lines
