"""The run's `heartbeat.json` — one small, atomically-replaced file the
supervisor's watchdog reads instead of inferring liveness from study-CSV
mtime.

Write discipline mirrors `checkpoint.py`: payload to a same-directory
`.tmp`, fsync, `os.replace` onto the final name — a reader never sees a
torn file, and a SIGKILL at any instant leaves either the previous
heartbeat or the new one. The payload always carries:

  version   int    heartbeat schema version (1)
  pid       int    writer process id
  updated   float  wall-clock unix seconds of the write
  step      int    training step as of the write

plus whatever the writer knows: `steps_per_sec`, `device_step_ms`,
`rss_mb`, `mfu`, `status`, the recorder's counter totals under `counters`
and its `last_event` summary.

Host-only on purpose (no jax import): `utils/jobs.py` reads heartbeats
from supervisor threads that must never initialize a backend.
"""

import json
import os
import pathlib
import time

__all__ = ["HEARTBEAT_NAME", "write_heartbeat", "read_heartbeat"]

HEARTBEAT_NAME = "heartbeat.json"
VERSION = 1


def write_heartbeat(directory, payload):
    """Atomically write `heartbeat.json` under `directory`; `payload` keys
    override nothing — `version`/`pid`/`updated` are stamped here so every
    heartbeat is self-describing and freshness-comparable."""
    directory = pathlib.Path(directory)
    record = {"version": VERSION, "pid": os.getpid(), "updated": time.time()}
    record.update(payload)
    path = directory / HEARTBEAT_NAME
    tmp = path.with_name(path.name + ".tmp")
    with tmp.open("w", encoding="utf-8") as fd:
        fd.write(json.dumps(record, ensure_ascii=False, indent="\t"))
        fd.flush()
        os.fsync(fd.fileno())
    os.replace(tmp, path)
    return path


def read_heartbeat(directory):
    """The parsed heartbeat of a run directory, or None when absent or
    unreadable (never raises: the watchdog must not die on a mangled
    file, and a missing heartbeat just means the fallback signal rules)."""
    path = pathlib.Path(directory) / HEARTBEAT_NAME
    try:
        record = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None  # absent/torn/mid-replace file: the fallback signal rules
    return record if isinstance(record, dict) else None
