"""The run's `heartbeat.json` — one small, atomically-replaced file the
supervisor's watchdog reads instead of inferring liveness from study-CSV
mtime.

Write discipline mirrors `checkpoint.py`: payload to a same-directory
`.tmp`, fsync, `os.replace` onto the final name — a reader never sees a
torn file, and a SIGKILL at any instant leaves either the previous
heartbeat or the new one. The payload always carries:

  version   int    heartbeat schema version (1)
  pid       int    writer process id
  updated   float  wall-clock unix seconds of the write
  step      int    training step as of the write

plus whatever the writer knows: `steps_per_sec`, `device_step_ms`,
`rss_mb`, `mfu`, `status`, the recorder's counter totals under `counters`
and its `last_event` summary.

Host-only on purpose (no jax import): `utils/jobs.py` reads heartbeats
from supervisor threads that must never initialize a backend.

Multi-host runs (`byzantinemomentum_tpu/cluster/`) extend the scheme one
level: every host process writes its own atomic
`hosts/host-<i>.heartbeat.json` (same discipline, same payload shape plus
`host`/`resume_step`), and the cluster launcher aggregates them into the
run's single top-level `heartbeat.json` — so the `Jobs` watchdog
supervises a whole fleet through the exact same file a single-process run
writes. The per-host files are the raw signal the launcher's liveness
view (`cluster/manifest.py::liveness_view`) is computed from.
"""

import json
import os
import pathlib
import time

__all__ = ["HEARTBEAT_NAME", "HOSTS_DIRNAME", "write_heartbeat",
           "read_heartbeat", "host_heartbeat_path", "write_host_heartbeat",
           "read_host_heartbeats"]

HEARTBEAT_NAME = "heartbeat.json"
# Per-host heartbeat files of a multi-host run live under this
# subdirectory of the run's result directory
HOSTS_DIRNAME = "hosts"
VERSION = 1


def write_heartbeat(directory, payload, name=HEARTBEAT_NAME):
    """Atomically write `name` (default `heartbeat.json`) under
    `directory`; `payload` keys override nothing — `version`/`pid`/
    `updated` are stamped here so every heartbeat is self-describing and
    freshness-comparable."""
    directory = pathlib.Path(directory)
    record = {"version": VERSION, "pid": os.getpid(), "updated": time.time()}
    record.update(payload)
    path = directory / name
    tmp = path.with_name(path.name + ".tmp")
    with tmp.open("w", encoding="utf-8") as fd:
        fd.write(json.dumps(record, ensure_ascii=False, indent="\t"))
        fd.flush()
        os.fsync(fd.fileno())
    os.replace(tmp, path)
    return path


def read_heartbeat(directory, name=HEARTBEAT_NAME):
    """The parsed heartbeat of a run directory, or None when absent or
    unreadable (never raises: the watchdog must not die on a mangled
    file, and a missing heartbeat just means the fallback signal rules)."""
    path = pathlib.Path(directory) / name
    try:
        record = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None  # absent/torn/mid-replace file: the fallback signal rules
    return record if isinstance(record, dict) else None


# ------------------------------------------------------------------------- #
# Per-host heartbeats of a multi-host run (`byzantinemomentum_tpu/cluster/`)

def host_heartbeat_path(run_dir, host_id):
    return (pathlib.Path(run_dir) / HOSTS_DIRNAME
            / f"host-{int(host_id)}.heartbeat.json")


def write_host_heartbeat(run_dir, host_id, payload):
    """Atomically write host `host_id`'s heartbeat under the run's
    `hosts/` directory; the `host` id is stamped into the payload so the
    file is self-describing even when moved."""
    path = host_heartbeat_path(run_dir, host_id)
    path.parent.mkdir(parents=True, exist_ok=True)
    record = {"host": int(host_id)}
    record.update(payload)
    return write_heartbeat(path.parent, record, name=path.name)


def read_host_heartbeats(run_dir):
    """{host_id: record} over every readable per-host heartbeat of a run
    (absent hosts simply have no entry; torn files are skipped — the
    liveness view treats both as 'no signal yet')."""
    hosts_dir = pathlib.Path(run_dir) / HOSTS_DIRNAME
    out = {}
    if not hosts_dir.is_dir():
        return out
    for path in sorted(hosts_dir.glob("host-*.heartbeat.json")):
        record = read_heartbeat(hosts_dir, name=path.name)
        if record is None:
            continue
        suffix = path.name[len("host-"):-len(".heartbeat.json")]
        if suffix.isdigit():
            out[int(suffix)] = record
    return out
