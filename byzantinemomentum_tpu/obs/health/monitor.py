"""Host-side statistical-process-control over the in-jit health stream.

The device half (`engine/health.py`) ships one health vector per step;
this monitor decides — online, with bounded state — whether the training
dynamics have left their own envelope. Per monitored channel it keeps an
EWMA location estimate and an EWMA of the absolute deviation (a robust
MAD-style scale proxy; for a normal stream sigma ~= 1.2533 * MAD), both
in LOG domain (the channels are ratios/norms spanning decades, and the
failure modes — ALIE variance collapse, divergence blow-up — are
multiplicative), and scores each observation as a signed z.

Detection is Western-Electric-style sustained-run rules over the recent
z window, not a single threshold — a lone noisy step must not trip the
rollback trigger, while a sustained drift must trip it BEFORE the state
goes non-finite:

  spike      one observation with |z| >= `z_spike` (default 6)
  run 2/3    2 of the last 3 observations with z >= `z_run2` (3.5), same side
  run 4/5    4 of the last 5 observations with z >= `z_run4` (2.5), same side
  nonfinite  any NaN/Inf count > 0 — immediate, warm-up exempt (a NaN
             burst during warm-up is still a NaN burst)

Hysteresis: while a channel is anomalous its baseline FREEZES (the
envelope must not adapt to the failure it is flagging) and the channel
clears only after `clear_after` consecutive in-control observations
(|z| < `z_clear`), emitting `health_cleared`. A `warmup` gate keeps the
first steps' pure-noise baselines from firing the statistical rules.

The blackbox: a bounded ring of the last `ring` full health vectors
(plus their z-scores) and the last anomaly edges, dumped as
`health_blackbox.json` — the run's post-mortem flight recording.
"""

import collections
import json
import math
import pathlib

from byzantinemomentum_tpu.obs import recorder

__all__ = ["BLACKBOX_NAME", "CHANNELS", "HealthMonitor", "load_blackbox"]

BLACKBOX_NAME = "health_blackbox.json"

# Channels scored by the SPC rules, in log10 domain: the paper's
# variance-to-norm ratio (ALIE-style collapse reads as a sustained
# negative run, divergence as a positive one), the update-to-weight
# ratio (the classical step-size health signal) and the global weight
# norm (blow-up reads here first). The non-finite counts are a hard
# rule, not a channel.
CHANNELS = ("var_ratio", "update_ratio", "weight_norm")

# sigma ~= _MAD_SIGMA * E|x - mean| for a normal stream
_MAD_SIGMA = 1.2533

# Log-domain floor: channels can legitimately be 0 (e.g. a zero update
# under lr 0); log10 of the floor keeps them finite without inventing
# structure
_TINY = 1e-30


def _log10(value):
    value = abs(float(value))
    return math.log10(value if value > _TINY else _TINY)


class _Channel:
    """One monitored channel's EWMA/MAD baseline + recent-z window."""

    __slots__ = ("mean", "mad", "seen", "window", "anomalous", "clean_run")

    def __init__(self):
        self.mean = None
        self.mad = 0.0
        self.seen = 0
        self.window = collections.deque(maxlen=5)
        self.anomalous = False
        self.clean_run = 0


class HealthMonitor:
    """Online SPC over the per-step health vectors.

    Args:
      alpha: EWMA smoothing factor (weight of the newest observation).
      warmup: observations before the statistical rules may fire (the
        non-finite rule is exempt).
      z_spike / z_run2 / z_run4: the rule thresholds (see module doc).
      z_clear: |z| below which an observation counts as in-control.
      clear_after: consecutive in-control observations before an
        anomalous channel clears (`health_cleared`).
      ring: bounded blackbox depth (last K full health vectors).
      metrics: optional `MetricsRegistry` (obs/metrics) — anomaly and
        clear EDGES bump `health_anomaly_edges` / `health_cleared_edges`
        counters so the metrics plane carries the same signal the
        telemetry stream does (scrapeable without tailing telemetry).
    """

    def __init__(self, *, alpha=0.05, warmup=30, z_spike=6.0, z_run2=3.5,
                 z_run4=2.5, z_clear=2.0, clear_after=10, ring=256,
                 metrics=None):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"EWMA alpha must be in (0, 1], got {alpha}")
        if warmup < 1:
            raise ValueError(f"Expected warmup >= 1, got {warmup}")
        if ring < 1:
            raise ValueError(f"Expected ring >= 1, got {ring}")
        if not z_clear <= z_run4 <= z_run2 <= z_spike:
            raise ValueError(
                f"Expected z_clear <= z_run4 <= z_run2 <= z_spike, got "
                f"{z_clear}/{z_run4}/{z_run2}/{z_spike}")
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.z_spike = float(z_spike)
        self.z_run2 = float(z_run2)
        self.z_run4 = float(z_run4)
        self.z_clear = float(z_clear)
        self.clear_after = int(clear_after)
        self.steps = 0
        self.anomalies_total = 0
        self.last_anomaly = None      # the newest rising edge's payload
        self.last_step = None
        self.var_ratio_ewma = None    # linear-domain EWMA (heartbeat)
        self._channels = {name: _Channel() for name in CHANNELS}
        self._nonfinite_active = False
        self._rollback_pending = False
        self._ring = collections.deque(maxlen=int(ring))
        self._edges = collections.deque(maxlen=64)
        if metrics is not None and getattr(metrics, "enabled", False):
            self._m_anomalies = metrics.counter("health_anomaly_edges")
            self._m_cleared = metrics.counter("health_cleared_edges")
        else:
            self._m_anomalies = self._m_cleared = None

    # -------------------------------------------------------------- #

    def _z(self, channel, x):
        """Signed z of log-domain observation `x` against the channel's
        frozen-or-live baseline (0.0 before any baseline exists)."""
        if channel.mean is None:
            return 0.0
        # Scale floor: a perfectly flat warm-up stream has MAD 0; a
        # relative floor keeps its z at 0 instead of +inf, and an
        # absolute floor keeps near-zero log-means sane
        floor = max(abs(channel.mean) * 1e-3, 1e-6)
        sigma = max(_MAD_SIGMA * channel.mad, floor)
        return (x - channel.mean) / sigma

    def _fold(self, channel, x):
        if channel.mean is None:
            channel.mean = x
            channel.mad = 0.0
            channel.seen += 1
            return
        # Warm-up uses the running average (alpha 1/seen) so the baseline
        # converges as fast as the data allows — a fixed small alpha
        # would leave the mean lagging an early-training ramp (weight
        # norm leaving the origin, the momentum warm-up shrinking the
        # variance ratio) and fire the run rules on the transient
        alpha = max(self.alpha, 1.0 / (channel.seen + 1))
        channel.mad = ((1.0 - alpha) * channel.mad
                       + alpha * abs(x - channel.mean))
        channel.mean = (1.0 - alpha) * channel.mean + alpha * x
        channel.seen += 1

    def _rule(self, channel):
        """The first Western-Electric rule the recent window violates
        (None when in control). Run rules require same-side excursions."""
        window = list(channel.window)
        z = window[-1]
        if abs(z) >= self.z_spike:
            return "spike", z
        for depth, need, thresh, name in ((3, 2, self.z_run2, "run2of3"),
                                          (5, 4, self.z_run4, "run4of5")):
            recent = window[-depth:]
            if len(recent) < need:
                continue
            for side in (1.0, -1.0):
                if sum(1 for v in recent if v * side >= thresh) >= need:
                    return name, z
        return None

    # -------------------------------------------------------------- #

    def update(self, step, vector):
        """Fold one step's health vector into the monitor.

        Args:
          step: the step number (stamped on emitted events).
          vector: a dict with `var_ratio`, `update_ratio`, `weight_norm`
            (floats), `nonfinite` (total NaN/Inf count across phases) and
            optionally `norm_hist` (list of bucket counts) plus any extra
            keys — the full vector lands in the blackbox ring verbatim.
        Returns:
          True while any anomaly (statistical or non-finite) is active.
        """
        self.steps += 1
        self.last_step = int(step)
        zs = {}
        for name in CHANNELS:
            channel = self._channels[name]
            raw = vector.get(name)
            if raw is None or not math.isfinite(float(raw)):
                # A non-finite channel value is covered by the hard rule
                # below; never fold it into the baseline
                continue
            x = _log10(raw)
            z = self._z(channel, x)
            channel.window.append(z)
            zs[name] = round(z, 3)
            rule = None
            if self.steps > self.warmup:
                rule = self._rule(channel)
            if rule is not None and not channel.anomalous:
                channel.anomalous = True
                channel.clean_run = 0
                self._edge(True, name, rule[0], rule[1], step, raw)
            elif channel.anomalous:
                if abs(z) < self.z_clear and rule is None:
                    channel.clean_run += 1
                    if channel.clean_run >= self.clear_after:
                        channel.anomalous = False
                        channel.clean_run = 0
                        self._edge(False, name, None, z, step, raw)
                else:
                    channel.clean_run = 0
            if not channel.anomalous:
                # Freeze the baseline while anomalous: the envelope must
                # not adapt to the failure it is flagging
                self._fold(channel, x)

        raw_var = vector.get("var_ratio")
        if raw_var is not None and math.isfinite(float(raw_var)):
            self.var_ratio_ewma = (
                float(raw_var) if self.var_ratio_ewma is None
                else (1.0 - self.alpha) * self.var_ratio_ewma
                + self.alpha * float(raw_var))

        nonfinite = float(vector.get("nonfinite") or 0.0)
        if nonfinite > 0 and not self._nonfinite_active:
            self._nonfinite_active = True
            self._edge(True, "nonfinite", "nonfinite", None, step, nonfinite)
        elif nonfinite == 0 and self._nonfinite_active:
            self._nonfinite_active = False
            self._edge(False, "nonfinite", None, None, step, nonfinite)

        entry = {"step": int(step), "z": zs}
        entry.update({k: self._jsonable(v) for k, v in vector.items()})
        self._ring.append(entry)
        return self.anomaly

    @staticmethod
    def _jsonable(value):
        if isinstance(value, (list, tuple)):
            return [float(v) for v in value]
        try:
            value = float(value)
        except (TypeError, ValueError):
            return str(value)
        # JSON has no Inf/NaN; the blackbox must stay parseable
        return value if math.isfinite(value) else repr(value)

    def _edge(self, rising, channel, rule, z, step, value):
        name = "health_anomaly" if rising else "health_cleared"
        payload = {"channel": channel, "step": int(step),
                   "value": self._jsonable(value)}
        if rising:
            payload["rule"] = rule
            if z is not None:
                payload["z"] = round(float(z), 3)
            self.anomalies_total += 1
            self.last_anomaly = dict(payload)
            self._rollback_pending = True
            if self._m_anomalies is not None:
                self._m_anomalies.inc()
        elif self._m_cleared is not None:
            self._m_cleared.inc()
        recorder.emit(name, **payload)
        self._edges.append({"kind": name, **payload})

    # -------------------------------------------------------------- #

    @property
    def anomaly(self):
        """True while any channel (or the non-finite rule) is active."""
        return (self._nonfinite_active
                or any(c.anomalous for c in self._channels.values()))

    def rollback_pending(self):
        """Consume-once early-warning trigger: True exactly once per
        anomaly rising edge (the driver's `--rollback-on-anomaly` poll —
        one rollback per episode, not one per loop iteration)."""
        pending = self._rollback_pending
        self._rollback_pending = False
        return pending

    def note_rollback(self):
        """The driver rolled the trajectory back: clear the active
        anomalies and recent windows (the post-restore stream is a
        different trajectory) while keeping the learned baselines."""
        self._rollback_pending = False
        self._nonfinite_active = False
        for channel in self._channels.values():
            channel.anomalous = False
            channel.clean_run = 0
            channel.window.clear()

    # -------------------------------------------------------------- #

    def summary(self):
        """JSON-safe snapshot — the heartbeat's `health` block and the
        run-end `health_summary` event payload."""
        return {
            "steps": self.steps,
            "anomaly": self.anomaly,
            "anomalies_total": self.anomalies_total,
            "last_anomaly": self.last_anomaly,
            "var_ratio_ewma": (round(self.var_ratio_ewma, 10)
                               if self.var_ratio_ewma is not None else None),
            "channels": {
                name: {"anomalous": c.anomalous,
                       "mean_log10": (round(c.mean, 4)
                                      if c.mean is not None else None),
                       "mad_log10": round(c.mad, 4)}
                for name, c in self._channels.items()},
        }

    def blackbox(self, reason):
        """The flight recording as one JSON-safe dict."""
        return {
            "kind": "health_blackbox",
            "reason": str(reason),
            "last_step": self.last_step,
            "summary": self.summary(),
            "edges": list(self._edges),
            "ring": list(self._ring),
        }

    def dump_blackbox(self, directory, reason):
        """Write `health_blackbox.json` under `directory` (latest dump
        wins — the newest post-mortem is the one that matters) and emit a
        `health_blackbox` event. Returns the path, or None when the
        write fails (a full disk must not kill the run on its way to a
        rollback)."""
        path = pathlib.Path(directory) / BLACKBOX_NAME
        try:
            path.write_text(json.dumps(self.blackbox(reason),
                                       ensure_ascii=False, indent="\t"))
        except OSError:
            return None
        recorder.emit("health_blackbox", path=str(path), reason=str(reason),
                      ring=len(self._ring))
        return path


def load_blackbox(directory):
    """The parsed `health_blackbox.json` of a run directory, or None when
    absent/torn (report tooling must not die on a mangled dump)."""
    path = pathlib.Path(directory) / BLACKBOX_NAME
    try:
        data = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    return data if (isinstance(data, dict)
                    and data.get("kind") == "health_blackbox") else None
