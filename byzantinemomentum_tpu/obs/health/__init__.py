"""The numerics flight recorder's host half.

`engine/health.py` computes the per-step tensor-health vector inside the
compiled step (`HEALTH_COLUMNS`); this package watches the streamed
vectors on the host:

* **monitor** (`monitor.py`) — `HealthMonitor`: online EWMA + MAD
  z-scores per channel with Western-Electric-style sustained-run rules,
  emitting `health_anomaly` / `health_cleared` events through the active
  recorder, arming the early-warning rollback trigger
  (`cli/attack.py --rollback-on-anomaly`), and keeping a bounded ring of
  the last K full health vectors that is dumped as
  `health_blackbox.json` on rollback, divergence give-up, SIGUSR1 or run
  end — so every failed run leaves a post-mortem.

Stdlib-only (the obs import discipline): no jax, no numpy — the monitor
folds a handful of floats per step on the study-CSV flush path.
"""

from byzantinemomentum_tpu.obs.health.monitor import (  # noqa: F401
    BLACKBOX_NAME,
    HealthMonitor,
    load_blackbox,
)

__all__ = ["BLACKBOX_NAME", "HealthMonitor", "load_blackbox"]
