"""Span/event/counter/gauge recorder writing an append-only
`telemetry.jsonl` per run.

Record schema — one JSON object per line, every record carries:

  t     float   wall-clock unix seconds the record was written
  kind  str     "span" | "event" | "counter" | "gauge"
  name  str     what the record describes (snake_case)

plus per-kind fields:

  span     id (int), parent (int | None), dur (float seconds); the record
           is written at span EXIT, so `t - dur` is the start time and
           nesting is reconstructed through `parent`
  event    data (dict, optional) — arbitrary JSON-safe facts
  counter  value (int, the monotonic running total), inc (int)
  gauge    value (float), plus optional data (e.g. the step sampled at)

Writes are line-buffered and flushed per record: a SIGKILL mid-run loses
at most the line being written, and a torn final line is skipped by
`load_records` (the reader) instead of poisoning analysis — the same
"walk past the torn tail" stance as `checkpoint.find_latest_valid`.

The module-level *active recorder* (`activate`/`deactivate` + the free
functions `emit`/`span`/`counter`) is how layers without a handle —
`checkpoint.py`, the faults retry path — land on the run's timeline.
Every free function is a cheap no-op when no recorder is active, so
library code can instrument unconditionally.
"""

import contextlib
import itertools
import json
import os
import pathlib
import threading
import time

from byzantinemomentum_tpu.utils.locking import NamedLock

__all__ = ["TELEMETRY_NAME", "Telemetry", "activate", "deactivate", "active",
           "emit", "span", "counter", "install_compile_listener",
           "load_records"]

TELEMETRY_NAME = "telemetry.jsonl"


def _jsonable(value):
    """Coerce a record field to something json.dumps accepts (numpy scalars
    and paths arrive from the driver; a repr beats a crashed recorder)."""
    if isinstance(value, (str, int, float, bool, type(None))):
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    item = getattr(value, "item", None)  # numpy scalars
    if callable(item):
        try:
            return item()
        except Exception:  # bmt: noqa[BMT-E05] arbitrary user payloads ride the emit path; str() below is the serialization contract of last resort
            pass
    return str(value)


class Telemetry:
    """One run's telemetry recorder (thread-safe; the driver's main loop
    and the jax.monitoring compile listener may both write)."""

    def __init__(self, directory, interval=50, filename=TELEMETRY_NAME):
        self.directory = pathlib.Path(directory)
        self.interval = max(1, int(interval))
        self.path = self.directory / filename
        self._fd = self.path.open("a", encoding="utf-8")
        self._lock = NamedLock("telemetry.file")
        self._ids = itertools.count(1)
        self._stack = []           # open span ids, innermost last
        self._counters = {}
        self._last_event = None    # {"name": ..., "t": ...}

    # -------------------------------------------------------------- #
    # Record writers

    def _write(self, record):
        with self._lock:
            if self._fd is None:
                return  # closed recorders drop silently (listener races)
            self._fd.write(json.dumps(record, ensure_ascii=False,
                                      separators=(",", ":")) + "\n")
            self._fd.flush()

    def event(self, name, **data):
        """Point-in-time fact; `data` lands under the record's `data` key."""
        record = {"t": time.time(), "kind": "event", "name": str(name)}
        if data:
            record["data"] = _jsonable(data)
        self._last_event = {"name": str(name), "t": record["t"]}
        self._write(record)

    @contextlib.contextmanager
    def span(self, name, **data):
        """Timed scope; nesting is recorded through parent span ids. The
        record is written at exit (`t - dur` recovers the start)."""
        span_id = next(self._ids)
        with self._lock:
            parent = self._stack[-1] if self._stack else None
            self._stack.append(span_id)
        start = time.monotonic()
        try:
            yield span_id
        finally:
            dur = time.monotonic() - start
            with self._lock:
                if span_id in self._stack:
                    self._stack.remove(span_id)
            record = {"t": time.time(), "kind": "span", "name": str(name),
                      "id": span_id, "parent": parent, "dur": dur}
            if data:
                record["data"] = _jsonable(data)
            self._write(record)

    def counter(self, name, inc=1):
        """Monotonic counter; returns the new running total."""
        inc = int(inc)
        if inc < 0:
            raise ValueError(f"Counter increments must be >= 0, got {inc}")
        with self._lock:
            total = self._counters.get(name, 0) + inc
            self._counters[name] = total
        self._write({"t": time.time(), "kind": "counter", "name": str(name),
                     "value": total, "inc": inc})
        return total

    def gauge(self, name, value, **data):
        """Sampled measurement (steps/s, device step ms, RSS, MFU)."""
        record = {"t": time.time(), "kind": "gauge", "name": str(name),
                  "value": float(value)}
        if data:
            record["data"] = _jsonable(data)
        self._write(record)

    # -------------------------------------------------------------- #
    # State the heartbeat snapshots

    @property
    def counters(self):
        with self._lock:
            return dict(self._counters)

    @property
    def last_event(self):
        return self._last_event

    def heartbeat(self, step, **gauges):
        """Atomically (re)write the run's `heartbeat.json` with the current
        counter totals and last-event summary (see `heartbeat.py`)."""
        from byzantinemomentum_tpu.obs.heartbeat import write_heartbeat
        payload = {"step": int(step), "counters": self.counters,
                   "last_event": self._last_event}
        payload.update({k: _jsonable(v) for k, v in gauges.items()})
        write_heartbeat(self.directory, payload)

    def close(self):
        with self._lock:
            if self._fd is not None:
                self._fd.close()
                self._fd = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ------------------------------------------------------------------------- #
# Module-level active recorder: how handle-less layers reach the timeline

_ACTIVE = None


def activate(telemetry):
    """Make `telemetry` the process's active recorder (returns it)."""
    global _ACTIVE
    _ACTIVE = telemetry
    return telemetry


def deactivate():
    """Clear the active recorder (does NOT close it)."""
    global _ACTIVE
    _ACTIVE = None


def active():
    return _ACTIVE


def emit(name, **data):
    """Record an event on the active recorder, if any."""
    if _ACTIVE is not None:
        _ACTIVE.event(name, **data)


def counter(name, inc=1):
    """Bump a counter on the active recorder, if any."""
    if _ACTIVE is not None:
        return _ACTIVE.counter(name, inc)
    return None


def span(name, **data):
    """Span context on the active recorder; a no-op scope when inactive."""
    if _ACTIVE is not None:
        return _ACTIVE.span(name, **data)
    return contextlib.nullcontext()


# ------------------------------------------------------------------------- #
# Recompile detection

def install_compile_listener(telemetry):
    """Count XLA (re)compiles through `jax.monitoring`'s duration events:
    every `backend_compile` key bumps the `recompiles` counter and records
    a `compile` event with the backend-reported duration (the broader
    `/jax/core/compile/...` family also fires per jaxpr TRACE — hundreds
    per run — so only the actual backend compile counts). After the warmup
    compiles, a rising counter mid-run is the recompile smell (shape
    drift, milestone-residual windows, quorum rebuilds).

    Returns True when the listener could be installed (the monitoring API
    is version-dependent; absence degrades to a zero counter, not a crash).
    Imports jax lazily — see the package import discipline.
    """
    try:
        from jax import monitoring
    except ImportError:
        return False
    register = getattr(monitoring, "register_event_duration_secs_listener",
                       None)
    if register is None:
        return False

    def _on_duration(event, duration, **kwargs):
        try:
            if "backend_compile" in str(event):
                telemetry.counter("recompiles")
                telemetry.event("compile", key=str(event),
                                seconds=float(duration))
        except Exception:  # bmt: noqa[BMT-E05] this callback runs inside jax's compile path; a dead recorder must never break compilation
            pass

    try:
        register(_on_duration)
    except Exception:  # bmt: noqa[BMT-E05] version-dependent monitoring API; registration failure degrades to a zero counter, not a crash
        return False
    return True


# ------------------------------------------------------------------------- #
# Reader

def load_records(path):
    """Parse a `telemetry.jsonl` (file path or run directory) into a list
    of record dicts, skipping unparsable lines (a SIGKILL can tear the last
    one). Returns [] for a missing file."""
    path = pathlib.Path(path)
    if path.is_dir():
        path = path / TELEMETRY_NAME
    records = []
    try:
        text = path.read_text(encoding="utf-8")
    except OSError:
        return records
    for line in text.split(os.linesep if os.linesep in text else "\n"):
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(record, dict):
            records.append(record)
    return records
