"""`python -m byzantinemomentum_tpu.obs` — telemetry tooling entry point.

Two modes:

* `--selfcheck`: exercise the whole recorder/heartbeat/report stack in a
  temporary directory and exit 0 iff every invariant holds — the CI smoke
  hook (`scripts/run_test_tiers.py` and ad-hoc container checks) that
  proves the observability layer works without running a training step.
  Includes the attribution smoke: a tiny named-scope program is traced on
  the CPU backend, its xplane parsed and attributed (`obs/attrib`), and
  the resulting artifact is printed as one `attribution: {...}` JSON line
  for the tier harness to record. The metrics phase (r18) proves the
  metrics plane the same way — scrape roundtrip, N-shard merge parity,
  bump-cost sanity — printing one `metrics: {...}` line.
* `<run_dir>`: render the one-page report (same as `scripts/obs_report.py`).
"""

import json
import sys
import tempfile


def selfcheck():
    """End-to-end smoke of the obs stack; returns 0 on success, raising
    AssertionError (non-zero exit) on any broken invariant."""
    import pathlib

    from byzantinemomentum_tpu import obs

    with tempfile.TemporaryDirectory(prefix="bmt-obs-selfcheck-") as tmp:
        tmp = pathlib.Path(tmp)
        telemetry = obs.Telemetry(tmp, interval=5)
        obs.activate(telemetry)
        try:
            telemetry.event("run_start", argv=["selfcheck"])
            with telemetry.span("outer"):
                with telemetry.span("inner", step=1):
                    pass
            assert telemetry.counter("recompiles") == 1
            assert telemetry.counter("recompiles", 2) == 3
            telemetry.gauge("steps_per_sec", 123.0, step=5)
            obs.emit("rollback", step=5)       # module-level path
            with obs.span("module_span"):
                pass
            # Forensics path: a synthetic run with one planted Byzantine
            # worker (index 4: never selected, sitting far from the cloud)
            # must flag exactly that worker through the active recorder
            tracker = obs.SuspicionTracker(5, min_steps=5)
            selection = [1.0, 1.0, 1.0, 1.0, 0.0]
            distances = [1.0, 1.1, 0.9, 1.0, 9.0]
            for step in range(40):
                tracker.update(step, selection, distances=distances)
            assert tracker.suspects == [4], tracker.suspects
            assert tracker.max() == tracker.suspicion[4]
            telemetry.event("forensics_summary", **tracker.summary())
            telemetry.event("run_end", status="completed")
            telemetry.heartbeat(step=5, steps_per_sec=123.0,
                                rss_mb=obs.host_rss_mb())
        finally:
            obs.deactivate()
            telemetry.close()

        records = obs.load_records(tmp)
        kinds = {r["kind"] for r in records}
        assert kinds == {"event", "span", "counter", "gauge"}, kinds
        flagged = [r["data"]["worker"] for r in records
                   if r["kind"] == "event" and r["name"] == "suspect_worker"]
        assert flagged == [4], flagged
        spans = {r["name"]: r for r in records if r["kind"] == "span"}
        assert spans["inner"]["parent"] == spans["outer"]["id"]
        assert spans["outer"]["parent"] is None
        counters = [r["value"] for r in records if r["kind"] == "counter"]
        assert counters == sorted(counters), "counter went backwards"
        heartbeat = obs.read_heartbeat(tmp)
        assert heartbeat is not None and heartbeat["step"] == 5
        assert heartbeat["counters"]["recompiles"] == 3
        assert heartbeat["last_event"]["name"] == "run_end"
        assert not (tmp / (obs.HEARTBEAT_NAME + ".tmp")).exists()

        from byzantinemomentum_tpu.obs.report import render_report
        report = render_report(tmp)
        assert "recompiles=3" in report and "run_end" in report
        assert "forensics:" in report and "suspects=[4]" in report

    # Closed-loop phase (PR 11): verdicts must become actions — outside
    # the recorder window above so its suspect/evict events don't mix
    # into the timeline the assertions just pinned
    closed_loop_selfcheck()
    health_selfcheck()
    metrics_selfcheck()
    attribution_selfcheck()
    print("obs selfcheck: OK")
    return 0


def metrics_selfcheck():
    """The metrics plane holds its three contracts: (a) scrape
    ROUNDTRIP — a registry served through a `MetricsEndpoint` and
    pulled with `scrape_target` comes back byte-identical to the local
    `dump()`, and a `MetricsScraper` round lands it in the on-disk
    ring; (b) merge PARITY — N shard registries observing disjoint
    slices of one sample stream merge (bucket-wise) to bit-identical
    quantiles with a single oracle registry that observed every sample;
    (c) OVERHEAD — a counter bump plus a histogram observe stays
    microseconds-scale (sanity ceiling only; the real 2% bound is the
    paired loadgen run in `BENCH_metrics_r*.json`). Host-side stdlib
    only — no engine, no jax. Prints one `metrics: {...}` JSON line the
    tier harness records."""
    import pathlib
    import random
    import time

    from byzantinemomentum_tpu.obs.metrics import (LATENCY_MS_BOUNDS,
                                                   MetricsEndpoint,
                                                   MetricsRegistry,
                                                   MetricsScraper,
                                                   NullRegistry,
                                                   load_snapshots,
                                                   merge_payloads,
                                                   quantile_from_buckets,
                                                   scrape_target)

    rng = random.Random(0x3E791C5)

    # (b) merge parity first — the merged payload also feeds (a)'s ring
    # assertion. 3 shards, disjoint slices, one oracle seeing it all.
    samples = [rng.lognormvariate(1.5, 1.2) for _ in range(3000)]
    oracle = MetricsRegistry(source="oracle")
    shards = [MetricsRegistry(source=f"shard-{i}") for i in range(3)]
    for index, value in enumerate(samples):
        oracle.histogram("serve_request_ms").observe(value)
        oracle.counter("serve_requests").inc()
        shard = shards[index % len(shards)]
        shard.histogram("serve_request_ms").observe(value)
        shard.counter("serve_requests").inc()
    merged = merge_payloads([shard.dump() for shard in shards])
    oracle_dump = oracle.dump()
    parity = []
    for q in (0.5, 0.9, 0.99):
        cells = [payload["metrics"]["serve_request_ms"]
                 for payload in (merged, oracle_dump)]
        got, want = (quantile_from_buckets(
            tuple(cell["bounds"]), cell["counts"], q, cell["max"])
            for cell in cells)
        assert got == want, (q, got, want)  # bit-for-bit, never approx
        parity.append((q, got))
    assert merged["metrics"]["serve_requests"]["value"] == len(samples)
    assert (merged["metrics"]["serve_request_ms"]["counts"]
            == oracle_dump["metrics"]["serve_request_ms"]["counts"])

    # (a) scrape roundtrip: endpoint -> pull verb -> exact payload, then
    # a scraper round appends the merged view to the on-disk ring
    endpoint = MetricsEndpoint(("127.0.0.1", 0), oracle.dump)
    endpoint.serve_background()
    try:
        pulled = scrape_target("127.0.0.1", endpoint.port)
        assert pulled == oracle.dump(), "scrape changed the payload"
        with tempfile.TemporaryDirectory(
                prefix="bmt-metrics-selfcheck-") as tmp:
            scraper = MetricsScraper(
                {"oracle": ("127.0.0.1", endpoint.port)}, pathlib.Path(tmp))
            snapshot = scraper.scrape_once(now=1000.0)
            assert snapshot["reached"] == ["oracle"], snapshot
            ring = load_snapshots(pathlib.Path(tmp))
            assert len(ring) == 1, ring
            assert ring[0]["merged"]["metrics"]["serve_requests"]["value"] \
                == len(samples)
    finally:
        endpoint.shutdown()
        endpoint.server_close()

    # (c) overhead sanity: one bump = counter inc + histogram observe;
    # the ceiling is generous (mechanics proof — a pathological lock or
    # ladder scan fails, scheduler noise does not)
    live, null = MetricsRegistry(), NullRegistry()
    bumps = 20000
    costs = {}
    for label, registry in (("live", live), ("null", null)):
        counter = registry.counter("selfcheck_total")
        hist = registry.histogram("selfcheck_ms",
                                  bounds=LATENCY_MS_BOUNDS)
        t0 = time.perf_counter()
        for i in range(bumps):
            counter.inc()
            hist.observe(float(i % 97))
        costs[label] = (time.perf_counter() - t0) / bumps * 1e6
    assert costs["live"] < 1000.0, costs  # < 1 ms/bump: mechanics only

    print("metrics: " + json.dumps({
        "scrape_roundtrip": True,
        "ring_snapshots": 1,
        "merge_shards": len(shards),
        "merge_samples": len(samples),
        "merge_parity": {f"p{int(q * 100)}": value
                         for q, value in parity},
        "bump_us_live": round(costs["live"], 3),
        "bump_us_null": round(costs["null"], 3),
        "overhead_bound_frac": 0.02,
    }, sort_keys=True))


def health_selfcheck():
    """The numerics flight recorder holds its detection contract: (a) a
    planted mid-run NaN burst is flagged IMMEDIATELY (the hard rule is
    warm-up exempt); (b) an ALIE-style variance-collapse stream — the
    Var ratio dropping two orders of magnitude while everything else
    stays nominal — is flagged within a bounded step count; (c) a clean
    stream with realistic multiplicative noise and slow drift produces
    ZERO false positives over hundreds of steps; (d) the blackbox ring
    stays bounded and dumps a parseable post-mortem. Host-side stdlib
    only — no engine, no jax. Prints one `health: {...}` JSON line the
    tier harness records."""
    import math
    import pathlib
    import random

    from byzantinemomentum_tpu.obs.health import (HealthMonitor,
                                                  load_blackbox)

    rng = random.Random(0xF11687)

    def vector(var, upd, weight, nonfinite=0):
        return {"var_ratio": var, "update_ratio": upd,
                "weight_norm": weight, "nonfinite": nonfinite,
                "norm_hist": [0.0] * 16}

    def noise(sigma=0.05):
        return math.exp(rng.gauss(0.0, sigma))

    # (c) clean stream: multiplicative noise + the slow weight-norm drift
    # of a healthy run — not one anomaly allowed
    clean = HealthMonitor()
    for step in range(300):
        clean.update(step, vector(0.5 * noise(), 1e-3 * noise(),
                                  6.0 * (1.0 + 0.002 * step) * noise()))
    assert clean.anomalies_total == 0, clean.summary()

    # (a) NaN burst at step 40 of an otherwise clean stream: the hard
    # rule must flag ON the burst step (bound: 0 extra steps)
    burst = HealthMonitor()
    nan_flagged = None
    for step in range(60):
        nonfinite = 3 if 40 <= step < 43 else 0
        burst.update(step, vector(0.5 * noise(), 1e-3 * noise(), 6.0,
                                  nonfinite=nonfinite))
        if burst.anomaly and nan_flagged is None:
            nan_flagged = step
    assert nan_flagged == 40, nan_flagged

    # (b) ALIE-style variance collapse at step 60: the envelope leaves
    # its own history — must flag within 5 steps of the collapse
    alie = HealthMonitor()
    collapse_at, collapse_flagged = 60, None
    for step in range(90):
        var = (0.5 if step < collapse_at else 0.005) * noise()
        alie.update(step, vector(var, 1e-3 * noise(), 6.0 * noise()))
        if alie.anomaly and collapse_flagged is None:
            collapse_flagged = step
    assert collapse_flagged is not None \
        and collapse_flagged - collapse_at <= 5, collapse_flagged
    assert alie.last_anomaly["channel"] == "var_ratio", alie.last_anomaly

    # (d) bounded blackbox ring + parseable dump round-trip
    ring = HealthMonitor(ring=32)
    for step in range(100):
        ring.update(step, vector(0.5, 1e-3, 6.0))
    box = ring.blackbox("selfcheck")
    assert len(box["ring"]) == 32, len(box["ring"])
    with tempfile.TemporaryDirectory(prefix="bmt-health-selfcheck-") as tmp:
        assert ring.dump_blackbox(tmp, "selfcheck") is not None
        loaded = load_blackbox(pathlib.Path(tmp))
        assert loaded is not None and loaded["reason"] == "selfcheck"

    print("health: " + json.dumps({
        "clean_steps": 300,
        "clean_false_positives": clean.anomalies_total,
        "nan_burst_lag": nan_flagged - 40,
        "collapse_lag": collapse_flagged - collapse_at,
        "collapse_rule": alie.last_anomaly.get("rule"),
        "ring_bound": len(box["ring"]),
    }, sort_keys=True))


def closed_loop_selfcheck(K=25):
    """The defense loop closes: (a) a planted Byzantine pair is flagged
    AND quarantined within K steps; (b) a framing stream — an honest
    victim starved of selection and pushed to the single-outlier
    distance bound — ends with ZERO evictions (the hysteresis/threshold
    proof: the statistical channels a framer can aim at a victim are
    weighted below the eviction threshold; see `arena/quarantine.py`).
    Host-side numpy only — no engine, no jax."""
    import numpy as np

    from byzantinemomentum_tpu.arena import QuarantinePolicy

    n, f = 8, 2
    # (a) rows 6/7 attack: never selected, distant, mutually identical
    policy = QuarantinePolicy(n, f)
    selection = np.ones(n)
    selection[6:] = 0.0
    distances = np.ones(n)
    distances[6:] = 9.0
    dmat = np.full((n, n), 5.0)
    np.fill_diagonal(dmat, np.inf)
    dmat[6, 7] = dmat[7, 6] = 0.01
    for step in range(K):
        mask = policy.update(step, selection, distances=distances,
                             dist_matrix=dmat)
    assert {6, 7} <= set(policy.tracker.suspects), policy.tracker.suspects
    evicted = set(policy.evicted_at)
    assert evicted and evicted <= {6, 7}, policy.summary()
    assert not mask[sorted(evicted)[0]] and mask[:6].all(), mask

    # (b) framing: victim 0 starved + the worst single-outlier distance
    # (z self-bounds at sqrt(n-1) — a framer cannot push it further)
    framed = QuarantinePolicy(n, f)
    selection = np.ones(n)
    selection[0] = 0.0
    distances = np.ones(n)
    distances[0] = 50.0
    clean = np.full((n, n), 5.0)
    np.fill_diagonal(clean, np.inf)
    for step in range(3 * K):
        framed.update(step, selection, distances=distances,
                      dist_matrix=clean)
    assert framed.evictions_total == 0, framed.summary()
    print(f"closed loop: evicted={sorted(evicted)} within {K} steps, "
          f"framing evictions=0")


def attribution_selfcheck():
    """Prove the attribution pipeline end to end on the CPU backend: trace
    a tiny program whose phases are named like the engine's, parse the
    xplane, join phases through the compiled HLO text, and hold the
    artifact's invariants (phases tile the window; the engine's scopes are
    found). Prints one `attribution: {...}` JSON line the tier harness
    records as its per-tier artifact."""
    import os
    import pathlib

    # Deterministic CPU xplanes — and no accidental TPU tunnel dependency
    # (this environment's sitecustomize can force a TPU platform; the
    # config update after import is the part that sticks, see
    # tests/conftest.py)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    from byzantinemomentum_tpu import obs

    @jax.jit
    def step(x):
        with jax.named_scope("honest"):
            y = x @ x
        with jax.named_scope("gar"):
            z = jnp.sort(y, axis=0)
        with jax.named_scope("update"):
            w = z * 2.0 + 1.0
        return w.sum()

    x = jnp.ones((192, 192), jnp.float32)
    step(x).block_until_ready()  # compile outside the window
    hlo_text = step.lower(x).compile().as_text()
    steps = 4
    with tempfile.TemporaryDirectory(prefix="bmt-attrib-selfcheck-") as tmp:
        tmp = pathlib.Path(tmp)
        trace_dir = tmp / "trace"
        jax.profiler.start_trace(str(trace_dir))
        for _ in range(steps):
            step(x).block_until_ready()
        jax.profiler.stop_trace()

        att = obs.attrib.attribute_trace(
            trace_dir, steps, hlo_text=hlo_text, backend="cpu",
            device_kind=jax.devices()[0].device_kind)
        phases = att["phases"]
        assert att["total_ms"] > 0.0, att
        for name in ("honest", "gar", "update"):
            assert phases[name]["ms"] > 0.0, (name, phases)
        # The artifact invariant the acceptance test leans on: the phase
        # buckets (incl. other + host) tile the traced window exactly
        total = sum(p["ms"] for p in phases.values())
        assert abs(total - att["total_ms"]) < 1e-6 * max(1.0, total), att
        # Round-trip through the artifact file and the one-pager section
        obs.attrib.write_attribution(tmp, att)
        assert obs.attrib.load_attribution(tmp)["steps"] == steps
        from byzantinemomentum_tpu.obs.report import render_report
        report = render_report(tmp)
        assert "perf attribution" in report and "honest" in report, report
        print("attribution: " + json.dumps({
            "backend": att["backend"],
            "steps": steps,
            "total_ms": round(att["total_ms"], 4),
            "phases_ms": {k: round(v["ms"], 4)
                          for k, v in sorted(phases.items())
                          if v["ms"] > 0.0},
            "op_classes_ms": {k: round(v, 4)
                              for k, v in sorted(att["op_classes"].items())},
            "host_gap_fraction": round(att["host_gap_fraction"], 4),
        }, sort_keys=True))


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if "--selfcheck" in argv:
        return selfcheck()
    from byzantinemomentum_tpu.obs.report import main as report_main
    return report_main(argv)


if __name__ == "__main__":
    sys.exit(main())
