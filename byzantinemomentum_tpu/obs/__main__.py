"""`python -m byzantinemomentum_tpu.obs` — telemetry tooling entry point.

Two modes:

* `--selfcheck`: exercise the whole recorder/heartbeat/report stack in a
  temporary directory and exit 0 iff every invariant holds — the CI smoke
  hook (`scripts/run_test_tiers.py` and ad-hoc container checks) that
  proves the observability layer works without running a training step.
* `<run_dir>`: render the one-page report (same as `scripts/obs_report.py`).
"""

import sys
import tempfile


def selfcheck():
    """End-to-end smoke of the obs stack; returns 0 on success, raising
    AssertionError (non-zero exit) on any broken invariant."""
    import pathlib

    from byzantinemomentum_tpu import obs

    with tempfile.TemporaryDirectory(prefix="bmt-obs-selfcheck-") as tmp:
        tmp = pathlib.Path(tmp)
        telemetry = obs.Telemetry(tmp, interval=5)
        obs.activate(telemetry)
        try:
            telemetry.event("run_start", argv=["selfcheck"])
            with telemetry.span("outer"):
                with telemetry.span("inner", step=1):
                    pass
            assert telemetry.counter("recompiles") == 1
            assert telemetry.counter("recompiles", 2) == 3
            telemetry.gauge("steps_per_sec", 123.0, step=5)
            obs.emit("rollback", step=5)       # module-level path
            with obs.span("module_span"):
                pass
            # Forensics path: a synthetic run with one planted Byzantine
            # worker (index 4: never selected, sitting far from the cloud)
            # must flag exactly that worker through the active recorder
            tracker = obs.SuspicionTracker(5, min_steps=5)
            selection = [1.0, 1.0, 1.0, 1.0, 0.0]
            distances = [1.0, 1.1, 0.9, 1.0, 9.0]
            for step in range(40):
                tracker.update(step, selection, distances=distances)
            assert tracker.suspects == [4], tracker.suspects
            assert tracker.max() == tracker.suspicion[4]
            telemetry.event("forensics_summary", **tracker.summary())
            telemetry.event("run_end", status="completed")
            telemetry.heartbeat(step=5, steps_per_sec=123.0,
                                rss_mb=obs.host_rss_mb())
        finally:
            obs.deactivate()
            telemetry.close()

        records = obs.load_records(tmp)
        kinds = {r["kind"] for r in records}
        assert kinds == {"event", "span", "counter", "gauge"}, kinds
        flagged = [r["data"]["worker"] for r in records
                   if r["kind"] == "event" and r["name"] == "suspect_worker"]
        assert flagged == [4], flagged
        spans = {r["name"]: r for r in records if r["kind"] == "span"}
        assert spans["inner"]["parent"] == spans["outer"]["id"]
        assert spans["outer"]["parent"] is None
        counters = [r["value"] for r in records if r["kind"] == "counter"]
        assert counters == sorted(counters), "counter went backwards"
        heartbeat = obs.read_heartbeat(tmp)
        assert heartbeat is not None and heartbeat["step"] == 5
        assert heartbeat["counters"]["recompiles"] == 3
        assert heartbeat["last_event"]["name"] == "run_end"
        assert not (tmp / (obs.HEARTBEAT_NAME + ".tmp")).exists()

        from byzantinemomentum_tpu.obs.report import render_report
        report = render_report(tmp)
        assert "recompiles=3" in report and "run_end" in report
        assert "forensics:" in report and "suspects=[4]" in report

    print("obs selfcheck: OK")
    return 0


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if "--selfcheck" in argv:
        return selfcheck()
    from byzantinemomentum_tpu.obs.report import main as report_main
    return report_main(argv)


if __name__ == "__main__":
    sys.exit(main())
