"""Raw dataset sources: pure-numpy parsers for the standard archive formats,
disk-cache discovery, and a deterministic synthetic fallback.

The reference delegates parsing/downloading to torchvision
(`experiments/dataset.py:100-132`, `download=True` at `:296`); this
environment has no torchvision and no network egress, so the parsers are
implemented directly against the published file formats:

* MNIST family (MNIST / FashionMNIST / KMNIST) — idx ubyte files
  (optionally gzipped; bare filenames without the dataset subdir are only
  accepted for plain MNIST, since the family shares filenames).
* CIFAR-10 / CIFAR-100 — the python-pickle batch files (optionally inside the
  distribution .tar.gz).

Search order for raw data: `$BMT_DATA_DIR`, `./data`,
`~/.cache/byzantinemomentum_tpu`, `/root/data`. When nothing is found, a
deterministic synthetic dataset with the same shapes, cardinalities and label
balance is generated (seeded by dataset name), so training, tests and
benchmarks run hermetically. Synthetic sizes can be shrunk via
`$BMT_SYNTH_TRAIN` / `$BMT_SYNTH_TEST` for fast tests.
"""

import gzip
import hashlib
import os
import pathlib
import pickle
import struct
import tarfile
import urllib.request
import zlib

import numpy as np

from byzantinemomentum_tpu import utils

__all__ = ["data_dirs", "load_mnist", "load_emnist", "load_qmnist",
           "load_cifar", "load_svhn", "synthetic_images",
           "download_enabled", "ensure_downloaded"]


def data_dirs():
    """Candidate directories holding raw dataset files."""
    dirs = []
    env = os.environ.get("BMT_DATA_DIR")
    if env:
        dirs.append(pathlib.Path(env))
    dirs.append(pathlib.Path.cwd() / "data")
    dirs.append(pathlib.Path.home() / ".cache" / "byzantinemomentum_tpu")
    dirs.append(pathlib.Path("/root/data"))
    return [d for d in dirs if d.is_dir()]


# --------------------------------------------------------------------------- #
# Opt-in checksummed download path (reference: torchvision `download=True`,
# reference `experiments/dataset.py:296`, and the LIBSVM URL fetch,
# `experiments/datasets/svm.py:68-76`). OFF by default: this build
# environment has no network egress, so the default path stays
# disk-or-synthetic; outside it, `BMT_DOWNLOAD=1` (or the CLI `--download`)
# lets the framework self-provision data.
#
# Checksums are `md5:<hex>` (the values torchvision pins for these exact
# files) or `sha256:<hex>`; entries with checksum None have no published
# digest and are fetched only under `BMT_DOWNLOAD_UNVERIFIED=1`.

_DL_MNIST = "https://ossci-datasets.s3.amazonaws.com/mnist/"
_DL_FASHION = "http://fashion-mnist.s3-website.eu-central-1.amazonaws.com/"
_DL_KMNIST = "http://codh.rois.ac.jp/kmnist/dataset/kmnist/"
_DL_QMNIST = "https://raw.githubusercontent.com/facebookresearch/qmnist/master/"

DOWNLOADS = {
    "mnist": [
        (_DL_MNIST + "train-images-idx3-ubyte.gz",
         "md5:f68b3c2dcbeaaa9fbdd348bbdeb94873",
         "MNIST/raw/train-images-idx3-ubyte.gz"),
        (_DL_MNIST + "train-labels-idx1-ubyte.gz",
         "md5:d53e105ee54ea40749a09fcbcd1e9432",
         "MNIST/raw/train-labels-idx1-ubyte.gz"),
        (_DL_MNIST + "t10k-images-idx3-ubyte.gz",
         "md5:9fb629c4189551a2d022fa330f9573f3",
         "MNIST/raw/t10k-images-idx3-ubyte.gz"),
        (_DL_MNIST + "t10k-labels-idx1-ubyte.gz",
         "md5:ec29112dd5afa0611ce80d1b7f02629c",
         "MNIST/raw/t10k-labels-idx1-ubyte.gz"),
    ],
    "fashionmnist": [
        (_DL_FASHION + "train-images-idx3-ubyte.gz",
         "md5:8d4fb7e6c68d591d4c3dfef9ec88bf0d",
         "FashionMNIST/raw/train-images-idx3-ubyte.gz"),
        (_DL_FASHION + "train-labels-idx1-ubyte.gz",
         "md5:25c81989df183df01b3e8a0aad5dffbe",
         "FashionMNIST/raw/train-labels-idx1-ubyte.gz"),
        (_DL_FASHION + "t10k-images-idx3-ubyte.gz",
         "md5:bef4ecab320f06d8554ea6380940ec79",
         "FashionMNIST/raw/t10k-images-idx3-ubyte.gz"),
        (_DL_FASHION + "t10k-labels-idx1-ubyte.gz",
         "md5:bb300cfdad3c16e7a12a480ee83cd310",
         "FashionMNIST/raw/t10k-labels-idx1-ubyte.gz"),
    ],
    # KMNIST/QMNIST digests are the ones torchvision pins for these exact
    # files (torchvision `datasets/mnist.py` KMNIST.resources,
    # `datasets/qmnist.py` QMNIST.resources), so neither dataset needs the
    # BMT_DOWNLOAD_UNVERIFIED escape hatch
    "kmnist": [
        (_DL_KMNIST + f, f"md5:{md5}", f"KMNIST/raw/{f}")
        for f, md5 in (
            ("train-images-idx3-ubyte.gz", "bdb82020997e1d708af4cf47b453dcf7"),
            ("train-labels-idx1-ubyte.gz", "e144d726b3acfaa3e44228e80efcd344"),
            ("t10k-images-idx3-ubyte.gz", "5c965bf0a639b31b8f53240b1b52f4d7"),
            ("t10k-labels-idx1-ubyte.gz", "7320c461ea6c1c855c0b718fb2a4b134"),
        )
    ],
    "qmnist": [
        (_DL_QMNIST + f + ".gz", f"md5:{md5}", f"QMNIST/raw/{f}.gz")
        for f, md5 in (
            ("qmnist-train-images-idx3-ubyte",
             "ed72d4157d28c017586c42bc6afe6370"),
            ("qmnist-train-labels-idx2-int",
             "0058f8dd561b90ffdd0f734c6a30e5e4"),
            ("qmnist-test-images-idx3-ubyte",
             "1394631089c404de565df7b7aeaf9412"),
            ("qmnist-test-labels-idx2-int",
             "5b5b05890a5e13444e108efe57b788aa"),
        )
    ],
    "cifar10": [
        ("https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz",
         "md5:c58f30108f718f92721af3b95e74349a", "cifar-10-python.tar.gz"),
    ],
    "cifar100": [
        ("https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz",
         "md5:eb9058c3a382ffc7106e4002c42a8d85", "cifar-100-python.tar.gz"),
    ],
    "phishing": [
        ("https://www.csie.ntu.edu.tw/~cjlin/libsvmtools/datasets/binary"
         "/phishing", None, "phishing"),
    ],
    "svhn": [
        ("http://ufldl.stanford.edu/housenumbers/train_32x32.mat",
         "md5:e26dedcc434d2e4c54c9b2d4a06d8373", "SVHN/train_32x32.mat"),
        ("http://ufldl.stanford.edu/housenumbers/test_32x32.mat",
         "md5:eb5a983be6a315427106f1b164d9cef3", "SVHN/test_32x32.mat"),
    ],
}


def download_enabled():
    return os.environ.get("BMT_DOWNLOAD", "").lower() not in ("", "0",
                                                              "false", "no")


def _download_root():
    """First writable data dir (created if none exists)."""
    env = os.environ.get("BMT_DATA_DIR")
    root = (pathlib.Path(env) if env
            else pathlib.Path.home() / ".cache" / "byzantinemomentum_tpu")
    root.mkdir(parents=True, exist_ok=True)
    return root


def _digest(path, checksum):
    algo, _, want = checksum.partition(":")
    h = hashlib.new(algo)
    with open(path, "rb") as fd:
        for chunk in iter(lambda: fd.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest(), want


def _fetch_env(name, default, cast):
    raw = os.environ.get(name, "")
    try:
        return cast(raw) if raw else default
    except ValueError:
        utils.warning(f"Invalid {name}={raw!r}; using {default}")
        return default


def _fetch(url, dest, checksum, opener=None):
    """Stream `url` to `dest` atomically (tmp + rename), verifying
    `checksum` before the rename so a bad payload never lands under a
    valid name. `opener` is injectable for tests.

    Degradation policy (`faults/retry.py`): the connection carries a stall
    timeout (a hung socket raises `OSError` and takes the documented
    disk/synthetic degrade path instead of blocking setup forever), and
    transient `OSError`s are retried with exponential backoff. Knobs:
    `BMT_FETCH_TIMEOUT` (seconds, default 60), `BMT_FETCH_ATTEMPTS`
    (default 3), `BMT_FETCH_BACKOFF` (base seconds, default 1). A checksum
    mismatch is NOT transient and never retried (same payload would come
    back; a reachable-but-corrupt source must raise)."""
    from byzantinemomentum_tpu.faults.retry import with_backoff

    if opener is None:
        timeout = _fetch_env("BMT_FETCH_TIMEOUT", 60.0, float)
        opener = lambda u: urllib.request.urlopen(u, timeout=timeout)  # noqa: E731
    dest.parent.mkdir(parents=True, exist_ok=True)
    tmp = dest.with_name(dest.name + ".part")

    def attempt():
        with opener(url) as response, open(tmp, "wb") as out:
            for chunk in iter(lambda: response.read(1 << 20), b""):
                out.write(chunk)
        if checksum is not None:
            got, want = _digest(tmp, checksum)
            if got != want:
                raise utils.UserException(
                    f"Checksum mismatch for {url}: expected {checksum}, "
                    f"got {got} — refusing to install the file")
        tmp.replace(dest)

    try:
        with_backoff(
            attempt,
            attempts=_fetch_env("BMT_FETCH_ATTEMPTS", 3, int),
            base_delay=_fetch_env("BMT_FETCH_BACKOFF", 1.0, float),
            on_retry=lambda i, delay, err: utils.warning(
                f"Fetch of {url} failed ({err}); retry in {delay:.0f}s"))
    finally:
        tmp.unlink(missing_ok=True)


def ensure_downloaded(name, opener=None):
    """Fetch `name`'s published files into the download root if downloading
    is enabled and they are not already present anywhere in the data dirs.
    Returns True if anything was fetched (callers re-probe the disk)."""
    if not download_enabled() or name not in DOWNLOADS:
        return False
    unverified_ok = os.environ.get(
        "BMT_DOWNLOAD_UNVERIFIED", "").lower() not in ("", "0", "false", "no")
    fetched = False
    for url, checksum, rel in DOWNLOADS[name]:
        # Probe the subdir-qualified path ONLY: the MNIST family shares
        # bare idx filenames, so a bare-basename probe would cross-match a
        # sibling dataset's cached tree and silently skip the fetch
        if _find(rel) is not None:
            continue
        if checksum is None and not unverified_ok:
            utils.warning(
                f"{name}: no published checksum for {url}; set "
                "BMT_DOWNLOAD_UNVERIFIED=1 to fetch it anyway")
            continue
        utils.trace(f"{name}: downloading {url}")
        try:
            _fetch(url, _download_root() / rel, checksum, opener=opener)
        except OSError as err:
            # Unreachable network degrades to the next source (disk probe,
            # then the synthetic fallback) — a checksum mismatch does NOT
            # take this path: a reachable-but-corrupt source must raise
            utils.warning(f"{name}: download of {url} failed ({err}); "
                          "continuing without it")
            continue
        fetched = True
    return fetched


def _find(*names):
    """Locate the first existing file among `names` in the data dirs (also
    checks one level of common subdirectories)."""
    for base in data_dirs():
        for name in names:
            for cand in (base / name, *(base.glob(f"*/{name}")),
                         *(base.glob(f"*/*/{name}"))):
                if cand.is_file():
                    return cand
    return None


def _find_top(*names):
    """Like `_find` but base-level only — for bare filenames that would
    otherwise glob into a SIBLING dataset's subdir (the MNIST family shares
    idx filenames, so `data/KMNIST/raw/train-images-idx3-ubyte` must not
    satisfy a plain-mnist request)."""
    for base in data_dirs():
        for name in names:
            cand = base / name
            if cand.is_file():
                return cand
    return None


# --------------------------------------------------------------------------- #
# idx (MNIST family)

# idx type codes (byte 3 of the magic): published MNIST/idx format table
_IDX_DTYPES = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.dtype(">i2"),
               0x0C: np.dtype(">i4"), 0x0D: np.dtype(">f4"),
               0x0E: np.dtype(">f8")}


def _read_idx(path):
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "rb") as fd:
        magic, = struct.unpack(">I", fd.read(4))
        code = (magic >> 8) & 0xFF
        ndim = magic & 0xFF
        dims = struct.unpack(f">{ndim}I", fd.read(4 * ndim))
        if code not in _IDX_DTYPES:
            raise utils.UserException(
                f"Invalid idx file {path}: unknown type code 0x{code:02X}")
        dtype = _IDX_DTYPES[code]
        data = np.frombuffer(fd.read(), dtype=dtype)
    # Native byte order out (QMNIST labels are big-endian int32 on disk)
    return data.reshape(dims).astype(np.dtype(dtype).newbyteorder("="))


_MNIST_FILES = {
    "train_x": ("train-images-idx3-ubyte", "train-images.idx3-ubyte"),
    "train_y": ("train-labels-idx1-ubyte", "train-labels.idx1-ubyte"),
    "test_x": ("t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"),
    "test_y": ("t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"),
}


def load_mnist(name, **unused):
    """Load an MNIST-family dataset (mnist, fashionmnist, kmnist) from
    disk, else synthesize.

    Returns dict(train_x u8[N,28,28,1], train_y i32[N], test_x, test_y).

    The three datasets ship IDENTICAL idx filenames, so bare (un-subdired)
    filenames are only accepted for plain `mnist`, and only at the top
    level of a data dir — otherwise a cached tree of one family member
    would silently satisfy another member's request with the wrong images.
    """
    ensure_downloaded(name)
    out = {}
    subdir = {"mnist": "MNIST", "fashionmnist": "FashionMNIST",
              "kmnist": "KMNIST"}[name]
    for key, names in _MNIST_FILES.items():
        cands = tuple(f"{subdir}/raw/{n}" for n in names) \
            + tuple(f"{subdir}/raw/{n}.gz" for n in names)
        path = _find(*cands)
        if path is None and name == "mnist":
            # Bare filenames: base-level only (a glob would cross-match a
            # sibling family dataset's raw/ directory)
            path = _find_top(*names, *(n + ".gz" for n in names))
        if path is None:
            utils.trace(f"{name}: raw files not found on disk; using the "
                        "deterministic synthetic fallback")
            return synthetic_images(name, shape=(28, 28, 1), classes=10,
                                    train=60000, test=10000)
        out[key] = _read_idx(path)
    out["train_x"] = out["train_x"][..., None]
    out["test_x"] = out["test_x"][..., None]
    out["train_y"] = out["train_y"].astype(np.int32)
    out["test_y"] = out["test_y"].astype(np.int32)
    return out


# EMNIST (torchvision `EMNIST`): per-split idx files under EMNIST/raw/.
# (name, classes, train size, test size); `letters` labels run 1..26 on disk
# (torchvision keeps them as-is — so does this loader).
_EMNIST_SPLITS = {
    "byclass": (62, 697932, 116323),
    "bymerge": (47, 697932, 116323),
    "balanced": (47, 112800, 18800),
    "letters": (26, 124800, 20800),
    "digits": (10, 240000, 40000),
    "mnist": (10, 60000, 10000),
}


def _load_idx_family(name, files, fallback, label_select=None):
    """Shared idx-family loading: probe ALL four paths before parsing any
    (a partial tree must not decompress hundreds of MB it then discards),
    parse, add the channel axis, cast/select labels to int32.

    `files`: {key: (candidate names...)}; `fallback`: () -> synthetic dict;
    `label_select`: optional fn extracting the class column from a parsed
    label array."""
    paths = {}
    for key, names in files.items():
        cands = [c for n in names for c in (n, n + ".gz")]
        paths[key] = _find(*cands)
        if paths[key] is None:
            utils.trace(f"{name}: raw files not found on disk; using the "
                        "deterministic synthetic fallback")
            return fallback()
    out = {key: _read_idx(path) for key, path in paths.items()}
    out["train_x"] = out["train_x"][..., None]
    out["test_x"] = out["test_x"][..., None]
    for key in ("train_y", "test_y"):
        y = out[key]
        out[key] = (label_select(y) if label_select else y).astype(np.int32)
    return out


def load_emnist(split="balanced"):
    """Load an EMNIST split (torchvision `datasets.EMNIST(split=...)`,
    wrapped by the reference's registry like every torchvision dataset,
    reference `experiments/dataset.py:100-132`; the split arrives through
    the `--dataset-args split:<name>` mini-language — an unexpected key
    raises, it is not swallowed). Images are parsed exactly as stored
    (torchvision applies no re-orientation either). NB `letters` labels run
    1..26 on disk and torchvision keeps them as-is — so does this loader,
    and its synthetic fallback matches (a 27-way head or a target shift is
    the caller's choice, exactly as with torchvision)."""
    if split not in _EMNIST_SPLITS:
        raise utils.UserException(
            f"Unknown EMNIST split {split!r}; expected one of "
            f"{sorted(_EMNIST_SPLITS)}")
    classes, n_train, n_test = _EMNIST_SPLITS[split]

    def fallback():
        out = synthetic_images(f"emnist-{split}", shape=(28, 28, 1),
                               classes=classes, train=n_train, test=n_test)
        if split == "letters":
            # Match the on-disk 1-based labels (class k prototype -> label
            # k+1; the image-label association is unchanged)
            out["train_y"] = out["train_y"] + 1
            out["test_y"] = out["test_y"] + 1
        return out

    files = {
        key: (f"EMNIST/raw/emnist-{split}-{role}-{part}",
              f"emnist-{split}-{role}-{part}")
        for key, role, part in (("train_x", "train", "images-idx3-ubyte"),
                                ("train_y", "train", "labels-idx1-ubyte"),
                                ("test_x", "test", "images-idx3-ubyte"),
                                ("test_y", "test", "labels-idx1-ubyte"))}
    return _load_idx_family(f"emnist-{split}", files, fallback)


def load_qmnist():
    """Load QMNIST (torchvision `datasets.QMNIST`): MNIST-format images with
    extended idx2-int label records — (N, 8) int32 rows whose first column
    is the class label (the remaining columns are provenance metadata the
    training pipeline does not consume, matching torchvision's default
    `compat=True` behavior of exposing only the class)."""
    ensure_downloaded("qmnist")
    files = {
        key: (f"QMNIST/raw/{name}", name)
        for key, name in (("train_x", "qmnist-train-images-idx3-ubyte"),
                          ("train_y", "qmnist-train-labels-idx2-int"),
                          ("test_x", "qmnist-test-images-idx3-ubyte"),
                          ("test_y", "qmnist-test-labels-idx2-int"))}
    return _load_idx_family(
        "qmnist", files,
        lambda: synthetic_images("qmnist", shape=(28, 28, 1), classes=10,
                                 train=60000, test=60000),
        label_select=lambda y: y[:, 0])


# --------------------------------------------------------------------------- #
# CIFAR

def _cifar_from_pickles(files, label_key):
    xs, ys = [], []
    for fd in files:
        entry = pickle.load(fd, encoding="bytes")
        xs.append(np.asarray(entry[b"data"], np.uint8))
        ys.append(np.asarray(entry[label_key], np.int32))
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return np.ascontiguousarray(x), np.concatenate(ys)


def load_cifar(classes, **unused):
    """Load CIFAR-10/100 from extracted batch files or the .tar.gz, else
    synthesize. Returns HWC uint8 images."""
    name = f"cifar{classes}"
    ensure_downloaded(name)
    if classes == 10:
        train_names = [f"cifar-10-batches-py/data_batch_{i}" for i in range(1, 6)]
        test_names = ["cifar-10-batches-py/test_batch"]
        tar_name = "cifar-10-python.tar.gz"
        label_key = b"labels"
    else:
        train_names = ["cifar-100-python/train"]
        test_names = ["cifar-100-python/test"]
        tar_name = "cifar-100-python.tar.gz"
        label_key = b"fine_labels"

    paths = [_find(n, pathlib.PurePath(n).name) for n in train_names + test_names]
    if all(p is not None for p in paths):
        with_open = [open(p, "rb") for p in paths]
        try:
            train_x, train_y = _cifar_from_pickles(with_open[:len(train_names)], label_key)
            test_x, test_y = _cifar_from_pickles(with_open[len(train_names):], label_key)
        finally:
            for fd in with_open:
                fd.close()
        return {"train_x": train_x, "train_y": train_y,
                "test_x": test_x, "test_y": test_y}

    tar_path = _find(tar_name)
    if tar_path is not None:
        with tarfile.open(tar_path, "r:gz") as tar:
            train_x, train_y = _cifar_from_pickles(
                [tar.extractfile(n) for n in train_names], label_key)
            test_x, test_y = _cifar_from_pickles(
                [tar.extractfile(n) for n in test_names], label_key)
        return {"train_x": train_x, "train_y": train_y,
                "test_x": test_x, "test_y": test_y}

    utils.trace(f"{name}: raw files not found on disk; using the "
                "deterministic synthetic fallback")
    return synthetic_images(name, shape=(32, 32, 3), classes=classes,
                            train=50000, test=10000)


# --------------------------------------------------------------------------- #
# SVHN (torchvision `datasets.SVHN`): MATLAB .mat containers


def load_svhn(**unused):
    """Load SVHN from the published `train_32x32.mat` / `test_32x32.mat`
    (torchvision's exact source files), else synthesize. X arrives
    (32, 32, 3, N) channel-last sample-minor; labels use 10 for digit '0',
    which torchvision maps to 0 (`torchvision/datasets/svhn.py`:
    `np.place(self.labels, self.labels == 10, 0)`) — so do we."""
    ensure_downloaded("svhn")
    train_p = _find("SVHN/train_32x32.mat", "train_32x32.mat")
    test_p = _find("SVHN/test_32x32.mat", "test_32x32.mat")
    if train_p is None or test_p is None:
        utils.trace("svhn: raw files not found on disk; using the "
                    "deterministic synthetic fallback")
        return synthetic_images("svhn", shape=(32, 32, 3), classes=10,
                                train=73257, test=26032)
    from scipy.io import loadmat  # in-image dependency; imported lazily

    def split(path):
        mat = loadmat(str(path))
        x = np.ascontiguousarray(np.transpose(mat["X"], (3, 0, 1, 2)))
        y = mat["y"].reshape(-1).astype(np.int32)
        y[y == 10] = 0
        return x.astype(np.uint8), y

    train_x, train_y = split(train_p)
    test_x, test_y = split(test_p)
    return {"train_x": train_x, "train_y": train_y,
            "test_x": test_x, "test_y": test_y}


# --------------------------------------------------------------------------- #
# Synthetic fallback

def synthetic_images(name, *, shape, classes, train, test):
    """Deterministic synthetic image dataset: each class is a fixed random
    prototype image plus per-sample noise, so models genuinely learn
    (accuracy above chance) and runs are reproducible across processes.

    Difficulty knobs (env, both float): `$BMT_SYNTH_SIGNAL` scales the
    prototype contrast around the mid-gray level (default 1.0; smaller =
    weaker class signal, slower learning) and `$BMT_SYNTH_NOISE` sets the
    per-pixel noise sigma (default 48). The accuracy-parity experiments use
    a small signal scale so a few-hundred-step run lands mid-range top-1
    instead of saturating (a parity metric that cannot fail is not
    evidence)."""
    train = int(os.environ.get("BMT_SYNTH_TRAIN", train))
    test = int(os.environ.get("BMT_SYNTH_TEST", test))
    signal = float(os.environ.get("BMT_SYNTH_SIGNAL", 1.0))
    sigma = float(os.environ.get("BMT_SYNTH_NOISE", 48.0))
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    protos = rng.integers(0, 256, size=(classes, *shape)).astype(np.float32)
    protos = 127.5 + signal * (protos - 127.5)

    def make(count, seed_off):
        r = np.random.default_rng((zlib.crc32(name.encode()) + seed_off) % (2**32))
        labels = r.integers(0, classes, size=count).astype(np.int32)
        # f32 noise, generated in chunks: full-size CIFAR in f64 would peak
        # at >1 GB for a fallback dataset
        images = np.empty((count, *shape), np.uint8)
        for lo in range(0, count, 8192):
            hi = min(lo + 8192, count)
            noise = sigma * r.standard_normal((hi - lo, *shape), dtype=np.float32)
            np.clip(protos[labels[lo:hi]] + noise, 0, 255, out=noise)
            images[lo:hi] = noise.astype(np.uint8)
        return images, labels

    train_x, train_y = make(train, 1)
    test_x, test_y = make(test, 2)
    return {"train_x": train_x, "train_y": train_y,
            "test_x": test_x, "test_y": test_y, "synthetic": True}
