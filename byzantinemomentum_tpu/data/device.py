"""Device-resident dataset: in-graph batch materialization.

The reference's input pipeline moves every batch host -> device
(reference `dataset.py:208-218`, `.to(device)` at `:217`). On TPU that
transfer — S worker batches per step — dominates the step time for small
models (measured: the n=25 CIFAR benchmark spent most of its step in host
sampling). The fast path here stages the WHOLE dataset in HBM once as uint8
(CIFAR-10 train = 150 MB — trivial against 16+ GB HBM), and per step ships
only `(S, B)` int32 indices + a `(S, B)` flip mask; the gather, dtype
conversion, normalization and horizontal flips all run inside the jitted
training step and fuse with the forward pass.
"""

import jax.numpy as jnp
import numpy as np

__all__ = ["DeviceData"]


class DeviceData:
    """Device copies of one split's inputs/labels + the traceable transform.

    Build via `DeviceData.pair(trainset, testset)` from the host `Dataset`
    objects, whose samplers keep driving index selection (identical epoch
    and shuffle semantics; only materialization moves on-device).
    """

    def __init__(self, dataset):
        self._host = dataset
        self.inputs = jnp.asarray(dataset._inputs)
        self.labels = jnp.asarray(dataset._labels)
        transform = dataset._transform
        self.flip = bool(getattr(transform, "flip", False))
        norm = getattr(transform, "norm", None)
        self.norm = None
        if norm is not None:
            self.norm = (jnp.asarray(norm[0], jnp.float32),
                         jnp.asarray(norm[1], jnp.float32))
        # Raw (non-image) datasets have no transform: gather passes through
        self.is_image = transform is not None

    @classmethod
    def pair(cls, trainset, testset):
        return cls(trainset), cls(testset)

    @staticmethod
    def supports(dataset):
        """Whether the dataset's transform is expressible in-graph (the
        default image transform or none); custom host transforms keep the
        host materialization path."""
        transform = dataset._transform
        return transform is None or hasattr(transform, "flip")

    @property
    def batch_size(self):
        return self._host.batch_size

    def sample_indices(self, count):
        """Host half: `(count, B)` indices + flip mask for `count` batches."""
        idx = np.stack([self._host.sample_indices() for _ in range(count)])
        flips = np.stack([self._host.sample_flips() for _ in range(count)])
        return idx.astype(np.int32), flips

    def gather(self, idx, flips):
        """In-graph batch materialization: `idx: i32[..., B]` ->
        `(f32[..., B, ...inputs], labels[..., B])`. Traceable; fuses into
        the surrounding jitted program."""
        x = jnp.take(self.inputs, idx, axis=0)
        y = jnp.take(self.labels, idx, axis=0)
        if self.is_image:
            x = x.astype(jnp.float32) / 255.0
            if self.flip:
                flipped = jnp.flip(x, axis=-2)  # width axis of (..., H, W, C)
                x = jnp.where(flips[..., None, None, None], flipped, x)
            if self.norm is not None:
                x = (x - self.norm[0]) / self.norm[1]
        else:
            x = x.astype(jnp.float32)
        return x, y
