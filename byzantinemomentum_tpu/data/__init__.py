"""Dataset registry and host-side input pipeline.

TPU-native redesign of the reference's `experiments/dataset.py`: instead of
wrapping torchvision `DataLoader`s into infinite generators (reference
`dataset.py:100-132`, `:248-268`), a dataset here is a pair of in-memory
numpy arrays plus a batch sampler that yields **fixed-shape** `(B, ...)`
batches forever. Fixed shapes matter on TPU: a varying trailing batch would
retrigger XLA compilation every epoch, so the train sampler wraps the epoch
boundary by completing the last batch from the next shuffle (the same scheme
the reference itself uses for tensor-level datasets, `dataset.py:315-328`)
instead of emitting a short batch.

Transforms follow the reference's defaults (`dataset.py:32-49`): MNIST
normalization (0.1307, 0.3081); KMNIST normalization (0.1918, 0.3483);
CIFAR normalization (0.4914, 0.4822, 0.4465) / (0.2023, 0.1994, 0.2010) +
random horizontal flip; FashionMNIST random horizontal flip. Note the
reference applies the *same* transform list to the test set (flips
included) — that quirk is preserved.

Raw data is loaded from disk when present (see `sources.py` for search paths
and the pure-numpy idx/pickle parsers); otherwise a deterministic synthetic
fallback with the same shapes and cardinalities is generated, so the whole
framework runs hermetically (this environment has no network egress and no
torchvision).
"""

import numpy as np

from byzantinemomentum_tpu import utils
from byzantinemomentum_tpu.data import sources

__all__ = [
    "datasets", "register", "Dataset", "make_datasets", "batch_dataset",
    "normalizations", "flip_train",
]

# Registry: name -> loader() -> dict with keys
#   train_x, train_y, test_x, test_y  (numpy; images uint8 HWC, labels int)
datasets = {}

# Per-dataset normalization constants: name -> (mean, std) over channels,
# applied after scaling to [0, 1] (reference `dataset.py:32-49`).
normalizations = {
    "mnist": ((0.1307,), (0.3081,)),
    "kmnist": ((0.1918,), (0.3483,)),
    "cifar10": ((0.4914, 0.4822, 0.4465), (0.2023, 0.1994, 0.2010)),
    "cifar100": ((0.4914, 0.4822, 0.4465), (0.2023, 0.1994, 0.2010)),
}

# Datasets whose default transform includes a random horizontal flip
# (reference `dataset.py:32-41`; applied to train AND test there).
flip_train = {"fashionmnist", "cifar10", "cifar100"}


def register(name, loader):
    """Register a dataset loader under `name`
    (reference `experiments/dataset.py:100-163` plugin discovery)."""
    if name in datasets:
        utils.warning(f"Dataset {name!r} registered twice; keeping the last")
    datasets[name] = loader
    return loader


class Dataset:
    """An infinite, fixed-shape batch sampler over an in-memory split.

    Mirrors the reference `Dataset.sample()` contract
    (`experiments/dataset.py:208-218`): every call yields one `(inputs,
    labels)` batch; the train flavor shuffles per epoch, the test flavor
    cycles in order (reference `make_datasets`, `dataset.py:296-299`).
    """

    def __init__(self, inputs, labels, batch_size, *, train, transform,
                 seed=0, name="dataset"):
        if len(inputs) < 1 or len(inputs) != len(labels):
            raise utils.UserException(
                f"Invalid dataset {name!r}: {len(inputs)} inputs vs {len(labels)} labels")
        self.name = name
        # Data provenance: True when the loader fell back to the synthetic
        # generator (set by `make_datasets`; consumed by bench/parity
        # artifacts so their JSON is self-describing)
        self.synthetic = False
        self._inputs = inputs
        self._labels = labels
        self._batch = min(batch_size or len(inputs), len(inputs))
        self._train = train
        self._transform = transform
        self._rng = np.random.default_rng(seed)
        self._cursor = 0
        self._order = None
        if train:
            self._order = self._rng.permutation(len(inputs))

    def __len__(self):
        return len(self._inputs)

    @property
    def batch_size(self):
        return self._batch

    def sample_indices(self):
        """Advance the sampler and return the next batch's indices
        `i64[B]` — the cheap host half of the device-resident fast path
        (the gather + transform run in-graph, see `data/device.py`)."""
        n = len(self._inputs)
        end = self._cursor + self._batch
        if self._train:
            if end >= n:
                # Epoch boundary: complete the batch from a fresh shuffle
                # (>= so the permutation regenerates even when the batch
                # size divides the dataset size exactly)
                select = self._order[self._cursor:]
                self._order = self._rng.permutation(n)
                extra = end - n
                if extra:
                    select = np.concatenate([select, self._order[:extra]])
            else:
                select = self._order[self._cursor:end]
        else:
            if end > n:
                select = np.concatenate(
                    [np.arange(self._cursor, n), np.arange(end % n)])
            else:
                select = np.arange(self._cursor, end)
        self._cursor = end % n
        return select

    def sample_flips(self):
        """Random horizontal-flip mask `bool[B]` for this dataset's default
        transform (all-False when flips don't apply)."""
        if self._transform is not None and getattr(self._transform, "flip", False):
            return self._rng.random(self._batch) < 0.5
        return np.zeros(self._batch, bool)

    def get_state(self):
        """Snapshot of the sampler's mutable state (cursor, epoch order, RNG)
        for exact checkpoint/resume — the reference documents that it does
        NOT checkpoint dataloader state and that resumed runs are therefore
        not reproducible (reference `README.md:105`); this closes that gap.
        The returned dict is msgpack/JSON-serializable (PCG64 raw state ints
        exceed 64 bits, so they are encoded as strings)."""
        rng_state = self._rng.bit_generator.state
        return {
            "cursor": int(self._cursor),
            "order": None if self._order is None else np.asarray(self._order).tolist(),
            "rng": {
                "bit_generator": rng_state["bit_generator"],
                "state": {k: str(v) for k, v in rng_state["state"].items()},
                "has_uint32": int(rng_state["has_uint32"]),
                "uinteger": int(rng_state["uinteger"]),
            },
        }

    def set_state(self, snapshot):
        """Restore a `get_state` snapshot. Decodes everything (and lets the
        bit-generator validate its state) before assigning cursor/order, so
        a malformed snapshot raises without leaving this sampler
        half-restored."""
        n = len(self._inputs)
        cursor = int(snapshot["cursor"])
        if not 0 <= cursor < n:
            raise utils.UserException(
                f"Sampler snapshot cursor {cursor} out of range for dataset "
                f"{self.name!r} of size {n}")
        order = snapshot["order"]
        order = None if order is None else np.asarray(order, np.int64)
        if order is not None and (len(order) != n or (order >= n).any()
                                  or (order < 0).any()):
            raise utils.UserException(
                f"Sampler snapshot order is inconsistent with dataset "
                f"{self.name!r} of size {n} (snapshot covers "
                f"{0 if order is None else len(order)} samples)")
        rng = snapshot["rng"]
        self._rng.bit_generator.state = {
            "bit_generator": rng["bit_generator"],
            "state": {k: int(v) for k, v in rng["state"].items()},
            "has_uint32": int(rng["has_uint32"]),
            "uinteger": int(rng["uinteger"]),
        }
        self._cursor = cursor
        self._order = order

    def sample(self):
        """Return the next `(inputs f32[B, ...], labels[B])` batch (host
        materialization path, reference `dataset.py:208-218`)."""
        select = self.sample_indices()
        x = self._inputs[select]
        y = self._labels[select]
        if self._transform is not None:
            x = self._transform(x, self._rng)
        return x, y

    # Generator protocol compatibility (the reference exposes datasets as
    # infinite iterables too, `dataset.py:220-243`)
    def __iter__(self):
        while True:
            yield self.sample()

    def epoch(self):
        """Yield exactly one epoch of batches, in this sampler's order.

        The reference advertises this but its implementation references a
        nonexistent attribute and crashes (`dataset.py:220-243`, bug at
        `:230` — documented in SURVEY.md); this one works. The final partial
        batch is NOT padded (variable shape — prefer `sample()` on TPU).
        """
        n = len(self._inputs)
        order = (self._order if self._train else np.arange(n))
        for lo in range(0, n, self._batch):
            select = order[lo:lo + self._batch]
            x = self._inputs[select]
            y = self._labels[select]
            if self._transform is not None:
                x = self._transform(x, self._rng)
            yield x, y


def _image_transform(name, no_transform):
    """Build the default per-batch transform for an image dataset: uint8 HWC
    -> float32 in [0,1], then normalization and (optionally) random
    horizontal flips (reference `dataset.py:32-63`)."""
    norm = normalizations.get(name)
    flip = (name in flip_train) and not no_transform

    def transform(batch, rng):
        x = batch.astype(np.float32) / 255.0
        if flip:
            mask = rng.random(len(x)) < 0.5
            x[mask] = x[mask, :, ::-1, :]
        if norm is not None and not no_transform:
            mean = np.asarray(norm[0], np.float32)
            std = np.asarray(norm[1], np.float32)
            x = (x - mean) / std
        return x

    # Metadata for the device-resident fast path (`data/device.py`)
    transform.flip = flip
    transform.norm = norm if not no_transform else None
    return transform


def make_datasets(dataset, train_batch=None, test_batch=None, *,
                  no_transform=False, seed=0, **custom_args):
    """Build the (trainset, testset) pair for a registered dataset name
    (reference `experiments/dataset.py:270-301`).

    `no_transform` maps the reference's `--no-transform` (raw ToTensor only,
    reference `attack.py:527-530`): scaling to [0,1] without normalization or
    flips.
    """
    if dataset not in datasets:
        utils.fatal_unavailable(datasets, dataset, what="dataset name")
    raw = datasets[dataset](**custom_args)
    if raw.get("kind", "image") == "image":
        transform = _image_transform(dataset, no_transform)
    else:
        transform = None
    trainset = Dataset(raw["train_x"], raw["train_y"], train_batch,
                       train=True, transform=transform, seed=seed,
                       name=dataset)
    testset = Dataset(raw["test_x"], raw["test_y"], test_batch,
                      train=False, transform=transform, seed=seed + 1,
                      name=dataset)
    trainset.synthetic = testset.synthetic = bool(raw.get("synthetic", False))
    return trainset, testset


def batch_dataset(inputs, labels, *, train=False, batch_size=None,
                  split=0.75, seed=0, name="custom"):
    """Split a raw tensor dataset and wrap one side in a sampler
    (reference `experiments/dataset.py:303-354`): `split < 1` is the train
    fraction, `split >= 1` the number of train samples."""
    n = len(inputs)
    if n < 1 or len(labels) != n:
        raise utils.UserException(
            f"Invalid or different input/output lengths: {len(inputs)} vs {len(labels)}")
    split_pos = min(max(1, int(n * split)) if split < 1 else int(split), n - 1)
    if train:
        return Dataset(inputs[:split_pos], labels[:split_pos], batch_size,
                       train=True, transform=None, seed=seed, name=name)
    return Dataset(inputs[split_pos:], labels[split_pos:], batch_size,
                   train=False, transform=None, seed=seed, name=name)


# --------------------------------------------------------------------------- #
# Built-in datasets (reference: torchvision's MNIST/FashionMNIST/CIFAR
# wrapped at `dataset.py:100-132`; LIBSVM phishing at
# `experiments/datasets/svm.py`)

register("mnist", lambda **kw: sources.load_mnist("mnist", **kw))
register("fashionmnist", lambda **kw: sources.load_mnist("fashionmnist", **kw))
# KMNIST ships in the same idx format under KMNIST/raw/ — the registry
# extends to further torchvision dataset names with the existing parsers
# (normalization constants from torchvision's KMNIST docs)
register("kmnist", lambda **kw: sources.load_mnist("kmnist", **kw))
# EMNIST/QMNIST ride the same idx parsers (QMNIST labels are idx2-int
# records); like the reference, datasets without a `transforms` entry get
# plain ToTensor semantics — [0,1] scaling, no normalization, no flips
# (reference `experiments/dataset.py:115-118`)
register("emnist", sources.load_emnist)
register("qmnist", sources.load_qmnist)
# SVHN parses torchvision's .mat source files (plain-ToTensor semantics:
# the reference's transforms dict has no svhn entry either)
register("svhn", sources.load_svhn)
register("cifar10", lambda **kw: sources.load_cifar(10, **kw))
register("cifar100", lambda **kw: sources.load_cifar(100, **kw))

from byzantinemomentum_tpu.data import svm as _svm  # noqa: E402  (self-registers "phishing")
