"""LIBSVM `phishing` dataset (reference `experiments/datasets/svm.py`).

68 dense features parsed from the LIBSVM sparse text format, labels in
{0, 1} shaped (N, 1) float32, split 8400/rest into train/test (reference
`svm.py:126` — 8400 chosen for divisibility). Loads `phishing` /
`phishing.txt` from the data dirs (the reference's `download=True` URL
fetch maps to the opt-in `BMT_DOWNLOAD=1` path in `data/sources.py` —
off by default since this build environment has no network egress); falls
back to a deterministic synthetic linearly-separable-ish binary problem
with identical shapes.
"""

import os

import numpy as np

from byzantinemomentum_tpu import data as _data
from byzantinemomentum_tpu import utils
from byzantinemomentum_tpu.data import sources

__all__ = ["load_phishing"]

FEATURES = 68
SPLIT = 8400
TOTAL = 11055  # cardinality of the published LIBSVM phishing dataset


def _parse_libsvm(path):
    text = path.read_text().strip().split("\n")
    inputs = np.zeros((len(text), FEATURES), np.float32)
    labels = np.empty((len(text), 1), np.float32)
    for index, entry in enumerate(text):
        parts = entry.split()
        labels[index, 0] = 1.0 if parts[0] == "1" else 0.0
        for setter in parts[1:]:
            offset, value = setter.split(":")
            inputs[index, int(offset) - 1] = float(value)
    return inputs, labels


def _synthetic_phishing():
    """Returns (inputs, labels, split): train size honors $BMT_SYNTH_TRAIN
    (default: the real 8400 split) and test size $BMT_SYNTH_TEST (default:
    the real remainder), so shrunken test runs keep a meaningful test set."""
    train = min(int(os.environ.get("BMT_SYNTH_TRAIN", SPLIT)), SPLIT)
    test = int(os.environ.get("BMT_SYNTH_TEST", TOTAL - SPLIT))
    total = train + test
    rng = np.random.default_rng(0x5F15)
    w = rng.normal(size=(FEATURES,)).astype(np.float32)
    inputs = rng.random((total, FEATURES), dtype=np.float32)
    logits = (inputs - 0.5) @ w + rng.normal(0, 0.5, total).astype(np.float32)
    labels = (logits > 0).astype(np.float32)[:, None]
    return inputs, labels, train


def load_phishing(**unused):
    sources.ensure_downloaded("phishing")
    path = sources._find("phishing", "phishing.txt", "phishing.libsvm")
    synthetic = path is None
    if path is not None:
        inputs, labels = _parse_libsvm(path)
        split = min(SPLIT, len(inputs) - 1)
    else:
        utils.trace("phishing: raw file not found on disk; using the "
                    "deterministic synthetic fallback")
        inputs, labels, split = _synthetic_phishing()
    return {"train_x": inputs[:split], "train_y": labels[:split],
            "test_x": inputs[split:], "test_y": labels[split:],
            "kind": "raw", "synthetic": synthetic}


_data.register("phishing", load_phishing)
