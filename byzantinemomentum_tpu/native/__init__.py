"""Native (host C++) GAR tier — ctypes bindings over `bmt_native.cpp`.

Mirrors the reference's optional `native` module surface
(`native.median.aggregate(gradients)`, `native.krum.aggregate(gradients, f,
m)`, `native.bulyan.aggregate(gradients, f, m)`,
`native.brute.aggregate(gradients, f)` — reference `aggregators/median.py:
22-26` etc.): import `byzantinemomentum_tpu.native as native`, then
`native.median.aggregate(G)`. The shared library is compiled on first use
with g++ (this environment has no pybind11; ctypes needs no build-time
Python headers) and cached next to the source. `native.available()` reports
whether the toolchain succeeded — callers degrade to the jnp kernels
otherwise, exactly how the reference degrades when its native module is
absent.

The tier also registers `cpp-<gar>` entries in the ops registry through
`jax.pure_callback`, so the host kernels remain selectable from the CLI
(`--gar cpp-median`) and usable inside the jitted training step. Note:
host callbacks require backend support — the axon TPU backend does not
implement them, so the `cpp-*` tier is a CPU-backend facility (its role:
an independent oracle and host fast path, mirroring the reference where
`native` was likewise an optional CPU-side accelerator).
"""

import ctypes
import pathlib
import subprocess

import numpy as np

from byzantinemomentum_tpu import utils

__all__ = ["available", "median", "krum", "bulyan", "brute"]

_HERE = pathlib.Path(__file__).parent
_SRC = _HERE / "bmt_native.cpp"
_LIB = _HERE / "libbmt_native.so"

_lib = None
_build_error = None


def _load():
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    try:
        if (not _LIB.is_file()
                or _LIB.stat().st_mtime < _SRC.stat().st_mtime):
            # Build to a private temp file, then atomically publish: two
            # processes may race on the first build, and CDLL of a
            # half-written .so fails nondeterministically. (-O3 without
            # -march=native: the cached .so may be reused on another host.)
            import os
            import tempfile
            with tempfile.NamedTemporaryFile(
                    suffix=".so", dir=str(_HERE), delete=False) as tmp:
                tmp_path = tmp.name
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC",
                     str(_SRC), "-o", tmp_path],
                    check=True, capture_output=True, text=True)
                os.replace(tmp_path, str(_LIB))
            finally:
                if pathlib.Path(tmp_path).exists():
                    pathlib.Path(tmp_path).unlink()
        lib = ctypes.CDLL(str(_LIB))
        for name, argtypes in (
                ("bmt_median", [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                                ctypes.c_void_p]),
                ("bmt_krum", [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                              ctypes.c_int, ctypes.c_int, ctypes.c_void_p]),
                ("bmt_bulyan", [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                                ctypes.c_int, ctypes.c_int, ctypes.c_void_p]),
                ("bmt_brute", [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                               ctypes.c_int, ctypes.c_void_p])):
            fn = getattr(lib, name)
            fn.argtypes = argtypes
            fn.restype = None
        _lib = lib
    except (subprocess.CalledProcessError, OSError) as err:
        detail = getattr(err, "stderr", "") or str(err)
        _build_error = detail
        utils.warning(f"native GAR tier unavailable ({detail.strip()[:200]}); "
                      "falling back to the jnp kernels")
    return _lib


def available():
    """Whether the compiled tier loaded (builds on first call)."""
    return _load() is not None


def _prep(gradients):
    g = np.ascontiguousarray(np.asarray(gradients, dtype=np.float32))
    if g.ndim != 2:
        raise ValueError(f"Expected an (n, d) matrix, got shape {g.shape}")
    return g


def _call(fn_name, gradients, *scalars):
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native tier unavailable: {_build_error}")
    g = _prep(gradients)
    n, d = g.shape
    out = np.empty((d,), np.float32)
    getattr(lib, fn_name)(
        g.ctypes.data_as(ctypes.c_void_p), n, d, *scalars,
        out.ctypes.data_as(ctypes.c_void_p))
    return out


class _Entry:
    """One `native.<gar>` namespace with the reference's `aggregate`
    signature."""

    def __init__(self, name, fn):
        self.name = name
        self.aggregate = fn

    def __repr__(self):
        return f"native.{self.name}"


median = _Entry("median", lambda gradients: _call("bmt_median", gradients))
krum = _Entry("krum", lambda gradients, f, m=None:
              _call("bmt_krum", gradients, int(f),
                    -1 if m is None else int(m)))
bulyan = _Entry("bulyan", lambda gradients, f, m=None:
                _call("bmt_bulyan", gradients, int(f),
                      -1 if m is None else int(m)))
brute = _Entry("brute", lambda gradients, f: _call("bmt_brute", gradients,
                                                   int(f)))


def register_cpp_gars():
    """Register `cpp-<gar>` ops-registry entries backed by the host tier via
    `jax.pure_callback` (keeps them usable inside the jitted step).

    Registration is eager but the g++ build is NOT: the library compiles on
    the first actual `cpp-*` aggregate call, so importing the package stays
    cheap and processes that never select a cpp GAR never invoke the
    toolchain."""
    import jax
    import jax.numpy as jnp

    from byzantinemomentum_tpu import ops
    from byzantinemomentum_tpu.ops import bulyan as bulyan_mod
    from byzantinemomentum_tpu.ops import brute as brute_mod
    from byzantinemomentum_tpu.ops import krum as krum_mod
    from byzantinemomentum_tpu.ops import median as median_mod

    def checked_with_toolchain(check):
        """Augment a GAR's `check` so selecting a cpp-* entry on a host
        without a working toolchain fails at setup with a clear message,
        not minutes later inside the first jitted step."""
        def check_wrapper(gradients=None, **kwargs):
            if not available():
                return ("the native C++ tier is unavailable on this host "
                        "(g++ build failed); use the jnp kernel of the same "
                        "name instead")
            return check(gradients=gradients, **kwargs)
        return check_wrapper

    def wrap(entry, scalar_args):
        def unchecked(gradients, f=None, m=None, **kwargs):
            args = {"f": f, "m": m}
            call_args = tuple(args[a] for a in scalar_args)

            def host(g):
                return entry.aggregate(np.asarray(g), *call_args)

            shape = jax.ShapeDtypeStruct(gradients.shape[1:], jnp.float32)
            return jax.pure_callback(host, shape, gradients, vmap_method="sequential")
        return unchecked

    ops.register("cpp-median", wrap(median, ()),
                 checked_with_toolchain(median_mod.check),
                 upper_bound=median_mod.upper_bound)
    ops.register("cpp-krum", wrap(krum, ("f", "m")),
                 checked_with_toolchain(krum_mod.check),
                 upper_bound=krum_mod.upper_bound)
    ops.register("cpp-bulyan", wrap(bulyan, ("f", "m")),
                 checked_with_toolchain(bulyan_mod.check),
                 upper_bound=bulyan_mod.upper_bound)
    ops.register("cpp-brute", wrap(brute, ("f",)),
                 checked_with_toolchain(brute_mod.check),
                 upper_bound=brute_mod.upper_bound)
    return True
