// Native (host C++) tier of the four accelerated GARs.
//
// Mirrors the out-of-tree CPython extension the reference opportunistically
// imports (`native.median.aggregate`, `native.krum.aggregate`,
// `native.bulyan.aggregate`, `native.brute.aggregate` — reference
// `aggregators/median.py:22-26`, `krum.py:22-26`, `bulyan.py:22-26`,
// `brute.py:23-27`). On TPU the fast tier is the XLA-compiled kernel
// (`native-<gar>` in the ops registry); this C++ tier serves as an
// independent host oracle for differential tests and as a CPU fast path,
// exposed to Python via ctypes (no pybind11 in this environment).
//
// Semantics pinned to the framework's jnp kernels (and through them to the
// reference): non-finite distances -> +inf, lower median with NaN-last
// ordering, stable tie-breaking by index, Bulyan's effective
// prune-without-score-update behavior.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <limits>
#include <numeric>
#include <vector>

namespace {

constexpr double kInf = std::numeric_limits<double>::infinity();

// NaN-last ascending comparator (matches jnp.sort / torch.sort semantics)
inline bool nan_last_less(float a, float b) {
  const bool na = std::isnan(a), nb = std::isnan(b);
  if (na) return false;
  if (nb) return true;
  return a < b;
}

// Pairwise Euclidean distances, non-finite -> +inf, +inf diagonal.
std::vector<double> pairwise(const float* g, int n, int d) {
  std::vector<double> dist(static_cast<size_t>(n) * n, kInf);
  for (int i = 0; i < n - 1; ++i) {
    for (int j = i + 1; j < n; ++j) {
      double acc = 0.0;
      const float* gi = g + static_cast<size_t>(i) * d;
      const float* gj = g + static_cast<size_t>(j) * d;
      for (int k = 0; k < d; ++k) {
        const double diff = static_cast<double>(gi[k]) - gj[k];
        acc += diff * diff;
      }
      double val = std::sqrt(acc);
      if (!std::isfinite(val)) val = kInf;
      dist[static_cast<size_t>(i) * n + j] = val;
      dist[static_cast<size_t>(j) * n + i] = val;
    }
  }
  return dist;
}

// Stable argsort of scores (ascending), index order breaks ties.
std::vector<int> stable_order(const std::vector<double>& scores) {
  std::vector<int> order(scores.size());
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(),
                   [&](int a, int b) { return scores[a] < scores[b]; });
  return order;
}

// Mean of the rows listed in sel[0..m) into out.
void mean_rows(const float* g, int d, const std::vector<int>& sel, int m,
               float* out) {
  for (int k = 0; k < d; ++k) out[k] = 0.0f;
  for (int s = 0; s < m; ++s) {
    const float* row = g + static_cast<size_t>(sel[s]) * d;
    for (int k = 0; k < d; ++k) out[k] += row[k];
  }
  const float inv = 1.0f / static_cast<float>(m);
  for (int k = 0; k < d; ++k) out[k] *= inv;
}

// Krum-style scores: per row, sum of the `m` smallest neighbor distances
// (the +inf diagonal sorts last and never enters for m <= n-1).
std::vector<double> krum_scores(const std::vector<double>& dist, int n,
                                int m) {
  std::vector<double> scores(n);
  std::vector<double> row(n);
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j < n; ++j) row[j] = dist[static_cast<size_t>(i) * n + j];
    std::sort(row.begin(), row.end());
    double acc = 0.0;
    for (int j = 0; j < m; ++j) acc += row[j];
    scores[i] = acc;
  }
  return scores;
}

// Coordinate-wise lower median with NaN-last ordering into out.
void lower_median(const float* g, int n, int d, float* out) {
  std::vector<float> col(n);
  const int mid = (n - 1) / 2;
  for (int k = 0; k < d; ++k) {
    for (int i = 0; i < n; ++i) col[i] = g[static_cast<size_t>(i) * d + k];
    std::nth_element(col.begin(), col.begin() + mid, col.end(), nan_last_less);
    out[k] = col[mid];
  }
}

// Coordinate-wise mean of the m values closest to center (stable by index).
void closest_mean(const float* g, int n, int d, const float* center, int m,
                  float* out) {
  std::vector<int> idx(n);
  std::vector<float> dev(n);
  for (int k = 0; k < d; ++k) {
    for (int i = 0; i < n; ++i)
      dev[i] = std::fabs(g[static_cast<size_t>(i) * d + k] - center[k]);
    std::iota(idx.begin(), idx.end(), 0);
    std::stable_sort(idx.begin(), idx.end(), [&](int a, int b) {
      return nan_last_less(dev[a], dev[b]);
    });
    float acc = 0.0f;
    for (int s = 0; s < m; ++s) acc += g[static_cast<size_t>(idx[s]) * d + k];
    out[k] = acc / static_cast<float>(m);
  }
}

}  // namespace

extern "C" {

// median: coordinate-wise lower median (cf. `native.median.aggregate`)
void bmt_median(const float* g, int n, int d, float* out) {
  lower_median(g, n, d, out);
}

// krum: Multi-Krum, m < 0 means the default m = n - f - 2
// (cf. `native.krum.aggregate`)
void bmt_krum(const float* g, int n, int d, int f, int m, float* out) {
  if (m < 0) m = n - f - 2;
  const auto dist = pairwise(g, n, d);
  const auto scores = krum_scores(dist, n, n - f - 1);
  auto order = stable_order(scores);
  mean_rows(g, d, order, m, out);
}

// bulyan: iterative Multi-Krum selection + averaged median
// (cf. `native.bulyan.aggregate`; effective reference pruning)
void bmt_bulyan(const float* g, int n, int d, int f, int m, float* out) {
  const int m_max = n - f - 2;
  if (m < 0) m = m_max;
  const auto dist = pairwise(g, n, d);
  auto scores = krum_scores(dist, n, m);
  const int rounds = n - 2 * f - 2;
  std::vector<float> selected(static_cast<size_t>(rounds) * d);
  for (int i = 0; i < rounds; ++i) {
    const int m_i = std::min(m, m_max - i);
    auto order = stable_order(scores);
    mean_rows(g, d, order, m_i, selected.data() + static_cast<size_t>(i) * d);
    scores[order[0]] = kInf;
  }
  const int m2 = rounds - 2 * f;
  std::vector<float> med(d);
  lower_median(selected.data(), rounds, d, med.data());
  closest_mean(selected.data(), rounds, d, med.data(), m2, out);
}

// brute: minimum-diameter subset of size n - f (cf. `native.brute.aggregate`)
void bmt_brute(const float* g, int n, int d, int f, float* out) {
  const auto dist = pairwise(g, n, d);
  const int k = n - f;
  std::vector<int> combo(k);
  std::iota(combo.begin(), combo.end(), 0);
  std::vector<int> best;
  double best_diam = kInf;
  for (;;) {
    double diam = 0.0;
    for (int a = 0; a < k - 1 && diam < best_diam; ++a)
      for (int b = a + 1; b < k; ++b)
        diam = std::max(diam,
                        dist[static_cast<size_t>(combo[a]) * n + combo[b]]);
    if (best.empty() || diam < best_diam) {
      best_diam = diam;
      best = combo;
    }
    // next combination (lexicographic)
    int i = k - 1;
    while (i >= 0 && combo[i] == n - k + i) --i;
    if (i < 0) break;
    ++combo[i];
    for (int j = i + 1; j < k; ++j) combo[j] = combo[j - 1] + 1;
  }
  mean_rows(g, d, best, k, out);
}

}  // extern "C"
