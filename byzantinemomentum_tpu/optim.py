"""Optimizer registry.

The reference exposes every `torch.optim` subclass through its `Optimizer`
wrapper (reference `experiments/optimizer.py:25-103`) while the driver always
constructs SGD with momentum 0 — the Byzantine-momentum algebra is hand-rolled
in the training loop (reference `attack.py:543-545`; the momentum placements
live in `engine/step.py` here for the same reason).

TPU-native design: an optimizer is a pair of pure functions over the flat
parameter vector,

    init(theta)                          -> opt_state (pytree)
    update(grad, opt_state, theta, lr)   -> (new_theta, new_opt_state)

with torch-style decoupled weight decay applied as `grad + wd * theta` before
the transformation (exactly torch SGD's behavior, which the default "sgd"
reproduces bit-for-bit). The adaptive optimizers are optax transformation
chains with the learning rate applied outside, so per-step lr schedules don't
retrigger compilation.
"""

import optax

from byzantinemomentum_tpu import utils

__all__ = ["optimizers", "register", "Optimizer", "build"]

# Registry: name -> builder(**kwargs) -> Optimizer
optimizers = {}


class Optimizer:
    """A named (init, update) pair (see module docstring)."""

    def __init__(self, name, init, update):
        self.name = name
        self.init = init
        self.update = update

    def __repr__(self):
        return f"Optimizer({self.name!r})"


def register(name, builder):
    if name in optimizers:
        utils.warning(f"Optimizer {name!r} registered twice; keeping the last")
    optimizers[name] = builder
    return builder


def build(name, weight_decay=0.0, **kwargs):
    """Instantiate an optimizer by registry name
    (reference `experiments/optimizer.py:53-74`)."""
    if name not in optimizers:
        utils.fatal_unavailable(optimizers, name, what="optimizer name")
    return optimizers[name](weight_decay=weight_decay, **kwargs)


def _plain_sgd(weight_decay=0.0, **kw):
    """torch.optim.SGD with momentum 0 (the reference driver's choice,
    reference `attack.py:543-545`): theta <- theta - lr*(g + wd*theta)."""
    def init(theta):
        return ()

    def update(grad, opt_state, theta, lr):
        return theta - lr * (grad + weight_decay * theta), opt_state

    return Optimizer("sgd", init, update)


def _from_optax(name, make_tx):
    """Wrap an optax scale-by-* chain: lr multiplies the transformed update,
    weight decay adds `wd * theta` to the gradient first (torch semantics)."""
    def builder(weight_decay=0.0, **kwargs):
        tx = make_tx(**kwargs)

        def init(theta):
            return tx.init(theta)

        def update(grad, opt_state, theta, lr):
            g = grad + weight_decay * theta
            delta, opt_state = tx.update(g, opt_state, theta)
            return theta + lr * delta, opt_state

        return Optimizer(name, init, update)
    return builder


register("sgd", _plain_sgd)
register("adam", _from_optax(
    "adam", lambda b1=0.9, b2=0.999, eps=1e-8, **kw:
    optax.chain(optax.scale_by_adam(b1=b1, b2=b2, eps=eps),
                optax.scale(-1.0))))
register("adamw", _from_optax(
    "adamw", lambda b1=0.9, b2=0.999, eps=1e-8, wd=1e-2, **kw:
    optax.chain(optax.scale_by_adam(b1=b1, b2=b2, eps=eps),
                optax.add_decayed_weights(wd),
                optax.scale(-1.0))))
register("rmsprop", _from_optax(
    "rmsprop", lambda decay=0.99, eps=1e-8, **kw:
    optax.chain(optax.scale_by_rms(decay=decay, eps=eps),
                optax.scale(-1.0))))
register("adagrad", _from_optax(
    "adagrad", lambda eps=1e-10, **kw:
    optax.chain(optax.scale_by_rss(initial_accumulator_value=0.0, eps=eps),
                optax.scale(-1.0))))
# Registry tail (the reference name-resolves every torch.optim subclass,
# reference `experiments/optimizer.py:32-51`; these cover the remaining
# commonly-named ones through the same optax pattern)
register("adamax", _from_optax(
    "adamax", lambda b1=0.9, b2=0.999, eps=1e-8, **kw:
    optax.chain(optax.scale_by_adamax(b1=b1, b2=b2, eps=eps),
                optax.scale(-1.0))))
register("adadelta", _from_optax(
    "adadelta", lambda rho=0.9, eps=1e-6, **kw:
    optax.chain(optax.scale_by_adadelta(rho=rho, eps=eps),
                optax.scale(-1.0))))
register("radam", _from_optax(
    "radam", lambda b1=0.9, b2=0.999, eps=1e-8, **kw:
    optax.chain(optax.scale_by_radam(b1=b1, b2=b2, eps=eps),
                optax.scale(-1.0))))
register("amsgrad", _from_optax(
    "amsgrad", lambda b1=0.9, b2=0.999, eps=1e-8, **kw:
    optax.chain(optax.scale_by_amsgrad(b1=b1, b2=b2, eps=eps),
                optax.scale(-1.0))))
