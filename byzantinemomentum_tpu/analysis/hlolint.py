"""hlolint — a structural linter over LOWERED programs (StableHLO text).

jaxlint (`analysis/lint.py`) sees the source; the lowering goldens
(`analysis/lowering.py`) see the final bytes. This module checks the
layer in between: properties of the lowered program that a fingerprint
cannot *explain* and an AST cannot *see*. Every rule reads the StableHLO
module text that `jax.jit(...).lower(...).as_text()` produces — no
execution, no backend beyond what the lowering itself needed.

Rules (`BMT-H01..H05`; listed by `python -m byzantinemomentum_tpu.analysis
--rules` next to the E-rules):

  BMT-H01  collective-census    a cell's `stablehlo.all_reduce` count must
                                equal what its builder declares (sharded
                                selection rules psum exactly one Gram;
                                coordinate-wise rules psum nothing).
  BMT-H02  worker-matrix-gather an `stablehlo.all_gather` producing a
                                tensor at worker-matrix scale — the whole
                                point of the psum'd-Gram kernels is that
                                the (n, d) matrix never crosses ICI.
  BMT-H03  donation-dropped     `donate_argnums` was requested but the
                                argument carries no `tf.aliasing_output`
                                attribute — the buffer would be copied,
                                not consumed in place.
  BMT-H04  f64-in-program       a `tensor<..xf64>` type anywhere — an
                                accidental float64 promotion (every hot
                                path here is f32/bf16 by design).
  BMT-H05  host-callback        a `stablehlo.custom_call` to a python
                                callback target in the lowered program —
                                a host round-trip on the hot path.

H01–H03 are *contract* rules: they only fire against an `Expect`
declaring what the builder intended (per-cell expectations come from
`analysis/lattice.py`). H04/H05 are unconditional.

Violations reuse the jaxlint `Violation` shape (path = the cell label,
line = the offending line of the StableHLO text), so the CLI renders
both registries uniformly.
"""

import dataclasses
import re

from byzantinemomentum_tpu.analysis.lint import Rule, Violation

__all__ = ["HLO_RULES", "Expect", "lint_module"]

# id -> Rule. A separate registry from lint.RULES: these rules take
# (text, expect, label), not a parsed source module.
HLO_RULES = {}


def _rule(rule_id, slug, summary):
    def wrap(fn):
        HLO_RULES[rule_id] = Rule(rule_id, slug, summary, fn)
        return fn
    return wrap


@dataclasses.dataclass(frozen=True)
class Expect:
    """What a cell's builder declares about its lowered program.

    Attributes:
      psums: exact `stablehlo.all_reduce` count (None = H01 skips).
      gather_limit: max element count an `stablehlo.all_gather` may
        produce; the lattice sets `n*d - 1` so gathering the worker
        matrix (or anything bigger) fails (None = H02 skips).
      donated: argument indices of `@main` that must carry the
        `tf.aliasing_output` input/output-aliasing attribute (empty =
        H03 skips).
    """

    psums: int = None
    gather_limit: int = None
    donated: tuple = ()


_TENSOR = re.compile(r"tensor<([0-9x]*)x?(f64|f32|f16|bf16|i\d+|ui\d+|i1)>")


def _tensor_elements(type_text):
    """Element count of the FIRST tensor type in `type_text` (1 for a
    scalar tensor<f32>), or None."""
    m = _TENSOR.search(type_text)
    if m is None:
        return None
    dims = m.group(1)
    count = 1
    for d in dims.split("x"):
        if d:
            count *= int(d)
    return count


def _op_lines(text, op):
    """(lineno, line) pairs where `op` is applied (generic or pretty MLIR
    spelling), excluding mentions inside attribute strings."""
    pat = re.compile(r"(=|^|\s)\"?" + re.escape(op) + r"\"?\s*[(<]")
    return [(i, line) for i, line in enumerate(text.splitlines(), 1)
            if pat.search(line)]


@_rule("BMT-H01", "collective-census",
       "the lowered program's all_reduce count differs from what the "
       "cell's builder declares")
def _check_collective_census(text, expect, label):
    if expect is None or expect.psums is None:
        return []
    hits = _op_lines(text, "stablehlo.all_reduce")
    if len(hits) == expect.psums:
        return []
    line = hits[0][0] if hits else 0
    return [Violation(
        label, line, 0, "BMT-H01",
        f"expected exactly {expect.psums} all_reduce collective(s), "
        f"found {len(hits)} — the cell's communication pattern drifted "
        f"from its builder's declaration")]


@_rule("BMT-H02", "worker-matrix-gather",
       "an all_gather materializes a tensor at worker-matrix scale "
       "(the (n, d) matrix must never be gathered)")
def _check_worker_matrix_gather(text, expect, label):
    if expect is None or expect.gather_limit is None:
        return []
    out = []
    for lineno, line in _op_lines(text, "stablehlo.all_gather"):
        # The result type is the LAST tensor type on the op line
        # (`... : (tensor<11x8xf32>) -> tensor<11x16xf32>`)
        types = _TENSOR.findall(line)
        result = line[line.rfind("tensor<"):] if types else ""
        elements = _tensor_elements(result)
        if elements is not None and elements > expect.gather_limit:
            out.append(Violation(
                label, lineno, 0, "BMT-H02",
                f"all_gather produces {result.split('>')[0]}> "
                f"({elements} elements > budget {expect.gather_limit}) — "
                f"the worker matrix is crossing the interconnect; psum "
                f"the Gram instead"))
    return out


@_rule("BMT-H03", "donation-dropped",
       "donate_argnums was requested but the lowered argument carries no "
       "input/output aliasing")
def _check_donation(text, expect, label):
    if expect is None or not expect.donated:
        return []
    m = re.search(r"func\.func (?:public )?@main\((.*?)\)\s*->", text,
                  re.DOTALL)
    if m is None:
        return [Violation(label, 1, 0, "BMT-H03",
                          "no @main function found in the lowered module")]
    signature = m.group(1)
    lineno = text[:m.start()].count("\n") + 1
    # Split the signature on top-level argument boundaries (%argN markers)
    args = re.split(r"(?=%arg\d+\s*:)", signature)
    args = [a for a in args if a.strip()]
    out = []
    for pos in expect.donated:
        aliased = (pos < len(args)
                   and "tf.aliasing_output" in args[pos])
        if not aliased:
            out.append(Violation(
                label, lineno, 0, "BMT-H03",
                f"argument {pos} was declared donated but carries no "
                f"tf.aliasing_output aliasing — the runtime will copy "
                f"instead of consuming the buffer in place"))
    return out


@_rule("BMT-H04", "f64-in-program",
       "a tensor<..xf64> type appears in the lowered program "
       "(accidental float64 promotion)")
def _check_f64(text, expect, label):
    out = []
    for lineno, line in enumerate(text.splitlines(), 1):
        if re.search(r"tensor<[0-9x]*f64>", line):
            out.append(Violation(
                label, lineno, 0, "BMT-H04",
                "f64 tensor in the lowered program — every hot path is "
                "f32/bf16 by design; find the promoting constant or cast"))
            break  # one report per module is enough
    return out


_CALLBACK = re.compile(
    r"stablehlo\.custom_call\"?\s*.*@\"?(\w*python\w*callback\w*|"
    r"xla_ffi_partitioned_python\w*)\"?")


@_rule("BMT-H05", "host-callback",
       "a python host-callback custom_call in the lowered program "
       "(host round-trip on the hot path)")
def _check_host_callback(text, expect, label):
    out = []
    for lineno, line in enumerate(text.splitlines(), 1):
        if "custom_call" in line and _CALLBACK.search(line):
            out.append(Violation(
                label, lineno, 0, "BMT-H05",
                "host python callback in the lowered program — the hot "
                "path must not synchronize with the host (io_callback/"
                "pure_callback/debug.print leak into the trace)"))
    return out


def lint_module(text, expect=None, label="<lowered>", rules=None):
    """Run the BMT-H rules over one lowered module's StableHLO text.

    Args:
      text: `lowered.as_text()` output.
      expect: optional `Expect` enabling the contract rules (H01-H03).
      label: cell name for the violation's path field.
      rules: optional rule-id subset.
    Returns a sorted list of `Violation`.
    """
    selected = HLO_RULES if rules is None else {
        k: v for k, v in HLO_RULES.items() if k in rules}
    out = []
    for r in selected.values():
        out.extend(r.check(text, expect, label))
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))
