"""BMT-L — whole-program lock discipline over an interprocedural
lock-order graph.

`analysis/concurrency.py`'s BMT-T rules are per-class: they see `with
self._lock:` around a blocking call in the SAME method, but are blind
to `scrape_once` holding the scraper lock across a call into
`append_snapshot` (a different module) that fsyncs. This module builds
the missing whole-program picture:

1. every parsed module's classes (via `concurrency.ClassThreads`) and
   top-level functions become analysis *units*;
2. cross-unit call edges are resolved through `self.method(...)`,
   typed attributes (`self.batcher = MicroBatcher(...)` makes
   `self.batcher.submit(...)` a call into `MicroBatcher.submit`), and
   package-imported module functions;
3. a bottom-up fixpoint computes, per unit, the transitive sets of
   locks acquired, blocking calls reached, and callbacks invoked —
   each with a `file:line` witness chain;
4. a top-down pass emits every acquisition edge `(held -> taken)` in
   the global lock-NAME graph plus the L-rule violations.

Lock naming: a `NamedLock("router.ring")` / `NamedCondition(...)`
literal is the lock's name; anonymous `threading.Lock()` attributes
fall back to `ClassName.attr` (module-level locks to `modstem.VAR`).
Names label *roles*, not instances — two Counters share the name
`metrics.counter`, which is why self-edges (name -> same name) are
dropped rather than reported as self-deadlock.

The rules (all *driver* rules: they register for the `--rules` table
and noqa validation, but fire from `build()`/`check()` here, not the
per-module jaxlint pass):

  BMT-L01  deadlock-cycle        SCC in the lock-order graph whose
                                 edges are exercised by >= 2 distinct
                                 thread roles (or any multi-instance
                                 role) — an actual deadlock.
  BMT-L02  blocking-under-lock   a curated-table blocking call
                                 (fsync, socket send/recv/accept,
                                 subprocess, time.sleep, future
                                 .result, jax.device_get /
                                 block_until_ready, bare queue.get)
                                 reached while a lock is held —
                                 directly or through the call graph.
  BMT-L03  lock-held-callback    a user/registry callback (ctor-param
                                 callable, *hook/on_*/observer name,
                                 or `emit()`) invoked under a lock —
                                 arbitrary foreign code inside the
                                 critical section.
  BMT-L04  inconsistent-order    both orders of a lock pair appear
                                 but only ever on one single-instance
                                 thread role — latent inversion, one
                                 refactor away from L01.
  BMT-L05  check-then-act-init   lazy init (`if x is None: x = ...` /
                                 `if k not in d: d[k] = ...`) on a
                                 module or object global with no lock
                                 held, in a threading module.
  BMT-L06  missing-schedule-model any file constructing Thread/Lock/
                                 Condition must be named by an
                                 `analysis/schedule.py` model
                                 (`MODEL_COVERAGE`) or carry a
                                 reasoned `# bmt: noqa[BMT-L06]`.

Suppression uses the standard per-line `# bmt: noqa[BMT-L02] reason`
(reason mandatory — enforced here exactly like jaxlint's BMT-E00).

The blessed hierarchy lives in `tests/goldens/locks.json` (lock names,
edge census, topological order): `check()` reports ok / drift /
missing / incomparable (python-version coordinate mismatch), and
`scripts/bless_locks.py` re-blesses, printing pruned/added census
entries. The runtime half is `utils/locking.py` + `analysis/contracts.
record_lock_edges`: actual named-lock acquisition edges observed while
serving must be a subset of this static graph.
"""

import ast
import dataclasses
import json
import pathlib
import sys

from byzantinemomentum_tpu.analysis import concurrency
from byzantinemomentum_tpu.analysis.lint import (
    Module, Violation, _dotted, _terminal, iter_python_files, rule)
from byzantinemomentum_tpu.analysis.concurrency import (
    _self_attr, module_classes)

__all__ = ["build", "check", "bless", "census", "static_edges",
           "LockGraph", "GOLDEN_PATH", "DEFAULT_PATHS"]

ROOT = pathlib.Path(__file__).resolve().parents[2]
DEFAULT_PATHS = (ROOT / "byzantinemomentum_tpu", ROOT / "scripts")
GOLDEN_PATH = ROOT / "tests" / "goldens" / "locks.json"

_WITNESS_CAP = 6

_LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition",
                             "NamedLock", "NamedCondition"})
_NAMED_FACTORIES = frozenset({"NamedLock", "NamedCondition"})
_THREAD_FACTORIES = _LOCK_FACTORIES | {"Thread"}

# The curated blocking-callable table (BMT-L02). Deliberately small and
# named: every entry is an unbounded (or disk/network-bound) wait that
# has no business inside a critical section. `.wait()`/`.join()` stay
# BMT-T04's per-class domain.
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep() parks the thread",
    "os.fsync": "os.fsync() waits on the disk",
    "os.replace": "os.replace() waits on the filesystem",
    "jax.device_get": "jax.device_get() blocks on device transfer",
    "jax.block_until_ready": "jax.block_until_ready() waits on the device",
    "socket.create_connection":
        "socket.create_connection() waits on the network",
}
_BLOCKING_ATTRS = {
    "sendall": "socket .sendall() waits on the network",
    "recv": "socket .recv() waits on the network",
    "recv_into": "socket .recv_into() waits on the network",
    "accept": "socket .accept() waits on the network",
    "connect": ".connect() waits on the network",
    "fsync": ".fsync() waits on the disk",
    "result": "future .result() is an unbounded wait",
    "urlopen": "urlopen() waits on the network",
    "getaddrinfo": "getaddrinfo() waits on the resolver",
    "device_get": ".device_get() blocks on device transfer",
    "block_until_ready": ".block_until_ready() waits on the device",
}

_CALLBACK_MARKERS = ("hook", "callback", "observer", "provider",
                     "listener")


# --------------------------------------------------------------------------- #
# Rule registration (driver rules: the checks live in build(), below)

def _driver_rule(rid, slug, summary):
    @rule(rid, slug, summary, driver=True)
    def _check(mod):
        return ()
    return _check


_driver_rule("BMT-L01", "deadlock-cycle",
             "a cycle in the whole-program lock-order graph reachable "
             "from >= 2 thread roles — these threads can deadlock")
_driver_rule("BMT-L02", "blocking-under-lock",
             "a curated-table blocking call (fsync/socket/subprocess/"
             "sleep/result/device_get/queue.get) reached while a lock "
             "is held, directly or through the call graph")
_driver_rule("BMT-L03", "lock-held-callback",
             "a user/registry callback or emit() invoked under a lock "
             "— foreign code runs inside the critical section")
_driver_rule("BMT-L04", "inconsistent-lock-order",
             "a lock pair acquired in both orders on a single thread "
             "role — latent inversion, one refactor from a deadlock")
_driver_rule("BMT-L05", "check-then-act-init",
             "lazy check-then-act initialization of a module/object "
             "global with no lock held in a threading module")
_driver_rule("BMT-L06", "missing-schedule-model",
             "a file constructing Thread/Lock/Condition that no "
             "analysis/schedule.py model names (MODEL_COVERAGE) and "
             "that carries no reasoned noqa")


# --------------------------------------------------------------------------- #
# Program model

def _rel(path):
    try:
        return pathlib.Path(path).resolve().relative_to(ROOT).as_posix()
    except ValueError:
        return str(path)


class _ClassInfo:
    """Per-class extras the lock graph needs on top of ClassThreads."""

    def __init__(self, modinfo, cls):
        self.modinfo = modinfo
        self.cls = cls
        self.lock_names = {}    # lock attr -> global lock name
        self.typed_attrs = {}   # attr -> class name it is constructed from
        self.param_attrs = set()  # attrs assigned from an __init__ param
        init = cls.methods.get("__init__")
        params = set()
        if init is not None:
            args = init.args
            params = {a.arg for a in (args.posonlyargs + args.args
                                      + args.kwonlyargs)} - {"self"}
        for method in cls.methods.values():
            for stmt in ast.walk(method):
                if not isinstance(stmt, ast.Assign):
                    continue
                for target in stmt.targets:
                    attr = _self_attr(target)
                    if attr is None:
                        continue
                    value = stmt.value
                    if isinstance(value, ast.Call):
                        factory = _terminal(value.func)
                        if attr in cls.lock_attrs:
                            self.lock_names.setdefault(
                                attr, self._lock_name(attr, factory, value))
                        elif (factory and factory[0].isupper()
                              and factory not in _THREAD_FACTORIES):
                            self.typed_attrs.setdefault(attr, factory)
                    elif (method is init and isinstance(value, ast.Name)
                          and value.id in params):
                        self.param_attrs.add(attr)
        for attr in cls.lock_attrs:
            self.lock_names.setdefault(attr, f"{cls.name}.{attr}")

    def _lock_name(self, attr, factory, call):
        if (factory in _NAMED_FACTORIES and call.args
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)):
            return call.args[0].value
        return f"{self.cls.name}.{attr}"


class _ModInfo:
    def __init__(self, mod):
        self.mod = mod
        self.rel = _rel(mod.path)
        self.stem = pathlib.Path(mod.path).stem
        self.classes = []       # filled by the builder (needs registries)
        self.funcs = {n.name: n for n in mod.tree.body
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
        self.module_locks = {}  # module-level var -> lock name
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            factory = _terminal(node.value.func)
            if factory not in _LOCK_FACTORIES:
                continue
            name = None
            if (factory in _NAMED_FACTORIES and node.value.args
                    and isinstance(node.value.args[0], ast.Constant)
                    and isinstance(node.value.args[0].value, str)):
                name = node.value.args[0].value
            for target in node.targets:
                if isinstance(target, ast.Name):
                    self.module_locks[target.id] = (
                        name or f"{self.stem}.{target.id}")
        # Names this module binds from package-internal imports: the
        # visibility gate for by-name function resolution (bare names
        # like `main` exist in every script; only resolve what the
        # module can actually see).
        self.pkg_names = set(self.funcs)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom):
                if node.level or (node.module or "").startswith(
                        "byzantinemomentum_tpu"):
                    self.pkg_names.update(
                        a.asname or a.name for a in node.names)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith("byzantinemomentum_tpu"):
                        self.pkg_names.add(
                            a.asname or a.name.split(".")[0])


@dataclasses.dataclass
class LockGraph:
    """The whole-program result: lock names, acquisition edges (with
    witness + exercising roles), cycles, and the L-rule violations that
    survived noqa filtering."""
    locks: set
    edges: dict          # (held, taken) -> {"witness", "roles", "path", "line"}
    cycles: list         # list of sorted lock-name lists (SCCs >= 2)
    violations: list     # unsuppressed Violations
    suppressed: int
    files: int


# --------------------------------------------------------------------------- #
# Event extraction

def _is_queueish(node):
    t = _terminal(node)
    return t is not None and (t.endswith("q") or "queue" in t.lower())


def _blocking_reason(call, info):
    """Why `call` is in the curated blocking table (None if it is not)."""
    func = call.func
    dotted = _dotted(func)
    if dotted in _BLOCKING_DOTTED:
        return _BLOCKING_DOTTED[dotted]
    if dotted is not None and dotted.startswith("subprocess."):
        return f"{dotted}() blocks on a child process"
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    if attr in _BLOCKING_ATTRS:
        return _BLOCKING_ATTRS[attr]
    if attr == "get" and not call.args:
        timeout = next((kw.value for kw in call.keywords
                        if kw.arg == "timeout"), None)
        bounded = timeout is not None and not (
            isinstance(timeout, ast.Constant) and timeout.value is None)
        receiver = _self_attr(func.value)
        queueish = _is_queueish(func.value) or (
            info is not None and receiver in info.cls.queue_attrs)
        if queueish and not bounded:
            return ".get() with no timeout parks on an empty queue"
    return None


def _is_callbackish(name):
    low = name.lower()
    return (any(m in low for m in _CALLBACK_MARKERS)
            or low.startswith("on_") or low.endswith("_cb")
            or low.endswith("_fn"))


class _Unit:
    """One analysis unit: a class method or a module function."""

    def __init__(self, key, modinfo, info, name, fn, roles):
        self.key = key            # ("C", rel, cls, meth) | ("F", rel, fn)
        self.modinfo = modinfo
        self.info = info          # _ClassInfo or None
        self.name = name          # display name: "Cls.meth" / "func"
        self.fn = fn
        self.roles = frozenset(roles) or frozenset({"caller"})
        self.acquires = []        # (lockname, node)
        self.blocks = []          # (reason, node)
        self.callbacks = []       # (desc, node)
        self.calls = []           # (desc, node, [unit keys], same_class)

    def held_at(self, node):
        held = set()
        info, mod = self.info, self.modinfo.mod
        if info is not None:
            held.update(info.lock_names.get(a, f"{info.cls.name}.{a}")
                        for a in info.cls.locks_at(node, self.key[3]))
        cur = mod.parent.get(node)
        while cur is not None and cur is not self.fn:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    ce = item.context_expr
                    if (isinstance(ce, ast.Name)
                            and ce.id in self.modinfo.module_locks):
                        held.add(self.modinfo.module_locks[ce.id])
            cur = mod.parent.get(cur)
        return held


def _lock_of(expr, unit):
    """The lock name `expr` denotes (a lock attribute or module lock),
    or None."""
    attr = _self_attr(expr)
    if attr is not None and unit.info is not None:
        return unit.info.lock_names.get(attr)
    if isinstance(expr, ast.Name):
        return unit.modinfo.module_locks.get(expr.id)
    return None


def _extract_events(unit, class_reg, func_reg):
    info, modinfo = unit.info, unit.modinfo
    cls = info.cls if info is not None else None
    for node in ast.walk(unit.fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                name = _lock_of(item.context_expr, unit)
                if name is not None:
                    unit.acquires.append((name, node))
            continue
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if (isinstance(func, ast.Attribute) and func.attr == "acquire"):
            name = _lock_of(func.value, unit)
            if name is not None:
                unit.acquires.append((name, node))
                continue
        reason = _blocking_reason(node, info)
        if reason is not None:
            unit.blocks.append((reason, node))
            continue
        # Same-class method call: summaries propagate through it, but
        # violations stay attributed inside the callee (ClassThreads'
        # inherited-locks already model the intra-class held set).
        self_callee = _self_attr(func)
        if cls is not None and self_callee in cls.methods:
            unit.calls.append((f"{cls.name}.{self_callee}", node,
                               [("C", modinfo.rel, cls.name, self_callee)],
                               True))
            continue
        # Typed-attribute method call: self.batcher.submit(...)
        if (isinstance(func, ast.Attribute) and info is not None):
            owner = _self_attr(func.value)
            if owner in info.typed_attrs:
                targets = []
                for ci in class_reg.get(info.typed_attrs[owner], ()):
                    if func.attr in ci.cls.methods:
                        targets.append(("C", ci.modinfo.rel,
                                        ci.cls.name, func.attr))
                if targets:
                    unit.calls.append(
                        (f"{info.typed_attrs[owner]}.{func.attr}",
                         node, targets, False))
                    continue
        # Package-visible module function call.
        terminal = _terminal(func)
        resolved = False
        if terminal in func_reg:
            visible = terminal in modinfo.pkg_names
            if not visible and isinstance(func, ast.Attribute):
                root = (_dotted(func.value) or "").split(".")[0]
                visible = root in modinfo.pkg_names
            if visible:
                unit.calls.append(
                    (terminal, node,
                     [("F", mi.rel, terminal) for mi, _ in
                      func_reg[terminal]], False))
                resolved = True
        if resolved:
            continue
        # Callback heuristics: a ctor-param callable invoked directly,
        # a callback-named attribute, or a bare emit().
        desc = None
        if isinstance(func, ast.Attribute):
            owner_attr = _self_attr(func)
            if owner_attr is not None and info is not None and (
                    owner_attr in info.param_attrs
                    or _is_callbackish(owner_attr)):
                desc = f"self.{owner_attr}"
            elif func.attr == "emit":
                desc = f"{_dotted(func) or 'emit'}()"
            elif _is_callbackish(func.attr):
                desc = f".{func.attr}()"
        elif isinstance(func, ast.Name) and _is_callbackish(func.id):
            desc = f"{func.id}()"
        if desc is not None:
            unit.callbacks.append((desc, node))


# --------------------------------------------------------------------------- #
# The builder

def _parse(paths):
    mods = []
    for f in iter_python_files(paths):
        try:
            mods.append(Module(str(f), f.read_text(encoding="utf-8")))
        except (SyntaxError, OSError):
            continue
    return mods


def _covered_files():
    """Repo-relative paths named by analysis/schedule.py models."""
    from byzantinemomentum_tpu.analysis import schedule
    return schedule.covered_files()


def _merge(dst, src, prefix):
    """Merge transitive summary `src` into `dst` behind a witness hop;
    returns True if anything new appeared."""
    changed = False
    for key, wit in src.items():
        if key not in dst:
            dst[key] = (prefix + wit)[:_WITNESS_CAP]
            changed = True
    return changed


def build(paths=None):
    """Parse `paths` (default: the package + scripts) and return the
    whole-program `LockGraph`."""
    paths = DEFAULT_PATHS if paths is None else paths
    mods = _parse(paths)
    infos = [_ModInfo(m) for m in mods]

    class_reg = {}   # class name -> [_ClassInfo]
    func_reg = {}    # function name -> [(modinfo, fn)]
    for mi in infos:
        for cls in module_classes(mi.mod):
            ci = _ClassInfo(mi, cls)
            mi.classes.append(ci)
            class_reg.setdefault(cls.name, []).append(ci)
        for name, fn in mi.funcs.items():
            func_reg.setdefault(name, []).append((mi, fn))

    units = {}
    for mi in infos:
        for ci in mi.classes:
            for mname, fn in ci.cls.methods.items():
                key = ("C", mi.rel, ci.cls.name, mname)
                units[key] = _Unit(key, mi, ci, f"{ci.cls.name}.{mname}",
                                   fn, ci.cls.roles.get(mname, ()))
        for fname, fn in mi.funcs.items():
            key = ("F", mi.rel, fname)
            units[key] = _Unit(key, mi, None, fname, fn, ("caller",))
    for unit in units.values():
        _extract_events(unit, class_reg, func_reg)

    # Bottom-up: transitive acquire/block/callback summaries.
    acq_t = {k: {} for k in units}
    blk_t = {k: {} for k in units}
    cb_t = {k: {} for k in units}
    for key, unit in units.items():
        rel = unit.modinfo.rel
        for name, node in unit.acquires:
            acq_t[key].setdefault(
                name, (f"{rel}:{node.lineno} takes {name}",))
        for reason, node in unit.blocks:
            blk_t[key].setdefault(
                reason, (f"{rel}:{node.lineno} {reason}",))
        for desc, node in unit.callbacks:
            cb_t[key].setdefault(
                desc, (f"{rel}:{node.lineno} calls {desc}",))
    changed = True
    while changed:
        changed = False
        for key, unit in units.items():
            rel = unit.modinfo.rel
            for desc, node, targets, _same in unit.calls:
                hop = (f"{rel}:{node.lineno} calls {desc}",)
                for t in targets:
                    if t not in units:
                        continue
                    changed |= _merge(acq_t[key], acq_t[t], hop)
                    changed |= _merge(blk_t[key], blk_t[t], hop)
                    changed |= _merge(cb_t[key], cb_t[t], hop)

    # Top-down: edges + L02/L03 violations.
    locks = set()
    for mi in infos:
        locks.update(mi.module_locks.values())
        for ci in mi.classes:
            locks.update(ci.lock_names.values())
    edges = {}
    raw = []

    def edge(held, taken, witness, roles, rel, line):
        if held == taken:
            return  # same NAME, not necessarily the same instance
        meta = edges.get((held, taken))
        if meta is None:
            edges[(held, taken)] = {"witness": witness, "roles": set(roles),
                                    "path": rel, "line": line}
        else:
            meta["roles"] |= roles

    for key, unit in units.items():
        rel = unit.modinfo.rel
        for name, node in unit.acquires:
            held = unit.held_at(node)
            for h in sorted(held - {name}):
                edge(h, name, (f"{rel}:{node.lineno} takes {name} "
                               f"holding {h}",), unit.roles,
                     rel, node.lineno)
        for reason, node in unit.blocks:
            held = unit.held_at(node)
            if held:
                raw.append(Violation(
                    unit.modinfo.mod.path, node.lineno, node.col_offset,
                    "BMT-L02",
                    f"{unit.name} holds {', '.join(sorted(held))}: "
                    f"{reason} — move the wait outside the lock"))
        for desc, node in unit.callbacks:
            held = unit.held_at(node)
            if held:
                raw.append(Violation(
                    unit.modinfo.mod.path, node.lineno, node.col_offset,
                    "BMT-L03",
                    f"{unit.name} invokes callback {desc} while holding "
                    f"{', '.join(sorted(held))} — foreign code runs "
                    f"inside the critical section"))
        for desc, node, targets, same in unit.calls:
            if same:
                continue  # intra-class: attributed inside the callee
            held = unit.held_at(node)
            if not held:
                continue
            for t in targets:
                if t not in units:
                    continue
                for name, wit in acq_t[t].items():
                    for h in sorted(held - {name}):
                        edge(h, name,
                             (f"{rel}:{node.lineno} calls {desc} "
                              f"holding {h}",) + wit,
                             unit.roles, rel, node.lineno)
                for reason, wit in blk_t[t].items():
                    raw.append(Violation(
                        unit.modinfo.mod.path, node.lineno,
                        node.col_offset, "BMT-L02",
                        f"{unit.name} holds {', '.join(sorted(held))} "
                        f"across a blocking call chain: "
                        f"{' -> '.join(wit)}"))
                for cbdesc, wit in cb_t[t].items():
                    raw.append(Violation(
                        unit.modinfo.mod.path, node.lineno,
                        node.col_offset, "BMT-L03",
                        f"{unit.name} holds {', '.join(sorted(held))} "
                        f"across a callback chain: {' -> '.join(wit)}"))

    cycles, cyc_violations = _cycle_violations(edges)
    raw.extend(cyc_violations)
    raw.extend(_l05_violations(infos))
    raw.extend(_l06_violations(infos))

    violations, suppressed = _filter_noqa(infos, raw)
    return LockGraph(locks=locks, edges=edges, cycles=cycles,
                     violations=violations, suppressed=suppressed,
                     files=len(mods))


# --------------------------------------------------------------------------- #
# L01/L04 — cycles and inversions

def _sccs(nodes, adjacency):
    """Tarjan, iterative; returns SCCs as lists."""
    index = {}
    low = {}
    on_stack = set()
    stack = []
    out = []
    counter = [0]
    for root in sorted(nodes):
        if root in index:
            continue
        work = [(root, iter(sorted(adjacency.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adjacency.get(nxt,
                                                                ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    member = stack.pop()
                    on_stack.discard(member)
                    scc.append(member)
                    if member == node:
                        break
                out.append(scc)
    return out


def _multi_instance(role):
    return not role.startswith("thread:")


def _cycle_violations(edges):
    adjacency = {}
    nodes = set()
    for (a, b) in edges:
        adjacency.setdefault(a, set()).add(b)
        nodes.update((a, b))
    cycles = []
    out = []
    for scc in _sccs(nodes, adjacency):
        if len(scc) < 2:
            continue
        members = sorted(scc)
        cycles.append(members)
        in_cycle = [(pair, meta) for pair, meta in sorted(edges.items())
                    if pair[0] in scc and pair[1] in scc]
        roles = set()
        for _, meta in in_cycle:
            roles |= meta["roles"]
        deadlock = (len(roles) >= 2
                    or any(_multi_instance(r) for r in roles))
        rid = "BMT-L01" if deadlock else "BMT-L04"
        witness = "; ".join(
            f"{a} -> {b} at {meta['witness'][0]}"
            for (a, b), meta in in_cycle[:4])
        anchor = in_cycle[0][1]
        if deadlock:
            message = (f"lock-order cycle {' -> '.join(members)} "
                       f"exercised by roles {{{', '.join(sorted(roles))}}}"
                       f" — these threads can deadlock; witnesses: "
                       f"{witness}")
        else:
            message = (f"lock pair {' -> '.join(members)} acquired in "
                       f"both orders on single role "
                       f"{{{', '.join(sorted(roles))}}} — latent "
                       f"inversion; pick one order; witnesses: {witness}")
        out.append(Violation(str(ROOT / anchor["path"]), anchor["line"],
                             0, rid, message))
    return cycles, out


# --------------------------------------------------------------------------- #
# L05 — check-then-act lazy init outside any lock

def _l05_violations(infos):
    out = []
    for mi in infos:
        mod = mi.mod
        if not concurrency._imports_threading(mod.tree):
            continue
        globals_ = {n.targets[0].id for n in mod.tree.body
                    if isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)}
        by_cls = {}
        for ci in mi.classes:
            for mname, fn in ci.cls.methods.items():
                by_cls[fn] = (ci, mname)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.If):
                continue
            hit = _l05_pattern(mod, node, globals_)
            if hit is None:
                continue
            target, kind = hit
            fn = mod.enclosing_function(node)
            if fn is None or isinstance(fn, ast.Lambda):
                continue
            ci_m = by_cls.get(fn)
            if kind == "attr":
                # Object-attribute lazy init only matters when the class
                # actually hands threads out.
                if ci_m is None:
                    continue
                ci, mname = ci_m
                if mname == "__init__" or not (
                        ci.cls.entries or ci.cls.escapes
                        or ci.cls.handler):
                    continue
            held = set()
            if ci_m is not None:
                ci, mname = ci_m
                held.update(ci.cls.locks_at(node, mname))
            cur = mod.parent.get(node)
            while cur is not None and cur is not fn:
                if isinstance(cur, (ast.With, ast.AsyncWith)):
                    for item in cur.items:
                        if (isinstance(item.context_expr, ast.Name)
                                and item.context_expr.id
                                in mi.module_locks):
                            held.add(item.context_expr.id)
                cur = mod.parent.get(cur)
            if held:
                continue
            out.append(Violation(
                mod.path, node.lineno, node.col_offset, "BMT-L05",
                f"check-then-act lazy init of {target!r} with no lock "
                f"held — two threads can both see it uninitialized and "
                f"both fill it; guard the check+fill with one lock"))
    return out


def _l05_pattern(mod, node, globals_):
    """(target, kind) for a lazy-init If, else None. kind is 'global'
    (module global, rebound under `global`), 'dict' (module-level dict
    fill) or 'attr' (`self.x` fill)."""
    test = node.test
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1):
        return None
    op = test.ops[0]
    if (isinstance(op, ast.Is)
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None):
        target = test.left
        if isinstance(target, ast.Name) and target.id in globals_:
            fn = mod.enclosing_function(node)
            declared = fn is not None and any(
                isinstance(s, ast.Global) and target.id in s.names
                for s in ast.walk(fn))
            if declared and _body_assigns_name(node.body, target.id):
                return target.id, "global"
        attr = _self_attr(target)
        if attr is not None and _body_assigns_attr(node.body, attr):
            return f"self.{attr}", "attr"
        return None
    if isinstance(op, ast.NotIn):
        container = test.comparators[0]
        if (isinstance(container, ast.Name) and container.id in globals_
                and _body_stores_subscript(node.body, container.id)):
            return container.id, "dict"
    return None


def _body_assigns_name(body, name):
    return any(isinstance(sub, ast.Name)
               and isinstance(sub.ctx, ast.Store) and sub.id == name
               for stmt in body for sub in ast.walk(stmt))


def _body_assigns_attr(body, attr):
    return any(_self_attr(sub) == attr
               and isinstance(sub.ctx, ast.Store)
               for stmt in body for sub in ast.walk(stmt)
               if isinstance(sub, ast.Attribute))


def _body_stores_subscript(body, name):
    return any(isinstance(sub, ast.Subscript)
               and isinstance(sub.ctx, (ast.Store,))
               and isinstance(sub.value, ast.Name)
               and sub.value.id == name
               for stmt in body for sub in ast.walk(stmt))


# --------------------------------------------------------------------------- #
# L06 — the thread-surface covenant, made mechanical

def _l06_violations(infos):
    try:
        covered = _covered_files()
    except Exception:  # bmt: noqa[BMT-E05] a broken schedule import must degrade to "nothing is covered" (every thread file flags), not crash the sweep
        covered = set()
    out = []
    for mi in infos:
        if mi.rel in covered:
            continue
        first = None
        for node in ast.walk(mi.mod.tree):
            if (isinstance(node, ast.Call)
                    and _terminal(node.func) in _THREAD_FACTORIES):
                if first is None or node.lineno < first.lineno:
                    first = node
        if first is None:
            continue
        out.append(Violation(
            mi.mod.path, first.lineno, first.col_offset, "BMT-L06",
            f"{mi.rel} constructs {_terminal(first.func)} but no "
            f"analysis/schedule.py model names it (MODEL_COVERAGE) — "
            f"add a model for its interleavings or a reasoned noqa on "
            f"this line"))
    return out


# --------------------------------------------------------------------------- #
# Suppression

def _filter_noqa(infos, raw):
    noqa = {mi.mod.path: mi.mod.noqa for mi in infos}
    seen = set()
    out = []
    suppressed = 0
    for v in sorted(raw, key=lambda v: (v.path, v.line, v.rule,
                                        v.message)):
        key = (v.path, v.line, v.rule, v.message)
        if key in seen:
            continue
        seen.add(key)
        table = noqa.get(v.path, {})
        entry = table.get(v.line)
        if entry is not None:
            ids, reason = entry
            if (v.rule in ids or "all" in ids) and reason:
                suppressed += 1
                continue
        out.append(v)
    return out, suppressed


# --------------------------------------------------------------------------- #
# Golden census

def _toolchain():
    return f"{sys.version_info[0]}.{sys.version_info[1]}"


def _topo_order(locks, edges):
    """Kahn with lexicographic tie-break; members of cycles come last,
    sorted (a clean repo has none)."""
    indeg = {n: 0 for n in locks}
    adjacency = {n: set() for n in locks}
    for (a, b) in edges:
        if b not in adjacency.get(a, set()):
            adjacency.setdefault(a, set()).add(b)
            indeg[b] = indeg.get(b, 0) + 1
            indeg.setdefault(a, 0)
    ready = sorted(n for n, d in indeg.items() if d == 0)
    order = []
    while ready:
        node = ready.pop(0)
        order.append(node)
        for nxt in sorted(adjacency.get(node, ())):
            indeg[nxt] -= 1
            if indeg[nxt] == 0:
                ready.append(nxt)
        ready.sort()
    order.extend(sorted(n for n in indeg if n not in set(order)))
    return order


def census(graph=None, paths=None):
    """The blessable payload: toolchain coordinate, lock names, edge
    census, topological order."""
    graph = build(paths) if graph is None else graph
    return {
        "python": _toolchain(),
        "locks": sorted(graph.locks),
        "edges": sorted(f"{a} -> {b}" for (a, b) in graph.edges),
        "order": _topo_order(graph.locks, graph.edges),
    }


def static_edges(paths=None, graph=None):
    """The static acquisition-edge set as (held, taken) name pairs —
    the superset the runtime log (contracts.record_lock_edges) must
    stay inside."""
    graph = build(paths) if graph is None else graph
    return set(graph.edges)


def check(path=GOLDEN_PATH, paths=None):
    """Sweep + golden gate. Returns a dict with `status` in
    ok | drift | missing | incomparable, the violation list, and the
    census counters; `ok` requires status ok/incomparable AND zero
    unsuppressed violations."""
    graph = build(paths)
    current = census(graph)
    report = {
        "locks": len(graph.locks),
        "edges": len(graph.edges),
        "cycles": len(graph.cycles),
        "files": graph.files,
        "violations": [v.as_dict() for v in graph.violations],
        "suppressed": graph.suppressed,
    }
    path = pathlib.Path(path)
    if not path.exists():
        report["status"] = "missing"
    else:
        blessed = json.loads(path.read_text(encoding="utf-8"))
        if blessed.get("python") != current["python"]:
            report["status"] = "incomparable"
            report["blessed_python"] = blessed.get("python")
        else:
            drift = {}
            for field in ("locks", "edges"):
                old = set(blessed.get(field, ()))
                new = set(current[field])
                if new - old:
                    drift[f"{field}_added"] = sorted(new - old)
                if old - new:
                    drift[f"{field}_removed"] = sorted(old - new)
            if drift:
                report["status"] = "drift"
                report["drift"] = drift
            else:
                report["status"] = "ok"
    report["ok"] = (report["status"] in ("ok", "incomparable")
                    and not graph.violations)
    return report


def bless(path=GOLDEN_PATH, paths=None):
    """Write the current census as the blessed hierarchy; returns
    (payload, changed, old_payload_or_None)."""
    path = pathlib.Path(path)
    old = None
    if path.exists():
        old = json.loads(path.read_text(encoding="utf-8"))
    payload = census(paths=paths)
    text = json.dumps(payload, indent=2, sort_keys=True) + "\n"
    changed = old != payload
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(text, encoding="utf-8")
    return payload, changed, old
