"""Deterministic interleaving harness — the dynamic twin of the BMT-T
lock-set lint (`analysis/concurrency.py`).

The static pass claims "this unguarded read-modify-write can lose an
update"; this module DEMONSTRATES it, reproducibly, and then pins the
fixed code as schedule-clean. The idea is stateless model checking in
the CHESS tradition: a small *model* of a threaded class runs under a
cooperative scheduler that serializes its threads — exactly one runs at
a time, every other parks on a semaphore — and hands control over only
at explicit *preemption points*:

  * `sched.point()` — a marked interleaving point (e.g. between the
    load and the store of a `+=`);
  * every acquire/release of the instrumented primitives the harness
    provides (`sched.lock()`, `sched.condition()`), whose blocking
    semantics are modeled inside the scheduler (a thread waiting on a
    held lock is simply not runnable, so a schedule can never "pick"
    it — and an empty runnable set with live threads is a detected
    DEADLOCK, reported with the schedule that produced it).

A *schedule* is the sequence of thread ids picked at each decision
point, rendered as a digit string ("0100111"): the same model + the
same schedule string replays the same interleaving, bit for bit. Three
drivers build on that determinism:

  run_schedule(model, "010...")   replay one schedule (a failing
                                  schedule string from CI reproduces
                                  locally by copy-paste);
  explore(model, max_preemptions) exhaustive bounded-preemption
                                  enumeration: every schedule reachable
                                  with at most K preemptions (switching
                                  away from a still-runnable thread) is
                                  run once. Small models exhaust in
                                  well under a second;
  random_walks(model, runs, seed) seeded random schedules for models
                                  too big to exhaust.

A model is a callable `model(sched) -> (thread_fns, check)`: build the
shared state (using `sched.lock()`/`sched.condition()`/`sched.point()`
at the boundaries that matter), return one function per thread plus a
`check()` that raises AssertionError if the final state violates the
invariant. Models must be pure host Python — no real blocking calls
(a real `time.sleep`/socket wait inside a model stalls the scheduler,
which reports it instead of hanging, via a watchdog timeout).

`selfcheck()` is the tier smoke (`python -m byzantinemomentum_tpu.analysis
--schedule-smoke`): it proves the planted lost-update in the PRE-FIX
`serve/service.py` counter pattern is FOUND by bounded exploration, and
that the fixed (stats-lock) pattern survives the same exhaustive
2-thread exploration with zero failures. Stdlib only — importing this
module never touches jax or numpy.
"""

import dataclasses
import random
import threading
import time

__all__ = ["Scheduler", "SchedLock", "SchedCondition", "DeadlockError",
           "SchedulerError", "RunResult", "ExploreReport", "run_schedule",
           "explore", "random_walks", "lost_update_model",
           "fixed_counter_model", "router_lost_forward_model",
           "router_forward_queue_model", "router_double_resolve_model",
           "router_single_disposition_model",
           "straggle_claim_unguarded_model", "straggle_claim_model",
           "metrics_scrape_torn_model", "metrics_scrape_model",
           "metrics_rotate_lost_model", "metrics_rotate_model",
           "incident_bundle_torn_model", "incident_bundle_model",
           "router_splice_lost_model", "router_splice_model",
           "scrape_publish_torn_model", "scrape_publish_model",
           "liveness_hook_racy_model", "liveness_hook_model",
           "MODEL_COVERAGE", "covered_files", "selfcheck"]

# A worker that fails to reach its next preemption point within this many
# seconds is assumed to have entered a REAL blocking call (which the
# scheduler cannot preempt) — the run aborts with SchedulerError instead
# of wedging the test process.
_WATCHDOG_S = 30.0


class DeadlockError(RuntimeError):
    """No runnable thread, but not every thread is done."""


class SchedulerError(RuntimeError):
    """The harness itself was misused (bad schedule, non-yielding model,
    relocking a held non-reentrant lock, ...)."""


class _Killed(BaseException):
    """Raised inside abandoned workers so they unwind instead of leaking
    parked threads after a deadlock/abort (BaseException: a model's
    `except Exception` must not swallow the teardown)."""


class _TState:
    __slots__ = ("sem", "done", "blocked", "waiting", "exc", "kill")

    def __init__(self):
        self.sem = threading.Semaphore(0)
        self.done = False
        self.blocked = None    # SchedLock this thread waits to acquire
        self.waiting = None    # SchedCondition this thread waits on
        self.exc = None
        self.kill = False


class Scheduler:
    """Cooperative serializer: exactly one model thread runs at a time;
    control returns here at every preemption point."""

    def __init__(self):
        self._main = threading.Semaphore(0)
        self._local = threading.local()
        self._states = []
        self.trace = []        # thread id picked at each decision
        self.decisions = []    # runnable-id tuple at each decision

    # ---------------------------------------------------------------- #
    # Worker-side protocol

    def _tid(self):
        try:
            return self._local.tid
        except AttributeError:
            raise SchedulerError(
                "instrumented primitive used outside a scheduled thread")

    def point(self):
        """A preemption point: pause here, let the scheduler decide who
        runs next."""
        self._pause(self._tid())

    def _pause(self, tid):
        state = self._states[tid]
        if state.kill:
            # Abandoned (deadlock teardown): unwind WITHOUT parking —
            # instrumented calls on the unwind path (a `with lock:`
            # __exit__ releasing) must not wait for a grant that will
            # never come
            raise _Killed()
        self._main.release()
        state.sem.acquire()
        if state.kill:
            raise _Killed()

    def lock(self):
        return SchedLock(self)

    def condition(self, lock=None):
        return SchedCondition(self, lock)

    # ---------------------------------------------------------------- #
    # Scheduler side

    def run(self, fns, picker, max_steps=20_000):
        """Run the model threads to completion under `picker(runnable,
        trace) -> tid`. Returns None; inspect `trace`/`decisions`.
        Raises DeadlockError when no thread is runnable, and re-raises
        the first model-thread exception (AssertionError included).
        `max_steps` bounds the schedule length: a model that spin-waits
        (always runnable, never done) is a harness misuse and raises
        SchedulerError instead of exploring forever — model waits with
        `sched.condition()`, not polling loops."""
        if len(fns) > 10:
            raise SchedulerError("schedule strings encode one digit per "
                                 "thread: at most 10 threads")
        self._states = [_TState() for _ in fns]
        threads = []
        for i, fn in enumerate(fns):
            def body(fn=fn, i=i):
                self._local.tid = i
                state = self._states[i]
                state.sem.acquire()
                try:
                    if not state.kill:
                        fn()
                except _Killed:
                    pass
                except BaseException as err:  # bmt: noqa[BMT-E05] the model's exception IS the result — it re-raises on the scheduler thread below
                    state.exc = err
                finally:
                    state.done = True
                    self._main.release()
            t = threading.Thread(target=body, daemon=True,  # bmt: noqa[BMT-L06] this IS the interleaving harness; its workers run one at a time under the scheduler's own handoff semaphores
                                 name=f"sched-{i}")
            threads.append(t)
            t.start()
        try:
            while True:
                runnable = [i for i, s in enumerate(self._states)
                            if not s.done and s.blocked is None
                            and s.waiting is None]
                if not runnable:
                    if all(s.done for s in self._states):
                        break
                    raise DeadlockError(
                        f"deadlock after schedule "
                        f"{''.join(map(str, self.trace))!r}: threads "
                        f"{[i for i, s in enumerate(self._states) if not s.done]} "
                        f"are blocked")
                if len(self.trace) >= max_steps:
                    raise SchedulerError(
                        f"schedule exceeded {max_steps} steps — a "
                        f"spin-wait in the model? (park with "
                        f"sched.condition().wait() instead of polling)")
                self.decisions.append(tuple(runnable))
                tid = picker(runnable, self.trace)
                if tid not in runnable:
                    raise SchedulerError(
                        f"picker chose thread {tid}, runnable: {runnable}")
                self.trace.append(tid)
                self._states[tid].sem.release()
                if not self._main.acquire(timeout=_WATCHDOG_S):
                    raise SchedulerError(
                        f"thread {tid} did not yield within {_WATCHDOG_S}s "
                        f"— a real blocking call inside the model?")
        finally:
            self._abandon()
        for state in self._states:
            if state.exc is not None:
                raise state.exc

    def _abandon(self):
        """Unwind every unfinished worker (deadlock/abort paths) so runs
        never leak parked threads."""
        for state in self._states:
            if not state.done:
                state.kill = True
                state.sem.release()
        deadline = time.monotonic() + _WATCHDOG_S
        for state in self._states:
            while not state.done and time.monotonic() < deadline:
                self._main.acquire(timeout=0.1)


class SchedLock:
    """Non-reentrant mutex whose blocking lives in the scheduler model:
    acquiring a held lock parks the thread (not runnable) until release."""

    __slots__ = ("_sched", "_owner")

    def __init__(self, sched):
        self._sched = sched
        self._owner = None

    def acquire(self):
        sched = self._sched
        tid = sched._tid()
        sched.point()                 # decision point before the acquire
        while self._owner is not None:
            if self._owner == tid:
                raise SchedulerError(
                    "re-acquiring a held SchedLock (non-reentrant): "
                    "a self-deadlock in the model")
            state = sched._states[tid]
            state.blocked = self
            sched._pause(tid)         # release() marks us runnable again
        self._owner = tid

    def release(self):
        sched = self._sched
        if self._owner != sched._tid():
            if sched._states[sched._tid()].kill:
                raise _Killed()  # interrupted mid-acquire; keep unwinding
            raise SchedulerError("releasing a SchedLock the thread "
                                 "does not hold")
        self._owner = None
        for state in sched._states:
            if state.blocked is self:
                state.blocked = None  # runnable; re-checks owner when run
        sched.point()                 # release is a decision point too

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class SchedCondition:
    """Condition variable over a `SchedLock` with wait/notify modeled in
    the scheduler (no spurious wakeups, no timeouts — model explicit
    wake signals instead)."""

    __slots__ = ("_sched", "_lock")

    def __init__(self, sched, lock=None):
        self._sched = sched
        self._lock = lock if lock is not None else SchedLock(sched)

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def wait(self):
        sched = self._sched
        tid = sched._tid()
        if self._lock._owner != tid:
            raise SchedulerError("SchedCondition.wait() without the lock")
        # Atomically: drop the lock, park until notified
        self._lock._owner = None
        for state in sched._states:
            if state.blocked is self._lock:
                state.blocked = None
        state = sched._states[tid]
        state.waiting = self
        sched._pause(tid)
        self._lock.acquire()          # woken: re-take the lock (may park)

    def notify(self, n=1):
        self._notify(n)

    def notify_all(self):
        self._notify(None)

    def _notify(self, n):
        sched = self._sched
        if self._lock._owner != sched._tid():
            raise SchedulerError("SchedCondition.notify() without the lock")
        woken = 0
        for state in sched._states:
            if state.waiting is self:
                state.waiting = None
                woken += 1
                if n is not None and woken >= n:
                    break


# --------------------------------------------------------------------------- #
# Drivers: replay, exhaustive bounded-preemption exploration, random walks

@dataclasses.dataclass
class RunResult:
    """One schedule's outcome. `schedule` is the full realized digit
    string (replayable); `error` is None on success, else the failure
    text (assertion, deadlock, model exception)."""

    schedule: str
    preemptions: int
    error: str = None

    @property
    def ok(self):
        return self.error is None


@dataclasses.dataclass
class ExploreReport:
    """What `explore`/`random_walks` covered: `runs` distinct schedules,
    the failing ones in `failures`, and whether the frontier was fully
    exhausted within the run cap."""

    runs: int
    failures: list
    max_preemptions: int
    exhausted: bool = True

    @property
    def ok(self):
        return not self.failures


def _preemptions(trace, decisions):
    count = 0
    for i in range(1, len(trace)):
        if trace[i - 1] in decisions[i] and trace[i] != trace[i - 1]:
            count += 1
    return count


def _forced_picker(forced):
    """Follow the forced prefix, then run the CURRENT thread as long as
    it stays runnable (fewest-preemption continuation), else the lowest
    runnable id — fully deterministic."""
    def picker(runnable, trace):
        if len(trace) < len(forced):
            tid = forced[len(trace)]
            if tid not in runnable:
                raise SchedulerError(
                    f"schedule step {len(trace)} picks thread {tid}, "
                    f"but runnable is {runnable}")
            return tid
        if trace and trace[-1] in runnable:
            return trace[-1]
        return runnable[0]
    return picker


def _run(model, forced):
    """One run under a forced schedule prefix. Returns (RunResult,
    decisions, trace)."""
    sched = Scheduler()
    fns, check = model(sched)
    error = None
    try:
        sched.run(fns, _forced_picker(forced))
        check()
    except (AssertionError, DeadlockError) as err:
        error = f"{type(err).__name__}: {err}"
    schedule = "".join(map(str, sched.trace))
    return (RunResult(schedule, _preemptions(sched.trace, sched.decisions),
                      error),
            list(sched.decisions), list(sched.trace))


def run_schedule(model, schedule=""):
    """Replay one schedule (prefix) of a model; returns its RunResult
    with the FULL realized schedule string."""
    forced = [int(c) for c in schedule]
    result, _, _ = _run(model, forced)
    return result


def explore(model, max_preemptions=3, max_runs=4000):
    """Exhaustive bounded-preemption exploration: depth-first over every
    divergence from already-realized schedules whose preemption count
    stays within the bound. Deterministic; each distinct schedule runs
    exactly once."""
    seen = set()       # realized schedules already run
    tried = set()      # forced prefixes already queued
    frontier = [()]
    failures = []
    runs = 0
    while frontier:
        if runs >= max_runs:
            return ExploreReport(runs, failures, max_preemptions,
                                 exhausted=False)
        forced = frontier.pop()
        result, decisions, trace = _run(model, list(forced))
        key = tuple(trace)
        if key in seen:
            continue
        seen.add(key)
        runs += 1
        if not result.ok:
            failures.append(result)
        # Branch: at every decision, every alternative pick that stays
        # within the preemption budget
        for i in range(len(trace)):
            for alt in decisions[i]:
                if alt == trace[i]:
                    continue
                prefix = key[:i] + (alt,)
                if prefix in tried:
                    continue
                if _preemptions(list(prefix), decisions[:i + 1]) \
                        > max_preemptions:
                    continue
                tried.add(prefix)
                frontier.append(prefix)
    return ExploreReport(runs, failures, max_preemptions)


def random_walks(model, runs=100, seed=0):
    """Seeded random schedules (for models too big to exhaust). The
    failing `RunResult.schedule` strings replay via `run_schedule`."""
    rng = random.Random(seed)
    failures = []
    seen = set()
    for _ in range(runs):
        sched = Scheduler()
        fns, check = model(sched)
        error = None
        try:
            sched.run(fns, lambda runnable, trace: rng.choice(runnable))
            check()
        except (AssertionError, DeadlockError) as err:
            error = f"{type(err).__name__}: {err}"
        schedule = "".join(map(str, sched.trace))
        seen.add(schedule)
        if error is not None:
            failures.append(RunResult(
                schedule, _preemptions(sched.trace, sched.decisions), error))
    return ExploreReport(len(seen), failures, max_preemptions=-1)


# --------------------------------------------------------------------------- #
# The canonical models: the serve counter race, before and after the fix

def lost_update_model(sched):
    """The PRE-fix `serve/service.py` counter pattern (fixture copy of
    `_resolve`'s `self._served += 1` at PR 13): two resolver-ish threads
    bump an unguarded counter; `sched.point()` sits exactly where the
    bytecode boundary between the LOAD and the STORE of `+=` is."""
    class Service:
        def __init__(self):
            self._served = 0

        def resolve(self):
            value = self._served          # the read of `+= 1`
            sched.point()                 # ... preempted here ...
            self._served = value + 1      # the write of `+= 1`

    svc = Service()

    def check():
        assert svc._served == 2, f"lost update: _served == {svc._served}"

    return [svc.resolve, svc.resolve], check


def fixed_counter_model(sched):
    """The FIXED pattern (`AggregationService._stats_lock`): the same
    read-modify-write, now guarded — every schedule must end at 2."""
    class Service:
        def __init__(self):
            self._stats_lock = sched.lock()
            self._served = 0

        def resolve(self):
            with self._stats_lock:
                value = self._served
                sched.point()
                self._served = value + 1

    svc = Service()

    def check():
        assert svc._served == 2, f"lost update: _served == {svc._served}"

    return [svc.resolve, svc.resolve], check


# --------------------------------------------------------------------------- #
# The fleet-router models (serve/fleet/router.py): the two interleavings
# that decide its design — a lost forward and a double disposition —
# each as the broken pattern the naive router would have, and the
# pattern the shipped router uses, pinned schedule-clean.

def router_lost_forward_model(sched):
    """The PRE-fix forwarding pattern: liveness read as a SEND GUARD.
    The connection thread checks the arc is alive and only then
    enqueues; concurrently the arc dies and its dead-arc cleanup errors
    everything queued. A kill landing BETWEEN the check and the enqueue
    leaves the line queued behind a dead arc after cleanup already ran —
    no reply, ever. Serial orders pass; one preemption finds it."""
    state = {"alive": True, "queue": [], "errored": []}

    def handler():
        # check-then-enqueue: the race
        if state["alive"]:
            sched.point()             # ... the kill + cleanup land here
            state["queue"].append(0)
        else:
            state["errored"].append(0)

    def killer():
        state["alive"] = False
        sched.point()
        # dead-arc cleanup: error whatever is queued NOW
        state["errored"].extend(state["queue"])
        state["queue"].clear()

    def check():
        assert 0 in state["errored"], (
            f"lost forward: line 0 has no disposition "
            f"(queued behind a dead arc: {state['queue']})")

    return [handler, killer], check


def router_forward_queue_model(sched):
    """The SHIPPED pattern (`FleetRouter.handle_line` + `_forward_loop`):
    the enqueue is UNCONDITIONAL — liveness is policy, never a send
    guard — and the queue's SINGLE consumer (the arc's forwarder) gives
    every item exactly one disposition, reading liveness at take time.
    Exhaustively clean at the same preemption bound that breaks the
    guarded version."""
    cond = sched.condition()
    state = {"alive": True, "queue": [], "answered": [], "errored": []}

    def handler():
        with cond:
            state["queue"].append(0)  # unconditional: a kill cannot
            cond.notify()             # land "between check and enqueue"

    def forwarder():
        with cond:
            while not state["queue"]:
                cond.wait()
            line = state["queue"].pop(0)
            alive = state["alive"]
        # the single consumer owns the disposition
        (state["answered"] if alive else state["errored"]).append(line)

    def killer():
        with cond:
            state["alive"] = False

    def check():
        disposed = state["answered"] + state["errored"]
        assert disposed == [0] and not state["queue"], (
            f"line 0 needs exactly one disposition: answered="
            f"{state['answered']} errored={state['errored']} "
            f"queued={state['queue']}")

    return [handler, forwarder, killer], check


def router_double_resolve_model(sched):
    """The PRE-fix in-flight cleanup: TWO detectors — the forwarder's
    send-error path and a health-watcher style sweeper — each see the
    same in-flight line and answer it. Interleaved, the client's one
    line gets two replies (and, had the sweeper re-SENT it, the shard
    would fold the cohort into its suspicion store twice — verdict
    corruption). Serial orders pass; one preemption finds it."""
    state = {"inflight": [0], "replies": []}

    def dispose(tag):
        def run():
            if state["inflight"]:            # saw the line...
                line = state["inflight"][0]
                sched.point()                # ... the other detector too
                state["replies"].append((tag, line))
                if line in state["inflight"]:
                    state["inflight"].remove(line)
        return run

    def check():
        assert len(state["replies"]) == 1, (
            f"line 0 disposed {len(state['replies'])} times: "
            f"{state['replies']}")

    return [dispose("error"), dispose("timeout")], check


def router_single_disposition_model(sched):
    """The SHIPPED pattern: taking the line OUT of the shared in-flight
    state (pop under the lock) IS claiming its disposition — whoever
    pops, replies; the loser finds nothing to take. In the real router
    the same ownership is structural: an `_Item` lives in exactly one
    forwarder's batch list, and the error path nulls its slot before
    anything else can see it. Exhaustively clean."""
    lock = sched.lock()
    state = {"inflight": [0], "replies": []}

    def dispose(tag):
        def run():
            with lock:
                line = (state["inflight"].pop(0) if state["inflight"]
                        else None)
            if line is not None:             # we own it now
                state["replies"].append((tag, line))
        return run

    def check():
        assert len(state["replies"]) == 1, (
            f"line 0 disposed {len(state['replies'])} times: "
            f"{state['replies']}")

    return [dispose("error"), dispose("timeout")], check


# --------------------------------------------------------------------------- #
# The straggle-window models (cluster/chaos.py::StraggleResumer): the
# launcher's only NEW thread in the elastic-fleet PR. A SIGSTOP'd host
# has exactly one pending SIGCONT window, and two parties race for it —
# the resumer thread (window elapsed: resume the host) and the launcher
# poll loop (straggler-policy kill or fleet teardown: cancel the window,
# then SIGKILL). The invariant: a window is disposed EXACTLY once, and a
# cancelled window never signals — a SIGCONT landing after the kill
# decision could resume a process mid-SIGKILL (or, later, a recycled
# pid).

def straggle_claim_unguarded_model(sched):
    """The PRE-fix shape: both parties CHECK the entry is pending, then
    act, with nothing making check+claim atomic. A preemption between
    them lets the resumer SIGCONT a window the launcher already
    cancelled on its kill path. Serial orders pass; one preemption
    finds it."""
    entry = {"state": "pending"}
    state = {"signals": [], "cancelled": []}

    def resumer():
        if entry["state"] == "pending":   # saw it pending...
            sched.point()                 # ... the cancel lands here
            entry["state"] = "resumed"
            state["signals"].append("SIGCONT")

    def canceller():
        if entry["state"] == "pending":
            sched.point()
            entry["state"] = "cancelled"
            state["cancelled"].append("kill")

    def check():
        disposed = len(state["signals"]) + len(state["cancelled"])
        assert disposed == 1, (
            f"window disposed {disposed} times: signals="
            f"{state['signals']} cancelled={state['cancelled']}")
        if state["cancelled"]:
            assert not state["signals"], (
                "cancelled window still SIGCONT'd — a killed host got "
                "resumed")

    return [resumer, canceller], check


def straggle_claim_model(sched):
    """The SHIPPED pattern (`StraggleResumer._loop` / `.cancel`): the
    state flip from `pending` IS the claim, taken under the lock; the
    signal runs outside the lock but only by whoever claimed. The loser
    finds the entry already disposed and does nothing. Exhaustively
    clean at the bound that breaks the unguarded version."""
    lock = sched.lock()
    entry = {"state": "pending"}
    state = {"signals": [], "cancelled": []}

    def resumer():
        with lock:
            mine = entry["state"] == "pending"
            if mine:
                entry["state"] = "resumed"
        if mine:                          # we own the disposition
            state["signals"].append("SIGCONT")

    def canceller():
        with lock:
            mine = entry["state"] == "pending"
            if mine:
                entry["state"] = "cancelled"
        if mine:
            state["cancelled"].append("kill")

    def check():
        disposed = len(state["signals"]) + len(state["cancelled"])
        assert disposed == 1, (
            f"window disposed {disposed} times: signals="
            f"{state['signals']} cancelled={state['cancelled']}")
        if state["cancelled"]:
            assert not state["signals"], (
                "cancelled window still SIGCONT'd — a killed host got "
                "resumed")

    return [resumer, canceller], check


# --------------------------------------------------------------------------- #
# The metrics-plane models (obs/metrics, r18): the two interleavings
# that decide its design — a scrape reading a torn multi-field histogram
# update, and a ring rotation overwriting a concurrent append — each as
# the broken pattern the naive implementation would have, and the
# shipped pattern, pinned schedule-clean.

def metrics_scrape_torn_model(sched):
    """The PRE-fix histogram update: `observe` bumps the bucket array
    and the running count as two separate unlocked stores; a concurrent
    scrape (`dump`) reading BETWEEN them exports a payload whose `count`
    disagrees with its bucket counts — a torn snapshot the fleet merge
    would then propagate into every downstream quantile. Serial orders
    pass; one preemption finds it."""
    hist = {"counts": [0], "count": 0}
    seen = []

    def observer():
        hist["counts"][0] += 1        # the bucket-array store...
        sched.point()                 # ... the scrape lands here ...
        hist["count"] += 1            # ... before the count store

    def scraper():
        seen.append({"counts": list(hist["counts"]),
                     "count": hist["count"]})

    def check():
        for snap in seen:
            assert sum(snap["counts"]) == snap["count"], (
                f"torn scrape: buckets {snap['counts']} vs count "
                f"{snap['count']}")

    return [observer, scraper], check


def metrics_scrape_model(sched):
    """The SHIPPED pattern (`Histogram.observe` / `.snapshot`): the
    multi-field update and the snapshot copy each run under the metric's
    lock, so every exported payload is internally coherent — and
    repeated scrapes see a monotonic count — no matter how the scraper
    interleaves with the bumper. Exhaustively clean at the bound that
    breaks the unlocked version."""
    lock = sched.lock()
    hist = {"counts": [0], "count": 0}
    seen = []

    def observer():
        with lock:
            hist["counts"][0] += 1
            sched.point()
            hist["count"] += 1

    def scraper():
        for _ in range(2):
            with lock:
                seen.append({"counts": list(hist["counts"]),
                             "count": hist["count"]})

    def check():
        for snap in seen:
            assert sum(snap["counts"]) == snap["count"], (
                f"torn scrape: buckets {snap['counts']} vs count "
                f"{snap['count']}")
        counts = [snap["count"] for snap in seen]
        assert counts == sorted(counts), (
            f"scraped counts regressed: {counts}")

    return [observer, scraper], check


def metrics_rotate_lost_model(sched):
    """The PRE-fix ring rotation: a rotator thread reads the file, trims
    to the newest lines, and writes the trimmed copy back while the
    scraper appends concurrently. An append landing between the
    rotator's read and its write-back is overwritten — the NEWEST
    snapshot (the one an operator debugging a live incident needs most)
    silently vanishes. Serial orders pass; one preemption finds it."""
    file = {"lines": ["s0", "s1"]}

    def appender():
        file["lines"] = list(file["lines"]) + ["s2"]

    def rotator():
        kept = file["lines"][-1:]     # read + trim...
        sched.point()                 # ... the append lands here ...
        file["lines"] = kept          # ... and the write-back loses it

    def check():
        assert "s2" in file["lines"], (
            f"rotation lost the newest snapshot: {file['lines']}")

    return [appender, rotator], check


def metrics_rotate_model(sched):
    """The SHIPPED pattern (`MetricsScraper.scrape_once` +
    `append_snapshot`): the ring has ONE writer — append and rotation
    happen inside the same lock-held call — so no snapshot can land
    between a rotation's read and its write-back; rotation only ever
    drops lines OLDER than the newest append. Exhaustively clean at the
    bound that breaks the unlocked version."""
    lock = sched.lock()
    file = {"lines": ["s0", "s1"]}

    def appender():
        with lock:
            lines = list(file["lines"])
            sched.point()
            file["lines"] = lines + ["s2"]

    def rotator():
        with lock:
            kept = file["lines"][-1:]
            sched.point()
            file["lines"] = kept

    def check():
        assert "s2" in file["lines"], (
            f"rotation lost the newest snapshot: {file['lines']}")

    return [appender, rotator], check


def incident_bundle_torn_model(sched):
    """The PRE-fix incident index claim (`obs/trace/incident.py`): two
    edge events capture concurrently, each reading the shared next-n
    counter and bumping it as separate unlocked steps. Both read the
    same n, both write `incident-<n>.json`, and `os.replace` makes the
    second silently OVERWRITE the first — one incident's evidence
    vanishes exactly when two incidents coincide, which is exactly when
    the evidence matters (a burn edge and the arc death that caused it
    land together). Serial orders pass; one preemption finds it."""
    state = {"n": 1}
    files = {}   # name -> reason (the os.replace'd directory)

    def capture(reason):
        def worker():
            n = state["n"]            # read the claim...
            sched.point()             # ... the other capture lands here
            state["n"] = n + 1        # ... then bump and write
            files[f"incident-{n}"] = reason
        return worker

    def check():
        assert len(files) == 2, (
            f"a bundle was overwritten: only {sorted(files)} survive "
            f"({files})")

    return [capture("slo_burn"), capture("arc_dead")], check


def incident_bundle_model(sched):
    """The SHIPPED pattern (`IncidentRecorder.capture`): the index is
    claimed — read AND bump — inside the recorder lock BEFORE any I/O,
    so concurrent captures hold distinct n and their atomic renames can
    never collide on a filename. Exhaustively clean at the bound that
    breaks the unlocked claim."""
    lock = sched.lock()
    state = {"n": 1}
    files = {}

    def capture(reason):
        def worker():
            with lock:
                n = state["n"]
                sched.point()
                state["n"] = n + 1
            files[f"incident-{n}"] = reason
        return worker

    def check():
        assert len(files) == 2, (
            f"a bundle was overwritten: only {sorted(files)} survive "
            f"({files})")

    return [capture("slo_burn"), capture("arc_dead")], check


def router_splice_lost_model(sched):
    """The PRE-fix splice ring (`FleetRouter._record_trace` before the
    joined buffer): two connection threads append their joined records
    to a shared bounded list with an UNLOCKED read-extend-store (the
    `list + [record]` rebind pattern). An append landing between the
    other thread's read and its store is dropped — a joined trace
    silently vanishes from the window and the critical-path histogram
    undercounts the convoy. Serial orders pass; one preemption finds
    it."""
    ring = {"records": []}

    def splice(record):
        def worker():
            records = list(ring["records"])   # read...
            sched.point()                     # ... the other splice lands
            ring["records"] = records + [record]   # ... rebind loses it
        return worker

    def check():
        assert len(ring["records"]) == 2, (
            f"a joined record was lost: {ring['records']}")

    return [splice("t1"), splice("t2")], check


def router_splice_model(sched):
    """The SHIPPED pattern (`TraceBuffer.add` under its internal lock —
    the joined ring IS a TraceBuffer): append and the completed-count
    bump happen atomically per record, so concurrent connection threads
    each land their whole record and the count matches the ring.
    Exhaustively clean at the bound that breaks the unlocked rebind."""
    lock = sched.lock()
    ring = {"records": [], "completed": 0}

    def splice(record):
        def worker():
            with lock:
                ring["records"].append(record)
                sched.point()
                ring["completed"] += 1
        return worker

    def check():
        assert len(ring["records"]) == 2, (
            f"a joined record was lost: {ring['records']}")
        assert ring["completed"] == 2, (
            f"completed count diverged: {ring['completed']}")

    return [splice("t1"), splice("t2")], check


def scrape_publish_torn_model(sched):
    """The WRONG way to take the r20 L02 fix (`MetricsScraper.
    scrape_once` held the scraper lock across the fsync'ing
    `append_snapshot`): moving the append out by dropping the lock
    entirely. Two scrape rounds (the scraper thread plus a test or
    selfcheck driving `scrape_once` directly) bump `scrapes` with an
    unlocked read-modify-write — one bump is lost and `last_snapshot`
    no longer corresponds to the count. One preemption finds it."""
    state = {"scrapes": 0, "last": None}
    appended = []

    def round_(tag):
        def worker():
            appended.append(tag)   # the (correctly) out-of-lock append
            n = state["scrapes"]
            sched.point()
            state["scrapes"] = n + 1
            state["last"] = tag
        return worker

    def check():
        assert state["scrapes"] == len(appended), (
            f"a scrape publish was lost: count {state['scrapes']} != "
            f"{len(appended)} appends")

    return [round_("a"), round_("b")], check


def scrape_publish_model(sched):
    """The SHIPPED snapshot-then-release pattern: the fsync'ing append
    runs OUTSIDE the scraper lock (the disk wait no longer convoys
    readers of `scrapes`/`last_snapshot`), then count and snapshot
    publish together under the lock. Exhaustively clean at the bound
    that breaks the unlocked variant."""
    lock = sched.lock()
    state = {"scrapes": 0, "last": None}
    appended = []

    def round_(tag):
        def worker():
            appended.append(tag)   # disk append, no lock held
            sched.point()          # the other round may land here
            with lock:
                n = state["scrapes"]
                sched.point()
                state["scrapes"] = n + 1
                state["last"] = tag
        return worker

    def check():
        assert state["scrapes"] == len(appended) == 2, (
            f"publish tore: count {state['scrapes']}, "
            f"{len(appended)} appends")
        assert state["last"] in appended

    return [round_("a"), round_("b")], check


def liveness_hook_racy_model(sched):
    """The PRE-fix `FleetRouter._set_liveness`: the liveness hook ran
    UNDER the hot ring lock, and the launcher's hook persists the
    manifest under its own lock. An independent launcher path that
    persists first and then inspects the ring takes the same two locks
    in the opposite order — bounded exploration finds the deadlock
    schedule (the harness reports an empty runnable set)."""
    ring = sched.lock()
    manifest = sched.lock()

    def flip():                    # router: hook inside the ring lock
        with ring:
            with manifest:         # the hook persists the manifest
                pass

    def persist_then_inspect():    # launcher: persist, then read ring
        with manifest:
            with ring:
                pass

    def check():
        pass

    return [flip, persist_then_inspect], check


def liveness_hook_model(sched):
    """The SHIPPED split: liveness transitions serialize on a COLD
    membership lock; the ring lock is only ever taken inside it (one
    global order membership -> {ring, manifest}) and never spans the
    hook. Two detectors reporting the same death dedupe on the
    membership lock (persist-before-flip: exactly one persists, one
    flips). The opposite-order launcher path from the racy model is
    ruled out by the static lock-order graph instead (the only edges
    are membership -> manifest and membership -> ring — acyclic), so
    this model stays small enough to exhaust. Exhaustively clean."""
    membership = sched.lock()
    ring = sched.lock()
    manifest = sched.lock()
    state = {"alive": True, "flips": 0, "persists": 0}

    def detect():                  # two watchers report the same death
        def worker():
            with membership:
                # alive only ever changes under membership, so the
                # dedupe check needs no ring acquisition
                if not state["alive"]:
                    return         # deduped: the flip already happened
                with manifest:     # the hook, outside the ring lock
                    state["persists"] += 1
                with ring:
                    state["alive"] = False
                    state["flips"] += 1
        return worker

    def check():
        assert state["flips"] == 1 and state["persists"] == 1, (
            f"transition did not dedupe: {state}")
        assert state["alive"] is False

    return [detect(), detect()], check


# --------------------------------------------------------------------------- #
# The thread-surface covenant (BMT-L06): every file that constructs a
# Thread/Lock/Condition must be named here by the model that pins its
# synchronization pattern, or carry a reasoned per-line noqa. Paths are
# repo-relative. Honest mapping only: a file listed under a model must
# actually follow the pattern that model exercises.

MODEL_COVERAGE = {
    # The serve stats counters (PR 14's day-one fix) — and every other
    # "one lock guards a handful of fields/dict entries" class: program
    # cache, metric cells, telemetry writer, job-log rotation.
    "lost_update_model": (
        "byzantinemomentum_tpu/serve/service.py",),
    "fixed_counter_model": (
        "byzantinemomentum_tpu/serve/service.py",
        "byzantinemomentum_tpu/serve/programs.py",
        "byzantinemomentum_tpu/obs/metrics/registry.py",
        "byzantinemomentum_tpu/obs/recorder.py",
        "byzantinemomentum_tpu/utils/jobs.py"),
    "router_forward_queue_model": (
        "byzantinemomentum_tpu/serve/fleet/router.py",),
    "router_single_disposition_model": (
        "byzantinemomentum_tpu/serve/fleet/router.py",),
    "straggle_claim_model": (
        "byzantinemomentum_tpu/cluster/straggler.py",),
    "metrics_scrape_model": (
        "byzantinemomentum_tpu/obs/metrics/scrape.py",),
    "metrics_rotate_model": (
        "byzantinemomentum_tpu/obs/metrics/scrape.py",),
    "incident_bundle_model": (
        "byzantinemomentum_tpu/obs/trace/incident.py",),
    "router_splice_model": (
        "byzantinemomentum_tpu/serve/fleet/router.py",
        "byzantinemomentum_tpu/obs/trace/request.py"),
    # r20: the two day-one BMT-L fixes, pinned schedule-clean.
    "scrape_publish_model": (
        "byzantinemomentum_tpu/obs/metrics/scrape.py",
        "byzantinemomentum_tpu/obs/metrics/slo.py"),
    "liveness_hook_model": (
        "byzantinemomentum_tpu/serve/fleet/router.py",
        "byzantinemomentum_tpu/serve/fleet/launcher.py"),
}


def covered_files():
    """Every repo-relative path some model vouches for."""
    out = set()
    for files in MODEL_COVERAGE.values():
        out.update(files)
    return out


def selfcheck(max_preemptions=3):
    """The lint-tier schedule smoke: every planted bug — the serve
    counter lost-update, the two router races (lost forward, double
    disposition), the straggle-window claim race, the two metrics-plane
    races (torn scrape, rotation-lost append) and the two r19 causal-
    plane races (torn incident bundle, lost splice) — must be FOUND
    within the preemption bound, and every fixed pattern must survive
    the same exhaustive exploration clean. Returns a JSON-safe report
    with `ok`."""
    t0 = time.monotonic()
    broken = explore(lost_update_model, max_preemptions=max_preemptions)
    fixed = explore(fixed_counter_model, max_preemptions=max_preemptions)
    r_lost = explore(router_lost_forward_model,
                     max_preemptions=max_preemptions)
    r_double = explore(router_double_resolve_model,
                       max_preemptions=max_preemptions)
    r_queue = explore(router_forward_queue_model,
                      max_preemptions=max_preemptions)
    r_single = explore(router_single_disposition_model,
                       max_preemptions=max_preemptions)
    s_unguarded = explore(straggle_claim_unguarded_model,
                          max_preemptions=max_preemptions)
    s_claim = explore(straggle_claim_model,
                      max_preemptions=max_preemptions)
    m_torn = explore(metrics_scrape_torn_model,
                     max_preemptions=max_preemptions)
    m_scrape = explore(metrics_scrape_model,
                       max_preemptions=max_preemptions)
    m_lost = explore(metrics_rotate_lost_model,
                     max_preemptions=max_preemptions)
    m_rotate = explore(metrics_rotate_model,
                       max_preemptions=max_preemptions)
    i_torn = explore(incident_bundle_torn_model,
                     max_preemptions=max_preemptions)
    i_bundle = explore(incident_bundle_model,
                       max_preemptions=max_preemptions)
    j_lost = explore(router_splice_lost_model,
                     max_preemptions=max_preemptions)
    j_splice = explore(router_splice_model,
                       max_preemptions=max_preemptions)
    p_torn = explore(scrape_publish_torn_model,
                     max_preemptions=max_preemptions)
    p_publish = explore(scrape_publish_model,
                        max_preemptions=max_preemptions)
    h_racy = explore(liveness_hook_racy_model,
                     max_preemptions=max_preemptions)
    h_split = explore(liveness_hook_model,
                      max_preemptions=max_preemptions)
    router_fixed_clean = (r_queue.ok and r_queue.exhausted
                          and r_single.ok and r_single.exhausted)
    straggle_fixed_clean = s_claim.ok and s_claim.exhausted
    metrics_fixed_clean = (m_scrape.ok and m_scrape.exhausted
                           and m_rotate.ok and m_rotate.exhausted)
    incident_fixed_clean = (i_bundle.ok and i_bundle.exhausted
                            and j_splice.ok and j_splice.exhausted)
    locks_fixed_clean = (p_publish.ok and p_publish.exhausted
                         and h_split.ok and h_split.exhausted)
    return {
        "ok": (bool(broken.failures) and fixed.ok and fixed.exhausted
               and bool(r_lost.failures) and bool(r_double.failures)
               and router_fixed_clean
               and bool(s_unguarded.failures) and straggle_fixed_clean
               and bool(m_torn.failures) and bool(m_lost.failures)
               and metrics_fixed_clean
               and bool(i_torn.failures) and bool(j_lost.failures)
               and incident_fixed_clean
               and bool(p_torn.failures) and bool(h_racy.failures)
               and locks_fixed_clean),
        "lost_update_found": bool(broken.failures),
        "witness": broken.failures[0].schedule if broken.failures else None,
        "schedules_prefix": broken.runs,
        "schedules_fixed": fixed.runs,
        "fixed_clean": fixed.ok,
        "router_lost_forward_found": bool(r_lost.failures),
        "router_lost_forward_witness": (r_lost.failures[0].schedule
                                        if r_lost.failures else None),
        "router_double_resolve_found": bool(r_double.failures),
        "router_double_resolve_witness": (r_double.failures[0].schedule
                                          if r_double.failures else None),
        "router_fixed_clean": router_fixed_clean,
        "schedules_router": (r_lost.runs + r_double.runs + r_queue.runs
                             + r_single.runs),
        "straggle_claim_found": bool(s_unguarded.failures),
        "straggle_claim_witness": (s_unguarded.failures[0].schedule
                                   if s_unguarded.failures else None),
        "straggle_fixed_clean": straggle_fixed_clean,
        "schedules_straggle": s_unguarded.runs + s_claim.runs,
        "metrics_scrape_torn_found": bool(m_torn.failures),
        "metrics_scrape_torn_witness": (m_torn.failures[0].schedule
                                        if m_torn.failures else None),
        "metrics_rotate_lost_found": bool(m_lost.failures),
        "metrics_rotate_lost_witness": (m_lost.failures[0].schedule
                                        if m_lost.failures else None),
        "metrics_fixed_clean": metrics_fixed_clean,
        "schedules_metrics": (m_torn.runs + m_scrape.runs + m_lost.runs
                              + m_rotate.runs),
        "incident_bundle_torn_found": bool(i_torn.failures),
        "incident_bundle_torn_witness": (i_torn.failures[0].schedule
                                         if i_torn.failures else None),
        "router_splice_lost_found": bool(j_lost.failures),
        "router_splice_lost_witness": (j_lost.failures[0].schedule
                                       if j_lost.failures else None),
        "incident_fixed_clean": incident_fixed_clean,
        "schedules_incident": (i_torn.runs + i_bundle.runs + j_lost.runs
                               + j_splice.runs),
        "scrape_publish_torn_found": bool(p_torn.failures),
        "scrape_publish_torn_witness": (p_torn.failures[0].schedule
                                        if p_torn.failures else None),
        "liveness_hook_deadlock_found": bool(h_racy.failures),
        "liveness_hook_deadlock_witness": (h_racy.failures[0].schedule
                                           if h_racy.failures else None),
        "locks_fixed_clean": locks_fixed_clean,
        "schedules_locks": (p_torn.runs + p_publish.runs + h_racy.runs
                            + h_split.runs),
        "exhausted": (broken.exhausted and fixed.exhausted
                      and r_lost.exhausted and r_double.exhausted
                      and r_queue.exhausted and r_single.exhausted
                      and s_unguarded.exhausted and s_claim.exhausted
                      and m_torn.exhausted and m_scrape.exhausted
                      and m_lost.exhausted and m_rotate.exhausted
                      and i_torn.exhausted and i_bundle.exhausted
                      and j_lost.exhausted and j_splice.exhausted
                      and p_torn.exhausted and p_publish.exhausted
                      and h_racy.exhausted and h_split.exhausted),
        "max_preemptions": max_preemptions,
        "seconds": round(time.monotonic() - t0, 3),
    }
