"""BMT-T — concurrency contracts: a RacerD-style lock-set lint over the
host-thread surface.

jaxlint (`analysis/lint.py`) covers traced JAX code and hlolint covers
lowered HLO; this module covers the THIRD execution substrate the serve/
cluster layers grew: host threads. The analysis is pure AST (one pass,
no imports executed) and per class, in the spirit of RacerD (Blackshear
et al., CACM 2019 — see PAPERS.md): infer which *thread role* each
method runs under, infer each shared attribute's *guarding lock* from
the lock held on the majority of its accesses, and report the
disciplined-concurrency violations this codebase can actually have.
What RacerD's Java deployment needed and Python does not — ownership
inference and value-escape tracking — is deliberately dropped: under
the GIL single bytecodes are atomic, so the bug class that matters is
the compound check-then-act / read-modify-write on `self` state shared
across threads, which the role × lock-set table catches.

Thread-role inference, per class (documented in the README):

  * a method passed as `threading.Thread(target=self.m)` is a thread
    entry — it and every method reachable from it through same-class
    `self.x()` calls run under role `thread:m`;
  * `handle` of a `socketserver.*RequestHandler` subclass (and its
    same-class callees) runs under role `handler` — one per connection
    under `ThreadingTCPServer`;
  * a bound method that ESCAPES by reference (`Worker(self._cb, ...)`,
    `x = self._cb`) is assumed to run on whatever thread calls it back:
    role `escape:m`. This is exactly how `serve/service.py` hands
    `_dispatch`/`_resolve` to the microbatcher's daemon threads;
  * public methods (and private ones nobody in the class calls) run
    under role `caller`;
  * `__init__` is excluded everywhere: construction happens-before any
    thread the object starts (the RacerD ownership assumption, reduced
    to the one case Python needs).

Only modules that import `threading` or `socketserver` are analyzed —
a class that never touches the thread machinery cannot share state
across threads it does not create (callbacks it hands to OTHER modules'
threads are that module's `escape:` surface).

Lock-set inference: a *lock attribute* is any `self.x` assigned from
`threading.Lock/RLock/Condition`. An access holds the locks of every
enclosing `with self.lock:` block, plus the locks held at EVERY
same-class call site of its method (so `_due`, only ever called by the
flusher inside `with self._cond:`, is correctly seen as guarded).

Rules (registered in `lint.RULES` beside the E-family, so the
`# bmt: noqa[BMT-Txx] reason` contract, BMT-E00 reason enforcement and
BMT-E09 dead-noqa detection all apply unchanged):

  BMT-T01  unguarded-cross-thread-write   an attribute written in one
           role and touched in another, with a write access holding no
           lock — the lost-update shape (`x += 1` from two threads).
  BMT-T02  inconsistent-guard             one attribute guarded by
           DIFFERENT locks on different accesses — each thread is
           mutually excluded only against itself.
  BMT-T03  lock-order-inversion           a cycle in the class's lock
           acquisition graph (A taken under B and B under A): the
           classic ABBA deadlock.
  BMT-T04  blocking-call-under-lock       `time.sleep`, socket calls,
           `future.result()`, `Event.wait`, `Thread.join`,
           `queue.get` ... while holding a lock — every other thread
           needing the lock stalls behind an unbounded wait.
           (`Condition.wait` on the held condition is the one correct
           blocking-under-lock pattern and is exempt.)
  BMT-T05  leaked-thread                  a non-daemon `Thread` that is
           never joined (and never marked daemon) — it outlives its
           owner and blocks interpreter shutdown.

The dynamic twin of this module is `analysis/schedule.py`: what the
lock-set table claims statically, the deterministic interleaving
harness demonstrates (and regression-pins) by exploring schedules.
"""

import ast

from byzantinemomentum_tpu.analysis.lint import (
    Violation, _dotted, _terminal, rule)

__all__ = ["ClassThreads", "module_classes"]


# --------------------------------------------------------------------------- #
# Shared syntactic helpers

_LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition",
                             "NamedLock", "NamedCondition"})
_EVENT_FACTORIES = frozenset({"Event", "Semaphore", "BoundedSemaphore",
                              "Barrier"})
_QUEUE_FACTORIES = frozenset({"Queue", "SimpleQueue", "LifoQueue",
                              "PriorityQueue"})

# Method calls that mutate the receiver in place: `self.q.append(x)` is a
# WRITE of `q` even though the attribute node itself is a Load. (Plain
# `.get`/lookups stay reads — a dict `.get` is pure.)
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "put", "put_nowait", "set",
})

# Call terminals that block unboundedly (T04). `.wait`/`.join`/`.get`
# are handled separately — they need receiver context.
_BLOCKING_TERMINALS = frozenset({
    "sleep", "result", "recv", "recv_into", "accept", "connect",
    "sendall", "urlopen", "getaddrinfo",
})

_SELF_NAMES = frozenset({"self"})


def _self_attr(node):
    """`self.x` -> "x" (None for anything else)."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id in _SELF_NAMES):
        return node.attr
    return None


def _imports_threading(tree):
    # The named wrappers (utils/locking) put a module in scope exactly
    # like a bare `import threading` would: they are locks.
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] in ("threading", "socketserver")
                   or a.name.endswith(".locking")
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            module = node.module or ""
            if (module.split(".")[0] in ("threading", "socketserver")
                    or module.endswith("locking")
                    or any(a.name in ("locking", "NamedLock",
                                      "NamedCondition")
                           for a in node.names)):
                return True
    return False


def _is_thread_call(node):
    return isinstance(node, ast.Call) and _terminal(node.func) == "Thread"


def _thread_target(call):
    """The `target=` expression of a Thread(...) call (None if absent)."""
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    return None


def _thread_is_daemon(call):
    for kw in call.keywords:
        if (kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True):
            return True
    return False


# --------------------------------------------------------------------------- #
# Per-class analysis

class ClassThreads:
    """The thread-role / lock-set table of one ClassDef."""

    def __init__(self, mod, node):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.methods = {c.name: c for c in node.body
                        if isinstance(c, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
        self.handler = any("RequestHandler" in (_terminal(b) or "")
                           for b in node.bases)
        self._classify_attrs()
        self._find_entries_and_escapes()
        self._call_graph()
        self._infer_roles()
        self._inherit_locks()
        self._collect_accesses()

    # -- attribute classification --------------------------------------- #

    def _classify_attrs(self):
        """Which `self.x` attributes are locks / events / queues /
        threads, from their construction sites."""
        self.lock_attrs, self.event_attrs = set(), set()
        self.queue_attrs, self.thread_attrs = set(), set()
        for method in self.methods.values():
            for stmt in ast.walk(method):
                if not isinstance(stmt, ast.Assign):
                    continue
                for target in stmt.targets:
                    attr = _self_attr(target)
                    if attr is None or not isinstance(stmt.value, ast.Call):
                        continue
                    factory = _terminal(stmt.value.func)
                    if factory in _LOCK_FACTORIES:
                        self.lock_attrs.add(attr)
                    elif factory in _EVENT_FACTORIES:
                        self.event_attrs.add(attr)
                    elif factory in _QUEUE_FACTORIES:
                        self.queue_attrs.add(attr)
                    elif factory == "Thread":
                        self.thread_attrs.add(attr)

    # -- thread entries and escaped callbacks ---------------------------- #

    def _find_entries_and_escapes(self):
        self.entries = set()      # methods that are Thread targets
        self.escapes = set()      # methods handed out by reference
        target_nodes = set()
        for method in self.methods.values():
            for call in ast.walk(method):
                if not _is_thread_call(call):
                    continue
                target = _thread_target(call)
                attr = _self_attr(target)
                if attr in self.methods:
                    self.entries.add(attr)
                    target_nodes.add(id(target))
        for method in self.methods.values():
            for n in ast.walk(method):
                attr = _self_attr(n)
                if (attr not in self.methods or id(n) in target_nodes
                        or not isinstance(n.ctx, ast.Load)):
                    continue
                parent = self.mod.parent.get(n)
                if isinstance(parent, ast.Call) and parent.func is n:
                    continue  # a plain `self.m(...)` call, not an escape
                self.escapes.add(attr)

    # -- same-class call graph ------------------------------------------- #

    def _call_graph(self):
        self.calls = {m: [] for m in self.methods}   # m -> [(callee, node)]
        for name, method in self.methods.items():
            for call in ast.walk(method):
                if not isinstance(call, ast.Call):
                    continue
                callee = _self_attr(call.func)
                if callee in self.methods:
                    self.calls[name].append((callee, call))

    # -- roles ------------------------------------------------------------ #

    def _infer_roles(self):
        """method -> set of role strings. Seeds: thread entries, the
        handler entry, escaped callbacks, and `caller` for public (or
        nowhere-called private) methods; roles then propagate along
        same-class call edges (`__init__` is ownership: excluded)."""
        roles = {m: set() for m in self.methods}
        for m in self.entries:
            roles[m].add(f"thread:{m}")
        if self.handler and "handle" in self.methods:
            roles["handle"].add("handler")
        for m in self.escapes:
            roles[m].add(f"escape:{m}")
        called = set()
        for caller, edges in self.calls.items():
            if caller == "__init__":
                continue
            called.update(callee for callee, _ in edges)
        for m in self.methods:
            if m == "__init__":
                continue
            public = not m.startswith("_") or (m.startswith("__")
                                               and m.endswith("__"))
            if public or (m not in called and not roles[m]):
                roles[m].add("caller")
        changed = True
        while changed:
            changed = False
            for caller, edges in self.calls.items():
                if caller == "__init__":
                    continue
                for callee, _ in edges:
                    missing = roles[caller] - roles[callee]
                    if missing:
                        roles[callee] |= missing
                        changed = True
        self.roles = roles

    # -- lock sets --------------------------------------------------------- #

    def _with_locks(self, node, method):
        """Lock attributes held at `node` through enclosing `with
        self.lock:` blocks inside `method`."""
        held = set()
        cur = self.mod.parent.get(node)
        while cur is not None and cur is not method:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    attr = _self_attr(item.context_expr)
                    if attr in self.lock_attrs:
                        held.add(attr)
            cur = self.mod.parent.get(cur)
        return held

    def _inherit_locks(self):
        """Locks a method's body may assume: the intersection over every
        same-class call site of (locks held at the site + the caller's
        own inherited locks). Monotone fixpoint from the empty set."""
        sites = {m: [] for m in self.methods}
        for caller, edges in self.calls.items():
            if caller == "__init__":
                continue
            for callee, call in edges:
                sites[callee].append(
                    (caller, self._with_locks(call, self.methods[caller])))
        inherited = {m: set() for m in self.methods}
        changed = True
        while changed:
            changed = False
            for m, callers in sites.items():
                if not callers:
                    continue
                new = None
                for caller, locks in callers:
                    held = locks | inherited[caller]
                    new = held if new is None else (new & held)
                if new != inherited[m]:
                    inherited[m] = new
                    changed = True
        self.inherited = inherited

    def locks_at(self, node, method_name):
        method = self.methods[method_name]
        return self._with_locks(node, method) | self.inherited[method_name]

    # -- accesses ----------------------------------------------------------- #

    def _collect_accesses(self):
        """attr -> [(kind, roles, locks, line, method)] for every data
        attribute touched outside `__init__`. A write is a Store/Del/
        AugAssign on `self.x`, a Store/Del through `self.x[...]`, or a
        mutating method call `self.x.append(...)`."""
        self.accesses = {}
        for name, method in self.methods.items():
            if name == "__init__":
                continue
            for n in ast.walk(method):
                attr = _self_attr(n)
                if (attr is None or attr in self.lock_attrs
                        or attr in self.event_attrs
                        or attr in self.methods):
                    continue
                kind = "read"
                if isinstance(n.ctx, (ast.Store, ast.Del)):
                    kind = "write"
                else:
                    parent = self.mod.parent.get(n)
                    if (isinstance(parent, ast.Attribute)
                            and parent.value is n
                            and parent.attr in _MUTATORS):
                        grand = self.mod.parent.get(parent)
                        if isinstance(grand, ast.Call) and grand.func is parent:
                            kind = "write"
                    elif (isinstance(parent, ast.Subscript)
                            and parent.value is n
                            and isinstance(parent.ctx, (ast.Store, ast.Del))):
                        kind = "write"
                self.accesses.setdefault(attr, []).append(
                    (kind, frozenset(self.roles[name]),
                     frozenset(self.locks_at(n, name)), n.lineno, name))

    # -- derived tables ------------------------------------------------------ #

    def cross_thread_attrs(self):
        """Attributes written outside `__init__` and touched under >= 2
        distinct roles (internally-synchronized queue attributes are
        exempt — `queue.Queue` carries its own lock)."""
        out = {}
        for attr, accs in self.accesses.items():
            if attr in self.queue_attrs:
                continue
            roles = set()
            for _, r, _, _, _ in accs:
                roles |= r
            if len(roles) >= 2 and any(k == "write" for k, _, _, _, _ in accs):
                out[attr] = accs
        return out

    def acquisition_edges(self):
        """[(held, taken, line)] — lock `taken` acquired while `held` is
        held, anywhere in the class (inherited locks included)."""
        edges = []
        for name, method in self.methods.items():
            for n in ast.walk(method):
                if not isinstance(n, (ast.With, ast.AsyncWith)):
                    continue
                for item in n.items:
                    taken = _self_attr(item.context_expr)
                    if taken not in self.lock_attrs:
                        continue
                    held = self.locks_at(n, name) - {taken}
                    edges.extend((h, taken, n.lineno) for h in sorted(held))
        return edges


def module_classes(mod):
    """The per-class analyses of one `lint.Module` (cached on the module
    object — every T-rule reads the same table). Modules that import
    neither `threading` nor `socketserver` analyze to nothing."""
    cached = getattr(mod, "_bmt_class_threads", None)
    if cached is None:
        if _imports_threading(mod.tree):
            cached = [ClassThreads(mod, n) for n in ast.walk(mod.tree)
                      if isinstance(n, ast.ClassDef)]
        else:
            cached = []
        mod._bmt_class_threads = cached
    return cached


def _role_names(roles):
    return ", ".join(sorted(roles))


# --------------------------------------------------------------------------- #
# BMT-T01 — unguarded cross-thread write

@rule("BMT-T01", "unguarded-cross-thread-write",
      "a `self.*` attribute written on one thread role and touched on "
      "another, with no lock held at a write — the lost-update race")
def _check_unguarded_write(mod):
    out = []
    for cls in module_classes(mod):
        for attr, accs in sorted(cls.cross_thread_attrs().items()):
            all_roles = set()
            for _, roles, _, _, _ in accs:
                all_roles |= roles
            seen_lines = set()
            for kind, roles, locks, line, method in accs:
                if kind != "write" or locks or line in seen_lines:
                    continue
                seen_lines.add(line)
                others = all_roles - roles
                out.append(Violation(
                    mod.path, line, 0, "BMT-T01",
                    f"{cls.name}.{attr} is written in {method}() "
                    f"[{_role_names(roles)}] with no lock, but is also "
                    f"touched from [{_role_names(others) or 'caller'}] — "
                    f"guard every access with one lock"))
    return out


# --------------------------------------------------------------------------- #
# BMT-T02 — inconsistent guard

@rule("BMT-T02", "inconsistent-guard",
      "one cross-thread attribute is guarded by DIFFERENT locks on "
      "different accesses — mutual exclusion holds against nobody")
def _check_inconsistent_guard(mod):
    out = []
    for cls in module_classes(mod):
        for attr, accs in sorted(cls.cross_thread_attrs().items()):
            counts = {}
            for _, _, locks, _, _ in accs:
                for lock in locks:
                    counts[lock] = counts.get(lock, 0) + 1
            if len(counts) < 2:
                continue
            majority = max(sorted(counts), key=lambda k: counts[k])
            seen_lines = set()
            for kind, roles, locks, line, method in accs:
                if not locks or majority in locks or line in seen_lines:
                    continue
                seen_lines.add(line)
                out.append(Violation(
                    mod.path, line, 0, "BMT-T02",
                    f"{cls.name}.{attr} is mostly guarded by "
                    f"self.{majority} but {method}() holds "
                    f"{', '.join('self.' + l for l in sorted(locks))} here "
                    f"— pick ONE guarding lock per attribute"))
    return out


# --------------------------------------------------------------------------- #
# BMT-T03 — lock-order inversion

@rule("BMT-T03", "lock-order-inversion",
      "a cycle in a class's lock-acquisition graph (A under B and B "
      "under A) — the ABBA deadlock")
def _check_lock_order(mod):
    out = []
    for cls in module_classes(mod):
        graph = {}   # held -> {taken: first line}
        for held, taken, line in cls.acquisition_edges():
            graph.setdefault(held, {}).setdefault(taken, line)
        reported = set()
        for a in sorted(graph):
            for b in sorted(graph[a]):
                if a in graph.get(b, ()) and frozenset((a, b)) not in reported:
                    reported.add(frozenset((a, b)))
                    line_ab, line_ba = graph[a][b], graph[b][a]
                    out.append(Violation(
                        mod.path, max(line_ab, line_ba), 0, "BMT-T03",
                        f"{cls.name} acquires self.{b} while holding "
                        f"self.{a} (line {line_ab}) AND self.{a} while "
                        f"holding self.{b} (line {line_ba}) — an ABBA "
                        f"deadlock; order the locks"))
    return out


# --------------------------------------------------------------------------- #
# BMT-T04 — blocking call under a lock

def _blocking_reason(cls, call):
    """Why `call` is an unbounded wait (None if it is not). The held
    condition's own `.wait()` is the one legitimate pattern (it releases
    the lock) and lock `.acquire()` is T03's domain, not T04's."""
    func = call.func
    dotted = _dotted(func)
    if dotted == "time.sleep":
        return "time.sleep() parks the thread with the lock held"
    if dotted is not None and dotted.startswith("subprocess."):
        return f"{dotted}() blocks on a child process"
    if not isinstance(func, ast.Attribute):
        return None
    receiver = _self_attr(func.value)
    name = func.attr
    if receiver in cls.lock_attrs:
        return None
    if name in _BLOCKING_TERMINALS and not isinstance(func.value,
                                                      ast.Constant):
        return f".{name}() is an unbounded wait"
    if name == "wait":
        if isinstance(func.value, ast.Constant):
            return None
        return ".wait() on a non-held primitive blocks with the lock held"
    if name == "join":
        if receiver in cls.thread_attrs:
            return ".join() on a thread blocks with the lock held"
        terminal = _terminal(func.value)
        if terminal and "thread" in terminal.lower():
            return ".join() on a thread blocks with the lock held"
        return None
    if name in ("get", "get_nowait") and receiver in cls.queue_attrs:
        if name == "get":
            return ".get() on a queue blocks with the lock held"
    return None


@rule("BMT-T04", "blocking-call-under-lock",
      "time.sleep / socket ops / future.result() / Event.wait / "
      "Thread.join while holding a lock — everyone needing the lock "
      "stalls behind an unbounded wait")
def _check_blocking_under_lock(mod):
    out = []
    for cls in module_classes(mod):
        for name, method in cls.methods.items():
            if name == "__init__":
                continue
            for call in ast.walk(method):
                if not isinstance(call, ast.Call):
                    continue
                locks = cls.locks_at(call, name)
                if not locks:
                    continue
                reason = _blocking_reason(cls, call)
                if reason is None:
                    continue
                out.append(Violation(
                    mod.path, call.lineno, call.col_offset, "BMT-T04",
                    f"{cls.name}.{name}() holds "
                    f"{', '.join('self.' + l for l in sorted(locks))}: "
                    f"{reason} — move the wait outside the lock"))
    return out


# --------------------------------------------------------------------------- #
# BMT-T05 — leaked thread

def _joined_or_daemonized(mod, binding):
    """Whether the module ever joins `binding` (a local name or a
    `self.x` attr string like "self._worker") or marks it daemon."""
    for n in ast.walk(mod.tree):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "join"
                and _dotted(n.func.value) == binding):
            return True
        if isinstance(n, ast.Assign):
            for target in n.targets:
                if (isinstance(target, ast.Attribute)
                        and target.attr == "daemon"
                        and _dotted(target.value) == binding
                        and isinstance(n.value, ast.Constant)
                        and n.value.value is True):
                    return True
    return False


@rule("BMT-T05", "leaked-thread",
      "a non-daemon Thread that is never joined (nor marked daemon) — "
      "it outlives its owner and blocks interpreter shutdown")
def _check_leaked_thread(mod):
    if not _imports_threading(mod.tree):
        return ()
    out = []
    for node in ast.walk(mod.tree):
        if not _is_thread_call(node) or _thread_is_daemon(node):
            continue
        parent = mod.parent.get(node)
        binding = None
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            binding = _dotted(parent.targets[0])
        if binding is not None and _joined_or_daemonized(mod, binding):
            continue
        out.append(Violation(
            mod.path, node.lineno, node.col_offset, "BMT-T05",
            "Thread created without daemon=True and never joined — pass "
            "daemon=True or join it on the shutdown path"))
    return out
