"""Lowering contracts — golden StableHLO fingerprints over the program
lattice.

`tests/test_diag.py` (PR 4) asserts one lowering invariant at one point
in time: `diagnostics=False` lowers byte-identically to the raw kernels.
This module generalizes that into a *blessed contract* over the whole
program lattice: every cell the builder enumerates
(`analysis/lattice.py` — the GAR × {plain, diag, masked} kernels, their
virtual-mesh sharded forms, and the serve-layer cell programs) is
lowered on fixed abstract specs, fingerprinted (sha256 of the StableHLO
text), and compared against `tests/goldens/lowerings.json`. Any drift
fails the lint tier until a human re-blesses
(`scripts/bless_lowerings.py`) — compilation behavior becomes a reviewed
artifact, not a silent side effect of a refactor.

The same lowering pass feeds the structural linter
(`analysis/hlolint.py`): each cell's declared contract — collective
census, no worker-matrix all-gather, donation honored — is checked
against the text that was just fingerprinted, so `check()` reports both
*that* a cell changed (fingerprint) and *what class of change* is
forbidden outright (structure).

Fingerprints are only comparable within one (jax version, backend) pair;
a mismatch there reports `incomparable` (exit 0 with a message), the same
INCOMPARABLE discipline as `scripts/bench_compare.py` — a toolchain bump
is not lowering drift, it is a re-bless.
"""

import hashlib
import json
import pathlib

__all__ = ["GOLDENS_PATH", "compute_cells", "snapshot", "bless", "check"]

GOLDENS_PATH = (pathlib.Path(__file__).resolve().parents[2]
                / "tests" / "goldens" / "lowerings.json")


def fingerprint(text):
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def _lowered(cells=None):
    """Yield `(cell, text)` over the lattice (one lowering pass —
    fingerprints and structural lint read the same text)."""
    from byzantinemomentum_tpu.analysis import lattice

    cells = lattice.enumerate_cells() if cells is None else cells
    for cell in cells:
        yield cell, cell.lower()


def compute_cells(cells=None):
    """name -> fingerprint over the enumerated lattice — PINNED cells
    only: structural-only cells (`LatticeCell.pin=False`, e.g. the full
    fused step) are linted by `check` but their churning bytes never
    enter the blessed goldens."""
    return {cell.key: fingerprint(text) for cell, text in _lowered(cells)
            if cell.pin}


def snapshot():
    """The blessable artifact: the cell fingerprints plus the toolchain
    coordinates they are only comparable under."""
    import jax

    from byzantinemomentum_tpu.analysis import lattice

    return {
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "spec": lattice.spec_info(),
        "cells": compute_cells(),
    }


def bless(path=GOLDENS_PATH):
    """(Re)write the goldens. Deterministic output (sorted keys, no
    timestamps): blessing twice in one toolchain is byte-idempotent.
    Cells the enumerator no longer produces are pruned (the whole file is
    the enumeration — `scripts/bless_lowerings.py` reports what fell
    out)."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(snapshot(), indent=2, sort_keys=True) + "\n")
    return path


def check(path=GOLDENS_PATH):
    """Compare the current lattice against the blessed goldens, and run
    the structural linter over every lowered cell.

    Returns a report dict with `status` one of:
      "ok"            — every fingerprint matches and no structural
                        violations;
      "drift"         — `drifted`/`added`/`removed` name the cells;
      "lint"          — fingerprints match but `violations` lists
                        BMT-H structural findings;
      "incomparable"  — goldens were blessed under another jax version or
                        backend (re-bless, do not fail CI on it);
      "missing"       — no goldens file (run scripts/bless_lowerings.py).
    """
    import jax

    from byzantinemomentum_tpu.analysis import hlolint

    path = pathlib.Path(path)
    if not path.is_file():
        return {"status": "missing", "path": str(path)}
    blessed = json.loads(path.read_text())
    here = {"jax": jax.__version__, "backend": jax.default_backend()}
    if (blessed.get("jax"), blessed.get("backend")) != (
            here["jax"], here["backend"]):
        return {"status": "incomparable", "blessed": {
            "jax": blessed.get("jax"), "backend": blessed.get("backend")},
            "current": here}
    current = {}
    violations = []
    for cell, text in _lowered():
        if cell.pin:
            current[cell.key] = fingerprint(text)
        violations.extend(
            hlolint.lint_module(text, cell.expect, label=cell.key))
    golden = blessed.get("cells", {})
    drifted = sorted(k for k in golden if k in current
                     and golden[k] != current[k])
    added = sorted(k for k in current if k not in golden)
    removed = sorted(k for k in golden if k not in current)
    if drifted or added or removed:
        status = "drift"
    elif violations:
        status = "lint"
    else:
        status = "ok"
    from byzantinemomentum_tpu.analysis import lattice

    structural = sum(1 for c in lattice.enumerate_cells() if not c.pin)
    return {"status": status, "drifted": drifted, "added": added,
            "removed": removed, "checked": len(current),
            "structural": structural,
            "violations": [v.as_dict() for v in violations]}
