"""Lowering contracts — golden StableHLO fingerprints per GAR cell.

`tests/test_diag.py` (PR 4) asserts one lowering invariant at one point
in time: `diagnostics=False` lowers byte-identically to the raw kernels.
This module generalizes that into a *blessed contract*: every
(GAR x variant) cell — the plain kernel, the diagnostics kernel, and the
masked dynamic-quorum degradation path — is lowered on a fixed spec,
fingerprinted (sha256 of the StableHLO text), and compared against
`tests/goldens/lowerings.json`. Any drift fails the lint tier until a
human re-blesses (`scripts/bless_lowerings.py`) — compilation behavior
becomes a reviewed artifact, not a silent side effect of a refactor.

Fingerprints are only comparable within one (jax version, backend) pair;
a mismatch there reports `incomparable` (exit 0 with a message), the same
INCOMPARABLE discipline as `scripts/bench_compare.py` — a toolchain bump
is not lowering drift, it is a re-bless.
"""

import hashlib
import json
import pathlib

__all__ = ["GOLDENS_PATH", "CELL_GARS", "VARIANTS", "compute_cells",
           "snapshot", "bless", "check"]

GOLDENS_PATH = (pathlib.Path(__file__).resolve().parents[2]
                / "tests" / "goldens" / "lowerings.json")

# Every first-tier registered rule with real kernels (the `native-` tier
# shares these kernels; `template` declines its own check)
CELL_GARS = ("average", "median", "trmean", "phocas", "meamed", "krum",
             "bulyan", "aksel", "cge", "brute")
VARIANTS = ("plain", "diag", "masked")

# The canonical spec: the benchmark's n=11 worker grid, f=2, a d big
# enough that every kernel takes its vectorized path
N, D, F = 11, 16, 2


def _cell_fn(gar, variant):
    """The traceable program of one cell (call with aval specs only)."""
    from byzantinemomentum_tpu.faults import quorum

    if variant == "plain":
        return lambda G: gar.unchecked(G, f=F)
    if variant == "diag":
        return lambda G: gar.diagnosed(G, f=F)
    if variant == "masked":
        return lambda G, active: quorum.masked_aggregate(
            gar, G, active, f_decl=F, dynamic=True)
    raise ValueError(f"Unknown lowering variant {variant!r}")


def _cell_text(gar, variant):
    import jax
    import jax.numpy as jnp

    spec = jax.ShapeDtypeStruct((N, D), jnp.float32)
    mask = jax.ShapeDtypeStruct((N,), jnp.bool_)
    args = (spec,) if variant != "masked" else (spec, mask)
    return jax.jit(_cell_fn(gar, variant)).lower(*args).as_text()


def fingerprint(text):
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def compute_cells(gars=None, variants=None):
    """name -> fingerprint over the (GAR x variant) grid (defaults read
    the module attributes at call time, so tests can shrink the grid)."""
    from byzantinemomentum_tpu import ops

    gars = CELL_GARS if gars is None else gars
    variants = VARIANTS if variants is None else variants
    cells = {}
    for name in gars:
        gar = ops.gars[name]
        for variant in variants:
            cells[f"{name}/{variant}"] = fingerprint(
                _cell_text(gar, variant))
    return cells


def snapshot():
    """The blessable artifact: the cell fingerprints plus the toolchain
    coordinates they are only comparable under."""
    import jax

    return {
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "spec": {"n": N, "d": D, "f": F},
        "cells": compute_cells(),
    }


def bless(path=GOLDENS_PATH):
    """(Re)write the goldens. Deterministic output (sorted keys, no
    timestamps): blessing twice in one toolchain is byte-idempotent."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(snapshot(), indent=2, sort_keys=True) + "\n")
    return path


def check(path=GOLDENS_PATH):
    """Compare the current lowerings against the blessed goldens.

    Returns a report dict with `status` one of:
      "ok"            — every cell fingerprint matches;
      "drift"         — `drifted`/`added`/`removed` name the cells;
      "incomparable"  — goldens were blessed under another jax version or
                        backend (re-bless, do not fail CI on it);
      "missing"       — no goldens file (run scripts/bless_lowerings.py).
    """
    import jax

    path = pathlib.Path(path)
    if not path.is_file():
        return {"status": "missing", "path": str(path)}
    blessed = json.loads(path.read_text())
    here = {"jax": jax.__version__, "backend": jax.default_backend()}
    if (blessed.get("jax"), blessed.get("backend")) != (
            here["jax"], here["backend"]):
        return {"status": "incomparable", "blessed": {
            "jax": blessed.get("jax"), "backend": blessed.get("backend")},
            "current": here}
    current = compute_cells()
    golden = blessed.get("cells", {})
    drifted = sorted(k for k in golden if k in current
                     and golden[k] != current[k])
    added = sorted(k for k in current if k not in golden)
    removed = sorted(k for k in golden if k not in current)
    status = "ok" if not (drifted or added or removed) else "drift"
    return {"status": status, "drifted": drifted, "added": added,
            "removed": removed, "checked": len(current)}
