"""Runtime compilation/dispatch contracts — the invariants jaxlint cannot
see from the source.

Two contracts, both cheap enough for tier-1:

  * recompile budget — the warm training loop must not recompile. A
    `count_compiles()` listener (the same `jax.monitoring`
    backend-compile signal the obs recorder consumes) counts actual XLA
    backend compiles over a window; `assert_recompile_budget` runs a warm
    step function N times under the counter — and under
    `jax_explain_cache_misses`, so a violation's log says *why* the cache
    missed — and fails when the count exceeds the declared budget
    (normally zero: every shape/dtype/static-arg drift is a bug).

  * transfer guard — the hot loop performs no implicit device<->host
    transfers. `no_implicit_transfers()` wraps
    `jax.transfer_guard("disallow")`: an un-device_put input, a Python
    scalar argument, or a stray `np.asarray` inside the window raises
    instead of silently stalling the pipeline.

jax imports are lazy: importing this module (or the analysis package CLI)
must work where no backend can initialize.
"""

import contextlib

__all__ = ["ContractError", "RecompileBudgetError", "count_compiles",
           "explain_cache_misses", "assert_recompile_budget",
           "no_implicit_transfers", "LockOrderError",
           "record_lock_edges", "assert_lock_edges_subset"]


class ContractError(AssertionError):
    """A static/lowering contract did not hold."""


class RecompileBudgetError(ContractError):
    """The warm loop compiled more programs than its declared budget."""


class CompileLog:
    """Backend-compile events observed inside a `count_compiles()` window."""

    def __init__(self):
        self.events = []
        self.active = True

    @property
    def count(self):
        return len(self.events)


@contextlib.contextmanager
def count_compiles():
    """Count XLA backend compiles within the context (yields a `CompileLog`).

    Counts the `/jax/core/compile/backend_compile*` duration events — the
    actual backend compiles, not per-jaxpr traces (same discrimination as
    `obs/recorder.py`'s recompile counter). Note one user-visible `jit`
    compile may emit several backend events (subcomputations); a budget of
    zero is exact either way, nonzero budgets should be measured, not
    derived.
    """
    from jax import monitoring

    log = CompileLog()

    def _listener(event, duration, **kwargs):
        if log.active and "backend_compile" in str(event):
            log.events.append(str(event))

    monitoring.register_event_duration_secs_listener(_listener)
    try:
        yield log
    finally:
        log.active = False  # the unregister below is best-effort
        try:
            from jax._src import monitoring as _monitoring_impl
            _monitoring_impl._unregister_event_duration_listener_by_callback(
                _listener)
        except (ImportError, AttributeError, ValueError):
            pass  # private API drifted: the inert listener stays, harmless


@contextlib.contextmanager
def explain_cache_misses():
    """Enable `jax_explain_cache_misses` within the context (restores the
    previous value): every tracing-cache miss logs its reason, which is
    exactly the diagnostic a tripped recompile budget needs."""
    import jax

    old = jax.config.jax_explain_cache_misses
    jax.config.update("jax_explain_cache_misses", True)
    try:
        yield
    finally:
        jax.config.update("jax_explain_cache_misses", old)


def assert_recompile_budget(step_fn, *, steps=3, budget=0, explain=True,
                            label="warm loop"):
    """Run `step_fn()` `steps` times and require at most `budget` backend
    compiles across the whole window.

    The caller warms the program up FIRST (one untimed call outside):
    this asserts the steady state, where any compile means shape drift,
    an unhashable static arg, or a Python-scalar cache key churning.
    Returns the observed compile count.
    """
    import jax

    with contextlib.ExitStack() as stack:
        if explain:
            stack.enter_context(explain_cache_misses())
        log = stack.enter_context(count_compiles())
        for _ in range(steps):
            result = step_fn()
            if result is not None:
                jax.block_until_ready(result)
    if log.count > budget:
        raise RecompileBudgetError(
            f"{label}: {log.count} backend compile(s) over {steps} warm "
            f"step(s), budget {budget} — the step is being retraced "
            f"(events: {log.events[:6]}{'...' if log.count > 6 else ''}); "
            f"run under explain_cache_misses() logging for the reason")
    return log.count


@contextlib.contextmanager
def no_implicit_transfers(scope="thread"):
    """`jax.transfer_guard("disallow")` with the contract's framing: inside
    the context any implicit device<->host transfer (un-committed inputs,
    Python scalar arguments, `np.asarray` on device values) raises.
    Explicit `jax.device_put`/`jax.device_get` remain allowed.

    `scope="thread"` (default) uses the thread-local context manager —
    right for a hot loop that dispatches on the calling thread.
    `scope="process"` sets the guard through the global config (restoring
    the previous value on exit), so worker threads are covered too — the
    serve selfcheck needs this: its dispatch and device-wait happen on
    the microbatcher's flusher/resolver daemon threads, which a
    thread-local guard on the submitting thread would never see."""
    import jax

    if scope == "thread":
        with jax.transfer_guard("disallow"):
            yield
        return
    if scope != "process":
        raise ValueError(
            f"Unknown transfer-guard scope {scope!r}; expected "
            f"'thread' or 'process'")
    old = jax.config.jax_transfer_guard
    jax.config.update("jax_transfer_guard", "disallow")
    try:
        yield
    finally:
        jax.config.update("jax_transfer_guard", old)


# --------------------------------------------------------------------------- #
# Lock-order contract (BMT-L runtime cross-check)
#
# The static half (`analysis/locks.py`) derives the whole-program
# lock-order graph from the source; this is the dynamic half. Every
# shared lock is a `utils/locking.NamedLock`, which reports each
# acquisition as `(held, taken)` pairs to an installed recorder. A
# serving window recorded under `record_lock_edges` therefore yields the
# set of ordering edges the process ACTUALLY exercised — and soundness of
# the static graph means that set must be a subset of the blessed static
# edges. An extra runtime edge is either a lock the analysis cannot see
# (fix the analysis) or a code path taking locks in an order the graph
# never blessed (fix the code); both are contract failures, not warnings.


class LockOrderError(ContractError):
    """The serving window exercised a lock-order edge the static
    lock-order graph does not contain."""


@contextlib.contextmanager
def record_lock_edges():
    """Record every NamedLock ordering edge exercised inside the window.

    Yields a set that fills with `(held_name, taken_name)` pairs as
    threads nest named locks; reads of the set are racy-but-monotone
    (callers inspect it after the window closes). Restores any
    previously installed recorder on exit, so windows nest."""
    from byzantinemomentum_tpu.utils import locking

    edges = set()
    previous = locking.install_recorder(edges.add)
    try:
        yield edges
    finally:
        locking.uninstall_recorder(previous)


def assert_lock_edges_subset(edges, static_edges=None, *, paths=None):
    """Assert a recorded edge set is covered by the static graph.

    `edges` is what `record_lock_edges` collected; `static_edges`
    defaults to a fresh `locks.static_edges()` sweep over `paths` (the
    repo, by default). Self-edges (same name held and taken — distinct
    instances sharing a role name, e.g. two `metrics.counter` cells) are
    ignored, matching the static graph's convention. Returns the number
    of distinct runtime edges checked; raises `LockOrderError` listing
    every uncovered edge otherwise."""
    from byzantinemomentum_tpu.analysis import locks

    if static_edges is None:
        static_edges = locks.static_edges(paths=paths)
    runtime = {(held, taken) for held, taken in edges if held != taken}
    extra = sorted(runtime - set(static_edges))
    if extra:
        rendered = ", ".join(f"{a} -> {b}" for a, b in extra)
        raise LockOrderError(
            f"{len(extra)} runtime lock-order edge(s) missing from the "
            f"static lock-order graph: {rendered} — either the analysis "
            f"cannot see an acquisition site (extend locks.py) or a code "
            f"path orders locks the blessed hierarchy never allowed")
    return len(runtime)
