"""Lattice-wide golden-cell enumeration — derived from the program builder.

PR 5 hand-listed 30 lowering cells (GAR × {plain, diag, masked}); this
module *derives* the cell grid from the compositional step-program
builder (`engine/program.py`), so the contract surface grows with the
builder instead of by hand:

  unsharded axis   every first-tier GAR × `program.VARIANTS`, lowered
                   through `program.defense_kernel` — the exact callables
                   the engine dispatches (the legacy 30 cells, same
                   keys), PLUS one `<gar>/masked-bucket` cell per rule:
                   the traced-count masked kernel at a PADDED serving
                   shape (`N_BUCKET` rows), the program the aggregation
                   service's bucket ladder actually compiles — its H02
                   census proves no worker-matrix gather sneaks into the
                   scan/enumeration variants.
  mesh axis        the same kernels rebuilt through the builder's
                   sharding axis (`program.shard_axis`) over VIRTUAL
                   meshes — `jax.make_mesh` over CPU host devices
                   (`--xla_force_host_platform_device_count`, the
                   `tests/conftest.py` trick) — giving the
                   `parallel/sharded.py` kernels StableHLO fingerprints,
                   a collective census, and CI coverage no TPU round ever
                   gave them. Keys: `<gar>/<variant>@mesh<k>`.
  serve axis       the aggregation service's compiled cell programs
                   (`serve/programs.py::_build`) with donation REQUESTED,
                   so the donation-honored contract (BMT-H03) has a real
                   surface. Keys: `serve/<gar>/n<N>f<F>d<D>b<B>[+diag]`.

Each cell carries an `hlolint.Expect` declaring its structural contract
(expected psum count, worker-matrix gather budget, donated argument
positions); `analysis/lowering.py` fingerprints AND structurally lints
every cell in one lowering pass.

The mesh cells need >= max(MESH_AXES) CPU devices: the CLI entrypoints
(`analysis/__main__.py`, `scripts/bless_lowerings.py`) force the host
platform device count before jax initializes, exactly as the test suite
does.
"""

import dataclasses

from byzantinemomentum_tpu.analysis import hlolint

__all__ = ["CELL_GARS", "VARIANTS", "MESH_AXES", "MESH_VARIANTS",
           "MULTIPROC_GARS", "SERVE_CELLS", "GRAM_RULES",
           "COORD_DIAG_RULES", "COORD_DIAG_PSUMS", "N", "N_BUCKET", "D",
           "F", "LatticeCell", "enumerate_cells", "lower_cell",
           "multiprocess_cells", "spec_info"]

# Every first-tier registered rule with real kernels (the `native-` tier
# shares these kernels; `template` declines its own check)
CELL_GARS = ("average", "median", "trmean", "phocas", "meamed", "krum",
             "bulyan", "aksel", "cge", "brute")

# The kernel-variant axis — read from the builder, not re-declared
VARIANTS = ("plain", "diag", "masked")

# Virtual-mesh model-axis sizes, and which variants lower per size (the
# diag axis on one mesh proves the psum'd-Gram diagnostics; the second
# mesh size pins that the communication pattern is shard-count-stable)
MESH_AXES = (2, 4)
MESH_VARIANTS = {2: ("plain", "diag"), 4: ("plain",)}

# Selection rules whose sharded kernels psum one distance Gram — the
# expected collective census of their mesh cells (everything else shards
# with zero communication or replicates)
GRAM_RULES = frozenset({"krum", "bulyan", "brute"})

# Coordinate-wise rules with a NATIVE sharded diagnostics kernel
# (`parallel/sharded.py::_coord_diag_builder`): their diag-under-mesh
# cells psum ONE tuple — (Gram, dev², kept-counts) — which StableHLO
# spells as three all_reduce ops (one per tuple leaf); the census pins
# that the tuple never unfuses into extra collectives. Median joined in
# the PR 11 round (was-median kept-counts — the last generic-fallback
# holdout of the ROADMAP's lattice rung 3).
COORD_DIAG_RULES = frozenset({"trmean", "phocas", "meamed", "median"})
COORD_DIAG_PSUMS = 3

# Serve-axis cells: (gar, n_bucket, f, d, diagnostics, batch) — masked
# -family rules incl. the r10 traced-count holdouts (bulyan's inert
# -round scan, brute's worst-case-sized enumeration), plus diagnostics
# cells; donation never declared (BMT-H03 pinned inert)
SERVE_CELLS = (
    ("krum", 16, 2, 32, True, 4),
    ("median", 8, 1, 32, False, 2),
    ("trmean", 8, 2, 32, False, 4),
    ("average", 4, 1, 32, True, 2),
    ("bulyan", 16, 2, 32, False, 2),
    ("brute", 8, 2, 32, True, 2),
)

# The canonical spec: the benchmark's n=11 worker grid, f=2, a d big
# enough that every kernel takes its vectorized path (and divides every
# mesh axis). N_BUCKET is the padded row count of the masked-bucket
# cells (the serve ladder bucket above N, `serve/programs.py`).
N, D, F = 11, 16, 2
N_BUCKET = 16


@dataclasses.dataclass(frozen=True)
class LatticeCell:
    """One golden cell: a stable key, a builder of `(fn, avals)`, and the
    structural contract its lowered text must satisfy.

    `pin=False` marks a STRUCTURAL-ONLY cell: its lowering is linted
    against `expect` on every check but its fingerprint is never blessed
    — the contract for programs whose bytes legitimately churn (the full
    fused step re-lowers with every engine change) but whose collective
    census must not."""

    key: str
    build: object   # () -> (traceable fn, tuple of ShapeDtypeStructs)
    expect: hlolint.Expect
    pin: bool = True

    def lower(self):
        """The cell's StableHLO text (lowered on abstract values only).
        Already-jitted builders (the serve programs, the donated update)
        lower directly so their jit options — donation above all — reach
        the text."""
        import jax

        fn, avals = self.build()
        if not hasattr(fn, "lower"):
            fn = jax.jit(fn)
        return fn.lower(*avals).as_text()


def _avals(variant):
    import jax
    import jax.numpy as jnp

    spec = jax.ShapeDtypeStruct((N, D), jnp.float32)
    mask = jax.ShapeDtypeStruct((N,), jnp.bool_)
    return (spec,) if variant != "masked" else (spec, mask)


def _plain_cell(name, variant):
    def build():
        from byzantinemomentum_tpu import ops
        from byzantinemomentum_tpu.engine import program

        return (program.defense_kernel(ops.gars[name], variant, f=F),
                _avals(variant))

    return LatticeCell(
        key=f"{name}/{variant}", build=build,
        expect=hlolint.Expect(psums=0, gather_limit=N * D - 1))


def _virtual_mesh(k):
    """A (workers=1, model=k) mesh over virtual CPU host devices."""
    import jax

    from byzantinemomentum_tpu.parallel.mesh import MODEL, WORKERS

    if len(jax.devices()) < k:
        raise RuntimeError(
            f"virtual-mesh lattice cells need {k} devices but only "
            f"{len(jax.devices())} are visible — set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={max(MESH_AXES)} "
            f"before jax initializes (the analysis CLI and bless script "
            f"do this themselves)")
    return jax.make_mesh((1, k), (WORKERS, MODEL))


def _mesh_cell(name, variant, k):
    def build():
        from byzantinemomentum_tpu import ops
        from byzantinemomentum_tpu.engine import program

        mesh = _virtual_mesh(k)
        facade = program.shard_axis(
            [(ops.gars[name], 1.0, {})], mesh, f=F)[0][0]
        return (program.defense_kernel(facade, variant, f=F),
                _avals(variant))

    if name in GRAM_RULES:
        psums = 1
    elif variant == "diag" and name in COORD_DIAG_RULES:
        psums = COORD_DIAG_PSUMS  # the tupled (Gram, dev², kept) psum
    else:
        psums = 0
    return LatticeCell(
        key=f"{name}/{variant}@mesh{k}", build=build,
        expect=hlolint.Expect(psums=psums, gather_limit=N * D - 1))


def _masked_bucket_cell(name):
    """The traced-count masked kernel at a PADDED shape — the exact
    program the aggregation service's bucket ladder compiles
    (`serve/programs.py`): `N_BUCKET` rows for an `N`-row request, the
    surplus masked inactive. Structural contract: no psums, and — the
    BMT-H02 guarantee the traced-count scan/enumeration variants must
    keep — no worker-matrix-scale gather (selection stays rank-predicate
    and one-hot arithmetic, never a dynamic row gather of the padded
    matrix)."""

    def build():
        from byzantinemomentum_tpu import ops
        from byzantinemomentum_tpu.engine import program

        import jax
        import jax.numpy as jnp

        spec = jax.ShapeDtypeStruct((N_BUCKET, D), jnp.float32)
        mask = jax.ShapeDtypeStruct((N_BUCKET,), jnp.bool_)
        return (program.defense_kernel(ops.gars[name], "masked", f=F),
                (spec, mask))

    return LatticeCell(
        key=f"{name}/masked-bucket", build=build,
        expect=hlolint.Expect(psums=0, gather_limit=N_BUCKET * D - 1))


def _quarantine_cell(name):
    """The closed defense loop's per-step program at the quarantine call
    site (`arena/quarantine.py::quarantine_defense_kernel`): sanitize +
    masked-quorum aggregate with the ACTIVE MASK and the reclaimed-quorum
    credit as runtime operands + the rule-agnostic suspicion aux.
    Structural contract: like the masked cells — no collectives, and no
    worker-matrix-scale gather (H02) — so an eviction is provably a
    runtime bool flip over this one program, never a retrace into a
    different one."""

    def build():
        import jax
        import jax.numpy as jnp

        from byzantinemomentum_tpu import ops
        from byzantinemomentum_tpu.arena.quarantine import (
            quarantine_defense_kernel)

        spec = jax.ShapeDtypeStruct((N, D), jnp.float32)
        mask = jax.ShapeDtypeStruct((N,), jnp.bool_)
        f_evicted = jax.ShapeDtypeStruct((), jnp.int32)
        return (quarantine_defense_kernel(ops.gars[name], f=F),
                (spec, mask, f_evicted))

    return LatticeCell(
        key=f"{name}/quarantine", build=build,
        expect=hlolint.Expect(psums=0, gather_limit=N * D - 1))


def _serve_cell(gar, n_bucket, f, d, diagnostics, batch):
    def build():
        import jax
        import jax.numpy as jnp

        from byzantinemomentum_tpu.serve import programs as serve_programs

        cell = serve_programs.Cell(gar, n_bucket, f, d, diagnostics)
        fn = serve_programs._build(cell)
        G = jax.ShapeDtypeStruct((batch, n_bucket, d), jnp.float32)
        active = jax.ShapeDtypeStruct((batch, n_bucket), jnp.bool_)
        return fn, (G, active)

    key = (f"serve/{gar}/n{n_bucket}f{f}d{d}b{batch}"
           + ("+diag" if diagnostics else ""))
    # No donation declared: BMT-H03 caught the PR 8 request as inert (no
    # output matches the packed matrix's shape), so the request is gone
    # and this cell pins the no-aliasing layout
    return LatticeCell(
        key=key, build=build,
        expect=hlolint.Expect(psums=0))


def _health_cell():
    """The numerics flight recorder's in-jit stats program
    (`engine/health.py::health_metrics`) at the canonical spec —
    histogram bucketing, Var ratio, norms and non-finite counts are pure
    elementwise/contraction work: no collectives, no worker-matrix
    gather. Pinned: the health-on step variant rides this fingerprint
    (the step program itself only churns with engine changes)."""

    def build():
        import jax
        import jax.numpy as jnp

        from byzantinemomentum_tpu.engine import health

        Gh = jax.ShapeDtypeStruct((N - F, D), jnp.float32)
        Ga = jax.ShapeDtypeStruct((F, D), jnp.float32)
        vec = jax.ShapeDtypeStruct((D,), jnp.float32)
        return jax.jit(health.health_metrics), (Gh, Ga, vec, vec, vec)

    return LatticeCell(
        key="engine/health-stats", build=build,
        expect=hlolint.Expect(psums=0, gather_limit=N * D - 1))


def _health_mesh_cell(k):
    """The d-sharded health stats (`engine/health.py::
    sharded_health_metrics`): shard-local partials with the width-aware
    real-column mask, ONE tupled psum — `health.HEALTH_PSUMS` all_reduce
    ops (per-row norm² partials + the packed scalar partials), the
    census that pins the tuple never unfuses."""

    def build():
        import jax
        import jax.numpy as jnp

        from byzantinemomentum_tpu.engine import health

        mesh = _virtual_mesh(k)
        Gh = jax.ShapeDtypeStruct((N - F, D), jnp.float32)
        Ga = jax.ShapeDtypeStruct((F, D), jnp.float32)
        vec = jax.ShapeDtypeStruct((D,), jnp.float32)
        return (jax.jit(health.sharded_health_metrics(mesh)),
                (Gh, Ga, vec, vec, vec))

    from byzantinemomentum_tpu.engine.health import HEALTH_PSUMS
    return LatticeCell(
        key=f"engine/health-stats@mesh{k}", build=build,
        expect=hlolint.Expect(psums=HEALTH_PSUMS, gather_limit=N * D - 1))


def _update_cell():
    """The engine's update-phase donation contract: the SGD update
    (`optim.py` — what actually runs inside the donated train step)
    consumes `theta` in place. This is the lattice's honest BMT-H03
    surface: the lowered argument MUST carry `tf.aliasing_output`."""

    def build():
        import jax
        import jax.numpy as jnp

        from byzantinemomentum_tpu import optim

        opt = optim.build("sgd", weight_decay=5e-4)
        theta = jax.ShapeDtypeStruct((D,), jnp.float32)
        lr = jax.ShapeDtypeStruct((), jnp.float32)

        def update(grad, th, lr):
            return opt.update(grad, (), th, lr)[0]

        return (jax.jit(update, donate_argnums=(1,)), (theta, theta, lr))

    return LatticeCell(
        key="engine/sgd-update@donate", build=build,
        expect=hlolint.Expect(psums=0, donated=(1,)))


def _full_step_cell():
    """STRUCTURAL-ONLY coverage of the FULL fused multi-chip step — the
    workers-axis `shard_map` of the grouped honest phase
    (`engine/step.py::_workers_grad_grouped_sharded`) composed with the
    d-sharded defense kernels, exactly what a `--mesh WxM` run compiles.

    The cell's fingerprint is deliberately NOT pinned (`pin=False`): the
    whole-step bytes churn with every engine change and re-blessing them
    per PR would be noise. What must NOT churn is the communication
    pattern, and that is what the BMT-H contract pins: exactly ONE
    explicit collective (krum's psum'd distance Gram — the grouped
    honest phase's shard_map is collective-free, worker rows are data
    parallel) and NO explicit worker-matrix all_gather (H02; the
    jit-propagated resharding at the shard_map boundaries never
    materializes the (n, d) matrix in the traced program).
    """

    def build():
        import jax
        import jax.numpy as jnp

        from byzantinemomentum_tpu import attacks, losses, models, ops
        from byzantinemomentum_tpu.engine import (
            EngineConfig, build_engine)
        from byzantinemomentum_tpu.parallel import sharded_train_step
        from byzantinemomentum_tpu.parallel.mesh import MODEL, WORKERS

        if len(jax.devices()) < 4:
            raise RuntimeError(
                "the full-step structural cell needs a (2, 2) virtual "
                "mesh — set XLA_FLAGS="
                "--xla_force_host_platform_device_count>=4 (the analysis "
                "CLI and bless script do this themselves)")
        mesh = jax.make_mesh((2, 2), (WORKERS, MODEL))
        cfg = EngineConfig(
            nb_workers=5, nb_decl_byz=1, nb_real_byz=1, nb_for_study=0,
            nb_for_study_past=1, momentum=0.9)
        engine = build_engine(
            cfg=cfg, model_def=models.build("simples-full"),
            loss=losses.Loss("nll"), criterion=losses.Criterion("top-k"),
            defenses=[(ops.gars["krum"], 1.0, {})],
            attack=attacks.attacks["empire"],
            attack_kwargs={"factor": 1.1})
        state = engine.init(jax.random.PRNGKey(0))
        fn = sharded_train_step(engine, mesh, state)
        S, B = cfg.nb_sampled, 4
        xs = jax.ShapeDtypeStruct((S, B, 28, 28, 1), jnp.float32)
        ys = jax.ShapeDtypeStruct((S, B), jnp.int32)
        lr = jax.ShapeDtypeStruct((), jnp.float32)
        return fn, (state, xs, ys, lr)

    return LatticeCell(
        key="engine/full-step@mesh2x2", build=build,
        expect=hlolint.Expect(psums=1, gather_limit=N * D - 1),
        pin=False)


# GARs whose multi-process cells the cluster census lowers: the two
# Gram-psum rules prove the cross-host (n, n) reduction, the two
# coordinate-wise rules prove zero-communication d-sharding across hosts
MULTIPROC_GARS = ("krum", "bulyan", "median", "average")


def multiprocess_cells(gars=MULTIPROC_GARS, *, min_processes=2):
    """Cells over a LIVE multi-process backend (`jax.distributed`): the
    d-sharded defense kernels rebuilt on a (workers=1, model=P) mesh
    spanning every process's devices, so the selection rules' Gram psum
    is a REAL cross-host collective. Keys: `<gar>/plain@proc<P>`.

    These cells cannot be blessed by the single-process CLIs (no fleet in
    the lint tier); instead every host of a cluster run lowers them,
    census-checks them, and writes its fingerprints for the launcher's
    cross-host agreement check (`cluster/host.py::_run_census`) — same
    census/fingerprint treatment, consensus instead of a committed file.

    `min_processes` guards against silently degrading to a single-process
    mesh (tests that only need the builder shape pass 1).
    """
    import jax
    import numpy as np

    from byzantinemomentum_tpu.parallel.mesh import MODEL, WORKERS
    from jax.sharding import Mesh

    procs = jax.process_count()
    if procs < min_processes:
        raise RuntimeError(
            f"multiprocess cells need a >= {min_processes}-process "
            f"fleet (jax.distributed), found {procs} — launch through "
            f"byzantinemomentum_tpu.cluster")
    devices = np.asarray(jax.devices())
    mesh = Mesh(devices.reshape(1, devices.size), (WORKERS, MODEL))

    def cell(name):
        def build():
            from byzantinemomentum_tpu import ops
            from byzantinemomentum_tpu.engine import program

            facade = program.shard_axis(
                [(ops.gars[name], 1.0, {})], mesh, f=F)[0][0]
            return (program.defense_kernel(facade, "plain", f=F),
                    _avals("plain"))

        return LatticeCell(
            key=f"{name}/plain@proc{procs}", build=build,
            expect=hlolint.Expect(
                psums=1 if name in GRAM_RULES else 0,
                gather_limit=N * D - 1),
            pin=False)

    return [cell(name) for name in gars]


def enumerate_cells(gars=None, variants=None, meshes=None, serve=None):
    """The full lattice, as `LatticeCell`s (defaults read the module
    attributes at call time, so tests can shrink the grid)."""
    gars = CELL_GARS if gars is None else gars
    variants = VARIANTS if variants is None else variants
    meshes = MESH_AXES if meshes is None else meshes
    serve = SERVE_CELLS if serve is None else serve
    cells = []
    for name in gars:
        for variant in variants:
            cells.append(_plain_cell(name, variant))
    if "masked" in variants:
        # The bucket axis: every rule's traced-count masked kernel at a
        # padded serving shape (H02 census: no worker-matrix gather)
        for name in gars:
            cells.append(_masked_bucket_cell(name))
        # The quarantine axis: the closed loop's defense-plus-aux program
        # (PR 11), runtime-mask contract per rule
        for name in gars:
            cells.append(_quarantine_cell(name))
    for k in meshes:
        for name in gars:
            for variant in MESH_VARIANTS.get(k, ("plain",)):
                if variant in variants:
                    cells.append(_mesh_cell(name, variant, k))
    for spec in serve:
        cells.append(_serve_cell(*spec))
    if serve:
        # The update-axis donation contract rides with the default grid
        # (shrunken test grids that drop the serve axis drop it too),
        # as does the structural-only full-step cell (linted every
        # check, never fingerprinted — see `_full_step_cell`), and the
        # flight recorder's health-stats cells (unsharded + the tupled-
        # psum d-sharded form; PR 15)
        cells.append(_update_cell())
        cells.append(_health_cell())
        if 2 in meshes:
            cells.append(_health_mesh_cell(2))
        cells.append(_full_step_cell())
    return cells


def lower_cell(cell):
    """`(key, StableHLO text, expect)` of one cell."""
    return cell.key, cell.lower(), cell.expect


def spec_info():
    """The enumeration coordinates recorded next to the fingerprints."""
    return {"n": N, "n_bucket": N_BUCKET, "d": D, "f": F,
            "meshes": [int(k) for k in MESH_AXES],
            "serve_cells": len(SERVE_CELLS),
            "structural_cells": sum(1 for c in enumerate_cells()
                                    if not c.pin)}
